"""Benchmark driver: one JSON line with the headline metric.

Measures training-step MFU (model FLOPs utilization) of the sharded train
engine on the local chip: a dense Qwen2.5-flavor model, packed 2k sequences,
full forward+backward+optimizer step via ``TrainEngine.train_batch``.

``vs_baseline`` normalizes our MFU against the reference system's assumed
training MFU on H800 (0.35 — typical of Megatron-backed dense-model RL
trainers at this scale; the reference publishes no per-GPU tok/s, see
SURVEY.md §6), making the comparison hardware-neutral.
"""

from __future__ import annotations

import json
import time

import numpy as np

REFERENCE_TRAIN_MFU = 0.35

# bf16 peak TFLOP/s per chip
PEAK_TFLOPS = {
    "v3": 123,
    "v4": 275,
    "v5e": 197,
    "v5 lite": 197,
    "v5p": 459,
    "v6e": 918,
    "v6 lite": 918,
    "trillium": 918,
    "cpu": 0.2,  # nominal, so the script degrades gracefully off-TPU
}


def peak_flops(device) -> float:
    kind = getattr(device, "device_kind", "cpu").lower()
    for name, tf in PEAK_TFLOPS.items():
        if name in kind:
            return tf * 1e12
    return PEAK_TFLOPS["cpu"] * 1e12


def param_count(params) -> int:
    import jax

    return sum(int(np.prod(x.shape)) for x in jax.tree.leaves(params))


def main():
    import jax

    from areal_tpu.api.data import MicroBatchSpec, SequenceSample
    from areal_tpu.base.topology import MeshSpec
    from areal_tpu.engine.optimizer import OptimizerConfig
    from areal_tpu.engine.train_engine import TrainEngine
    from areal_tpu.interfaces.sft_interface import sft_loss_fn
    from areal_tpu.models import transformer
    from areal_tpu.models.config import TransformerConfig

    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"

    if on_tpu:
        # ~0.5B dense model (fits v5e 16G HBM with fp32 adam states)
        cfg = TransformerConfig(
            n_layers=24,
            hidden_dim=1024,
            n_q_heads=16,
            n_kv_heads=8,
            head_dim=64,
            intermediate_dim=5504,
            vocab_size=32768,
            max_position_embeddings=4096,
            use_attention_bias=True,
            dtype="bfloat16",
            remat=True,
        )
        seq_len, n_seqs, timed_steps = 2048, 16, 3
    else:
        cfg = TransformerConfig(
            n_layers=4,
            hidden_dim=256,
            n_q_heads=4,
            n_kv_heads=2,
            head_dim=64,
            intermediate_dim=1024,
            vocab_size=2048,
            max_position_embeddings=1024,
            dtype="float32",
        )
        seq_len, n_seqs, timed_steps = 512, 4, 2

    # fp32 master weights; the model casts to cfg.dtype (bf16) at use, so
    # compute runs on the MXU in bf16 while adam states stay fp32.
    params = transformer.init_params(cfg, jax.random.PRNGKey(0))
    n_params = param_count(params)

    mesh = MeshSpec().make_mesh(jax.devices()[:1])
    engine = TrainEngine(
        cfg,
        mesh,
        params,
        optimizer_cfg=OptimizerConfig(lr=1e-5),
        total_train_steps=100,
    )

    rng = np.random.default_rng(0)
    tokens_per_step = n_seqs * seq_len
    sample = SequenceSample.from_default(
        seqlens=[seq_len] * n_seqs,
        ids=list(range(n_seqs)),
        data={
            "packed_input_ids": rng.integers(
                0, cfg.vocab_size, (tokens_per_step,)
            ).astype(np.int64),
            "prompt_mask": np.zeros((tokens_per_step,), bool),
        },
    )
    mb_spec = MicroBatchSpec(n_mbs=1)

    engine.train_batch(sample, sft_loss_fn, mb_spec)  # compile + warmup
    t0 = time.perf_counter()
    for _ in range(timed_steps):
        engine.train_batch(sample, sft_loss_fn, mb_spec)
    dt = (time.perf_counter() - t0) / timed_steps

    toks_per_sec = tokens_per_step / dt
    flops_per_tok = 6 * n_params  # dense fwd+bwd
    mfu = toks_per_sec * flops_per_tok / peak_flops(dev)

    print(
        json.dumps(
            {
                "metric": "train_step_mfu",
                "value": round(mfu, 4),
                "unit": "fraction_of_peak",
                "vs_baseline": round(mfu / REFERENCE_TRAIN_MFU, 4),
                "detail": {
                    "device": getattr(dev, "device_kind", dev.platform),
                    "n_params": n_params,
                    "tokens_per_sec": round(toks_per_sec, 1),
                    "step_time_s": round(dt, 4),
                    "tokens_per_step": tokens_per_step,
                },
            }
        )
    )


if __name__ == "__main__":
    main()

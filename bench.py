"""Benchmark driver: one JSON line with the headline metric.

Measures training-step MFU (model FLOPs utilization) of the sharded train
engine on the local chip: a dense Qwen2.5-flavor model, packed 2k sequences,
full forward+backward+optimizer step via ``TrainEngine.train_batch``.

``vs_baseline`` normalizes our MFU against the reference system's assumed
training MFU on H800 (0.35 — typical of Megatron-backed dense-model RL
trainers at this scale; the reference publishes no per-GPU tok/s, see
SURVEY.md §6), making the comparison hardware-neutral.
"""

from __future__ import annotations

import json
import time

import numpy as np

REFERENCE_TRAIN_MFU = 0.35

# bf16 peak TFLOP/s per chip
PEAK_TFLOPS = {
    "v3": 123,
    "v4": 275,
    "v5e": 197,
    "v5 lite": 197,
    "v5p": 459,
    "v6e": 918,
    "v6 lite": 918,
    "trillium": 918,
    "cpu": 0.2,  # nominal, so the script degrades gracefully off-TPU
}


def peak_flops(device) -> float:
    kind = getattr(device, "device_kind", "cpu").lower()
    for name, tf in PEAK_TFLOPS.items():
        if name in kind:
            return tf * 1e12
    return PEAK_TFLOPS["cpu"] * 1e12


def param_count(params) -> int:
    import jax

    return sum(int(np.prod(x.shape)) for x in jax.tree.leaves(params))


def bench_generation(cfg, params, n_reqs=32, prompt_len=512, max_new=512):
    """Continuous-batching rollout throughput on one chip: batched prefill
    tok/s and sustained decode tok/s (the BASELINE.json north-star metric's
    single-chip component)."""
    import time

    import jax
    import jax.numpy as jnp

    from areal_tpu.api.model_api import (
        APIGenerateInput,
        GenerationHyperparameters,
    )
    from areal_tpu.engine.inference_server import ContinuousBatchingEngine

    bf16 = params  # caller passes an inference-dtype copy
    rng = np.random.default_rng(1)

    def run(max_new_tokens):
        eng = ContinuousBatchingEngine(
            cfg,
            bf16,
            max_batch=n_reqs,
            kv_cache_len=bench_gen_cache_len(prompt_len, max_new),
            chunk_size=128,
        )
        gcfg = GenerationHyperparameters(
            max_new_tokens=max_new_tokens, temperature=1.0
        )
        for i in range(n_reqs):
            ids = rng.integers(0, cfg.vocab_size, (prompt_len,)).tolist()
            eng.submit(
                APIGenerateInput(
                    qid=str(i), prompt_ids=ids, input_ids=ids, gconfig=gcfg
                )
            )
        t0 = time.perf_counter()
        eng._admit()
        int(eng.cache.lengths[0])  # force sync
        t_prefill = time.perf_counter() - t0
        t0 = time.perf_counter()
        n_decoded = 0
        while eng.has_work:
            n_decoded += eng.step()
        t_decode = time.perf_counter() - t0
        return t_prefill, t_decode, n_decoded

    # warmup must cover every attention-length bucket the timed run will
    # touch (the engine recompiles the decode chunk per pow2 cache prefix)
    run(max_new)
    t_prefill, t_decode, n_decoded = run(max_new)
    return {
        "prefill_toks_per_sec": round(n_reqs * prompt_len / t_prefill, 1),
        "decode_toks_per_sec": round(n_decoded / t_decode, 1),
        "batch": n_reqs,
        "prompt_len": prompt_len,
        "max_new_tokens": max_new,
    }


def bench_gen_cache_len(prompt_len, max_new):
    """Smallest 128-multiple covering the bench sequences.  Round-up to a
    power of two looked harmless but was measured catastrophic: a 2048-slot
    cache for 1032-token rows put B=64 under memory pressure (lazy
    execution keeps >1 donated cache generation alive) and decode fell to
    2.3k tok/s; right-sized 1152 slots reach 7.2k on the same chip."""
    n = prompt_len + max_new + 8
    return -(-n // 128) * 128


def main():
    import jax

    from areal_tpu.api.data import MicroBatchSpec, SequenceSample
    from areal_tpu.base.topology import MeshSpec
    from areal_tpu.engine.optimizer import OptimizerConfig
    from areal_tpu.engine.train_engine import TrainEngine
    from areal_tpu.interfaces.sft_interface import sft_loss_fn
    from areal_tpu.models import transformer
    from areal_tpu.models.config import TransformerConfig

    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"

    if on_tpu:
        # ~0.5B dense model (fits v5e 16G HBM with fp32 adam states).
        # head_dim=128 matches the Qwen2.5 family the reference trains and
        # fully fills the TPU's 128-lane tiles in the attention kernel
        # (head_dim=64 measured ~2x slower attention).
        cfg = TransformerConfig(
            n_layers=24,
            hidden_dim=1024,
            n_q_heads=8,
            n_kv_heads=4,
            head_dim=128,
            intermediate_dim=5504,
            vocab_size=32768,
            max_position_embeddings=4096,
            use_attention_bias=True,
            dtype="bfloat16",
            remat=True,
        )
        seq_len, n_seqs, timed_steps = 2048, 16, 3
    else:
        cfg = TransformerConfig(
            n_layers=4,
            hidden_dim=256,
            n_q_heads=4,
            n_kv_heads=2,
            head_dim=64,
            intermediate_dim=1024,
            vocab_size=2048,
            max_position_embeddings=1024,
            dtype="float32",
        )
        seq_len, n_seqs, timed_steps = 512, 4, 2

    # fp32 master weights; the model casts to cfg.dtype (bf16) at use, so
    # compute runs on the MXU in bf16 while adam states stay fp32.
    params = transformer.init_params(cfg, jax.random.PRNGKey(0))
    n_params = param_count(params)
    # independent bf16 copy for the generation bench — the train engine
    # DONATES its param buffers every step, invalidating aliases
    import jax.numpy as jnp

    gen_params = jax.tree.map(lambda x: x.astype(jnp.bfloat16), params)

    mesh = MeshSpec().make_mesh(jax.devices()[:1])
    engine = TrainEngine(
        cfg,
        mesh,
        params,
        optimizer_cfg=OptimizerConfig(lr=1e-5),
        total_train_steps=100,
    )

    rng = np.random.default_rng(0)
    tokens_per_step = n_seqs * seq_len
    sample = SequenceSample.from_default(
        seqlens=[seq_len] * n_seqs,
        ids=list(range(n_seqs)),
        data={
            "packed_input_ids": rng.integers(
                0, cfg.vocab_size, (tokens_per_step,)
            ).astype(np.int64),
            "prompt_mask": np.zeros((tokens_per_step,), bool),
        },
    )
    mb_spec = MicroBatchSpec(n_mbs=1)

    # two warmups: first compiles, second lets buffer donation settle
    engine.train_batch(sample, sft_loss_fn, mb_spec)
    engine.train_batch(sample, sft_loss_fn, mb_spec)
    t0 = time.perf_counter()
    for _ in range(timed_steps):
        engine.train_batch(sample, sft_loss_fn, mb_spec)
    dt = (time.perf_counter() - t0) / timed_steps

    toks_per_sec = tokens_per_step / dt
    flops_per_tok = 6 * n_params  # dense fwd+bwd
    mfu = toks_per_sec * flops_per_tok / peak_flops(dev)

    gen = (
        bench_generation(cfg, gen_params)
        if on_tpu
        else bench_generation(
            cfg, gen_params, n_reqs=2, prompt_len=32, max_new=16
        )
    )

    # train->generation weight publish: sharded raw-param checkpoint in
    # inference dtype (the <1s single-host budget from the reference's <3s
    # at 1k-GPU scale, blog/AReaL_v0_2.md:52-54)
    import shutil
    import tempfile

    from areal_tpu.engine.checkpoint import save_params, wait_for_saves

    pub_dir = tempfile.mkdtemp(prefix="areal-bench-pub-")
    try:
        save_params(gen_params, pub_dir + "/v0", cast_dtype="bfloat16")  # warm
        t0 = time.perf_counter()
        save_params(
            gen_params, pub_dir + "/v1", cast_dtype="bfloat16", wait=False
        )
        publish_block_s = time.perf_counter() - t0  # trainer stall
        wait_for_saves()
        publish_commit_s = time.perf_counter() - t0  # durable + advertised
    finally:
        shutil.rmtree(pub_dir, ignore_errors=True)

    print(
        json.dumps(
            {
                "metric": "train_step_mfu",
                "value": round(mfu, 4),
                "unit": "fraction_of_peak",
                "vs_baseline": round(mfu / REFERENCE_TRAIN_MFU, 4),
                "detail": {
                    "device": getattr(dev, "device_kind", dev.platform),
                    "n_params": n_params,
                    "tokens_per_sec": round(toks_per_sec, 1),
                    "step_time_s": round(dt, 4),
                    "tokens_per_step": tokens_per_step,
                    "weight_publish_block_s": round(publish_block_s, 4),
                    "weight_publish_commit_s": round(publish_commit_s, 3),
                    "generation": gen,
                },
            }
        )
    )


if __name__ == "__main__":
    main()

"""Benchmark driver: one JSON line with the headline metric.

Headline: **effective RL throughput per peak-TFLOP** — trained tokens per
second of a full generate->train step on one chip, normalized by the chip's
peak bf16 TFLOP/s, against a baseline DERIVED from the reference system's
published end-to-end numbers (not an assumed constant):

    reference async 1.5B run: 1000 PPO steps in 14.8 h on 16 nodes x 8 H800
    (reference: blog/AReaL_v0_3.md:109-113), batch 512 prompts x 16 answers
    = 8192 sequences/step (reference: benchmark/verl_.../README.md:40-46).
    Mean total sequence length is not published; assumed 8000 tokens
    (~1k prompt + ~7k response, consistent with the 31k cap and <5%
    truncation, reference: blog/AReaL_v0_2.md:88).  That gives
    8192*8000 / 53.28 s / 128 GPUs / 989 TFLOP/s = 9.72 tok/s per TFLOP/s.

Components also measured (in `detail`): train-step MFU (param-only and
attention-corrected, plus an 8k-context row — hardware efficiency holds
~0.40-0.43 attn-corrected from 2k to 8k on v5e), decode/prefill
throughput at 0.5B (batch 32 and 64) and at the Qwen2.5-1.5B architecture,
interruptible-vs-drain weight-update throughput (the reference's +12-17%
mechanism, blog/AReaL_v0_3.md:125), and publish block/commit latency
(reference budget <3 s, blog/AReaL_v0_2.md:52-54).

Round 5 moved the headline to the RECIPE REGIME: the effective row runs
~8k-token sequences (prompt 7.5k + 512 generated) through the PAGED
serving engine, so the baseline's assumed 8000-token mean cancels instead
of flattering a short-sequence number; `detail` adds the paged-vs-dense
decode A/B at 2k-32k context (1.5B arch) with the 16x16k capacity row,
and the chunked-prefill decode-stall A/B.

Round 6 adds the train-MFU lever sweep: `train_remat_moment_sweep` runs
{remat_policy x optimizer-moment dtype} cells at the bench batch (graduated
remat presets from models/remat.py x bf16/factored Adam moments from
OptimizerConfig), reporting per cell tok/s/TFLOP and XLA's peak-temp
allocation, with would-OOM cells reported from the memory analysis instead
of crashed; the decode A/B's `paged_flash_attention_deep` column is now
unconditional (first hardware numbers); and the device probe retries with
backoff and on final failure emits a structured JSON error record at rc=0
(round 5's bench died to a hung `jax.devices()` on an unreachable TPU).

Round 7 measures the deep-pipelined serving hot path: the generation
section reports `engine_over_jit` (engine decode vs the isolated
decode_chunk jit loop at the same shapes — the 0.78x gap VERDICT r5 #5
flagged), a `ring_ab` sub-row sweeping the engine's `pipeline_depth`
(K in-flight chunks + dispatch-time async output fetch) with the
host/device/fetch split per K, and a `prefill_ab` section attributing the
round-5 prefill regression (jit ceiling vs engine dense admit vs paged
chunked admit, repeated so tunnel variance is visible as spread).  The
decode A/B additionally derives a `PagedDispatchTable` (engine/dispatch.py)
from its own 3-column rows, and the whole round's diffable numbers are
duplicated into a compact top-level `summary` object so BENCH_rNN.json's
`parsed` field carries them even when `detail` is huge.

Round 8 measures the cross-request radix prefix cache
(engine/prefix_cache.py): a `prefix_cache_ab` section replays multi-turn
conversations — every turn re-sends the WHOLE growing conversation under a
fresh qid, the shape of the reference's multi-turn agent loops over
SGLang's radix cache — with the cache on vs off, reporting the
cached-token fraction (prompt tokens served from cache instead of
re-prefilled), suffix-only prefill work, and end-to-end replay tok/s.
The section runs off-TPU too (tiny shapes) so the summary always carries
it.

Caveats stated where measured: ONE chip, sync gen+train (the reference's
number is 128-GPU async); 1.5B uses the true Qwen2.5-1.5B architecture
with random weights (zero-egress image has no checkpoint; the HF importer
is parity-tested separately); the 1.5B fp32-adam train state (21 GB)
exceeds one v5e, so the effective row keeps the 0.5B model (the recipe
trains 1.5B on an 8-chip FSDP mesh — dryrun-validated).
"""

from __future__ import annotations

import json
import os
import time

import numpy as np

# ---- derived reference baseline (see module docstring) --------------------
REF_SEQS_PER_STEP = 512 * 16
REF_MEAN_SEQ_LEN_ASSUMED = 8000
REF_STEP_SECONDS = 14.8 * 3600 / 1000
REF_N_GPUS = 16 * 8
REF_GPU_PEAK_TFLOPS = 989  # H800 dense bf16
REF_TOK_PER_SEC_PER_TFLOP = (
    REF_SEQS_PER_STEP
    * REF_MEAN_SEQ_LEN_ASSUMED
    / REF_STEP_SECONDS
    / REF_N_GPUS
    / REF_GPU_PEAK_TFLOPS
)

# bf16 peak TFLOP/s per chip
PEAK_TFLOPS = {
    "v3": 123,
    "v4": 275,
    "v5e": 197,
    "v5 lite": 197,
    "v5p": 459,
    "v6e": 918,
    "v6 lite": 918,
    "trillium": 918,
    "cpu": 0.2,  # nominal, so the script degrades gracefully off-TPU
}


def peak_flops(device) -> float:
    kind = getattr(device, "device_kind", "cpu").lower()
    for name, tf in PEAK_TFLOPS.items():
        if name in kind:
            return tf * 1e12
    return PEAK_TFLOPS["cpu"] * 1e12


def param_count(params) -> int:
    import jax

    return sum(int(np.prod(x.shape)) for x in jax.tree.leaves(params))


def bench_gen_cache_len(prompt_len, max_new):
    """Smallest 128-multiple covering the bench sequences.  Round-up to a
    power of two looked harmless but was measured catastrophic: a 2048-slot
    cache for 1032-token rows put B=64 under memory pressure (lazy
    execution keeps >1 donated cache generation alive) and decode fell to
    2.3k tok/s; right-sized 1152 slots reach 7.2k on the same chip."""
    n = prompt_len + max_new + 8
    return -(-n // 128) * 128


def make_engine(cfg, params, n_reqs, prompt_len, max_new, chunk=128, **kw):
    from areal_tpu.engine.inference_server import ContinuousBatchingEngine

    return ContinuousBatchingEngine(
        cfg,
        params,
        max_batch=n_reqs,
        kv_cache_len=bench_gen_cache_len(prompt_len, max_new),
        chunk_size=chunk,
        **kw,
    )


def submit_wave(
    eng, cfg, n_reqs, prompt_len, max_new, tag, lens=None, greedy=False
):
    from areal_tpu.api.model_api import (
        APIGenerateInput,
        GenerationHyperparameters,
    )

    import zlib

    # crc32, not hash(): str hashes are salted per interpreter launch and
    # would make the prompt stream differ across bench runs
    rng = np.random.default_rng(zlib.crc32(tag.encode()))
    qids = []
    for i in range(n_reqs):
        ids = rng.integers(0, cfg.vocab_size, (prompt_len,)).tolist()
        mn = int(lens[i]) if lens is not None else max_new
        qid = f"{tag}{i}"
        qids.append(qid)
        eng.submit(
            APIGenerateInput(
                qid=qid,
                prompt_ids=ids,
                input_ids=ids,
                gconfig=GenerationHyperparameters(
                    max_new_tokens=mn,
                    **({"greedy": True} if greedy
                       else {"temperature": 1.0}),
                ),
            )
        )
    return qids


def lcp_divergence(ref_streams, got_streams):
    """Greedy divergence between two {qid: tokens} stream maps:
    ``1 - (longest-common-prefix tokens / reference tokens)`` — one
    early flip charges the whole tail (the conservative definition).
    Returns ``(rate, diverged_request_count)``.  THE quality-gate
    statistic of ``bench_kv_quant_ab``; the tier-1 divergence pin
    (tests/engine/test_kv_quant.py) imports this same function so the
    asserted bar can never drift from what the bench reports."""
    total = matched = diverged = 0
    for qid, ref in ref_streams.items():
        got = got_streams[qid]
        lcp = 0
        for a, b in zip(ref, got):
            if a != b:
                break
            lcp += 1
        total += len(ref)
        matched += lcp
        diverged += int(lcp < max(len(ref), len(got)))
    return round(1.0 - matched / max(total, 1), 4), diverged


def drain(eng):
    n = 0
    while eng.has_work:
        n += eng.step()
    eng.drain_results()
    return n


def _split_fracs(split):
    attributed = max(
        split["host_s"] + split["device_s"] + split["fetch_s"], 1e-9
    )
    return {
        "host_s": round(split["host_s"], 4),
        "device_s": round(split["device_s"], 4),
        "fetch_s": round(split["fetch_s"], 4),
        "chunks": int(split["chunks"]),
        "host_frac": round(split["host_s"] / attributed, 3),
        "device_frac": round(split["device_s"] / attributed, 3),
        "fetch_frac": round(split["fetch_s"] / attributed, 3),
    }


def _jit_decode_rate(cfg, params, B, L, S, W=128):
    """Isolated ``decode_chunk`` jit-loop throughput (tok/s) at the
    engine's exact shapes and sampling — the engine-overhead-free ceiling
    that ``engine_over_jit`` divides by (VERDICT r5 #5: the engine ran at
    ~0.78x of this and nobody could say which overhead ate the rest)."""
    import jax
    import jax.numpy as jnp

    from areal_tpu.engine.sampling import SamplingParams, sample_logits
    from areal_tpu.models.transformer import KVCache, decode_chunk

    sp = SamplingParams()  # the engine's default sampler

    def sample(logits, rng):
        return sample_logits(logits, rng, sp)

    def no_stop(toks):
        return jnp.zeros_like(toks, bool)

    dense_jit = jax.jit(
        decode_chunk,
        static_argnames=(
            "cfg", "chunk_size", "sample_fn", "stop_fn", "attn_len"
        ),
        donate_argnums=(2,),
    )
    key = jax.random.PRNGKey(0)
    kd = jax.random.normal(
        key,
        (cfg.n_layers, B, cfg.n_kv_heads, S, cfg.head_dim),
        jnp.bfloat16,
    ) * 0.05
    cache = KVCache(k=kd, v=kd + 0.0, lengths=jnp.full((B,), L, jnp.int32))
    cur = jnp.full((B,), 7, jnp.int32)
    active = jnp.ones((B,), bool)
    budgets = jnp.full((B,), 10_000, jnp.int32)
    rng = jax.random.PRNGKey(1)
    times, cur_h = [], cur
    for _ in range(4):
        t0 = time.perf_counter()
        cache, out_t, _, _, _, _, budgets, rng = dense_jit(
            params, cfg, cache, cur_h, active, budgets, rng,
            chunk_size=W, sample_fn=sample, stop_fn=no_stop, attn_len=S,
        )
        # route the sampled tokens through the host like the engine does
        cur_h = jnp.asarray(np.asarray(out_t[:, -1]))
        times.append(time.perf_counter() - t0)
    del cache, kd
    return B * W / min(times[2:])


def bench_generation(
    cfg, params, n_reqs, prompt_len=512, max_new=512,
    pipeline_depth=2, ring_ab=(), jit_ratio=False,
):
    """Continuous-batching throughput on one chip: batched prefill tok/s
    and sustained decode tok/s under a ``pipeline_depth``-deep in-flight
    chunk ring.  ``jit_ratio`` adds the isolated decode_chunk loop at the
    same shapes and the engine/jit ratio; ``ring_ab`` sweeps pipeline
    depths (shorter waves, compiles shared) reporting tok/s + the
    host/device/fetch split per K — the fetch_frac column is the direct
    readout of whether the dispatch-time async output copy is hiding the
    tunnel RTT.  The engine is dropped before returning so its KV cache
    (and its reference to ``params``) frees promptly."""
    eng = make_engine(
        cfg, params, n_reqs, prompt_len, max_new,
        pipeline_depth=pipeline_depth,
    )
    # warmup compiles every attention bucket the timed run touches
    submit_wave(eng, cfg, n_reqs, prompt_len, max_new, "w")
    drain(eng)
    submit_wave(eng, cfg, n_reqs, prompt_len, max_new, "t")
    t0 = time.perf_counter()
    eng._admit()
    int(np.asarray(eng.cache.lengths)[0])  # force prefill completion
    t_prefill = time.perf_counter() - t0
    # zero the timing counters so the split covers ONLY the timed decode
    # phase (warmup compiles + admission would otherwise dominate host_s)
    eng.time_host_s = eng.time_device_s = eng.time_fetch_s = 0.0
    eng.chunks_total = 0
    t0 = time.perf_counter()
    n_decoded = drain(eng)
    t_decode = time.perf_counter() - t0
    split = eng.timing_split()
    fetch_overlap = {
        "async_fetches": int(eng.async_fetches_total),
        "ready_at_harvest": int(eng.fetch_ready_total),
    }
    del eng
    out = {
        "prefill_toks_per_sec": round(n_reqs * prompt_len / t_prefill, 1),
        "decode_toks_per_sec": round(n_decoded / t_decode, 1),
        "batch": n_reqs,
        "prompt_len": prompt_len,
        "max_new_tokens": max_new,
        "pipeline_depth": pipeline_depth,
        # decode-loop time attribution (engine-vs-jit gap): host
        # bookkeeping vs blocked-on-device vs output fetch (tunnel/PCIe)
        "decode_split": _split_fracs(split),
        "fetch_overlap": fetch_overlap,
    }
    if jit_ratio:
        S = bench_gen_cache_len(prompt_len, max_new)
        jit_rate = _jit_decode_rate(cfg, params, n_reqs, prompt_len, S)
        out["jit_decode_toks_per_sec"] = round(jit_rate, 1)
        out["engine_over_jit"] = round(
            out["decode_toks_per_sec"] / max(jit_rate, 1e-9), 3
        )
    for K in ring_ab:
        # shorter waves; every attention bucket is already compiled by
        # the main run (lengths pass through the same power-of-two
        # buckets while growing), so each K pays only its own decode
        eng = make_engine(
            cfg, params, n_reqs, prompt_len, max_new, pipeline_depth=K
        )
        ab_new = max_new // 2
        submit_wave(eng, cfg, n_reqs, prompt_len, ab_new, f"rk{K}")
        eng._admit()
        int(np.asarray(eng.cache.lengths)[0])
        eng.time_host_s = eng.time_device_s = eng.time_fetch_s = 0.0
        eng.chunks_total = 0
        t0 = time.perf_counter()
        n = drain(eng)
        dt = time.perf_counter() - t0
        ksplit = _split_fracs(eng.timing_split())
        out.setdefault("ring_ab", {})[f"k{K}"] = {
            "decode_toks_per_sec": round(n / dt, 1),
            "host_frac": ksplit["host_frac"],
            "device_frac": ksplit["device_frac"],
            "fetch_frac": ksplit["fetch_frac"],
            "fetch_ready_frac": round(
                eng.fetch_ready_total / max(eng.chunks_total, 1), 3
            ),
        }
        del eng
    return out


def bench_trace_overhead_ab(
    cfg, params, n_reqs=32, prompt_len=256, max_new=256, repeats=2,
):
    """Flight-recorder overhead A/B: sustained decode tok/s with tracing
    off / sampled (the production default rate) / always-on.  The claim
    the acceptance bar tracks is "sampled tracing costs < 2% decode
    tok/s vs tracing-off" — measured here, machine-parseable, and
    diffable across rounds like the other A/Bs.  Each arm rebuilds the
    engine under a fresh tracer (the engine binds the process tracer at
    construction); the warmup wave pre-compiles every attention bucket,
    and each arm reports the best of ``repeats`` timed waves (decode is
    deterministic; the variance is host noise)."""
    from areal_tpu.observability import tracing

    arms = {
        "off": tracing.TraceConfig(enabled=False),
        "sampled": tracing.TraceConfig(),  # the production default rate
        "always": tracing.TraceConfig(sample_rate=1.0),
    }
    prev = tracing.get_tracer()
    out = {}
    try:
        for arm, tcfg in arms.items():
            tracing.set_tracer(
                tracing.Tracer(tcfg, worker=f"bench-{arm}")
            )
            eng = make_engine(cfg, params, n_reqs, prompt_len, max_new)
            submit_wave(eng, cfg, n_reqs, prompt_len, max_new, f"tow{arm}")
            drain(eng)  # warm: compiles shared across arms' shapes
            best = 0.0
            for r in range(repeats):
                submit_wave(
                    eng, cfg, n_reqs, prompt_len, max_new, f"tot{arm}{r}"
                )
                eng._admit()
                int(np.asarray(eng.cache.lengths)[0])  # prefill done
                t0 = time.perf_counter()
                n = drain(eng)
                best = max(best, n / (time.perf_counter() - t0))
            out[arm] = {
                "decode_toks_per_sec": round(best, 1),
                "sample_rate": (
                    0.0 if not tcfg.enabled else tcfg.sample_rate
                ),
            }
            del eng
    finally:
        tracing.set_tracer(prev)
    off = out["off"]["decode_toks_per_sec"]
    for arm in ("sampled", "always"):
        out[arm]["overhead_frac_vs_off"] = round(
            1.0 - out[arm]["decode_toks_per_sec"] / max(off, 1e-9), 4
        )
    return out


def bench_obs_ledger_report(
    cfg, params, n_reqs=32, prompt_len=256, max_new=256, repeats=2,
):
    """HBM-ledger + recompile-sentinel report: the observability
    acceptance numbers in one diffable dict.

    * ledger-on vs ledger-off decode tok/s (same warmup-wave +
      best-of-repeats protocol as ``bench_trace_overhead_ab``) with the
      <2% overhead bar tracked as ``overhead_frac_vs_off``;
    * per-subsystem ledger bytes + peaks under the live decode wave,
      and the reconciliation verdict against the allocator's own
      in-use bytes (vacuous on backends without memory_stats — the
      CPU smoke still proves the plumbing);
    * steady-state sentinel: the armed guard sees ZERO fresh compiles
      across the timed steady-shape decode waves, then >=1 attributed
      fire after a FORCED cache-bucket change (a second engine with a
      different KV bucket against the same module-level jits);
    * leak audit: ``engine.close()`` returns no leaks and the ledger
      reads back to the zero baseline."""
    from areal_tpu.base.monitor import device_memory_stats
    from areal_tpu.engine import inference_server as eng_mod
    from areal_tpu.observability.compile_watch import CompileWatch
    from areal_tpu.observability.hbm_ledger import HbmLedger
    from areal_tpu.observability.registry import MetricsRegistry

    out = {"overhead_bar_frac": 0.02}
    for arm in ("off", "on"):
        led = HbmLedger(enabled=(arm == "on"))
        eng = make_engine(
            cfg, params, n_reqs, prompt_len, max_new, hbm_ledger=led
        )
        watch = reg = None
        if arm == "on":
            reg = MetricsRegistry()
            watch = CompileWatch(
                registry=reg, quiet_after_steps=1, monitoring=False
            )
            sig = (
                f"cache_len={eng.kv_cache_len},chunk={eng.chunk_size},"
                f"batch={eng.max_batch}"
            )
            for fn_name, fn in (
                ("decode_chunk", eng_mod._decode_chunk),
                ("admit_rows", eng_mod._admit_rows),
                ("sample_rows", eng_mod._sample_rows),
            ):
                watch.watch(fn_name, fn, signature=lambda s=sig: s)
        submit_wave(eng, cfg, n_reqs, prompt_len, max_new, f"olw{arm}")
        drain(eng)  # warm: every bucket this arm will touch is compiled
        if watch is not None:
            watch.poll()  # absorb the warmup compiles, then declare
            watch.note_step(1)  # the loop steady — the guard is armed
        best = 0.0
        for r in range(repeats):
            submit_wave(
                eng, cfg, n_reqs, prompt_len, max_new, f"olt{arm}{r}"
            )
            eng._admit()
            int(np.asarray(eng.cache.lengths)[0])  # prefill done
            t0 = time.perf_counter()
            n = drain(eng)
            best = max(best, n / (time.perf_counter() - t0))
        out[arm] = {"decode_toks_per_sec": round(best, 1)}
        if arm == "on":
            # steady decode over warmed shapes: the armed sentinel must
            # stay silent (any count here is an acceptance failure)
            steady = watch.poll()
            out[arm]["steady_compiles"] = int(sum(steady.values()))
            # ledger attribution while the engine is live, + the
            # reconcile verdict against the allocator's own number
            snap = led.snapshot()
            out[arm]["hbm_bytes"] = {
                k: int(v) for k, v in snap.items() if v
            }
            out[arm]["hbm_peak_bytes"] = {
                k: int(v) for k, v in led.watermarks().items() if v
            }
            gauges = device_memory_stats()
            in_use = [
                v for k, v in gauges.items()
                if k.endswith("/hbm_in_use_gb")
            ]
            rec = led.reconcile(
                reg, int(sum(in_use) * 1e9) if in_use else None
            )
            out[arm]["reconcile"] = {
                "ok": rec["ok"],
                "vacuous": rec["vacuous"],
                "drift_gb": rec["drift_gb"],
            }
            # forced bucket change: a second engine with a DIFFERENT
            # KV bucket drives fresh compiles of the same module-level
            # jits -> the armed sentinel must fire (>=1) and attribute
            forced = make_engine(
                cfg, params, n_reqs, prompt_len + 128, 8,
                hbm_ledger=HbmLedger(enabled=False),
            )
            submit_wave(forced, cfg, n_reqs, prompt_len + 128, 8, "olf")
            drain(forced)
            burst = watch.poll()
            out[arm]["sentinel"] = {
                "forced_compiles": int(sum(burst.values())),
                "fires_total": int(
                    watch.stats()["xla_sentinel_fires_total"]
                ),
                "stall_counter_recompile": float(
                    reg.counter("areal_trace_stall_total").value(
                        kind="recompile"
                    )
                ),
            }
            forced.close()
            # leak audit: clean shutdown returns the ledger to baseline
            out[arm]["close_leaks"] = {
                k: int(v) for k, v in eng.close().items()
            }
            out[arm]["ledger_zero_after_close"] = all(
                v == 0 for v in led.snapshot().values()
            )
        else:
            eng.close()
        del eng
    off_tps = out["off"]["decode_toks_per_sec"]
    out["on"]["overhead_frac_vs_off"] = round(
        1.0 - out["on"]["decode_toks_per_sec"] / max(off_tps, 1e-9), 4
    )
    return out


def bench_prefix_reuse(cfg, params, n_reqs=32, group_size=8, prompt_len=512):
    """Group-prompt KV dedup at admission (the radix-cache role of the
    reference's patched SGLang, realhf/impl/model/backend/sglang.py:369):
    time the admission prefill of ``n_reqs`` rows over ``n_reqs/group_size``
    unique prompts (a sampling group's n copies each) vs all-unique."""
    from areal_tpu.api.model_api import (
        APIGenerateInput,
        GenerationHyperparameters,
    )

    rng = np.random.default_rng(11)

    def submit(eng, n_unique, tag):
        prompts = [
            rng.integers(0, cfg.vocab_size, (prompt_len,)).tolist()
            for _ in range(n_unique)
        ]
        for i in range(n_reqs):
            eng.submit(
                APIGenerateInput(
                    qid=f"{tag}{i // (n_reqs // n_unique)}-{i}",
                    prompt_ids=prompts[i // (n_reqs // n_unique)],
                    input_ids=prompts[i // (n_reqs // n_unique)],
                    gconfig=GenerationHyperparameters(
                        max_new_tokens=4, temperature=1.0
                    ),
                )
            )

    def admit_time(n_unique, tag):
        # engine shapes match bench_generation's b32 run (same cache bucket
        # and chunk), so every decode/prefill jit EXCEPT the m-unique
        # admission bucket is already compiled — keeps bench wall time flat
        eng = make_engine(cfg, params, n_reqs, prompt_len, 512, chunk=128)
        submit(eng, n_unique, f"w{tag}")  # warmup: compile this m-bucket
        drain(eng)
        base_toks = eng.prefill_tokens_total
        submit(eng, n_unique, tag)
        t0 = time.perf_counter()
        eng._admit()
        int(np.asarray(eng.cache.lengths)[0])  # force prefill completion
        dt = time.perf_counter() - t0
        toks = eng.prefill_tokens_total - base_toks
        del eng
        return dt, toks

    t_unique, toks_unique = admit_time(n_reqs, "u")
    t_grouped, toks_grouped = admit_time(n_reqs // group_size, "g")
    return {
        "batch": n_reqs,
        "group_size": group_size,
        "prompt_len": prompt_len,
        "admit_s_unique_prompts": round(t_unique, 4),
        "admit_s_grouped_prompts": round(t_grouped, 4),
        # wall speedup is fetch-overhead-bound behind the tunnel; the token
        # ratio is the exact compute reduction (one prefill per group)
        "admit_wall_speedup": round(t_unique / max(t_grouped, 1e-9), 2),
        "prefill_tokens_unique": int(toks_unique),
        "prefill_tokens_grouped": int(toks_grouped),
        "prefill_work_reduction": round(
            toks_unique / max(toks_grouped, 1), 2
        ),
    }


def bench_prefix_cache_ab(
    cfg,
    params,
    n_sessions=8,
    turns=4,
    prompt_len=512,
    user_len=64,
    max_new=64,
    page=256,
    chunk=128,
):
    """Multi-turn conversation replay over the cross-request radix prefix
    cache (engine/prefix_cache.py), cache on vs off.  Every turn re-sends
    the WHOLE growing conversation under a FRESH qid — the reference's
    multi-turn agent shape (realhf/system/partial_rollout.py over SGLang's
    radix cache), where same-qid continuation parking cannot help and only
    the cross-request cache saves the prefix re-prefill.  ``n_sessions``
    conversations replay in lockstep (one submit wave per turn, drained
    before the next), so the decode batch matches between arms and the A/B
    isolates the admission/prefill savings.

    Reported per arm: end-to-end replay tok/s (generated tokens / wall),
    ``cached_token_frac`` (prompt tokens served from cache / prompt tokens
    submitted — 0 by construction with the cache off), and the suffix
    prefill token count the cache arm actually paid."""
    from areal_tpu.api.model_api import (
        APIGenerateInput,
        GenerationHyperparameters,
    )

    import zlib

    # longest prompt the replay submits + its generation
    final_prompt = prompt_len + (turns - 1) * (max_new + user_len)

    def replay(eng, tag):
        """Returns (wall_s, generated_tokens, prompt_tokens_submitted)."""
        rngs = [
            np.random.default_rng(zlib.crc32(f"{tag}s{s}".encode()))
            for s in range(n_sessions)
        ]
        convs = [
            rng.integers(0, cfg.vocab_size, (prompt_len,)).tolist()
            for rng in rngs
        ]
        gen_toks = 0
        prompt_toks = 0
        t0 = time.perf_counter()
        for j in range(turns):
            for s, conv in enumerate(convs):
                prompt_toks += len(conv)
                eng.submit(
                    APIGenerateInput(
                        qid=f"{tag}s{s}@t{j}",
                        prompt_ids=conv,
                        input_ids=conv,
                        gconfig=GenerationHyperparameters(
                            max_new_tokens=max_new, temperature=1.0
                        ),
                    )
                )
            while eng.has_work:
                eng.step()
            outs = eng.drain_results()
            for s, rng in enumerate(rngs):
                out = outs[f"{tag}s{s}@t{j}"]
                gen_toks += len(out.output_ids)
                convs[s] = (
                    convs[s]
                    + list(out.output_ids)
                    + rng.integers(0, cfg.vocab_size, (user_len,)).tolist()
                )
        return time.perf_counter() - t0, gen_toks, prompt_toks

    def arm(enabled, tag):
        eng = make_engine(
            cfg, params, n_sessions, final_prompt, max_new, chunk=chunk,
            cache_mode="paged",
            page_size=page,
            # headroom so capacity trims don't dominate the A/B: the cache
            # may keep earlier turns resident beyond the live rows' pool
            kv_pool_tokens=2 * n_sessions
            * bench_gen_cache_len(final_prompt, max_new),
            prefix_cache=enabled,
        )
        replay(eng, f"w{tag}")  # warmup: compile every turn's buckets
        s0 = eng.prefix_cache_stats()
        p0 = eng.prefill_tokens_total
        wall, gen_toks, prompt_toks = replay(eng, tag)
        st = eng.prefix_cache_stats()
        row = {
            "replay_s": round(wall, 3),
            "toks_per_sec": round(gen_toks / max(wall, 1e-9), 1),
            "generated_tokens": int(gen_toks),
            "prompt_tokens_submitted": int(prompt_toks),
            "cached_token_frac": round(
                (st["cached_tokens_total"] - s0["cached_tokens_total"])
                / max(prompt_toks, 1),
                3,
            ),
            "prefill_tokens": int(eng.prefill_tokens_total - p0),
            "cache_hits": int(st["hits_total"] - s0["hits_total"]),
            "cache_evictions": int(
                st["evictions_total"] - s0["evictions_total"]
            ),
        }
        del eng
        return row

    on = arm(True, "on")
    off = arm(False, "off")
    return {
        "sessions": n_sessions,
        "turns": turns,
        "prompt_len": prompt_len,
        "user_len": user_len,
        "max_new": max_new,
        "page_size": page,
        "cache_on": on,
        "cache_off": off,
        "replay_wall_speedup": round(
            off["replay_s"] / max(on["replay_s"], 1e-9), 2
        ),
        "prefill_work_reduction": round(
            off["prefill_tokens"] / max(on["prefill_tokens"], 1), 2
        ),
    }


def bench_prefix_cache_hier(
    cfg,
    params,
    counts=(2, 8),
    turns=2,
    prompt_len=128,
    user_len=24,
    max_new=24,
    page=32,
    chunk=32,
    capacity_frac=0.2,
    pool_rows=4,
    host_bytes=1 << 30,
):
    """Hierarchical prefix cache: cached-token-frac vs CONVERSATION COUNT
    curves, host spill tier on vs off (engine/prefix_cache.py host tier).

    The HBM radix cache is capped (``capacity_frac`` of a FIXED pool
    sized for ``pool_rows`` rows), so as the conversation count grows
    the working set of sessions evicts itself — exactly the chat-scale
    failure the host tier exists for.  Sessions replay round-robin, one
    at a time (pressure comes from the CACHE working set, not batch
    concurrency), every turn re-sending the whole conversation under a
    fresh qid.  With the tier OFF, overflowed prefixes die and returning
    sessions re-prefill; ON, they spill to host and swap back in, so
    ``cached_token_frac`` stays high as the count crosses the HBM
    capacity — the curve pair IS the win.

    Sub-arms are never silently capped: a (count, arm) cell that raises
    is recorded as ``{"error": ...}`` and named in ``dropped``; parity
    for that count is then reported as unverified, not assumed."""
    import zlib

    from areal_tpu.api.model_api import (
        APIGenerateInput,
        GenerationHyperparameters,
    )
    from areal_tpu.engine.sampling import SamplingParams

    final_prompt = prompt_len + (turns - 1) * (max_new + user_len)
    pool_tokens = pool_rows * bench_gen_cache_len(final_prompt, max_new)

    def replay(eng, n_conv, tag):
        """Round-robin conversation replay; returns (streams, row)."""
        rngs = [
            np.random.default_rng(zlib.crc32(f"{tag}s{s}".encode()))
            for s in range(n_conv)
        ]
        convs = [
            rng.integers(0, cfg.vocab_size, (prompt_len,)).tolist()
            for rng in rngs
        ]
        streams = {}
        prompt_toks = 0
        gen_toks = 0
        t0 = time.perf_counter()
        for j in range(turns):
            for s in range(n_conv):
                qid = f"{tag}s{s}t{j}"
                prompt_toks += len(convs[s])
                eng.submit(
                    APIGenerateInput(
                        qid=qid,
                        prompt_ids=convs[s],
                        input_ids=convs[s],
                        gconfig=GenerationHyperparameters(
                            max_new_tokens=max_new, greedy=True
                        ),
                    )
                )
                while eng.has_work:
                    eng.step()
                out = eng.drain_results()[qid]
                streams[(s, j)] = list(out.output_ids)
                gen_toks += len(out.output_ids)
                convs[s] = (
                    convs[s]
                    + list(out.output_ids)
                    + rngs[s].integers(
                        0, cfg.vocab_size, (user_len,)
                    ).tolist()
                )
        return streams, {
            "replay_s": round(time.perf_counter() - t0, 3),
            "generated_tokens": int(gen_toks),
            "prompt_tokens_submitted": int(prompt_toks),
        }

    def arm(n_conv, tier_bytes, tag):
        eng = make_engine(
            cfg, params, 2, final_prompt, max_new, chunk=chunk,
            cache_mode="paged",
            page_size=page,
            kv_pool_tokens=pool_tokens,
            prefix_cache=True,
            prefix_cache_capacity_frac=capacity_frac,
            prefix_cache_host_bytes=tier_bytes,
            sampling=SamplingParams(greedy=True),
        )
        # parked rows would mask cache pressure (fresh-qid turns never
        # resume them); TTL 0 releases a row the step after it parks
        eng.park_ttl_steps = 0
        streams, row = replay(eng, n_conv, tag)
        st = eng.prefix_cache_stats()
        row.update(
            cached_token_frac=round(
                st["cached_tokens_total"]
                / max(row["prompt_tokens_submitted"], 1),
                3,
            ),
            prefill_tokens=int(eng.prefill_tokens_total),
            spilled_blocks=int(st["spilled_blocks_total"]),
            restored_blocks=int(st["restored_blocks_total"]),
            host_dropped_blocks=int(st["host_dropped_blocks_total"]),
            evictions=int(st["evictions_total"]),
        )
        # leak audit: drain parked rows, flush both tiers, and require
        # the pool pristine + zero host bytes (tier-1 asserts this)
        eng.step()
        eng.step()
        eng._prefix_cache.flush()
        st = eng.prefix_cache_stats()
        row["leak_free"] = bool(
            eng.free_pool_blocks == eng.n_blocks
            and st["host_bytes_held"] == 0
            and st["host_blocks_held"] == 0
        )
        cap = eng._prefix_cache.capacity_blocks
        del eng
        return streams, row, cap

    out = {
        "counts": list(counts),
        "turns": turns,
        "prompt_len": prompt_len,
        "user_len": user_len,
        "max_new": max_new,
        "page_size": page,
        "capacity_frac": capacity_frac,
        "pool_tokens": pool_tokens,
        "host_bytes": host_bytes,
        "sweep": {},
        "dropped": [],
    }
    for n_conv in counts:
        cell = {}
        arms = {}
        for name, tier_bytes in (("host_on", host_bytes), ("host_off", 0)):
            try:
                streams, row, cap = arm(n_conv, tier_bytes, f"c{n_conv}")
                arms[name] = streams
                cell[name] = row
                out["capacity_blocks"] = cap
            except Exception as e:  # noqa: BLE001 - a cell is data
                cell[name] = {"error": f"{type(e).__name__}: {e}"[:300]}
                out["dropped"].append(f"c{n_conv}/{name}")
        if len(arms) == 2:
            cell["token_parity"] = arms["host_on"] == arms["host_off"]
            cell["cached_token_frac_gain"] = round(
                cell["host_on"]["cached_token_frac"]
                - cell["host_off"]["cached_token_frac"],
                3,
            )
        else:
            cell["token_parity"] = None  # unverified, not assumed
        out["sweep"][f"c{n_conv}"] = cell
    return out


def bench_kv_fabric_ab(
    cfg,
    params,
    counts=(2, 8),
    turns=3,
    prompt_len=128,
    user_len=24,
    max_new=24,
    page=32,
    chunk=32,
):
    """Fleet-wide KV fabric A/B: session-migration replay on a 2-server
    in-process fleet, cross-server prefix pull on vs off.

    Every session runs turn 0 on the OWNER server, then migrates to the
    TARGET for all later turns — the poster-child workload for the
    fabric (cache-aware routing just lost, e.g. on a rebalance or a
    server death).  Fabric ON, the target is handed ``kv_source`` and
    pulls the owner's cached prefix over the segment transport
    (export_prefix -> import_prefix_segment, the worker's pump driven
    in-process); OFF, it re-prefills the whole conversation.  The
    diffable wins: FLEET ``cached_token_frac`` (both servers' radix
    hits over all prompt tokens submitted anywhere) and the target's
    re-prefill token count — the acceptance bar is a strictly higher
    fleet frac and a >=2x re-prefill reduction, with greedy streams
    token-identical across arms (the fabric buys FLOPs, never tokens)
    and both pools pristine after a flush.

    Sub-arms are never silently capped: a (count, arm) cell that raises
    is recorded as ``{"error": ...}`` and named in ``dropped``; parity
    for that count is then reported as unverified, not assumed."""
    import zlib

    from areal_tpu.api.model_api import (
        APIGenerateInput,
        GenerationHyperparameters,
    )
    from areal_tpu.engine.sampling import SamplingParams

    final_prompt = prompt_len + (turns - 1) * (max_new + user_len)
    cache_len = bench_gen_cache_len(final_prompt, max_new)

    def submit(eng, qid, ids, source=None):
        eng.submit(
            APIGenerateInput(
                qid=qid,
                prompt_ids=ids,
                input_ids=ids,
                gconfig=GenerationHyperparameters(
                    max_new_tokens=max_new, greedy=True
                ),
            )
        )
        if source is not None:
            # the schedule response's kv_source hint, as partial_rollout
            # attaches it (request metadata on the queued admission)
            with eng._lock:
                eng._pending[-1].metadata = {"kv_source": source}

    def pump(target, owner, max_steps=6000):
        """Step the target while servicing its pull intents from the
        owner — the generation-server worker's pull pump in-process."""
        for _ in range(max_steps):
            if not target.has_work:
                return
            target.step()
            for preq in target.drain_prefix_pull_requests():
                segs = owner.export_prefix(preq["qid"], preq["tokens"])
                if not segs:
                    target.prefix_pull_failed(preq["qid"], "miss")
                    continue
                for seg in segs:
                    ok, _ = target.import_prefix_segment(seg)
                    if not ok:
                        break
        raise RuntimeError("kv_fabric replay did not drain")

    def pristine(eng):
        eng.step()
        eng.step()
        if eng._prefix_cache is not None:
            eng._prefix_cache.flush()
        return bool(
            eng.free_pool_blocks == eng.n_blocks
            and (np.asarray(eng._block_ref) == 0).all()
        )

    def arm(n_conv, fabric, tag):
        servers = {}
        for role in ("owner", "target"):
            eng = make_engine(
                cfg, params, 2, final_prompt, max_new, chunk=chunk,
                cache_mode="paged",
                page_size=page,
                # roomy pool: the owner keeps every session's turn-0
                # prefix radix-resident for the later pulls
                kv_pool_tokens=(n_conv + 2) * cache_len,
                prefix_cache=True,
                prefix_pull_min_tokens=page,
                sampling=SamplingParams(greedy=True),
            )
            eng.park_ttl_steps = 0  # fresh-qid turns never resume rows
            servers[role] = eng
        owner, target = servers["owner"], servers["target"]
        rngs = [
            np.random.default_rng(zlib.crc32(f"{tag}s{s}".encode()))
            for s in range(n_conv)
        ]
        convs = [
            rng.integers(0, cfg.vocab_size, (prompt_len,)).tolist()
            for rng in rngs
        ]
        streams = {}
        prompt_toks = 0
        migrated_toks = 0
        gen_toks = 0
        t0 = time.perf_counter()
        for j in range(turns):
            for s in range(n_conv):
                qid = f"{tag}s{s}t{j}"
                prompt_toks += len(convs[s])
                if j == 0:  # warm turn on the owner
                    submit(owner, qid, convs[s])
                    while owner.has_work:
                        owner.step()
                    out = owner.drain_results()[qid]
                else:  # the session migrated: later turns on the target
                    migrated_toks += len(convs[s])
                    submit(
                        target, qid, convs[s],
                        source="owner" if fabric else None,
                    )
                    pump(target, owner)
                    out = target.drain_results()[qid]
                streams[(s, j)] = list(out.output_ids)
                gen_toks += len(out.output_ids)
                convs[s] = (
                    convs[s]
                    + list(out.output_ids)
                    + rngs[s].integers(
                        0, cfg.vocab_size, (user_len,)
                    ).tolist()
                )
        fleet_cached = sum(
            e.prefix_cache_stats()["cached_tokens_total"]
            for e in servers.values()
        )
        pst = target.prefix_peer_stats()
        row = {
            "replay_s": round(time.perf_counter() - t0, 3),
            "generated_tokens": int(gen_toks),
            "prompt_tokens_submitted": int(prompt_toks),
            "migrated_prompt_tokens": int(migrated_toks),
            "fleet_cached_token_frac": round(
                fleet_cached / max(prompt_toks, 1), 3
            ),
            "target_prefill_tokens": int(target.prefill_tokens_total),
            "pulls_total": int(pst["pulls_total"]),
            "pull_bytes_total": int(pst["pull_bytes_total"]),
            "pull_rejects": dict(pst["pull_rejects"]),
            # leak audit: drain parked rows, flush the radix tiers, and
            # require both pools pristine (tier-1 asserts this)
            "leak_free": pristine(owner) and pristine(target),
        }
        del owner, target, servers
        return streams, row

    out = {
        "counts": list(counts),
        "turns": turns,
        "prompt_len": prompt_len,
        "user_len": user_len,
        "max_new": max_new,
        "page_size": page,
        "sweep": {},
        "dropped": [],
    }
    for n_conv in counts:
        cell = {}
        arms = {}
        for name, fabric in (("fabric_on", True), ("fabric_off", False)):
            try:
                streams, row = arm(n_conv, fabric, f"c{n_conv}")
                arms[name] = streams
                cell[name] = row
            except Exception as e:  # noqa: BLE001 - a cell is data
                cell[name] = {"error": f"{type(e).__name__}: {e}"[:300]}
                out["dropped"].append(f"c{n_conv}/{name}")
        if len(arms) == 2:
            cell["token_parity"] = arms["fabric_on"] == arms["fabric_off"]
            cell["cached_token_frac_gain"] = round(
                cell["fabric_on"]["fleet_cached_token_frac"]
                - cell["fabric_off"]["fleet_cached_token_frac"],
                3,
            )
            cell["reprefill_token_reduction"] = round(
                cell["fabric_off"]["target_prefill_tokens"]
                / max(cell["fabric_on"]["target_prefill_tokens"], 1),
                2,
            )
        else:
            cell["token_parity"] = None  # unverified, not assumed
        out["sweep"][f"c{n_conv}"] = cell
    return out


def bench_kv_quant_ab(
    cfg,
    params,
    n_reqs=8,
    prompt_len=256,
    max_new=64,
    page=256,
    chunk=32,
    turns=3,
    sessions=4,
    user_len=24,
    capacity_frac=0.5,
    divergence_bar=0.35,
):
    """Quantized KV cache A/B (``GenServerConfig.kv_cache_dtype``):
    fp ("auto") vs int8 per-block-quantized pools on the paged serving
    path, at EQUAL pool budgets.

    Reported, all MEASURED on the arms actually run:

    * ``blocks_per_hbm_byte_gain`` — bytes per pool block from the
      allocated arrays' true itemsize (int8 data + f32 scales vs model
      dtype), i.e. how many more paged blocks one HBM byte buys;
    * ``max_concurrent_rows`` — full-context rows a FIXED byte budget
      (the fp arm's pool) holds per arm;
    * ``decode`` — greedy decode tok/s per arm on an identical wave,
      plus the int8 arm's greedy divergence rate vs the fp arm
      (per-request longest-common-prefix, so one early flip counts the
      whole tail — the conservative definition);
    * ``prefix_equal_hbm`` — the multi-turn replay with the radix cache
      capped at the SAME HBM bytes per arm: the int8 arm's pool holds
      ~2x the blocks, so ``cached_token_frac`` rises at equal memory;
    * ``auto_token_parity`` — the "auto" arm against a DENSE engine on
      the same wave: the quantization plumbing must leave the
      unquantized path token-identical (pinned in tier-1).

    The ``quality_ok`` gate asserts the decode-wave divergence rate
    under ``divergence_bar``; the int8 engine (the arm under test)
    folds the check into its ``areal_inference_kv_quant_*`` divergence
    counters.
    Sub-arms never silently cap: a cell that raises is recorded as
    ``{"error": ...}`` and named in ``dropped``."""
    import zlib

    from areal_tpu.api.model_api import (
        APIGenerateInput,
        GenerationHyperparameters,
    )
    from areal_tpu.engine.sampling import SamplingParams

    out = {
        "batch": n_reqs,
        "prompt_len": prompt_len,
        "max_new": max_new,
        "page_size": page,
        "divergence_bar": divergence_bar,
        "dropped": [],
    }

    def decode_arm(kv_dtype):
        eng = make_engine(
            cfg, params, n_reqs, prompt_len, max_new, chunk=chunk,
            cache_mode="paged", page_size=page,
            kv_cache_dtype=kv_dtype,
            sampling=SamplingParams(greedy=True),
        )
        # IDENTICAL tags (= identical prompt streams and qids) across
        # arms: the divergence comparison is token-by-token per qid
        submit_wave(
            eng, cfg, n_reqs, prompt_len, max_new, "kvwarm", greedy=True
        )
        drain(eng)  # warmup: compile this arm's buckets
        qids = submit_wave(
            eng, cfg, n_reqs, prompt_len, max_new, "kvwave", greedy=True
        )
        t0 = time.perf_counter()
        while eng.has_work:
            eng.step()
        dt = time.perf_counter() - t0
        outs = eng.drain_results()
        streams = {q: list(outs[q].output_ids) for q in qids}
        n_tok = sum(len(s) for s in streams.values())
        row = {
            "decode_toks_per_sec": round(n_tok / max(dt, 1e-9), 1),
            "generated_tokens": int(n_tok),
            "bytes_per_block": int(eng._pool_block_bytes()),
            "pool_blocks": int(eng.n_blocks),
            "storage_bits": eng.kv_quant_stats()["storage_bits"],
        }
        return eng, streams, row

    # -- decode wave + storage-density numbers (equal pool budget) ---------
    try:
        eng_fp, fp_streams, fp_row = decode_arm("auto")
        eng_q, q_streams, q_row = decode_arm("int8")
        div_rate, n_div = lcp_divergence(fp_streams, q_streams)
        # the measured check lands on the INT8 arm's quality counters
        # (the areal_inference_kv_quant_divergence_* series) — it is
        # the arm whose storage is under test; the fp arm is the
        # reference and its counters stay zero
        eng_q.note_kv_divergence_check(len(fp_streams), n_div)
        gain = fp_row["bytes_per_block"] / max(q_row["bytes_per_block"], 1)
        budget = fp_row["bytes_per_block"] * fp_row["pool_blocks"]
        bpr = eng_fp.blocks_per_row
        out["bytes_per_block"] = {
            "auto": fp_row["bytes_per_block"],
            "int8": q_row["bytes_per_block"],
        }
        out["blocks_per_hbm_byte_gain"] = round(gain, 3)
        out["max_concurrent_rows"] = {
            "budget_bytes": int(budget),
            "auto": int(fp_row["pool_blocks"] // bpr),
            "int8": int(
                (budget // q_row["bytes_per_block"]) // bpr
            ),
        }
        out["decode"] = {
            "auto": fp_row,
            "int8": q_row,
            "divergence_rate": div_rate,
            "diverged_requests": int(n_div),
            "quality_ok": bool(div_rate <= divergence_bar),
        }
        del eng_q
    except Exception as e:  # noqa: BLE001 - a cell is data
        out["decode"] = {"error": f"{type(e).__name__}: {e}"[:300]}
        out["dropped"].append("decode")
        eng_fp = None
        fp_streams = {}

    # -- "auto" arm parity pin: the unquantized path must be untouched -----
    try:
        if eng_fp is None:
            raise RuntimeError("decode arm dropped")
        dense = make_engine(
            cfg, params, n_reqs, prompt_len, max_new, chunk=chunk,
            cache_mode="dense",
            sampling=SamplingParams(greedy=True),
        )
        qids = submit_wave(
            dense, cfg, n_reqs, prompt_len, max_new, "kvwave", greedy=True
        )
        drain_outs = {}
        while dense.has_work:
            dense.step()
        for q, o in dense.drain_results().items():
            drain_outs[q] = list(o.output_ids)
        out["auto_token_parity"] = bool(
            all(drain_outs[q] == fp_streams[q] for q in qids)
        )
        del dense
    except Exception as e:  # noqa: BLE001
        out["auto_token_parity"] = None
        out["dropped"].append(f"auto_parity: {type(e).__name__}: {e}"[:120])
    finally:
        del eng_fp

    # -- prefix cache at equal HBM: int8 pools hold ~2x the blocks ---------
    final_prompt = prompt_len + (turns - 1) * (max_new + user_len)
    fp_pool_tokens = sessions * bench_gen_cache_len(final_prompt, max_new)

    def replay_arm(kv_dtype, pool_tokens, tag):
        eng = make_engine(
            cfg, params, 2, final_prompt, max_new, chunk=chunk,
            cache_mode="paged", page_size=page,
            kv_pool_tokens=pool_tokens,
            kv_cache_dtype=kv_dtype,
            prefix_cache_capacity_frac=capacity_frac,
            sampling=SamplingParams(greedy=True),
        )
        eng.park_ttl_steps = 0  # fresh-qid turns never resume parks
        rngs = [
            np.random.default_rng(zlib.crc32(f"{tag}s{s}".encode()))
            for s in range(sessions)
        ]
        convs = [
            rng.integers(0, cfg.vocab_size, (prompt_len,)).tolist()
            for rng in rngs
        ]
        streams = {}
        prompt_toks = 0
        for j in range(turns):
            for s in range(sessions):
                qid = f"{tag}s{s}t{j}"
                prompt_toks += len(convs[s])
                eng.submit(
                    APIGenerateInput(
                        qid=qid,
                        prompt_ids=convs[s],
                        input_ids=convs[s],
                        gconfig=GenerationHyperparameters(
                            max_new_tokens=max_new, greedy=True
                        ),
                    )
                )
                while eng.has_work:
                    eng.step()
                o = eng.drain_results()[qid]
                streams[qid] = list(o.output_ids)
                convs[s] = (
                    convs[s]
                    + list(o.output_ids)
                    + rngs[s].integers(
                        0, cfg.vocab_size, (user_len,)
                    ).tolist()
                )
        st = eng.prefix_cache_stats()
        row = {
            "pool_tokens": int(pool_tokens),
            "pool_blocks": int(eng.n_blocks),
            "pool_bytes": int(
                eng._pool_block_bytes() * eng.n_blocks
            ),
            "capacity_blocks": int(st["capacity_blocks"]),
            "cached_token_frac": round(
                st["cached_tokens_total"] / max(prompt_toks, 1), 3
            ),
            "prefill_tokens": int(eng.prefill_tokens_total),
        }
        del eng
        return streams, row

    try:
        fp_rep_streams, fp_rep = replay_arm("auto", fp_pool_tokens, "r")
        # equal HBM: scale the int8 arm's pool tokens by the measured
        # per-block byte ratio so both arms' pools cost the same bytes
        bb = out.get("bytes_per_block")
        ratio = (
            bb["auto"] / bb["int8"]
            if isinstance(bb, dict)
            else 2.0
        )
        q_pool_tokens = int(fp_pool_tokens * ratio)
        q_rep_streams, q_rep = replay_arm("int8", q_pool_tokens, "r")
        rep_div, rep_n_div = lcp_divergence(fp_rep_streams, q_rep_streams)
        out["prefix_equal_hbm"] = {
            "auto": fp_rep,
            "int8": q_rep,
            "divergence_rate": rep_div,
            "diverged_requests": int(rep_n_div),
            "cached_token_frac_gain": round(
                q_rep["cached_token_frac"] - fp_rep["cached_token_frac"],
                3,
            ),
        }
    except Exception as e:  # noqa: BLE001
        out["prefix_equal_hbm"] = {"error": f"{type(e).__name__}: {e}"[:300]}
        out["dropped"].append("prefix_equal_hbm")
    return out


def bench_weight_quant_ab(
    cfg,
    params,
    n_reqs=8,
    prompt_len=256,
    max_new=64,
    page=256,
    chunk=32,
    turns=3,
    sessions=4,
    user_len=24,
    divergence_bar=0.35,
    stage_bytes_bar=1.8,
):
    """Quantized serving weights A/B (``GenServerConfig.
    serving_weight_dtype``): the model-dtype param tree ("auto") vs the
    int8 + per-output-channel-scale serving format on the same engine
    paths.

    Reported, all MEASURED on the arms actually run:

    * ``param_hbm`` — the resident serving tree's byte footprint per
      arm (the HBM a quantized fleet frees for paged blocks / prefix
      cache) and the reduction ratio;
    * ``staged_swap`` — a staged weight swap per arm against a
      published snapshot pair (full tree + the ``v*-int8`` sibling the
      manifest advertises): bytes actually restored, stage seconds
      (decode running), commit pause ms — the ``bytes_ratio`` >=
      ``stage_bytes_bar`` gate is the "half-byte staged swaps" claim;
    * ``decode`` — greedy decode tok/s per arm on an identical paged
      wave, plus the int8 arm's divergence rate vs the full-precision
      arm (per-request longest common prefix — one early flip charges
      the whole tail);
    * ``replay`` — the multi-turn replay (paged + radix prefix cache)
      divergence rate: THE ``quality_ok`` gate's workload, folded into
      the int8 engine's ``areal_inference_weight_quant_*`` counters;
    * ``max_concurrent_rows`` — full-context rows a FIXED HBM budget
      (full weights + the fp pool) holds when weight-int8 frees weight
      bytes into pool blocks, with and without kv int8 COMPOSED (the
      PR-12 format) — the capacity story the two quantizations buy
      together;
    * ``auto_token_parity`` — the "auto" arm against a dense engine on
      the same wave: the weight-quant plumbing must leave the
      unquantized path token-identical (pinned in tier-1).

    Sub-arms never silently cap: a cell that raises is recorded as
    ``{"error": ...}`` and named in ``dropped``."""
    import shutil
    import tempfile
    import threading
    import zlib

    import jax

    from areal_tpu.api.model_api import (
        APIGenerateInput,
        GenerationHyperparameters,
    )
    from areal_tpu.engine import checkpoint
    from areal_tpu.engine.sampling import SamplingParams
    from areal_tpu.models import quantize

    out = {
        "batch": n_reqs,
        "prompt_len": prompt_len,
        "max_new": max_new,
        "page_size": page,
        "divergence_bar": divergence_bar,
        "stage_bytes_bar": stage_bytes_bar,
        "dropped": [],
    }

    def decode_arm(swd, kv_dtype="auto"):
        eng = make_engine(
            cfg, params, n_reqs, prompt_len, max_new, chunk=chunk,
            cache_mode="paged", page_size=page,
            serving_weight_dtype=swd, kv_cache_dtype=kv_dtype,
            sampling=SamplingParams(greedy=True),
        )
        # IDENTICAL tags (= identical prompt streams and qids) across
        # arms: the divergence comparison is token-by-token per qid
        submit_wave(
            eng, cfg, n_reqs, prompt_len, max_new, "wqwarm", greedy=True
        )
        drain(eng)  # warmup: compile this arm's buckets
        qids = submit_wave(
            eng, cfg, n_reqs, prompt_len, max_new, "wqwave", greedy=True
        )
        t0 = time.perf_counter()
        while eng.has_work:
            eng.step()
        dt = time.perf_counter() - t0
        outs = eng.drain_results()
        streams = {q: list(outs[q].output_ids) for q in qids}
        n_tok = sum(len(s) for s in streams.values())
        st = eng.weight_quant_stats()
        row = {
            "decode_toks_per_sec": round(n_tok / max(dt, 1e-9), 1),
            "generated_tokens": int(n_tok),
            "param_bytes": int(st["param_bytes"]),
            "storage_bits": int(st["storage_bits"]),
            "quantized_leaves": int(st["quantized_leaves"]),
            "pool_block_bytes": int(eng._pool_block_bytes()),
        }
        return eng, streams, row

    # -- decode wave + param-HBM numbers -----------------------------------
    try:
        eng_fp, fp_streams, fp_row = decode_arm("auto")
        eng_q, q_streams, q_row = decode_arm("int8")
        div_rate, n_div = lcp_divergence(fp_streams, q_streams)
        out["param_hbm"] = {
            "auto_bytes": fp_row["param_bytes"],
            "int8_bytes": q_row["param_bytes"],
            "reduction": round(
                fp_row["param_bytes"] / max(q_row["param_bytes"], 1), 3
            ),
        }
        out["decode"] = {
            "auto": fp_row,
            "int8": q_row,
            "divergence_rate": div_rate,
            "diverged_requests": int(n_div),
        }
        # -- max concurrent rows at a FIXED HBM budget (weights + pool),
        # composing kv int8 (PR 12): freed weight bytes buy pool blocks
        budget = fp_row["param_bytes"] + (
            fp_row["pool_block_bytes"] * eng_fp.n_blocks
        )
        bpr = eng_fp.blocks_per_row
        cells = {}
        kv_bb = {"auto": fp_row["pool_block_bytes"]}
        try:
            eng_kv, _, kv_row = decode_arm("auto", kv_dtype="int8")
            kv_bb["int8"] = kv_row["pool_block_bytes"]
            del eng_kv
        except Exception as e:  # noqa: BLE001
            out["dropped"].append(
                f"kv_int8_block_bytes: {type(e).__name__}: {e}"[:120]
            )
        for warm, wbytes in (
            ("auto", fp_row["param_bytes"]),
            ("int8", q_row["param_bytes"]),
        ):
            for kvarm, bb in kv_bb.items():
                cells[f"w_{warm}+kv_{kvarm}"] = int(
                    max(budget - wbytes, 0) // bb // bpr
                )
        out["max_concurrent_rows"] = {
            "budget_bytes": int(budget), **cells
        }
        del eng_q
    except Exception as e:  # noqa: BLE001 - a cell is data
        out["decode"] = {"error": f"{type(e).__name__}: {e}"[:300]}
        out["dropped"].append("decode")
        eng_fp = None
        fp_streams = {}

    # -- "auto" arm parity pin: the unquantized path must be untouched -----
    try:
        if eng_fp is None:
            raise RuntimeError("decode arm dropped")
        dense = make_engine(
            cfg, params, n_reqs, prompt_len, max_new, chunk=chunk,
            cache_mode="dense",
            sampling=SamplingParams(greedy=True),
        )
        qids = submit_wave(
            dense, cfg, n_reqs, prompt_len, max_new, "wqwave", greedy=True
        )
        while dense.has_work:
            dense.step()
        dense_streams = {
            q: list(o.output_ids) for q, o in dense.drain_results().items()
        }
        out["auto_token_parity"] = bool(
            all(dense_streams[q] == fp_streams[q] for q in qids)
        )
        del dense
    except Exception as e:  # noqa: BLE001
        out["auto_token_parity"] = None
        out["dropped"].append(f"auto_parity: {type(e).__name__}: {e}"[:120])
    finally:
        del eng_fp

    # -- staged swap A/B: bytes restored + stage/commit time per format ----
    pub = tempfile.mkdtemp(prefix="areal-wquant-")
    try:
        snap = os.path.join(pub, "v1")
        checkpoint.save_params(params, snap)
        qpath = checkpoint.quant_snapshot_path(snap)
        qavals = checkpoint.save_quantized_params(params, qpath)
        checkpoint.write_manifest(
            jax.tree.map(
                lambda x: jax.ShapeDtypeStruct(np.shape(x), x.dtype), params
            ),
            snap,
            version=1,
            serving_quant={
                "int8": checkpoint.quant_manifest_entry(qavals, qpath)
            },
        )

        def staged_arm(swd):
            eng = make_engine(
                cfg, params, n_reqs, prompt_len, max_new, chunk=chunk,
                cache_mode="paged", page_size=page,
                serving_weight_dtype=swd,
                sampling=SamplingParams(greedy=True),
            )
            submit_wave(
                eng, cfg, n_reqs, prompt_len, max_new, f"wqsw{swd}",
                greedy=True,
            )
            tok = 0
            while eng.has_work and tok < n_reqs * chunk:
                tok += eng.step()
            # the negotiation the generation server runs: int8 engines
            # restore the advertised sibling tree, auto the full one
            restore_path = qpath if swd == "int8" else snap
            template = eng.weight_restore_template(
                "int8" if swd == "int8" else "full"
            )
            box = {}

            def _stage():
                try:
                    p = checkpoint.load_params_staged(
                        template, restore_path, chunk_bytes=1 << 20
                    )
                    box["bytes"] = quantize.tree_bytes(p)
                    eng.stage_weights(eng.prepare_weights(p), 1)
                except Exception as e:  # noqa: BLE001 - reported
                    box["error"] = repr(e)

            th = threading.Thread(target=_stage, daemon=True)
            t_st = time.perf_counter()
            th.start()
            while th.is_alive():
                eng.step()  # decode CONTINUES during staging
            th.join()
            if "error" in box:
                raise RuntimeError(box["error"])
            stage_s = time.perf_counter() - t_st
            t0 = time.perf_counter()
            eng.pause()
            eng.step()
            eng.commit_staged(expected_version=1)
            eng.resume()
            while eng.version != 1:
                eng.step()
            pause_s = time.perf_counter() - t0
            drain(eng)
            del eng
            return {
                "staged_bytes": int(box["bytes"]),
                "stage_ms": round(stage_s * 1e3, 1),
                "commit_pause_ms": round(pause_s * 1e3, 1),
            }

        fp_sw = staged_arm("auto")
        q_sw = staged_arm("int8")
        ratio = fp_sw["staged_bytes"] / max(q_sw["staged_bytes"], 1)
        out["staged_swap"] = {
            "auto": fp_sw,
            "int8": q_sw,
            "bytes_ratio": round(ratio, 3),
            "bytes_ok": bool(ratio >= stage_bytes_bar),
        }
    except Exception as e:  # noqa: BLE001
        out["staged_swap"] = {"error": f"{type(e).__name__}: {e}"[:300]}
        out["dropped"].append("staged_swap")
    finally:
        shutil.rmtree(pub, ignore_errors=True)

    # -- multi-turn replay (paged + prefix cache): THE quality gate --------
    def replay_arm(swd, tag):
        eng = make_engine(
            cfg, params, 2,
            prompt_len + (turns - 1) * (max_new + user_len), max_new,
            chunk=chunk, cache_mode="paged", page_size=page,
            serving_weight_dtype=swd,
            sampling=SamplingParams(greedy=True),
        )
        eng.park_ttl_steps = 0  # fresh-qid turns never resume parks
        rngs = [
            np.random.default_rng(zlib.crc32(f"{tag}s{s}".encode()))
            for s in range(sessions)
        ]
        convs = [
            rng.integers(0, cfg.vocab_size, (prompt_len,)).tolist()
            for rng in rngs
        ]
        streams = {}
        for j in range(turns):
            for s in range(sessions):
                qid = f"{tag}s{s}t{j}"
                eng.submit(
                    APIGenerateInput(
                        qid=qid,
                        prompt_ids=convs[s],
                        input_ids=convs[s],
                        gconfig=GenerationHyperparameters(
                            max_new_tokens=max_new, greedy=True
                        ),
                    )
                )
                while eng.has_work:
                    eng.step()
                o = eng.drain_results()[qid]
                streams[qid] = list(o.output_ids)
                convs[s] = (
                    convs[s]
                    + list(o.output_ids)
                    + rngs[s].integers(
                        0, cfg.vocab_size, (user_len,)
                    ).tolist()
                )
        return eng, streams

    try:
        eng_rf, fp_rep = replay_arm("auto", "wqr")
        del eng_rf
        eng_rq, q_rep = replay_arm("int8", "wqr")
        rep_div, rep_n_div = lcp_divergence(fp_rep, q_rep)
        # the measured check lands on the INT8 arm's quality counters
        # (the areal_inference_weight_quant_divergence_* series) — it is
        # the arm whose storage is under test
        eng_rq.note_weight_divergence_check(len(fp_rep), rep_n_div)
        out["replay"] = {
            "requests": len(fp_rep),
            "divergence_rate": rep_div,
            "diverged_requests": int(rep_n_div),
            "quality_ok": bool(rep_div <= divergence_bar),
        }
        del eng_rq
    except Exception as e:  # noqa: BLE001
        out["replay"] = {"error": f"{type(e).__name__}: {e}"[:300]}
        out["dropped"].append("replay")
    return out


def bench_slo_report(
    cfg,
    params,
    n_sessions=6,
    turns=3,
    prompt_len=192,
    user_len=32,
    max_new=48,
    page=256,
    chunk=32,
    overhead_reqs=32,
    overhead_prompt=256,
    overhead_new=256,
    overhead_repeats=2,
):
    """Request-level SLO report (observability/latency.py):

    * **multi_turn** — the multi-turn replay workload split across TWO
      engines posing as separate servers; each engine's TTFT/TPOT
      digests are FLEET-MERGED (exact: fixed log buckets) and reported
      as p50/p95/p99 alongside per-server p99 — the same merge the
      master's aggregator performs over scraped pages.
    * **spec_decode** — the repetitive-trace workload with speculative
      decoding ON (greedy + paged), so the report covers the serving
      mode whose TTFT/TPOT shape differs most from plain decode.
    * **overhead_ab** — sustained decode tok/s with SLO tracking on vs
      off; the tracked acceptance bar is on < 2% overhead vs off (same
      bar as the flight recorder's).

    ``merge_within_bound`` cross-checks the merged p50/p95/p99 against
    the pooled raw records' inverted-CDF quantiles — the documented
    digest error bound, asserted in tier-1 by
    tests/engine/test_bench_sweep.py."""
    import zlib

    from areal_tpu.api.model_api import (
        APIGenerateInput,
        GenerationHyperparameters,
    )
    from areal_tpu.engine.sampling import SamplingParams
    from areal_tpu.engine.spec_decode import SpecDecodeParams
    from areal_tpu.observability.latency import (
        SLO_REL_ERROR_BOUND,
        LatencyDigest,
    )

    def _pct(digest):
        p = digest.percentiles()
        return {
            k: (round(v, 6) if isinstance(v, float) else v)
            for k, v in p.items()
        }

    def _fleet(engines, records):
        """Fleet-merge the engines' digests + cross-check vs raw
        records (the pooled inverted-CDF quantiles must sit within the
        documented bound of the merged digest's)."""
        fleet = {"ttft_s": LatencyDigest(), "tpot_s": LatencyDigest()}
        servers = {}
        for eng in engines:
            digs = {
                k: LatencyDigest.from_dict(v)
                for k, v in eng.slo_digests().items()
            }
            for k in fleet:
                fleet[k].merge(digs[k])
            servers[eng.server_name] = {
                "ttft_p99": digs["ttft_s"].quantile(0.99),
                "tpot_p99": digs["tpot_s"].quantile(0.99),
                "records": eng.slo_records_total,
            }
        checks = []
        for field, dig in fleet.items():
            raw = sorted(
                r.ttft_s if field == "ttft_s" else r.tpot_s
                for r in records
                if (field == "ttft_s" or r.tpot_s is not None)
            )
            for q in (0.50, 0.95, 0.99):
                if not raw:
                    continue
                # inverted-CDF: the ceil(q*n)-th smallest raw value
                emp = raw[
                    min(len(raw) - 1, max(0, int(np.ceil(q * len(raw))) - 1))
                ]
                got = dig.quantile(q)
                if emp > 0 and got is not None:
                    checks.append(abs(got - emp) / emp)
        return {
            "fleet": {k: _pct(d) for k, d in fleet.items()},
            "servers": servers,
            "merge_max_rel_err": round(max(checks), 4) if checks else None,
            "merge_within_bound": bool(
                not checks or max(checks) <= SLO_REL_ERROR_BOUND + 1e-12
            ),
        }

    def multi_turn():
        engines = [
            make_engine(
                cfg, params, n_sessions,
                prompt_len + (turns - 1) * (max_new + user_len), max_new,
                chunk=chunk, cache_mode="paged", page_size=page,
                server_name=f"srv{j}",
            )
            for j in range(2)
        ]
        records = []
        rngs = [
            np.random.default_rng(zlib.crc32(f"slo-s{s}".encode()))
            for s in range(n_sessions)
        ]
        convs = [
            rng.integers(0, cfg.vocab_size, (prompt_len,)).tolist()
            for rng in rngs
        ]
        for j in range(turns):
            for s, conv in enumerate(convs):
                eng = engines[s % 2]  # session -> "server" routing
                eng.submit(
                    APIGenerateInput(
                        qid=f"slo-s{s}@t{j}",
                        prompt_ids=conv,
                        input_ids=conv,
                        gconfig=GenerationHyperparameters(
                            max_new_tokens=max_new, temperature=1.0
                        ),
                        metadata={"slo_schedule_wait_s": 0.0},
                    )
                )
            for eng in engines:
                drain(eng)
            for s, rng in enumerate(rngs):
                convs[s] = convs[s] + rng.integers(
                    0, cfg.vocab_size, (max_new + user_len,)
                ).tolist()
        for eng in engines:
            records.extend(eng.drain_slo_records())
        out = _fleet(engines, records)
        out["records"] = len(records)
        engines.clear()  # free both engines' KV/params before the next arm
        return out

    def spec_workload():
        eng = make_engine(
            cfg, params, n_sessions, prompt_len, max_new, chunk=chunk,
            cache_mode="paged", page_size=page,
            sampling=SamplingParams(greedy=True),
            spec_decode_params=SpecDecodeParams(
                enabled=True, max_draft_tokens=7
            ),
            server_name="srv-spec",
        )
        for i in range(n_sessions):
            rng = np.random.default_rng(zlib.crc32(f"slor{i}".encode()))
            motif = rng.integers(0, 2, (12,)).tolist()
            ids = (motif * (prompt_len // 12 + 1))[:prompt_len]
            eng.submit(
                APIGenerateInput(
                    qid=f"slosp{i}",
                    prompt_ids=ids,
                    input_ids=ids,
                    gconfig=GenerationHyperparameters(
                        max_new_tokens=max_new, greedy=True
                    ),
                )
            )
        drain(eng)
        records = eng.drain_slo_records()
        out = _fleet([eng], records)
        out["records"] = len(records)
        del eng
        return out

    def overhead_ab():
        rows = {}
        for arm, on in (("off", False), ("on", True)):
            eng = make_engine(
                cfg, params, overhead_reqs, overhead_prompt,
                overhead_new, slo_tracking=on,
            )
            submit_wave(
                eng, cfg, overhead_reqs, overhead_prompt, overhead_new,
                f"slow{arm}",
            )
            drain(eng)  # warmup: compiles shared across arms
            best = 0.0
            for r in range(overhead_repeats):
                submit_wave(
                    eng, cfg, overhead_reqs, overhead_prompt,
                    overhead_new, f"slot{arm}{r}",
                )
                eng._admit()
                int(np.asarray(eng.cache.lengths)[0])  # prefill done
                t0 = time.perf_counter()
                n = drain(eng)
                best = max(best, n / (time.perf_counter() - t0))
            rows[arm] = round(best, 1)
            del eng
        return {
            "slo_off_toks_per_sec": rows["off"],
            "slo_on_toks_per_sec": rows["on"],
            "overhead_frac_vs_off": round(
                1.0 - rows["on"] / max(rows["off"], 1e-9), 4
            ),
        }

    return {
        "error_bound": round(SLO_REL_ERROR_BOUND, 4),
        "multi_turn": multi_turn(),
        "spec_decode": spec_workload(),
        "overhead_ab": overhead_ab(),
    }


def bench_pd_disagg_ab(
    cfg,
    params,
    n_interactive=8,
    interactive_prompt=48,
    interactive_new=12,
    turns=2,
    n_wave=5,
    wave_prompt=640,
    wave_new=4,
    page=64,
    chunk=8,
    prefill_chunk=128,
    arms=("unified", "disagg", "disagg_streamed"),
    prefill_mesh=None,
):
    """Disaggregated prefill/decode A/B under MIXED load (ROADMAP item 2)
    + the streamed-vs-monolithic handoff A/B (ISSUE 15).

    Workload: ``n_interactive`` chat sessions decoding short turns (the
    latency-sensitive stream) while a concurrent wave of ``n_wave``
    long-prompt requests prefills (the throughput batch that, on a
    unified fleet, steals a fill chunk out of every decode step).  All
    arms get the SAME two engines' worth of hardware:

    * **unified** — two unified engines, sessions and wave spread across
      both; every engine interleaves wave fill chunks with interactive
      decode, so interactive TTFT absorbs the wave.
    * **disagg** — one prefill engine + one decode engine with the
      PR-13 MONOLITHIC handoff: the whole unit (gather + wire + scatter
      of every block) moves serially AFTER prefill completes.
    * **disagg_streamed** — same split, but each fill chunk's finalized
      blocks stream into D as numbered segments WHILE the rest of the
      prompt still fills (import_handoff_segment's engine half), so at
      prefill-done only the final tail+metadata segment remains.

    Reported per (arm, workload): fleet-merged TTFT/TPOT p50/p99 from
    per-request LatencyRecords folded into the SLO plane's
    ``LatencyDigest``, handoff count/bytes/latency, greedy stream parity
    across ALL arms as DATA, and the headline ``stream_ab`` row: the
    RESUME GAP (prefill-done -> decode-resume, measured on the
    long-prompt wave) monolithic vs streamed, with the >=2x-reduction
    and p99-TTFT-no-worse verdicts the acceptance bar names.  Asserted
    as a CPU smoke in tests/system/test_pd_disagg.py.

    ``prefill_mesh`` runs the PREFILL engine on a device mesh (the
    heterogeneous big-mesh-prefill / small-mesh-decode deployment) —
    the hetero sub-arm's driver (see :func:`bench_pd_disagg_hetero`).
    """
    import zlib

    from areal_tpu.api.model_api import (
        APIGenerateInput,
        GenerationHyperparameters,
    )
    from areal_tpu.engine.sampling import SamplingParams
    from areal_tpu.observability.latency import LatencyDigest

    total_interactive = interactive_new * (1 + turns)

    def mk(name, streaming=False, mesh=None):
        eng = make_engine(
            cfg, params, n_interactive + n_wave, wave_prompt,
            total_interactive, chunk=chunk, cache_mode="paged",
            page_size=page, prefill_chunk_tokens=prefill_chunk,
            sampling=SamplingParams(greedy=True), server_name=name,
            handoff_streaming=streaming, mesh=mesh,
        )
        # sessions park through the whole wave phase; the default TTL
        # (512 steps) could evict a quiet session mid-measurement
        eng.park_ttl_steps = 1 << 20
        return eng

    def req(qid, ids, mn, workload, handoff=False):
        md = {"workload": workload, "slo_schedule_wait_s": 0.0}
        if handoff:
            # the manager's two-stage routing sets this in production;
            # the bench drives the engine halves directly
            md["handoff_to"] = "peer"
        return APIGenerateInput(
            qid=qid, prompt_ids=ids, input_ids=ids,
            gconfig=GenerationHyperparameters(
                max_new_tokens=mn, greedy=True
            ),
            metadata=md,
        )

    iconvs = [
        np.random.default_rng(zlib.crc32(f"pdi{s}".encode()))
        .integers(0, cfg.vocab_size, (interactive_prompt,)).tolist()
        for s in range(n_interactive)
    ]
    wconvs = [
        np.random.default_rng(zlib.crc32(f"pdw{i}".encode()))
        .integers(0, cfg.vocab_size, (wave_prompt,)).tolist()
        for i in range(n_wave)
    ]

    def run_arm(mode):
        """Chunked-generation driver over two interleaved engines —
        each request behaves like a partial_rollout client: submit a
        chunk, collect, submit the continuation.  Disagg arms put the
        first chunk on P with the handoff flag; the driver moves the KV
        P->D exactly like the generation-server worker (monolithic:
        whole unit when the prefill result lands; streamed: segments
        pumped into D as P's fill chunks emit them, final segment at
        the result)."""
        disagg = mode != "unified"
        streamed = mode == "disagg_streamed"
        if disagg:
            P = mk("pd-P", streaming=streamed, mesh=prefill_mesh)
            D = mk("pd-D")
            engines = [P, D]
        else:
            engines = [mk("uni-0"), mk("uni-1")]
        handoff_ms = []
        resume_gap_ms = []
        handoff_fail = [0]
        seg_fail = [0]

        recs = {}

        def pump_segments():
            if not streamed:
                return
            for seg in P.drain_handoff_segments():
                ok, _ = D.import_handoff_segment(seg)
                if not ok and not seg.get("abort"):
                    seg_fail[0] += 1

        def start(qid, ids, total, per, workload, uni_idx):
            recs[qid] = dict(
                ids=list(ids), left=total, per=per, workload=workload,
                uni=engines[uni_idx % len(engines)], first=True,
                stream=[], waiting=False, cur=None, done=False,
            )

        def submit_next(r, qid):
            mn = min(r["per"], r["left"])
            if disagg:
                eng = P if r["first"] else D
                eng.submit(
                    req(qid, r["ids"], mn, r["workload"],
                        handoff=r["first"])
                )
            else:
                eng = r["uni"]
                eng.submit(req(qid, r["ids"], mn, r["workload"]))
            r["cur"], r["waiting"] = eng, True

        def fold_chunk(r, out):
            r["stream"].extend(out.output_ids)
            r["ids"].extend(out.output_ids)
            r["left"] -= len(out.output_ids)
            r["done"] = (
                r["left"] <= 0
                or not out.output_ids
                or not out.no_eos
            )

        def finish_handoff(qid, r, out):
            """Prefill-stage result landed: move the REMAINING KV and
            time prefill-done -> decode-resume (the resume gap).  The
            monolithic arm pays gather + import of EVERY block here;
            the streamed arm only drains the final segment (everything
            else already scattered under D's decode chunks)."""
            t0 = time.perf_counter()
            if streamed:
                pump_segments()  # the final (tail + metadata) segment
            else:
                unit = P.export_handoff(qid)
                ok = False
                if unit is not None:
                    ok, _ = D.import_handoff(unit)
                if not ok:
                    handoff_fail[0] += 1
            r["first"] = False
            fold_chunk(r, out)
            if r["done"]:
                handoff_ms.append((time.perf_counter() - t0) * 1e3)
                return
            submit_next(r, qid)
            # step D until the continuation is RESUMED (decoding, not
            # filling): the wall clock from prefill-done to here is the
            # bubble streaming exists to shrink
            for _ in range(50_000):
                if any(
                    row is not None and row.req.qid == qid
                    and not row.parked and not row.filling
                    for row in D.rows
                ):
                    break
                D.step()
            dt = (time.perf_counter() - t0) * 1e3
            handoff_ms.append(dt)
            if qid.startswith("pdw"):
                resume_gap_ms.append(dt)

        def pump(max_steps=200_000):
            for _ in range(max_steps):
                live = False
                for eng in engines:
                    if eng.has_work:
                        eng.step()
                        live = True
                # streamed: export segments ride into D while P's later
                # fill chunks are still running — THE overlap
                pump_segments()
                for qid, r in recs.items():
                    if not r["waiting"]:
                        continue
                    out = r["cur"].try_get_result(qid)
                    if out is None:
                        continue
                    r["waiting"] = False
                    if disagg and r["first"] and out.output_ids:
                        finish_handoff(qid, r, out)
                        live = True
                        continue
                    r["first"] = False
                    fold_chunk(r, out)
                    if not r["done"]:
                        submit_next(r, qid)
                        live = True
                if not live and all(
                    r["done"] or not r["waiting"] for r in recs.values()
                ):
                    if all(r["done"] for r in recs.values()):
                        return
                    # nothing in flight but requests remain: submit them
                    for qid, r in recs.items():
                        if not r["done"] and not r["waiting"]:
                            submit_next(r, qid)
            raise RuntimeError("pd_disagg driver did not converge")

        # -- setup: establish every session's first turn, pre-wave
        for s, conv in enumerate(iconvs):
            start(f"pds{s}", conv, total_interactive, interactive_new,
                  "interactive", s)
        # sessions stop after turn 0 (budget throttled by `left` vs the
        # measured turns below): cap left to one turn for the setup pump
        for r in recs.values():
            r["_left_total"] = r["left"]
            r["left"] = interactive_new
        for qid, r in recs.items():
            submit_next(r, qid)
        pump()
        for eng in engines:
            eng.drain_slo_records()  # setup latencies: not measured
        # -- measured window: the wave prefills while sessions keep
        # decoding turns
        for r in recs.values():
            r["left"] = r["_left_total"] - (
                len(r["stream"])
            )
            r["done"] = r["left"] <= 0
        for i, conv in enumerate(wconvs):
            start(f"pdw{i}", conv, wave_new, wave_new, "wave",
                  i)
        for qid, r in recs.items():
            if not r["done"] and not r["waiting"]:
                submit_next(r, qid)
        pump()
        records = []
        for eng in engines:
            records.extend(eng.drain_slo_records())
        digs: Dict[str, Dict[str, LatencyDigest]] = {}
        for rec in records:
            d = digs.setdefault(
                rec.workload,
                {"ttft_s": LatencyDigest(), "tpot_s": LatencyDigest()},
            )
            d["ttft_s"].observe(rec.ttft_s)
            if rec.tpot_s is not None:
                d["tpot_s"].observe(rec.tpot_s)
        out = {}
        for wl, d in sorted(digs.items()):
            out[wl] = {
                "records": d["ttft_s"].count,
                "ttft_p50_ms": _q_ms(d["ttft_s"], 0.50),
                "ttft_p99_ms": _q_ms(d["ttft_s"], 0.99),
                "tpot_p50_ms": _q_ms(d["tpot_s"], 0.50),
                "tpot_p99_ms": _q_ms(d["tpot_s"], 0.99),
            }
        if disagg:
            hs = [P.handoff_stats(), D.handoff_stats()]
            out["handoff"] = {
                "count": hs[1]["imports_total"],
                "exports": hs[0]["exports_total"],
                "segments": hs[0]["segment_exports_total"],
                "segment_imports": hs[1]["segment_imports_total"],
                "failed": handoff_fail[0] + seg_fail[0],
                "bytes_total": hs[0]["bytes_total"],
                "mean_ms": round(float(np.mean(handoff_ms)), 2)
                if handoff_ms else None,
                "max_ms": round(float(np.max(handoff_ms)), 2)
                if handoff_ms else None,
                "resume_gap_wave_ms": {
                    "n": len(resume_gap_ms),
                    "mean": round(float(np.mean(resume_gap_ms)), 3)
                    if resume_gap_ms else None,
                    "max": round(float(np.max(resume_gap_ms)), 3)
                    if resume_gap_ms else None,
                },
                "import_rejects": hs[1]["import_rejects"],
            }
            if prefill_mesh is not None:
                out["prefill_mesh_devices"] = int(
                    prefill_mesh.devices.size
                )
        streams = {qid: list(r["stream"]) for qid, r in recs.items()}
        engines.clear()
        return out, streams

    def _q_ms(dig, q):
        v = dig.quantile(q)
        return round(v * 1e3, 3) if v is not None else None

    out: Dict[str, object] = {}
    streams = {}
    for arm in arms:
        try:
            out[arm], streams[arm] = run_arm(arm)
        except Exception as e:  # noqa: BLE001 - dropped sub-arm is data
            import traceback

            traceback.print_exc()
            out[arm] = {"error": f"{type(e).__name__}: {e}"[:300]}

    def _ok(a):
        return isinstance(out.get(a), dict) and "error" not in out[a]

    good = [a for a in arms if _ok(a)]
    if "unified" in good and len(good) > 1:
        out["parity_ok"] = all(
            streams[a] == streams["unified"] for a in good
            if a != "unified"
        )
        u = out["unified"].get("interactive", {}).get("ttft_p99_ms")
        best = out[good[1]].get("interactive", {}).get("ttft_p99_ms")
        out["interactive_ttft_p99_improved"] = (
            u is not None and best is not None and best < u
        )
    if _ok("disagg") and _ok("disagg_streamed"):
        # the streamed-vs-monolithic headline: resume gap on the wave
        # (>=2x bar) + interactive p99 TTFT no worse than monolithic
        # (1.2x slack: both are wall-clock over few records, and the
        # streamed path must merely not regress)
        mono = out["disagg"]["handoff"]["resume_gap_wave_ms"]["mean"]
        strm = out["disagg_streamed"]["handoff"]["resume_gap_wave_ms"][
            "mean"
        ]
        mono_p99 = out["disagg"].get("interactive", {}).get("ttft_p99_ms")
        strm_p99 = out["disagg_streamed"].get("interactive", {}).get(
            "ttft_p99_ms"
        )
        out["stream_ab"] = {
            "resume_gap_mono_ms": mono,
            "resume_gap_streamed_ms": strm,
            "resume_gap_ratio": (
                round(mono / strm, 2)
                if mono is not None and strm not in (None, 0)
                else None
            ),
            "resume_gap_improved_2x": (
                mono is not None
                and strm not in (None, 0)
                and mono / strm >= 2.0
            ),
            "mono_interactive_ttft_p99_ms": mono_p99,
            "streamed_interactive_ttft_p99_ms": strm_p99,
            "streamed_ttft_no_worse": (
                mono_p99 is not None
                and strm_p99 is not None
                and strm_p99 <= 1.2 * mono_p99
            ),
        }
    return out


def bench_pd_disagg_hetero(
    n_chips=2, n_sessions=2, interactive_prompt=24, interactive_new=6,
    n_wave=2, wave_prompt=96, wave_new=3, page=16, chunk=4,
    prefill_chunk=32,
):
    """Heterogeneous-mesh P/D sub-arm (ROADMAP item 2 called it
    "routable but unmeasured"): a BIG-mesh prefill engine (dense TP over
    ``n_chips``) streams KV handoffs into a SMALL single-chip decode
    engine — parity + TTFT rows recorded as data through the same
    mixed-load driver.  CPU-smoke capable via a child process with a
    provisioned virtual CPU mesh, like ``sharded_serving``."""
    import jax

    if len(jax.devices()) >= n_chips:
        return _pd_hetero_measure(
            n_chips=n_chips, n_sessions=n_sessions,
            interactive_prompt=interactive_prompt,
            interactive_new=interactive_new, n_wave=n_wave,
            wave_prompt=wave_prompt, wave_new=wave_new, page=page,
            chunk=chunk, prefill_chunk=prefill_chunk,
        )
    import json as _json
    import subprocess
    import sys

    args = dict(
        n_chips=n_chips, n_sessions=n_sessions,
        interactive_prompt=interactive_prompt,
        interactive_new=interactive_new, n_wave=n_wave,
        wave_prompt=wave_prompt, wave_new=wave_new, page=page,
        chunk=chunk, prefill_chunk=prefill_chunk,
    )
    repo_root = os.path.dirname(os.path.abspath(__file__))
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (
        f"--xla_force_host_platform_device_count={n_chips}"
    )
    env["PYTHONPATH"] = repo_root
    proc = subprocess.run(
        [
            sys.executable,
            os.path.join(repo_root, "bench.py"),
            "--pd-hetero-child",
            _json.dumps(args),
        ],
        env=env,
        cwd=repo_root,
        capture_output=True,
        text=True,
        timeout=600,
    )
    lines = [
        l for l in proc.stdout.strip().splitlines() if l.startswith("{")
    ]
    if proc.returncode != 0 or not lines:
        return {
            "error": (
                f"child rc={proc.returncode}: "
                + (proc.stderr or proc.stdout)[-500:]
            )
        }
    return _json.loads(lines[-1])


def _pd_hetero_measure(
    n_chips=2, n_sessions=2, interactive_prompt=24, interactive_new=6,
    n_wave=2, wave_prompt=96, wave_new=3, page=16, chunk=4,
    prefill_chunk=32,
):
    """In-process half of the hetero sub-arm: n_chips-TP prefill mesh,
    single-chip decode, streamed handoff — rides the pd_disagg driver
    with ``prefill_mesh`` set, unified arm as the parity reference."""
    import jax

    from areal_tpu.base.topology import MeshSpec
    from areal_tpu.models import transformer

    dense_cfg, _ = _sharded_serving_cfgs(jax.default_backend() == "tpu")
    params = transformer.init_params(dense_cfg, jax.random.PRNGKey(0))
    mesh = MeshSpec(model=n_chips).make_mesh(jax.devices()[:n_chips])
    res = bench_pd_disagg_ab(
        dense_cfg, params,
        n_interactive=n_sessions, interactive_prompt=interactive_prompt,
        interactive_new=interactive_new, turns=1, n_wave=n_wave,
        wave_prompt=wave_prompt, wave_new=wave_new, page=page,
        chunk=chunk, prefill_chunk=prefill_chunk,
        arms=("unified", "disagg_streamed"), prefill_mesh=mesh,
    )
    res["prefill_mesh"] = f"m{n_chips}"
    res["decode_mesh_devices"] = 1
    return res


def _pd_hetero_child(argv_json: str) -> None:
    """Child-process entry for the hetero CPU-smoke path: the parent
    provisioned the virtual CPU mesh via env; measure and print ONE
    JSON line."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    print(json.dumps(_pd_hetero_measure(**json.loads(argv_json))))


def bench_spec_decode_ab(
    cfg,
    params,
    batches=(32, 64),
    prompt_len=512,
    max_new=256,
    motif_len=12,
    motif_alphabet=2,
    page=256,
    chunk=64,
    max_draft=7,
):
    """Self-speculative decoding A/B on a REPETITIVE-trace workload
    (engine/spec_decode.py): decode tok/s with n-gram draft + batched
    paged verify ON vs OFF, per batch size, under GREEDY sampling (the
    mode speculative decode is exact in).  Prompts tile a per-row
    random motif over a SMALL token alphabet: greedy decode from such
    low-entropy context settles into near-periodic output even for the
    bench's random-weight models — the synthetic proxy for what trained
    models do on real math/code traces, which is the regime n-gram
    drafting feeds on (the reported ``accept_rate`` makes the regime
    explicit).  Both arms submit identical prompts; the timed phase
    starts after admission/prefill completes, so the ratio isolates
    decode.

    Reported per batch: decode tok/s per arm, ``spec_over_off`` (the
    acceptance bar tracks >= 1.3x here), the measured acceptance rate,
    ``accepted_tokens_per_step`` (tokens emitted per verify pass), and
    ``derived_min_accept_rate`` — the break-even EMA threshold implied
    by the measured verify-vs-decode cost, the number recipe configs pin
    into ``GenServerConfig.spec_decode.min_accept_rate``
    (engine/dispatch.spec_break_even_accept_rate)."""
    import zlib

    from areal_tpu.api.model_api import (
        APIGenerateInput,
        GenerationHyperparameters,
    )
    from areal_tpu.engine.dispatch import spec_break_even_accept_rate
    from areal_tpu.engine.sampling import SamplingParams
    from areal_tpu.engine.spec_decode import SpecDecodeParams

    def submit_repetitive(eng, B, tag):
        for i in range(B):
            # motif seeded by ROW ONLY: warmup and timed waves (and both
            # arms) decode identical traces, so every window bucket the
            # timed wave touches is compiled by the warmup
            rng = np.random.default_rng(zlib.crc32(f"row{i}".encode()))
            alpha = min(motif_alphabet, cfg.vocab_size)
            motif = rng.integers(0, alpha, (motif_len,)).tolist()
            ids = (motif * (prompt_len // motif_len + 1))[:prompt_len]
            eng.submit(
                APIGenerateInput(
                    qid=f"{tag}{i}",
                    prompt_ids=ids,
                    input_ids=ids,
                    gconfig=GenerationHyperparameters(
                        max_new_tokens=max_new, greedy=True
                    ),
                )
            )

    def decode_timed(eng):
        """(tokens, seconds) of the post-admission decode phase."""
        while eng.has_work and (eng.n_pending > 0 or eng._filling):
            eng.step()
        t0 = time.perf_counter()
        n = 0
        while eng.has_work:
            n += eng.step()
        eng.drain_results()
        return n, time.perf_counter() - t0

    def arm(B, spec_on, tag):
        eng = make_engine(
            cfg, params, B, prompt_len, max_new, chunk=chunk,
            cache_mode="paged",
            page_size=page,
            sampling=SamplingParams(greedy=True),
            spec_decode_params=(
                SpecDecodeParams(enabled=True, max_draft_tokens=max_draft)
                if spec_on
                else None
            ),
        )
        submit_repetitive(eng, B, f"w{tag}")  # warmup: compiles
        drain(eng)
        submit_repetitive(eng, B, tag)
        n, dt = decode_timed(eng)
        row = {
            "decode_toks_per_sec": round(n / max(dt, 1e-9), 1),
            "decode_tokens": int(n),
        }
        if spec_on:
            s = eng.spec_stats()
            row["accept_rate"] = round(
                s["accepted_total"] / max(s["drafted_total"], 1), 3
            )
            # PER-ROW tokens emitted per verify pass (1 correction +
            # accepted drafts), the quantity dispatch.py's a*k+1 model
            # describes — a verify chunk batches many rows, so dividing
            # by chunks would overstate this by ~the batch size
            row["accepted_tokens_per_step"] = round(
                (s["accepted_total"] + s["draft_row_passes_total"])
                / max(s["draft_row_passes_total"], 1),
                2,
            )
            row["verify_chunks"] = int(s["verify_chunks_total"])
            row["fallback_rows"] = int(s["fallback_rows_total"])
        del eng
        return row

    out = {
        "prompt_len": prompt_len,
        "max_new": max_new,
        "motif_len": motif_len,
        "motif_alphabet": motif_alphabet,
        "max_draft_tokens": max_draft,
        "workload": (
            "repetitive-trace (tiled per-row small-alphabet motif), "
            "greedy"
        ),
    }
    for B in batches:
        off = arm(B, False, f"so{B}_")
        on = arm(B, True, f"sn{B}_")
        ratio = round(
            on["decode_toks_per_sec"]
            / max(off["decode_toks_per_sec"], 1e-9),
            3,
        )
        a = on.get("accept_rate", 0.0)
        tokens_per_pass = 1.0 + a * max_draft
        # measured verify cost in plain-decode-step units, backed out of
        # the A/B itself: on/off = tokens_per_pass / c
        c = tokens_per_pass / max(ratio, 1e-9)
        out[f"b{B}"] = {
            "spec_off": off,
            "spec_on": on,
            "spec_over_off": ratio,
            "verify_cost_over_decode_step": round(c, 3),
            "derived_min_accept_rate": round(
                spec_break_even_accept_rate(c, max_draft), 3
            ),
        }
    return out


def bench_prefill_ab(cfg, params, n_reqs=32, prompt_len=512, repeats=3):
    """Admission-path prefill A/B (VERDICT r5 #2: the in-round bench saw
    prefill fall 35.8k -> 23.8k tok/s at b32/512/0.5B between rounds with
    no attribution).  Three columns, each repeated ``repeats`` times:

    * ``jit``: one batched ``prefill`` call at [n_reqs, prompt_len] —
      the compute ceiling, no engine anywhere (r4 and r5 share this
      code, so if THIS column moved, the delta is the chip/tunnel, not
      the admission path);
    * ``engine_dense``: the engine's ``_admit`` wave (group dedup,
      shape bucketing, host bookkeeping, one completion fetch) with
      max_new=1 so every row finishes at admission and the wave repeats
      on a drained engine — the r4-equivalent admission path;
    * ``engine_paged_chunked``: the identical wave admitted through the
      paged fill queue in ``prefill_chunk_tokens`` chunks — the round-5
      addition, now issuing a wave's chunks back-to-back with no host
      round-trip between them when nothing is decoding.

    Per-repeat values are reported, not just a mean: under the axon
    tunnel a single wave can swing >1.5x run-to-run, and the jit column
    swings with it — ``spread`` vs the column DELTAS is what separates
    tunnel variance from a real admission-path regression."""
    import jax
    import jax.numpy as jnp

    from areal_tpu.engine.batching import bucket_len
    from areal_tpu.engine.inference_server import ContinuousBatchingEngine
    from areal_tpu.models.transformer import KVCache, prefill

    B, P = n_reqs, prompt_len
    T = bucket_len(P)
    rng = np.random.default_rng(13)
    toks = jnp.asarray(
        rng.integers(0, cfg.vocab_size, (B, T)).astype(np.int32)
    )
    lens = jnp.full((B,), P, jnp.int32)
    positions = jnp.tile(jnp.arange(T, dtype=jnp.int32)[None], (B, 1))
    seg = (positions < lens[:, None]).astype(jnp.int32)

    @jax.jit
    def jit_prefill(p, toks, positions, seg, lens):
        cache = KVCache.zeros(cfg, B, T, dtype=jnp.bfloat16)
        logits, _ = prefill(
            p, cfg, toks, positions, seg, cache,
            last_pos=jnp.maximum(lens - 1, 0),
        )
        return jnp.sum(logits)  # scalar fetch forces the whole call

    def time_jit():
        t0 = time.perf_counter()
        float(jit_prefill(params, toks, positions, seg, lens))
        return B * P / (time.perf_counter() - t0)

    float(jit_prefill(params, toks, positions, seg, lens))  # compile
    jit_rates = [round(time_jit(), 1) for _ in range(repeats)]

    def engine_rates(mode):
        kw = dict(cache_mode=mode)
        if mode == "paged":
            kw.update(page_size=1024, prefill_chunk_tokens=1024)
        eng = ContinuousBatchingEngine(
            cfg, params, max_batch=B,
            kv_cache_len=bench_gen_cache_len(P, 4), chunk_size=128, **kw
        )

        def wave(tag):
            # max_new=1: rows sample their first token and finish AT
            # admission, so the wave repeats on a fully drained engine
            submit_wave(eng, cfg, B, P, 1, tag)
            t0 = time.perf_counter()
            while eng.has_work:
                eng.step()
            dt = time.perf_counter() - t0
            eng.drain_results()
            return B * P / dt

        wave(f"w{mode}")  # compile this mode's admission path
        rates = [round(wave(f"t{mode}{i}"), 1) for i in range(repeats)]
        del eng
        return rates

    dense_rates = engine_rates("dense")
    paged_rates = engine_rates("paged")
    return {
        "batch": B,
        "prompt_len": P,
        "jit_toks_per_sec": jit_rates,
        "engine_dense_toks_per_sec": dense_rates,
        "engine_paged_chunked_toks_per_sec": paged_rates,
        "best": {
            "jit": max(jit_rates),
            "engine_dense": max(dense_rates),
            "engine_paged_chunked": max(paged_rates),
        },
        "engine_dense_over_jit": round(
            max(dense_rates) / max(max(jit_rates), 1e-9), 3
        ),
    }


def bench_interruption(cfg, params, n_reqs=32, prompt_len=256):
    """Interruptible vs drain-before-update weight swaps under a
    heterogeneous-length workload (the reference ablates this mechanism at
    +12-17% throughput, blog/AReaL_v0_3.md:125).

    Both modes process the same requests and apply the same number of
    weight updates; 'interrupt' applies them mid-flight (in-flight KV
    recomputed under new weights), 'drain' holds each update until every
    in-flight row finishes (the non-interruptible server's behavior —
    the long tail stalls the swap and admissions behind it)."""
    lens = np.linspace(64, 768, n_reqs).astype(int)
    np.random.default_rng(7).shuffle(lens)  # interleave short/long rows
    total_updates = 3

    def run(mode):
        eng = make_engine(cfg, params, 16, prompt_len, int(lens.max()))
        submit_wave(eng, cfg, n_reqs, prompt_len, None, "w", lens=lens)
        # warmup must also compile the WEIGHT-SWAP path (batched re-prefill
        # of in-flight rows hits shape buckets the plain drain never sees)
        warm_updates = 0
        warm_tok = 0
        while eng.has_work:
            warm_tok += eng.step()
            if warm_updates < total_updates and warm_tok > (
                (warm_updates + 1) * n_reqs * 100
            ):
                eng.update_weights(params, version=warm_updates + 1)
                warm_updates += 1
        eng.drain_results()
        eng.version = 0
        submit_wave(eng, cfg, n_reqs, prompt_len, None, mode, lens=lens)
        updates_done = 0
        n_tok = 0
        t0 = time.perf_counter()
        visible_lat = []
        while eng.has_work:
            n_tok += eng.step()
            want_update = (
                updates_done < total_updates
                and n_tok > (updates_done + 1) * n_reqs * 100
            )
            if want_update:
                if mode == "drain":
                    # non-interruptible: hold admissions and wait for every
                    # in-flight row (the long tail stalls the swap)
                    eng.hold_admissions = True
                    while eng.n_inflight > 0 or eng.inflight_chunks > 0:
                        n_tok += eng.step()
                tu = time.perf_counter()
                eng.update_weights(params, version=updates_done + 1)
                # update applies at the next step; measure visibility
                while eng.version != updates_done + 1:
                    n_tok += eng.step()
                visible_lat.append(time.perf_counter() - tu)
                eng.hold_admissions = False
                updates_done += 1
        dt = time.perf_counter() - t0
        eng.drain_results()
        del eng
        return n_tok / dt, visible_lat

    tput_int, lat_int = run("interrupt")
    tput_drain, _ = run("drain")
    return {
        "interrupt_toks_per_sec": round(tput_int, 1),
        "drain_toks_per_sec": round(tput_drain, 1),
        "interrupt_gain": round(tput_int / max(tput_drain, 1e-9), 4),
        "update_visible_latency_s": round(float(np.mean(lat_int)), 3),
        "n_updates": total_updates,
    }


def _weight_swap_cfg():
    """Tiny greedy-decode model for the swap A/B: the mechanism under
    test — restore off the paused critical path vs on it — is
    model-size-independent, and the tiny tree keeps the CPU-smoke arm
    honest (both paths restore the SAME snapshot)."""
    from areal_tpu.models.config import TransformerConfig

    return TransformerConfig(
        n_layers=2, hidden_dim=64, n_q_heads=4, n_kv_heads=2,
        head_dim=32, intermediate_dim=128, vocab_size=512,
        max_position_embeddings=512, dtype="float32",
    )


def _weight_swap_measure_arm(
    arm, n_reqs=4, prompt_len=32, max_new=48, page=32, chunk=8,
    repeats=2, n_chips=2,
):
    """One ``weight_swap_ab`` arm (dense | paged_prefix | mesh): the
    FULL-reload swap (pause covers restore + transfer + flip) vs the
    STAGED swap (restore while decode continues; pause covers only
    ring-drain + pointer flip) on the same mid-generation workload, plus
    post-swap token parity against a fresh engine running the new
    weights.  Mirrors the engine half of the fleet protocol exactly
    (generation_server's stage thread + commit barrier drive the same
    engine calls)."""
    import shutil
    import tempfile
    import threading

    import jax

    from areal_tpu.engine import checkpoint
    from areal_tpu.engine.sampling import SamplingParams
    from areal_tpu.models import transformer

    cfg = _weight_swap_cfg()
    params0 = transformer.init_params(cfg, jax.random.PRNGKey(0))
    params1 = transformer.init_params(cfg, jax.random.PRNGKey(42))
    kw = dict(sampling=SamplingParams(greedy=True))
    if arm == "dense":
        kw.update(cache_mode="dense")
    elif arm == "paged_prefix":
        kw.update(
            cache_mode="paged", page_size=page,
            prefill_chunk_tokens=max(page, 64), prefix_cache=True,
        )
    elif arm == "mesh":
        from areal_tpu.base.topology import MeshSpec

        kw.update(
            cache_mode="paged", page_size=page,
            prefill_chunk_tokens=max(page, 64),
            mesh=MeshSpec(model=n_chips).make_mesh(
                jax.devices()[:n_chips]
            ),
        )
    else:
        raise ValueError(arm)
    pub = tempfile.mkdtemp(prefix="areal-swapab-")
    try:
        # two snapshots: ``same`` re-publishes the CURRENT weights (the
        # timed swaps are token-neutral, so the full and staged arms run
        # on byte-identical decode workloads), ``new`` carries genuinely
        # new weights for the final flip whose post-swap stream the
        # fresh-engine replay must reproduce
        snap_same = os.path.join(pub, "v_same")
        snap_new = os.path.join(pub, "v_new")
        checkpoint.save_params(params0, snap_same)
        checkpoint.write_manifest(params0, snap_same, version=0)
        checkpoint.save_params(params1, snap_new)
        checkpoint.write_manifest(params1, snap_new, version=1)
        # ONE engine per arm, seeded from a RESTORED tree: every tree the
        # engine ever holds (initial, full-swapped, staged) then shares
        # one committed-sharding jit variant, and the warm-up swap below
        # pays the re-prefill shape-bucket compiles — so the timed
        # windows measure the swap mechanism, not first-use compiles (a
        # long-lived server is past both after its first swap)
        eng = make_engine(
            cfg,
            checkpoint.load_params_like(params0, snap_same),
            n_reqs, prompt_len, max_new, chunk=chunk, **kw,
        )
        trigger = n_reqs * max_new // 4
        wave_n = [0]

        def wave(tag=None):
            wave_n[0] += 1
            submit_wave(
                eng, cfg, n_reqs, prompt_len, max_new,
                tag or f"w{wave_n[0]}{arm}",
            )

        def run_to_trigger():
            tok = 0
            while eng.has_work and tok < trigger:
                tok += eng.step()

        version = [0]

        def full_swap():
            version[0] += 1
            t0 = time.perf_counter()
            eng.pause()
            eng.step()  # quiesce the in-flight ring
            # the legacy path's restore happens INSIDE the pause
            p = checkpoint.load_params_like(eng.params, snap_same)
            eng.update_weights(p, version=version[0])
            eng.resume()
            while eng.version != version[0]:
                eng.step()
            return time.perf_counter() - t0

        def staged_swap(snap):
            version[0] += 1
            v, box = version[0], {}

            def _stage():
                try:
                    p = checkpoint.load_params_staged(
                        eng.params, snap, chunk_bytes=1 << 20
                    )
                    eng.stage_weights(p, v)
                except Exception as e:  # noqa: BLE001 - reported
                    box["error"] = repr(e)

            th = threading.Thread(target=_stage, daemon=True)
            t_st, tok = time.perf_counter(), 0
            th.start()
            while th.is_alive():
                tok += eng.step()  # decode CONTINUES during staging
            th.join()
            if "error" in box:
                raise RuntimeError(box["error"])
            stage_s = time.perf_counter() - t_st
            t0 = time.perf_counter()
            eng.pause()
            eng.step()
            eng.commit_staged(expected_version=v)
            eng.resume()
            while eng.version != v:
                eng.step()
            return (
                time.perf_counter() - t0,
                stage_s,
                tok / max(stage_s, 1e-9),
            )

        # warm-up swap: compiles the ring-drain/re-prefill buckets once
        wave()
        run_to_trigger()
        full_swap()
        drain(eng)
        fulls, stageds, stage_ss, stage_tps, before_tps = [], [], [], [], []
        for _ in range(repeats):
            wave()
            run_to_trigger()
            t_b, tok_b = time.perf_counter(), 0
            while eng.has_work and tok_b < n_reqs * chunk:
                tok_b += eng.step()
            before_tps.append(tok_b / max(time.perf_counter() - t_b, 1e-9))
            fulls.append(full_swap())
            drain(eng)
            wave()
            run_to_trigger()
            p_s, s_s, s_tps = staged_swap(snap_same)
            stageds.append(p_s)
            stage_ss.append(s_s)
            stage_tps.append(s_tps)
            drain(eng)
        # the REAL flip: staged swap to the NEW weights mid-wave, then a
        # post-swap wave whose greedy stream a fresh engine running the
        # new weights must reproduce token-for-token
        wave()
        run_to_trigger()
        staged_swap(snap_new)
        drain(eng)
        eng.drain_results()
        submit_wave(eng, cfg, n_reqs, prompt_len, max_new, f"p{arm}")
        while eng.has_work:
            eng.step()
        post = {
            q: list(o.output_ids) for q, o in eng.drain_results().items()
        }
        del eng
        fresh = make_engine(
            cfg,
            checkpoint.load_params_like(params1, snap_new),
            n_reqs, prompt_len, max_new, chunk=chunk, **kw,
        )
        submit_wave(fresh, cfg, n_reqs, prompt_len, max_new, f"p{arm}")
        while fresh.has_work:
            fresh.step()
        ref = {
            q: list(o.output_ids)
            for q, o in fresh.drain_results().items()
        }
        del fresh
        full_pause = min(fulls)
        staged_pause = min(stageds)
        return {
            "full_pause_ms": round(full_pause * 1e3, 1),
            "staged_pause_ms": round(staged_pause * 1e3, 1),
            "staged_stage_ms": round(min(stage_ss) * 1e3, 1),
            "pause_ratio": round(staged_pause / max(full_pause, 1e-9), 4),
            "staged_below_full": bool(staged_pause < full_pause),
            "decode_tps_before": round(float(np.mean(before_tps)), 1),
            "decode_tps_during_stage": round(float(np.mean(stage_tps)), 1),
            "post_swap_parity": bool(post == ref),
        }
    finally:
        shutil.rmtree(pub, ignore_errors=True)


def _weight_swap_child(argv_json: str) -> None:
    """Child-process entry for the mesh arm off-TPU: the parent
    provisioned the virtual CPU devices; measure and print ONE JSON
    line."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    print(json.dumps(_weight_swap_measure_arm("mesh", **json.loads(argv_json))))


def bench_weight_swap_ab(
    n_reqs=4, prompt_len=32, max_new=48, page=32, chunk=8, repeats=2,
    mesh_chips=2,
):
    """Zero-downtime weight sync A/B (ISSUE 8's acceptance bench): the
    staged (stage-while-decoding -> pointer-flip commit) swap against
    the legacy full reload, per serving arm — pause-ms, decode tok/s
    around the swap, and post-swap fresh-replay token parity.  Runs
    off-TPU too (tiny shapes; the mesh arm spawns a virtual-CPU-mesh
    child when this process lacks devices, like ``sharded_serving``)."""
    import jax

    shape = dict(
        n_reqs=n_reqs, prompt_len=prompt_len, max_new=max_new,
        page=page, chunk=chunk, repeats=repeats,
    )
    out = {"backend": jax.default_backend()}
    for arm in ("dense", "paged_prefix"):
        try:
            out[arm] = _weight_swap_measure_arm(arm, **shape)
        except Exception as e:  # noqa: BLE001 - an arm failure is data
            out[arm] = {"error": f"{type(e).__name__}: {e}"[:300]}
    if len(jax.devices()) >= mesh_chips:
        try:
            out["mesh"] = _weight_swap_measure_arm(
                "mesh", n_chips=mesh_chips, **shape
            )
        except Exception as e:  # noqa: BLE001
            out["mesh"] = {"error": f"{type(e).__name__}: {e}"[:300]}
    else:
        import json as _json
        import subprocess
        import sys

        repo_root = os.path.dirname(os.path.abspath(__file__))
        env = dict(os.environ)
        env.pop("XLA_FLAGS", None)
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = (
            f"--xla_force_host_platform_device_count={mesh_chips}"
        )
        env["PYTHONPATH"] = repo_root
        try:
            proc = subprocess.run(
                [
                    sys.executable,
                    os.path.join(repo_root, "bench.py"),
                    "--weight-swap-child",
                    _json.dumps({**shape, "n_chips": mesh_chips}),
                ],
                env=env,
                cwd=repo_root,
                capture_output=True,
                text=True,
                timeout=600,
            )
            lines = [
                l for l in proc.stdout.strip().splitlines()
                if l.startswith("{")
            ]
            if proc.returncode != 0 or not lines:
                out["mesh"] = {
                    "error": (
                        f"child rc={proc.returncode}: "
                        + (proc.stderr or proc.stdout)[-500:]
                    )
                }
            else:
                out["mesh"] = _json.loads(lines[-1])
        except Exception as e:  # noqa: BLE001
            out["mesh"] = {"error": f"{type(e).__name__}: {e}"[:300]}
    arms_ok = [
        v for k, v in out.items()
        if isinstance(v, dict) and "staged_below_full" in v
    ]
    out["staged_below_full_all"] = bool(arms_ok) and all(
        v["staged_below_full"] for v in arms_ok
    )
    out["post_swap_parity_all"] = bool(arms_ok) and all(
        v.get("post_swap_parity") for v in arms_ok
    )
    return out



def _probe_devices(
    max_attempts: int = 3,
    base_delay_s: float = 2.0,
    attempt_timeout_s: float = 120.0,
):
    """``jax.devices()`` with bounded retry/backoff AND a per-attempt
    timeout: the axon shim can HANG backend init when the TPU is
    unreachable, not just raise (round 5 lost the whole bench to exactly
    that).  On final failure this emits the structured JSON error record
    on stdout and returns None — the rc=0 path for the capture harness,
    so ``BENCH_rNN.json`` is never a raw traceback."""
    import sys
    import threading

    import jax

    last = "unknown"
    attempts_made = 0
    for attempt in range(max_attempts):
        attempts_made = attempt + 1
        box = {}

        def probe():
            try:
                box["devices"] = jax.devices()
            except Exception as e:  # noqa: BLE001 - reported as data
                box["error"] = f"{type(e).__name__}: {e}"

        t = threading.Thread(target=probe, daemon=True)
        t.start()
        t.join(attempt_timeout_s)
        if "devices" in box:
            return box["devices"]
        if "error" not in box:
            # TIMEOUT: the probe thread is still blocked inside backend
            # init and holds jax's init lock — retrying would only queue
            # behind the same lock, so go straight to the error record
            last = (
                f"timeout: jax.devices() still blocked after "
                f"{attempt_timeout_s:.0f}s (unreachable TPU backend?)"
            )
            break
        last = box["error"]
        if attempt + 1 < max_attempts:
            delay = min(base_delay_s * 2**attempt, 30.0)
            print(
                f"[bench] device probe failed (attempt {attempt + 1}/"
                f"{max_attempts}): {last[:200]}; retrying in {delay:.0f}s",
                file=sys.stderr,
                flush=True,
            )
            time.sleep(delay)
    print(
        json.dumps(
            {
                "metric": "effective_rl_toks_per_sec_per_tflop",
                "value": None,
                "unit": "tok/s per bf16-TFLOP/s (1 chip, sync gen+train)",
                "error": {
                    "stage": "jax.devices",
                    "message": last[:2000],
                    "attempts": attempts_made,
                },
            }
        )
    )
    return None


def _sharded_serving_cfgs(on_tpu: bool):
    """(dense_cfg, moe_cfg) for the sharded-serving A/B.  Small even on
    TPU: the section measures the SCALING of the sharded engine (mesh
    collectives + EP dispatch on the hot path), not peak model tok/s —
    the other generation sections own that."""
    import dataclasses

    from areal_tpu.models.config import TransformerConfig

    if on_tpu:
        dense = TransformerConfig(
            n_layers=8, hidden_dim=1024, n_q_heads=8, n_kv_heads=4,
            head_dim=128, intermediate_dim=2816, vocab_size=32768,
            max_position_embeddings=4096, dtype="bfloat16",
        )
    else:
        dense = TransformerConfig(
            n_layers=2, hidden_dim=64, n_q_heads=4, n_kv_heads=2,
            head_dim=32, intermediate_dim=128, vocab_size=512,
            max_position_embeddings=512, dtype="float32",
        )
    moe = dataclasses.replace(
        dense,
        intermediate_dim=dense.intermediate_dim // 2,
        moe_intermediate_dim=dense.intermediate_dim // 2,
        n_experts=4,
        n_experts_per_tok=2,
        moe_aux_loss_coef=0.01,
        moe_z_loss_coef=0.001,
    )
    return dense, moe


def _sharded_serving_measure(
    n_chips=2, n_reqs=4, prompt_len=32, max_new=32, page=32, chunk=8
):
    """Decode tok/s at 1 vs ``n_chips`` chips for a dense-TP arm and a
    moe-EP arm, with token parity between the two engines asserted as
    data (greedy decode: the sharded engine must reproduce the
    single-chip stream exactly)."""
    import jax

    from areal_tpu.base.topology import MeshSpec
    from areal_tpu.engine.sampling import SamplingParams
    from areal_tpu.models import transformer

    on_tpu = jax.default_backend() == "tpu"
    dense_cfg, moe_cfg = _sharded_serving_cfgs(on_tpu)
    out = {"n_chips": n_chips, "backend": jax.default_backend()}

    def run(eng, cfg, tag, parity_tag):
        submit_wave(eng, cfg, n_reqs, prompt_len, max_new, f"w{tag}")
        drain(eng)  # warm: compiles included here, not in the timing
        submit_wave(eng, cfg, n_reqs, prompt_len, max_new, f"t{tag}")
        t0 = time.perf_counter()
        n = drain(eng)
        dt = time.perf_counter() - t0
        # parity wave: SAME tag (= same prompts/qids) on both engines so
        # the sharded stream is compared token-for-token
        submit_wave(eng, cfg, n_reqs, prompt_len, max_new, parity_tag)
        while eng.has_work:
            eng.step()
        outs = eng.drain_results()
        return n / max(dt, 1e-9), {
            q: list(o.output_ids) for q, o in outs.items()
        }

    for arm, cfg, spec in (
        ("dense_tp", dense_cfg, MeshSpec(model=n_chips)),
        ("moe_ep", moe_cfg, MeshSpec(expert=n_chips)),
    ):
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        kw = dict(
            sampling=SamplingParams(greedy=True),
            cache_mode="paged", page_size=page,
            prefill_chunk_tokens=max(page, 64),
        )
        e1 = make_engine(
            cfg, params, n_reqs, prompt_len, max_new, chunk=chunk, **kw
        )
        tps1, toks1 = run(e1, cfg, f"{arm}1", f"p{arm}")
        del e1
        mesh = spec.make_mesh(jax.devices()[:n_chips])
        eN = make_engine(
            cfg, params, n_reqs, prompt_len, max_new, chunk=chunk,
            mesh=mesh, **kw,
        )
        row = {
            "chips1_decode_toks_per_sec": round(tps1, 1),
        }
        if arm == "moe_ep":
            w = eN.params["layers"]["mlp"]["experts"]["gate"]
            # sharded for real, never silently replicated (acceptance
            # criterion: shard_shape != shape)
            row["expert_shard_ok"] = bool(
                w.sharding.shard_shape(w.shape) != w.shape
            )
        tpsN, toksN = run(eN, cfg, f"{arm}N", f"p{arm}")
        del eN
        row[f"chips{n_chips}_decode_toks_per_sec"] = round(tpsN, 1)
        row["scaling_x"] = round(tpsN / max(tps1, 1e-9), 3)
        row["token_parity"] = toks1 == toksN
        out[arm] = row
    return out


def bench_sharded_serving(
    n_chips=2, n_reqs=4, prompt_len=32, max_new=32, page=32, chunk=8
):
    """Sharded-serving scaling A/B (ROADMAP item 1's bench): decode tok/s
    at 1 vs N chips, dense-TP and moe-EP arms.

    CPU-smoke capable: when the current process has too few devices (a
    plain off-TPU run initializes ONE CPU device, and jax 0.4.x cannot
    grow the device count post-init), the measurement runs in a child
    process with a provisioned virtual CPU mesh and its JSON line is
    parsed back — so the summary always carries the section."""
    import jax

    if len(jax.devices()) >= n_chips:
        return _sharded_serving_measure(
            n_chips=n_chips, n_reqs=n_reqs, prompt_len=prompt_len,
            max_new=max_new, page=page, chunk=chunk,
        )
    import json as _json
    import subprocess
    import sys

    args = dict(
        n_chips=n_chips, n_reqs=n_reqs, prompt_len=prompt_len,
        max_new=max_new, page=page, chunk=chunk,
    )
    repo_root = os.path.dirname(os.path.abspath(__file__))
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (
        f"--xla_force_host_platform_device_count={n_chips}"
    )
    env["PYTHONPATH"] = repo_root
    proc = subprocess.run(
        [
            sys.executable,
            os.path.join(repo_root, "bench.py"),
            "--sharded-serving-child",
            _json.dumps(args),
        ],
        env=env,
        cwd=repo_root,
        capture_output=True,
        text=True,
        timeout=600,
    )
    lines = [
        l for l in proc.stdout.strip().splitlines() if l.startswith("{")
    ]
    if proc.returncode != 0 or not lines:
        return {
            "error": (
                f"child rc={proc.returncode}: "
                + (proc.stderr or proc.stdout)[-500:]
            )
        }
    return _json.loads(lines[-1])


def _sharded_serving_child(argv_json: str) -> None:
    """Child-process entry for the CPU-smoke path: the parent provisioned
    the virtual CPU mesh via env; measure and print ONE JSON line."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    print(json.dumps(_sharded_serving_measure(**json.loads(argv_json))))


def bench_gateway_ab(
    cfg,
    params,
    n_bulk=8,
    n_interactive=8,
    prompt_len=128,
    bulk_new=256,
    inter_new=16,
    page=32,
    chunk=16,
    max_batch=4,
    max_steps=6000,
):
    """Serving-gateway A/B: an interactive SSE burst landing on a
    2-engine fleet mid bulk-rollout storm, tenant admission ON vs OFF.

    The load shape is the gateway's worst case: ``n_bulk`` long
    bulk-tenant generations claim the fleet's cache rows first, then
    ``n_interactive`` short interactive streams burst in.  Admission
    OFF, every bulk request admits and the burst queues behind the
    storm (TTFT ~ the bulk generation length).  Admission ON, the bulk
    tenant's token bucket caps the storm at half the fleet's rows
    (typed ``rate_limited`` rejects for the rest — the 429s a real
    client would retry) and stamps priority classes, so the burst finds
    free rows immediately.  The diffable win is interactive p99 TTFT
    (steps is the deterministic unit; wall seconds reported alongside);
    the acceptance bar is STRICTLY better p99 with admission on, plus
    SSE-stream/rollout-path token parity and a zero-leak block audit on
    every engine of both arms."""
    import zlib

    from areal_tpu.api.model_api import (
        APIGenerateInput,
        GenerationHyperparameters,
    )
    from areal_tpu.engine.sampling import SamplingParams
    from areal_tpu.gateway.admission import AdmissionPlane, TenantPolicy
    from areal_tpu.gateway.server import (
        EngineBackend,
        estimate_tokens,
        run_request,
    )

    cache_len = bench_gen_cache_len(prompt_len, bulk_new)
    bulk_est = estimate_tokens(prompt_len, bulk_new)
    inter_est = estimate_tokens(prompt_len, inter_new)

    def prompt_ids(tag):
        rng = np.random.default_rng(zlib.crc32(tag.encode()))
        return rng.integers(0, cfg.vocab_size, (prompt_len,)).tolist()

    def ginp(qid, ids, max_new):
        return APIGenerateInput(
            qid=qid,
            prompt_ids=list(ids),
            input_ids=list(ids),
            gconfig=GenerationHyperparameters(
                max_new_tokens=max_new, greedy=True
            ),
        )

    def pristine(eng):
        eng.step()
        eng.step()
        if eng._prefix_cache is not None:
            eng._prefix_cache.flush()
        return bool(
            eng.free_pool_blocks == eng.n_blocks
            and (np.asarray(eng._block_ref) == 0).all()
        )

    def mk_fleet():
        engines = {}
        for name in ("srv0", "srv1"):
            eng = make_engine(
                cfg, params, max_batch, prompt_len, bulk_new, chunk=chunk,
                cache_mode="paged",
                page_size=page,
                kv_pool_tokens=(max_batch + 1) * cache_len,
                sampling=SamplingParams(greedy=True),
            )
            eng.park_ttl_steps = 0  # fresh qids never resume: no parked rows
            engines[name] = eng
        return engines

    def _pct(vals, q):
        return round(float(np.percentile(np.asarray(vals, float), q)), 4)

    def arm(admission, tag):
        engines = mk_fleet()
        plane = None
        if admission:
            plane = AdmissionPlane([
                # the storm's cap: a bucket holding half the storm up
                # front, refilling too slowly to matter inside the bench
                TenantPolicy(
                    "bulk_load",
                    priority="bulk",
                    rate_tokens_per_s=1e-6,
                    burst_tokens=(n_bulk // 2) * bulk_est,
                ),
                TenantPolicy("interactive", priority="interactive"),
            ])
        backend = EngineBackend(engines, plane=plane)

        # warm the prefill/decode jits out of the TTFT measurement
        for name in engines:
            backend.submit(
                ginp(f"{tag}-warm-{name}", prompt_ids(f"{tag}w{name}"), 2),
                "interactive", "", False,
            )
        for _ in range(max_steps):
            backend.pump_once()
            if not backend.has_work():
                break
        for eng in engines.values():
            eng.drain_results()

        # the bulk storm claims rows first
        bulk_admitted = 0
        bulk_rejects = {}
        for i in range(n_bulk):
            dec = backend.admit("bulk_load", bulk_est)
            if dec["ok"]:
                bulk_admitted += 1
                backend.submit(
                    ginp(f"{tag}-bulk{i}", prompt_ids(f"{tag}b{i}"),
                         bulk_new),
                    "bulk_load", dec.get("priority", ""), False,
                )
            else:
                bulk_rejects[dec["reason"]] = (
                    bulk_rejects.get(dec["reason"], 0) + 1
                )
        for _ in range(3):  # storm settles into its cache rows
            backend.pump_once()

        # the interactive burst: SSE-style streamed requests, TTFT = the
        # first drained stream chunk
        handles = {}
        t_submit = {}
        for i in range(n_interactive):
            qid = f"{tag}-int{i}"
            dec = backend.admit("interactive", inter_est)
            assert dec["ok"], dec
            t_submit[qid] = time.perf_counter()
            handles[qid] = backend.submit(
                ginp(qid, prompt_ids(f"{tag}i{i}"), inter_new),
                "interactive", dec.get("priority", ""), True,
            )
        ttft_steps = {}
        ttft_s = {}
        streams = {qid: [] for qid in handles}
        done = set()
        for step in range(1, max_steps + 1):
            backend.pump_once()
            for qid, h in handles.items():
                if qid in done:
                    continue
                r = backend.poll(h)
                toks = r.get("tokens") or []
                if toks and qid not in ttft_steps:
                    ttft_steps[qid] = step
                    ttft_s[qid] = time.perf_counter() - t_submit[qid]
                streams[qid].extend(toks)
                if r.get("done"):
                    done.add(qid)
                    backend.finish(
                        h, len(streams[qid]) + prompt_len, inter_est
                    )
            if len(done) == n_interactive:
                break
        else:
            raise RuntimeError("interactive burst did not drain")
        # drain the surviving storm, then audit for leaks
        for _ in range(max_steps):
            if not backend.has_work():
                break
            backend.pump_once()
        for eng in engines.values():
            eng.drain_results()
        row = {
            "bulk_admitted": int(bulk_admitted),
            "bulk_rejects": bulk_rejects,
            "interactive_ttft_steps": {
                "p50": _pct(list(ttft_steps.values()), 50),
                "p99": _pct(list(ttft_steps.values()), 99),
                "max": max(ttft_steps.values()),
            },
            "interactive_ttft_s": {
                "p50": _pct(list(ttft_s.values()), 50),
                "p99": _pct(list(ttft_s.values()), 99),
            },
            "interactive_tokens": int(sum(len(s) for s in streams.values())),
            "leak_free": all(pristine(e) for e in engines.values()),
        }
        if plane is not None:
            row["tenants"] = plane.stats()
        return row

    def parity():
        """Greedy token identity across the three read paths: the SSE
        stream's chunk concat, the request's final result, and a plain
        rollout-style submission of the same prompt."""
        eng = make_engine(
            cfg, params, 2, prompt_len, inter_new, chunk=chunk,
            cache_mode="paged", page_size=page,
            kv_pool_tokens=4 * bench_gen_cache_len(prompt_len, inter_new),
            # no prefix cache: a radix hit would prefill only the suffix,
            # and the changed reduction order can flip near-tied argmax
            # on tiny models — parity wants bit-identical prefills
            prefix_cache=False,
            sampling=SamplingParams(greedy=True),
        )
        eng.park_ttl_steps = 0
        backend = EngineBackend({"srv": eng})
        ids = prompt_ids("parity")
        chunks = []
        out = run_request(
            backend, ginp("par-gw", ids, inter_new),
            "interactive", "interactive",
            stream=True, on_chunk=chunks.append,
            pump=backend.pump_once,
        )
        concat = [t for c in chunks for t in c]
        eng.submit(ginp("par-rollout", ids, inter_new))
        while eng.has_work:
            eng.step()
        rollout = eng.drain_results()["par-rollout"]
        return {
            "stream_concat_matches_result": bool(
                concat == list(out["result"]["output_ids"])
            ),
            "gateway_matches_rollout": bool(
                list(out["result"]["output_ids"])
                == list(rollout.output_ids)
            ),
            "leak_free": pristine(eng),
        }

    def two_gateways(n_requests=12, cap=5):
        """ROADMAP item 1(c) nibble: TWO gateway front doors (two
        ``FleetBackend``s, each with its own manager connection — the
        two-``GatewayWorker`` deployment shape) share ONE real
        manager's admission plane over the combined ``gateway_submit``
        RPC.  The capped tenant's bucket holds exactly ``cap``
        requests up front and refills too slowly to matter inside the
        bench, so with both gateways racing from their own threads the
        plane must admit EXACTLY ``cap`` across the pair — one
        over-admit means a decision escaped the plane's lock.  Pure
        control plane: no engines; admitted requests dispatch to
        null gen-server clients."""
        import threading

        from areal_tpu.api.system_api import GserverManagerConfig
        from areal_tpu.base import logging_ as logging_mod
        from areal_tpu.base.monitor import RolloutStat
        from areal_tpu.gateway.server import FleetBackend
        from areal_tpu.system.gserver_manager import (
            GserverManager,
            GserverManagerClient,
        )

        est = float(estimate_tokens(prompt_len, inter_new))
        m = GserverManager.__new__(GserverManager)
        m.config = GserverManagerConfig(
            schedule_policy="least_requests",
            n_servers=4,
            serve_mode="router",
            tenants=[
                dict(
                    name="capped",
                    priority="bulk",
                    rate_tokens_per_s=1e-6,
                    burst_tokens=cap * est,
                ),
                dict(name="interactive", priority="interactive"),
            ],
        )
        m.server_addrs = [f"2gw-fs{i}" for i in range(4)]
        m.logger = logging_mod.getLogger("bench-2gw")
        m._round_robin = 0
        m._qid_server = {}
        m._server_load = {a: 0 for a in m.server_addrs}
        m._server_tokens = {a: 0.0 for a in m.server_addrs}
        m._server_devices = {a: 1 for a in m.server_addrs}
        m._server_mesh = {a: "" for a in m.server_addrs}
        m._qid_tokens = {}
        m._group_server = {}
        m._group_prefix = {}
        m._group_tokens = {}
        m.rollout_stat = RolloutStat()
        m._model_version = 0
        m._expr, m._trial = "bench-2gw", "t0"
        m._clients = {}
        m._init_metrics()
        import zmq as _zmq

        m._serve_mode = "router"
        m._ctx = _zmq.Context.instance()
        m._sock = m._ctx.socket(_zmq.ROUTER)
        port = m._sock.bind_to_random_port("tcp://127.0.0.1")
        m.addr = f"127.0.0.1:{port}"

        stop = threading.Event()

        def serve():
            while not stop.is_set():
                if m._sock.poll(timeout=10):
                    m._serve()

        st = threading.Thread(target=serve, daemon=True,
                              name="2gw-serve")
        st.start()

        class _NullGenClient:
            """Admitted requests have nowhere real to go — the arm
            measures the admission plane, not generation."""

            def call(self, cmd, payload, timeout=None):
                return {}

            def close(self):
                pass

        results = {}
        errors = []
        lock = threading.Lock()
        barrier = threading.Barrier(3)

        def gateway(gname):
            client = GserverManagerClient(addr=m.addr, timeout=60.0)
            backend = FleetBackend(
                client, client_factory=lambda addr: _NullGenClient()
            )
            admitted = rejected = inter_ok = 0
            try:
                barrier.wait()
                for i in range(n_requests):
                    dec, handle = backend.admit_and_submit(
                        ginp(f"{gname}-cap{i}",
                             prompt_ids(f"{gname}c{i}"), inter_new),
                        "capped", est, False,
                    )
                    if dec.get("ok"):
                        admitted += 1
                        assert handle and handle["url"], handle
                    else:
                        rejected += 1
                        assert dec.get("reason") == "rate_limited", dec
                    # the uncapped tenant proves this front door stays
                    # live even after its capped traffic is throttled
                    dec2, h2 = backend.admit_and_submit(
                        ginp(f"{gname}-int{i}",
                             prompt_ids(f"{gname}n{i}"), inter_new),
                        "interactive", est, False,
                    )
                    if dec2.get("ok") and h2:
                        inter_ok += 1
            except Exception as e:  # noqa: BLE001 - becomes arm data
                with lock:
                    errors.append(
                        f"{gname}: {type(e).__name__}: {e}"[:200]
                    )
            finally:
                client.close()
            with lock:
                results[gname] = {
                    "capped_admitted": admitted,
                    "capped_rejected": rejected,
                    "interactive_admitted": inter_ok,
                }

        threads = [
            threading.Thread(target=gateway, args=(g,), daemon=True,
                             name=f"2gw-{g}")
            for g in ("gw0", "gw1")
        ]
        for t in threads:
            t.start()
        barrier.wait()
        for t in threads:
            t.join(timeout=120.0)
        stop.set()
        st.join(timeout=5.0)
        m._sock.close(linger=0)

        total = sum(r["capped_admitted"] for r in results.values())
        row = {
            "n_requests_per_gateway": n_requests,
            "capped_tenant_slots": cap,
            "per_gateway": results,
            "total_capped_admitted": int(total),
            # THE acceptance bar: admission stayed atomic across the
            # two front doors — the shared bucket filled exactly, never
            # over
            "no_tenant_over_admit": bool(total == cap and not errors),
            "both_gateways_served": bool(
                len(results) == 2
                and all(
                    r["interactive_admitted"] == n_requests
                    for r in results.values()
                )
            ),
            "plane_tenants": m._admission.stats(),
        }
        if errors:
            row["errors"] = errors[:3]
        return row

    out = {
        "n_bulk": n_bulk,
        "n_interactive": n_interactive,
        "prompt_len": prompt_len,
        "bulk_new": bulk_new,
        "inter_new": inter_new,
        "max_batch_per_engine": max_batch,
        "admission_on": arm(True, "on"),
        "admission_off": arm(False, "off"),
        "parity": parity(),
        "two_gateways": two_gateways(),
    }
    on_p99 = out["admission_on"]["interactive_ttft_steps"]["p99"]
    off_p99 = out["admission_off"]["interactive_ttft_steps"]["p99"]
    out["p99_ttft_steps_improvement"] = round(off_p99 / max(on_p99, 1), 2)
    out["interactive_p99_ttft_better_with_admission"] = bool(
        on_p99 < off_p99
    )
    out["leak_free"] = bool(
        out["admission_on"]["leak_free"]
        and out["admission_off"]["leak_free"]
        and out["parity"]["leak_free"]
    )
    out["no_tenant_over_admit"] = bool(
        out["two_gateways"]["no_tenant_over_admit"]
    )
    return out


def bench_control_plane_ab(
    n_servers=64,
    n_groups=48,
    group_size=16,
    n_gateway=96,
    n_threads=16,
    prompt_len=128,
    new_tokens=64,
    update_rpc_s=0.05,
):
    """Manager control-plane A/B: schedules/sec and p99 schedule wait
    under a mixed rollout+gateway storm at ``n_servers`` registered
    fake servers, across the two serve loops (strict-lockstep REP vs
    batched ROUTER) and the two pick paths (O(N) scan vs O(log N)
    incremental indexes).  Pure CPU — no engine, no real gen servers:
    the managers are hand-built with fake addresses but serve over
    REAL ZMQ sockets with real threaded ``GserverManagerClient``s, so
    the arms measure the actual wire + serve-loop + scheduling stack.

    Storm shape: ``n_groups`` rollout groups of ``group_size`` siblings
    plus ``n_gateway`` interactive requests, spread over ``n_threads``
    client threads.  The baseline arms issue one RPC per sibling and
    an admit+schedule RPC pair per gateway request (the pre-batching
    client protocol); the fully-optimized arm issues one
    ``schedule_batch`` per group and one combined ``gateway_submit``
    per gateway request.  Every arm also gets the SAME mid-storm
    weight-update publication (real ``_flush_and_update`` fan-out over
    fake per-server clients whose RPCs sleep ``update_rpc_s``): the
    rep arms pay it INLINE on the serve thread — the pre-ROUTER
    behavior — while the router arms run it on the update pool, so
    scheduling never stalls.  The acceptance bar is >= 5x
    schedules/sec for router+indexed+batched vs rep+scan+unbatched;
    ``parity`` reports scan-vs-indexed pick identity over a
    deterministic mixed trace for all three policies (the exhaustive
    version is a tier-1 property test)."""
    import queue as queue_mod
    import threading

    from areal_tpu.api.system_api import GserverManagerConfig
    from areal_tpu.base import logging_
    from areal_tpu.base.monitor import RolloutStat
    from areal_tpu.system.gserver_manager import (
        GserverManager,
        GserverManagerClient,
    )

    class _FakeGenClient:
        """Stands in for a GenServerClient during the weight-update
        fan-out: every RPC just sleeps the configured latency."""

        def call(self, cmd, payload, timeout=None):
            time.sleep(update_rpc_s)
            if cmd == "update_weights":
                return {"num_interrupted": 0}
            return {}

    def mk_manager(serve_mode, indexed, policy="least_requests",
                   bind=True):
        m = GserverManager.__new__(GserverManager)
        m.config = GserverManagerConfig(
            schedule_policy=policy,
            n_servers=n_servers,
            serve_mode=serve_mode,
            routing_index=indexed,
        )
        m.server_addrs = [f"fs{i}" for i in range(n_servers)]
        m.logger = logging_.getLogger("bench-cp")
        m._round_robin = 0
        m._qid_server = {}
        m._server_load = {a: 0 for a in m.server_addrs}
        m._server_tokens = {a: 0.0 for a in m.server_addrs}
        m._server_devices = {a: 1 for a in m.server_addrs}
        m._server_mesh = {a: "" for a in m.server_addrs}
        m._qid_tokens = {}
        m._group_server = {}
        m._group_prefix = {}
        m._group_tokens = {}
        m.rollout_stat = RolloutStat()
        m._model_version = 0
        m._expr, m._trial = "bench-cp", f"{serve_mode}-{int(indexed)}"
        m._clients = {a: _FakeGenClient() for a in m.server_addrs}
        m._init_metrics()
        if bind:
            import zmq as _zmq

            m._serve_mode = serve_mode
            m._ctx = _zmq.Context.instance()
            m._sock = m._ctx.socket(
                _zmq.ROUTER if serve_mode == "router" else _zmq.REP
            )
            port = m._sock.bind_to_random_port("tcp://127.0.0.1")
            m.addr = f"127.0.0.1:{port}"
        return m

    def _pct(vals, q):
        return round(float(np.percentile(np.asarray(vals, float), q)), 6)

    est_tokens = float(prompt_len + new_tokens)
    n_schedules = n_groups * group_size + n_gateway

    def run_arm(serve_mode, indexed, batched):
        m = mk_manager(serve_mode, indexed)
        stop = threading.Event()
        fire_update = threading.Event()
        update_info = {"version": 1, "path": "bench-ckpt-v1",
                       "format": "hf"}

        def serve():
            # the worker's _poll loop, minus the scrapes: serve, then
            # kick a published weight update when one appears.  Blocking
            # on the socket (instead of NOBLOCK-spinning) keeps the GIL
            # free for the in-process client threads — in deployment
            # the manager is its own process and never shares one.
            fired = False
            while not stop.is_set():
                if m._sock.poll(timeout=10):
                    m._serve()
                if fire_update.is_set() and not fired:
                    fired = True
                    # rep mode: runs INLINE right here, stalling every
                    # queued schedule; router mode: hops to the update
                    # pool and this loop keeps serving
                    m._start_weight_update(update_info)

        st = threading.Thread(target=serve, daemon=True,
                              name=f"cp-serve-{serve_mode}")
        st.start()

        jobs = queue_mod.Queue()
        for g in range(n_groups):
            jobs.put(("rollout", g))
        for i in range(n_gateway):
            jobs.put(("gateway", i))
        waits = []  # one entry per LOGICAL schedule decision
        rpcs = [0]
        lock = threading.Lock()
        errors = []
        barrier = threading.Barrier(n_threads + 1)

        def worker():
            client = GserverManagerClient(addr=m.addr, timeout=60.0)
            try:
                barrier.wait()
                while True:
                    try:
                        kind, i = jobs.get_nowait()
                    except queue_mod.Empty:
                        return
                    local, n_rpc = [], 0
                    if kind == "rollout":
                        qids = [f"r{i}-{j}" for j in range(group_size)]
                        if batched:
                            t0 = time.perf_counter()
                            out = client.call("schedule_batch", {
                                "qids": qids,
                                "prompt_len": prompt_len,
                                "new_token_budget": new_tokens,
                            })
                            dt = time.perf_counter() - t0
                            n_rpc += 1
                            assert len(out["responses"]) == group_size
                            local = [dt] * group_size
                        else:
                            for q in qids:
                                t0 = time.perf_counter()
                                client.call("schedule_request", {
                                    "qid": q,
                                    "prompt_len": prompt_len,
                                    "new_token_budget": new_tokens,
                                })
                                local.append(time.perf_counter() - t0)
                                n_rpc += 1
                    else:
                        qid = f"gw{i}"
                        t0 = time.perf_counter()
                        if batched:
                            resp = client.call("gateway_submit", {
                                "tenant": "interactive",
                                "tokens": est_tokens,
                                "qid": qid,
                                "prompt_len": prompt_len,
                                "new_token_budget": new_tokens,
                            })
                            n_rpc += 1
                            assert resp["ok"] and resp["schedule"]["url"]
                        else:
                            dec = client.call("gateway_admit", {
                                "tenant": "interactive",
                                "tokens": est_tokens,
                            })
                            assert dec["ok"]
                            client.call("schedule_request", {
                                "qid": qid,
                                "prompt_len": prompt_len,
                                "new_token_budget": new_tokens,
                            })
                            n_rpc += 2
                        local = [time.perf_counter() - t0]
                    with lock:
                        waits.extend(local)
                        rpcs[0] += n_rpc
            except Exception as e:  # noqa: BLE001 - becomes arm data
                with lock:
                    errors.append(f"{type(e).__name__}: {e}"[:200])
            finally:
                client.close()

        threads = [
            threading.Thread(target=worker, daemon=True,
                             name=f"cp-client-{t}")
            for t in range(n_threads)
        ]
        for t in threads:
            t.start()
        batch_sum0, batch_cnt0 = m._m_ctl_batch.snapshot()
        barrier.wait()
        t0 = time.perf_counter()
        fire_update.set()  # the update publishes as the storm lands
        for t in threads:
            t.join(timeout=120.0)
        wall = time.perf_counter() - t0
        # router arms: let the async update finish before teardown so
        # both arms end at the bumped version (proves it really ran)
        deadline = time.monotonic() + 60.0
        while (
            getattr(m, "_weight_update_fut", None) is not None
            and not m._weight_update_fut.done()
            and time.monotonic() < deadline
        ):
            time.sleep(0.005)
        m._harvest_weight_update()
        stop.set()
        st.join(timeout=5.0)
        batch_sum1, batch_cnt1 = m._m_ctl_batch.snapshot()
        pool = getattr(m, "_update_pool", None)
        if pool is not None:
            pool.shutdown(wait=False)
        m._sock.close(linger=0)
        row = {
            "schedules_per_sec": round(n_schedules / max(wall, 1e-9), 1),
            "wall_s": round(wall, 4),
            "rpcs": int(rpcs[0]),
            "schedule_wait_s": {
                "p50": _pct(waits, 50),
                "p99": _pct(waits, 99),
            } if waits else None,
            "scheduled": len(waits),
            "model_version_after": int(m._model_version),
        }
        if serve_mode == "router" and batch_cnt1 > batch_cnt0:
            row["mean_serve_batch"] = round(
                (batch_sum1 - batch_sum0) / (batch_cnt1 - batch_cnt0), 2
            )
        if errors:
            row["errors"] = errors[:3]
        return row

    def parity():
        """Scan-vs-indexed pick identity over one deterministic mixed
        trace (schedules with group collisions, releases, direct
        load/token writes) per policy."""
        import random

        out = {}
        for policy in ("least_requests", "least_token_usage",
                       "round_robin"):
            seqs = []
            for indexed in (False, True):
                m = mk_manager("rep", indexed, policy=policy, bind=False)
                rng = random.Random(1234)
                seq, live = [], []
                for step in range(400):
                    op = rng.random()
                    if op < 0.6 or not live:
                        g = rng.randrange(120)
                        qid = f"g{g}-{rng.randrange(group_size)}"
                        r = m._schedule_request(
                            qid, rng.randrange(1, 256),
                            rng.randrange(1, 128),
                        )
                        seq.append(r["url"])
                        live.append(qid)
                    elif op < 0.85:
                        m._release_scheduled(
                            live.pop(rng.randrange(len(live)))
                        )
                    else:
                        # direct operator/test-style map writes: the
                        # observed dicts must keep the index honest
                        a = m.server_addrs[
                            rng.randrange(len(m.server_addrs))
                        ]
                        m._server_tokens[a] = (
                            m._server_tokens[a] + 48.0
                        )
                        m._server_load[a] = m._server_load[a] + 1
                seqs.append(seq)
            out[policy] = bool(seqs[0] == seqs[1])
        return out

    arms = {
        "rep_scan": run_arm("rep", indexed=False, batched=False),
        "rep_indexed": run_arm("rep", indexed=True, batched=False),
        "router_scan": run_arm("router", indexed=False, batched=False),
        "router_indexed": run_arm("router", indexed=True, batched=True),
    }
    par = parity()
    base = arms["rep_scan"]["schedules_per_sec"]
    opt = arms["router_indexed"]["schedules_per_sec"]
    return {
        "n_servers": n_servers,
        "n_groups": n_groups,
        "group_size": group_size,
        "n_gateway": n_gateway,
        "n_threads": n_threads,
        "n_schedules": n_schedules,
        **arms,
        "speedup": round(opt / max(base, 1e-9), 2),
        "meets_5x": bool(opt >= 5.0 * base),
        "parity": par,
        "routing_parity": bool(all(par.values())),
    }


#: per-section outcomes for the machine-parseable summary:
#: {name: {"status": "ok"|"error"|"timeout", "seconds": wall}}.  A round
#: that loses sections still reports WHICH ones and why.
_SECTION_STATUS = {}

#: default per-section watchdog; generous because a cold section may pay
#: multiple fresh XLA compiles (the decode A/B's deep-kernel cells run
#: ~30-40s of compile EACH)
SECTION_TIMEOUT_S = 900.0


def _section(fn, *args, name=None, timeout_s=None, **kw):
    """Run one bench section; a failure becomes DATA (error string) so a
    single section can never zero out the whole round's bench.

    With ``name`` the section also runs under its own fail-safe
    (``areal_tpu.base.watchdog.run_bounded`` — the daemon-thread
    watchdog shared with ``dryrun_multichip``'s phases): a section that
    HANGS (an axon backend init wedging inside a dispatch — BENCH_r05
    lost all of rounds 8/9's TPU numbers to exactly one such hang)
    forfeits only its own numbers; the round continues and the outcome
    lands in the summary's per-section ``status`` table."""
    import traceback

    if name is None:
        try:
            return fn(*args, **kw)
        except Exception as e:  # noqa: BLE001 - report, don't die
            traceback.print_exc()
            return {"error": f"{type(e).__name__}: {e}"[:300]}

    from areal_tpu.base.watchdog import run_bounded

    budget = timeout_s if timeout_s is not None else SECTION_TIMEOUT_S
    out = run_bounded(
        fn, *args, name=f"bench-{name}", timeout_s=budget, **kw
    )
    _SECTION_STATUS[name] = {
        "status": out["status"], "seconds": out["seconds"]
    }
    if out["status"] == "timeout":
        return {
            "error": f"section {name!r} still running after {budget:.0f}s",
            "status": "timeout",
        }
    if out["status"] == "error":
        return {"error": out["error"]}
    return out["result"]


#: the machine-parseable summary's contract: these keys are ALWAYS
#: present (value None when a section didn't run), so round-over-round
#: diffs and the capture harness's `parsed` field never KeyError.
#: Guarded by a tier-1 schema test (tests/engine/test_bench_sweep.py).
SUMMARY_REQUIRED_KEYS = (
    "pipeline_depth",
    "decode",
    "ring_ab",
    "prefill_ab",
    "prefix_cache_ab",
    "prefix_cache_hier",
    "kv_fabric_ab",
    "kv_quant_ab",
    "weight_quant_ab",
    "trace_overhead_ab",
    "obs_ledger_report",
    "spec_decode_ab",
    "slo_report",
    "pd_disagg_ab",
    "gateway_ab",
    "control_plane_ab",
    "sharded_serving",
    "weight_swap_ab",
    "train_packing_ab",
    "paged_decode_ab",
    "dispatch_table",
    "sections",
)


def build_summary(
    gen,
    prefill_ab=None,
    prefix_cache_ab=None,
    prefix_cache_hier=None,
    kv_fabric_ab=None,
    kv_quant_ab=None,
    weight_quant_ab=None,
    trace_overhead_ab=None,
    obs_ledger_report=None,
    spec_decode_ab=None,
    slo_report=None,
    pd_disagg_ab=None,
    gateway_ab=None,
    control_plane_ab=None,
    sharded_serving=None,
    weight_swap_ab=None,
    train_packing_ab=None,
    decode_ab=None,
    pipeline_depth=2,
):
    """Compact machine-parseable summary: the round's DIFFABLE numbers
    (decode split + ring A/B, prefill A/B, the paged 3-column table and
    the dispatch thresholds it derives, the spec-decode off/on A/B, and
    each section's run status) duplicated out of `detail` so the capture
    harness's `parsed` field carries them even when the full detail blob
    is huge or the tail is truncated.  Always emits every key in
    ``SUMMARY_REQUIRED_KEYS`` and always round-trips ``json.dumps`` —
    the tier-1 schema test pins both."""

    def _gen_summary(g):
        if not isinstance(g, dict):
            return None
        return {
            "prefill_toks_per_sec": g.get("prefill_toks_per_sec"),
            "decode_toks_per_sec": g.get("decode_toks_per_sec"),
            "engine_over_jit": g.get("engine_over_jit"),
            "decode_split": g.get("decode_split"),
        }

    return {
        "pipeline_depth": pipeline_depth,
        "decode": {k: _gen_summary(v) for k, v in (gen or {}).items()},
        "ring_ab": (gen.get("b32") or {}).get("ring_ab")
        if isinstance((gen or {}).get("b32"), dict)
        else None,
        "prefill_ab": prefill_ab,
        "prefix_cache_ab": prefix_cache_ab,
        "prefix_cache_hier": prefix_cache_hier,
        "kv_fabric_ab": kv_fabric_ab,
        "kv_quant_ab": kv_quant_ab,
        "weight_quant_ab": weight_quant_ab,
        "trace_overhead_ab": trace_overhead_ab,
        "obs_ledger_report": obs_ledger_report,
        "spec_decode_ab": spec_decode_ab,
        "slo_report": slo_report,
        "pd_disagg_ab": pd_disagg_ab,
        "gateway_ab": gateway_ab,
        "control_plane_ab": control_plane_ab,
        "sharded_serving": sharded_serving,
        "weight_swap_ab": weight_swap_ab,
        "train_packing_ab": train_packing_ab,
        "paged_decode_ab": (
            {
                k: [
                    row.get("dense_toks_per_sec"),
                    row.get("paged_toks_per_sec"),
                    row.get("paged_deep_toks_per_sec"),
                ]
                for k, row in decode_ab.items()
                if isinstance(row, dict) and k.startswith("ctx")
            }
            if isinstance(decode_ab, dict)
            else None
        ),
        "dispatch_table": (
            decode_ab.get("derived_dispatch_table")
            if isinstance(decode_ab, dict)
            else None
        ),
        "sections": dict(_SECTION_STATUS),
    }


def bench_decode_ab(cfg15, params15, cases=None, page=1024, chunk=64,
                    capacity_case=True):
    """Paged vs bucketed-dense decode at the recipe's context regime
    (2k/8k/16k/32k, Qwen2.5-1.5B architecture) — chunk-level A/B of the
    exact jitted functions the serving engine dispatches, over synthetic
    KV (decode throughput does not depend on KV values).  Each timed
    chunk routes its sampled tokens through the host (the engine's real
    pattern; it also defeats the axon tunnel's lazy-execution memo).

    Also reports the CAPACITY row: at the reference recipe's 31k max gen
    len, a dense cache must reserve kv_cache_len per row
    (16 rows x 32k x 28 KB/token = 14.7 GB — over v5e HBM before the
    3.1 GB of weights), while the paged pool allocates only the tokens
    rows actually hold: 16 concurrent 16k-token rows run here."""
    import jax
    import jax.numpy as jnp

    from areal_tpu.models import paged
    from areal_tpu.models.transformer import KVCache, decode_chunk

    W = chunk
    BS = page

    def greedy(logits, _rng):
        return (
            jnp.argmax(logits, -1).astype(jnp.int32),
            jnp.max(jax.nn.log_softmax(logits), -1),
        )

    def no_stop(toks):
        return jnp.zeros_like(toks, bool)

    def bucket(n):
        p = 256
        while p < n:
            p <<= 1
        return p

    dense_jit = jax.jit(
        decode_chunk,
        static_argnames=(
            "cfg", "chunk_size", "sample_fn", "stop_fn", "attn_len"
        ),
        donate_argnums=(2,),
    )
    Hkv, hd = cfg15.n_kv_heads, cfg15.head_dim
    kv_bytes_per_tok = cfg15.n_layers * Hkv * hd * 2 * 2

    def run_dense(L, B):
        # the ENGINE right-sizes its cache to the workload
        # (bench_gen_cache_len), so dense reads L + slack, not pow2(L)
        S = -(-(L + 2 * W + 8) // 256) * 256
        key = jax.random.PRNGKey(0)
        kd = jax.random.normal(
            key, (cfg15.n_layers, B, Hkv, S, hd), jnp.bfloat16
        ) * 0.05
        cache = KVCache(
            k=kd, v=kd + 0.0, lengths=jnp.full((B,), L, jnp.int32)
        )
        cur = jnp.full((B,), 7, jnp.int32)
        active = jnp.ones((B,), bool)
        budgets = jnp.full((B,), 10_000, jnp.int32)
        rng = jax.random.PRNGKey(1)
        times, cur_h = [], cur
        for _ in range(4):
            t0 = time.perf_counter()
            cache, out_t, _, _, _, _, budgets, rng = dense_jit(
                params15, cfg15, cache, cur_h, active,
                budgets, rng, chunk_size=W, sample_fn=greedy,
                stop_fn=no_stop, attn_len=S,
            )
            cur_h = jnp.asarray(np.asarray(out_t[:, -1]))
            times.append(time.perf_counter() - t0)
        del cache, kd
        return B * W / min(times[2:])

    def run_paged(L, B, kv_cache_len=None, deep=False):
        S = bucket(L + 2 * W + 8)
        MB = -(-(kv_cache_len or S) // BS)
        used = -(-(L + 2 * W + 8) // BS)
        NB = B * used + 2  # pool sized by ACTUAL tokens, not reservation
        key = jax.random.PRNGKey(0)
        kp = jax.random.normal(
            key, (cfg15.n_layers, NB, Hkv, BS, hd), jnp.bfloat16
        ) * 0.05
        vp = kp + 0.0
        tables = np.zeros((B, MB), np.int32)
        for b in range(B):
            tables[b, :used] = np.arange(b * used, (b + 1) * used)
        tables = jnp.asarray(tables)
        lengths = jnp.full((B,), L, jnp.int32)
        cur = jnp.full((B,), 7, jnp.int32)
        active = jnp.ones((B,), bool)
        budgets = jnp.full((B,), 10_000, jnp.int32)
        rng = jax.random.PRNGKey(1)
        times, cur_h = [], cur
        for _ in range(4):
            t0 = time.perf_counter()
            (kp, vp, lengths, out_t, _, _, _, active, budgets, rng) = (
                paged.paged_decode_chunk(
                    params15, kp, vp, cfg15, tables, lengths, cur_h,
                    active, budgets, rng, W, greedy, no_stop,
                    use_kernel=True, max_len=(kv_cache_len or S),
                    deep_kernel=deep,
                )
            )
            cur_h = jnp.asarray(np.asarray(out_t[:, -1]))
            times.append(time.perf_counter() - t0)
        del kp, vp
        return B * W / min(times[2:])

    def safe(fn, *a, **kw):
        try:
            return fn(*a, **kw)
        except Exception as e:  # noqa: BLE001 - OOM rows are DATA here
            if "memory" in str(e).lower() or "hbm" in str(e).lower():
                return None
            raise

    rows = {}
    measured = {}
    for L, B in (cases or ((2048, 16), (8192, 16), (16384, 16), (32768, 8))):
        d = safe(run_dense, L, B)
        p = safe(run_paged, L, B)
        # the manual-DMA-ring "deep" kernel is the UNCONDITIONAL third
        # column: it shipped OFF-by-default for two rounds with no hardware
        # numbers, so every default row now records dense vs paged vs deep
        # side by side (each deep cell is a fresh ~30-40s compile — that is
        # the price of finally measuring it)
        pd = safe(run_paged, L, B, deep=True)
        row = {
            "dense_toks_per_sec": round(d, 1) if d else "OOM",
            "paged_toks_per_sec": round(p, 1) if p else "OOM",
            "paged_deep_toks_per_sec": round(pd, 1) if pd else "OOM",
            "paged_over_dense": round(p / d, 3) if (p and d) else None,
            "deep_over_dense": round(pd / d, 3) if (pd and d) else None,
        }
        rows[f"ctx{L}_b{B}"] = row
        measured[L] = {"dense": d, "paged": p, "deep": pd}
    # turn the 3-column A/B into the thresholds cache_mode="auto" should
    # dispatch on; recipe configs pin these once a hardware round fills
    # them in (GenServerConfig.paged_min_cache_len / deep_kernel_min_context)
    from areal_tpu.engine.dispatch import derive_dispatch_table

    rows["derived_dispatch_table"] = derive_dispatch_table(
        measured
    ).as_dict()
    if capacity_case:
        # CAPACITY: the recipe regime — kv_cache_len 32768 (31k max gen
        # len), 16 concurrent rows actually holding 16k tokens.  Dense
        # must reserve B x kv_cache_len; paged allocates B x actual.
        dense_reserved_gb = 16 * 32768 * kv_bytes_per_tok / 2**30
        p_cap = safe(run_paged, 16384, 16, kv_cache_len=32768)
        rows["capacity_16x16k_at_32k_reservation"] = {
            "paged_toks_per_sec": round(p_cap, 1) if p_cap else "OOM",
            "paged_pool_gb": round(
                16 * (16384 + 136) * kv_bytes_per_tok / 2**30, 2
            ),
            "dense_reserved_gb": round(dense_reserved_gb, 2),
            "dense_fits_v5e": dense_reserved_gb + 3.1 < 15.75,
        }
    return rows


def bench_chunked_prefill(
    cfg, gen_params, long_len=15 * 1024, kv_len=16384,
    prefill_chunk=1024, page=1024, short_new=3000, short_prompt=128,
):
    """Decode-stall A/B during a LONG-prompt admission (round-4 verdict
    #2): 8 short rows decode continuously; a 15k-token prompt arrives.
    The dense engine prefills the whole wave in one call (decode stalls
    for its duration); the paged engine admits it in
    ``prefill_chunk_tokens`` chunks interleaved with decode chunks, so
    the longest decode gap is ~one chunk's prefill.  Reported: the max
    inter-step wall gap observed by the short rows after the long
    admission, per mode."""
    from areal_tpu.api.model_api import (
        APIGenerateInput,
        GenerationHyperparameters,
    )
    from areal_tpu.engine.inference_server import ContinuousBatchingEngine

    rng = np.random.default_rng(5)
    long_prompt = rng.integers(0, cfg.vocab_size, (long_len,)).tolist()

    def run(mode):
        eng = ContinuousBatchingEngine(
            cfg,
            gen_params,
            max_batch=10,
            kv_cache_len=kv_len,
            chunk_size=64,
            cache_mode=mode,
            page_size=page,
            prefill_chunk_tokens=prefill_chunk,
        )
        for i in range(8):
            ids = rng.integers(0, cfg.vocab_size, (short_prompt,)).tolist()
            eng.submit(
                APIGenerateInput(
                    qid=f"s{mode}{i}", prompt_ids=ids, input_ids=ids,
                    gconfig=GenerationHyperparameters(
                        max_new_tokens=short_new, temperature=1.0
                    ),
                )
            )
        # warm the decode path, then the LONG admission path (compile)
        for _ in range(4):
            eng.step()
        warm = rng.integers(0, cfg.vocab_size, (long_len,)).tolist()
        eng.submit(
            APIGenerateInput(
                qid=f"w{mode}", prompt_ids=warm, input_ids=warm,
                gconfig=GenerationHyperparameters(
                    max_new_tokens=4, temperature=1.0
                ),
            )
        )
        while eng.try_get_result(f"w{mode}") is None:
            eng.step()
        for _ in range(3):
            eng.step()
        # timed: submit the long prompt, watch per-step gaps until it
        # finishes admission + its first tokens
        eng.submit(
            APIGenerateInput(
                qid=f"L{mode}", prompt_ids=long_prompt,
                input_ids=long_prompt,
                gconfig=GenerationHyperparameters(
                    max_new_tokens=4, temperature=1.0
                ),
            )
        )
        gaps = []
        t_prev = time.perf_counter()
        for _ in range(400):
            eng.step()
            now = time.perf_counter()
            gaps.append(now - t_prev)
            t_prev = now
            if eng.try_get_result(f"L{mode}") is not None:
                break
        eng.pause()
        eng.drain_results()
        return max(gaps)

    stall_paged = run("paged")
    stall_dense = run("dense")
    return {
        "long_prompt_tokens": long_len,
        "decode_stall_dense_s": round(stall_dense, 3),
        "decode_stall_paged_chunked_s": round(stall_paged, 3),
        "stall_reduction": round(stall_dense / max(stall_paged, 1e-9), 2),
    }


# {remat_policy x moment-dtype} sweep cells (the train-MFU levers).
# Moment presets map to OptimizerConfig fields; policies are the graduated
# remat presets (areal_tpu/models/remat.py).
MOMENT_PRESETS = {
    "fp32": {},
    "bf16_mu": {"mu_dtype": "bfloat16"},
    "bf16_mu_nu": {"mu_dtype": "bfloat16", "nu_dtype": "bfloat16"},
    "factored": {"mu_dtype": "bfloat16", "factored_second_moment": True},
}

DEFAULT_SWEEP_CELLS = (
    ("none", "fp32"),  # rounds 1-5 baseline configuration
    ("none", "bf16_mu"),
    ("offload_qkv", "bf16_mu"),
    ("attn_out", "bf16_mu"),
    ("mlp", "bf16_mu"),
    ("qkv_attn", "bf16_mu"),
    ("attn_out", "bf16_mu_nu"),
    ("attn_out", "factored"),
)


def bench_train_packing_ab(
    cfg,
    n_seqs=64,
    len_range=(64, 8192),
    sigma=1.0,
    max_tokens_per_mb=16384,
    timed_steps=2,
    seed=0,
    lr=1e-5,
):
    """Sequence-packing A/B on a long-tail RL-shaped workload: per-row
    padded vs FFD segment-packed train steps (engine ``pack_sequences``).

    RL response lengths are long-tail by nature — one 8k reasoning trace
    in a batch of mostly-short rows pads the whole padded [n, B, T] stack
    to T=8192.  Lengths are lognormal (median ~4x the floor) clipped to
    ``len_range``; both arms run the SAME sample and token budget through
    TrainEngine.train_batch (sft loss), so the reported padded-slot count,
    padding fraction, tok/s, and MFU isolate the batch layout.  The two
    arms' first-step losses must agree (same objective, different layout)
    — reported as ``loss_parity_abs``.  CPU-smoke capable at tiny shapes;
    tok/s and MFU are data for the TPU re-run."""
    import gc

    import jax

    from areal_tpu.api.data import MicroBatchSpec, SequenceSample
    from areal_tpu.base.topology import MeshSpec
    from areal_tpu.engine.optimizer import OptimizerConfig
    from areal_tpu.engine.train_engine import TrainEngine
    from areal_tpu.interfaces.sft_interface import sft_loss_fn
    from areal_tpu.models import transformer

    lmin, lmax = len_range
    rng = np.random.default_rng(seed)
    lens = np.clip(
        np.round(np.exp(rng.normal(np.log(lmin * 4.0), sigma, n_seqs))),
        lmin,
        lmax,
    ).astype(int)
    total_tokens = int(lens.sum())
    sample = SequenceSample.from_default(
        seqlens=lens.tolist(),
        ids=[f"p{i}" for i in range(n_seqs)],
        data={
            "packed_input_ids": rng.integers(
                0, cfg.vocab_size, (total_tokens,)
            ).astype(np.int64),
            "prompt_mask": np.zeros((total_tokens,), bool),
        },
    )
    mb_spec = MicroBatchSpec(max_tokens_per_mb=max_tokens_per_mb)
    peak_tf = peak_flops(jax.devices()[0]) / 1e12

    def run_arm(pack):
        # arms run SEQUENTIALLY and free their engine: two resident
        # 0.5B fp32-adam states would not share a v5e with the other
        # sections' remnants
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        mesh = MeshSpec().make_mesh(jax.devices()[:1])
        eng = TrainEngine(
            cfg,
            mesh,
            params,
            optimizer_cfg=OptimizerConfig(lr=lr),
            total_train_steps=100,
            pack_sequences=pack,
        )
        first = eng.train_batch(sample, sft_loss_fn, mb_spec)  # compile
        eng.train_batch(sample, sft_loss_fn, mb_spec)  # donation settles
        t0 = time.perf_counter()
        for _ in range(timed_steps):
            eng.train_batch(sample, sft_loss_fn, mb_spec)
        dt = (time.perf_counter() - t0) / timed_steps
        tps = total_tokens / dt
        row = {
            "padded_slots": eng.last_padded_slots,
            "padding_frac": round(eng.last_padding_frac, 4),
            "toks_per_sec": round(tps, 1),
            "tok_per_sec_per_tflop": round(tps / peak_tf, 3),
            "first_step_loss": round(float(first["loss"]), 6),
            "n_mbs": first["n_mbs"],
        }
        if eng.last_mfu > 0:
            row["mfu"] = round(eng.last_mfu, 4)
        del eng, params
        gc.collect()
        return row

    padded = run_arm(False)
    packed = run_arm(True)
    return {
        "workload": {
            "n_seqs": n_seqs,
            "total_tokens": total_tokens,
            "len_min": int(lens.min()),
            "len_p50": int(np.median(lens)),
            "len_max": int(lens.max()),
            "max_tokens_per_mb": max_tokens_per_mb,
        },
        "padded": padded,
        "packed": packed,
        "padded_slots_ratio": round(
            padded["padded_slots"] / max(packed["padded_slots"], 1), 2
        ),
        "toks_per_sec_speedup": round(
            packed["toks_per_sec"] / max(padded["toks_per_sec"], 1e-9), 3
        ),
        "loss_parity_abs": round(
            abs(padded["first_step_loss"] - packed["first_step_loss"]), 6
        ),
    }


def bench_train_sweep(
    cfg_base,
    seq_len,
    n_seqs,
    dev,
    timed_steps=2,
    cells=DEFAULT_SWEEP_CELLS,
    hbm_gb=None,
    lr=1e-5,
    progress=None,
):
    """Train-step sweep over {remat_policy x moment dtype} at the standard
    bench batch: per cell, AOT-compile the full fused step (grad + clip +
    adamw apply; areal_tpu/models/remat.py ``compile_train_step``), read
    XLA's memory analysis, and — when the accounting says it fits — run
    timed steps.  Reported per cell: tok/s, tok/s/TFLOP, peak temp
    allocation, argument bytes, optimizer-state bytes, and ``fits_hbm``.

    This turns "fits v5e at the bench batch" into a MEASURED property per
    preset instead of an OOM crash (``qkv_attn`` at fp32 moments measured
    17.0G vs 15.75G in r4): cells whose memory analysis exceeds the budget
    are reported with their numbers and skipped for timing, so the sweep
    always completes.  CPU-validatable at tiny shapes
    (tests/engine/test_bench_sweep.py)."""
    import dataclasses

    import jax
    import jax.numpy as jnp

    from areal_tpu.engine.optimizer import (
        OptimizerConfig,
        make_optimizer,
        opt_state_bytes,
    )
    from areal_tpu.models import remat, transformer

    if hbm_gb is None:
        try:
            stats = dev.memory_stats() or {}
        except Exception:  # noqa: BLE001 - CPU/older runtimes have none
            stats = {}
        hbm_gb = stats.get("bytes_limit", 0) / 2**30 or None
    peak_tf = peak_flops(dev) / 1e12
    rng = np.random.default_rng(3)
    batch = {
        "tokens": jnp.asarray(
            rng.integers(0, cfg_base.vocab_size, (n_seqs, seq_len)),
            jnp.int32,
        ),
        "positions": jnp.tile(
            jnp.arange(seq_len, dtype=jnp.int32), (n_seqs, 1)
        ),
        "seg_ids": jnp.ones((n_seqs, seq_len), jnp.int32),
        "prompt_mask": jnp.zeros((n_seqs, seq_len), bool),
    }
    tokens_per_step = n_seqs * seq_len

    def run_cell(policy, moment):
        cfg = dataclasses.replace(cfg_base, remat=True, remat_policy=policy)
        ocfg = OptimizerConfig(lr=lr, **MOMENT_PRESETS[moment])
        compiled, _ = remat.compile_train_step(
            cfg, ocfg, n_seqs=n_seqs, seq_len=seq_len
        )
        mem = remat.memory_summary(compiled) or {}
        row = {k: round(v, 6) for k, v in mem.items()}
        need_gb = mem.get("peak_temp_gb", 0.0) + mem.get("argument_gb", 0.0)
        # no analysis -> fitness UNKNOWN (None), never a measured-looking
        # True; the cell still runs, guarded by the caller's _section
        fits = (
            None
            if hbm_gb is None or not mem
            else bool(need_gb < hbm_gb)
        )
        row["fits_hbm"] = fits
        if fits is False:
            # the memory analysis IS the result: report why this cell
            # cannot run instead of crashing the chip on it
            row["skipped"] = f"needs {need_gb:.2f} GB of {hbm_gb:.2f}"
            return row
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        tx = make_optimizer(ocfg, 100)
        opt_state = jax.jit(tx.init)(params)
        row["opt_state_mb"] = round(opt_state_bytes(opt_state) / 2**20, 3)
        p, o = params, opt_state
        p, o, loss = compiled(p, o, batch)  # warmup (donation settles)
        float(loss)
        t0 = time.perf_counter()
        for _ in range(timed_steps):
            p, o, loss = compiled(p, o, batch)
        final_loss = float(loss)  # forces the whole timed chain
        dt = (time.perf_counter() - t0) / timed_steps
        tps = tokens_per_step / dt
        row["toks_per_sec"] = round(tps, 1)
        row["tok_per_sec_per_tflop"] = round(tps / peak_tf, 3)
        row["loss"] = round(final_loss, 4)
        del p, o, params, opt_state
        return row

    out = {"seq_len": seq_len, "n_seqs": n_seqs, "hbm_gb": hbm_gb}
    for policy, moment in cells:
        if progress:
            progress(f"train sweep: {policy} x {moment}")
        out[f"{policy}|{moment}"] = _section(run_cell, policy, moment)
    return out


def qwen25_15b_config():
    """The true Qwen2.5-1.5B architecture (hidden 1536, 28 layers, GQA
    12q/2kv, head 128, inter 8960, vocab 151936, tied embedding) — random
    weights; the HF importer is logit-parity-tested separately."""
    from areal_tpu.models.config import TransformerConfig

    return TransformerConfig(
        n_layers=28,
        hidden_dim=1536,
        n_q_heads=12,
        n_kv_heads=2,
        head_dim=128,
        intermediate_dim=8960,
        vocab_size=151936,
        max_position_embeddings=32768,
        use_attention_bias=True,
        tied_embedding=True,
        dtype="bfloat16",
    )


def main():
    import sys

    import jax
    import jax.numpy as jnp

    _t0 = time.perf_counter()

    def mark(msg):
        print(f"[bench {time.perf_counter() - _t0:5.0f}s] {msg}",
              file=sys.stderr, flush=True)

    from areal_tpu.api.data import MicroBatchSpec, SequenceSample
    from areal_tpu.base.topology import MeshSpec
    from areal_tpu.engine.optimizer import OptimizerConfig
    from areal_tpu.engine.train_engine import TrainEngine
    from areal_tpu.interfaces.sft_interface import sft_loss_fn
    from areal_tpu.models import transformer
    from areal_tpu.models.config import TransformerConfig

    devs = _probe_devices()
    if devs is None:
        return  # structured error record already emitted; exit rc=0
    dev = devs[0]
    on_tpu = dev.platform == "tpu"

    if on_tpu:
        # ~0.5B dense model (largest that fits v5e 16G with fp32 adam
        # states).  head_dim=128 fills the TPU's 128-lane tiles.
        cfg = TransformerConfig(
            n_layers=24,
            hidden_dim=1024,
            n_q_heads=8,
            n_kv_heads=4,
            head_dim=128,
            intermediate_dim=5504,
            vocab_size=32768,
            max_position_embeddings=4096,
            use_attention_bias=True,
            dtype="bfloat16",
            remat=True,
        )
        seq_len, n_seqs, timed_steps = 2048, 16, 3
        # b64 is back (dropped in r6 for wall budget): the round-7
        # acceptance bar is engine decode >= 0.9x the isolated jit loop
        # AT B=64, so both batches report engine_over_jit
        gen_batches = (32, 64)
    else:
        cfg = TransformerConfig(
            n_layers=4,
            hidden_dim=256,
            n_q_heads=4,
            n_kv_heads=2,
            head_dim=64,
            intermediate_dim=1024,
            vocab_size=2048,
            max_position_embeddings=1024,
            dtype="float32",
        )
        seq_len, n_seqs, timed_steps = 512, 4, 2
        gen_batches = (2,)

    # fp32 master weights; the model casts to bf16 at use (MXU compute),
    # adam states stay fp32.
    params = transformer.init_params(cfg, jax.random.PRNGKey(0))
    n_params = param_count(params)
    # independent bf16 copy for generation (train engine donates its params)
    gen_params = jax.tree.map(lambda x: x.astype(jnp.bfloat16), params)

    mesh = MeshSpec().make_mesh(jax.devices()[:1])
    engine = TrainEngine(
        cfg,
        mesh,
        params,
        optimizer_cfg=OptimizerConfig(lr=1e-5),
        total_train_steps=100,
    )

    rng = np.random.default_rng(0)
    tokens_per_step = n_seqs * seq_len
    sample = SequenceSample.from_default(
        seqlens=[seq_len] * n_seqs,
        ids=list(range(n_seqs)),
        data={
            "packed_input_ids": rng.integers(
                0, cfg.vocab_size, (tokens_per_step,)
            ).astype(np.int64),
            "prompt_mask": np.zeros((tokens_per_step,), bool),
        },
    )
    mb_spec = MicroBatchSpec(n_mbs=1)

    def time_train(s, toks):
        """tok/s of engine.train_batch on sample ``s`` (two warmups: first
        compiles, second lets buffer donation settle)."""
        engine.train_batch(s, sft_loss_fn, mb_spec)
        engine.train_batch(s, sft_loss_fn, mb_spec)
        t0 = time.perf_counter()
        for _ in range(timed_steps):
            engine.train_batch(s, sft_loss_fn, mb_spec)
        return toks / ((time.perf_counter() - t0) / timed_steps)

    def mfu_attn(tps, T):
        # attention-corrected MFU; causal self-attention fwd+bwd adds
        # 12 * L * Hq * hd * (T/2) FLOPs/token to the 6N param term
        attn = 12 * cfg.n_layers * cfg.n_q_heads * cfg.head_dim * (T / 2)
        return tps * (6 * n_params + attn) / peak_flops(dev)

    mark("train 2k")
    train_toks_per_sec = time_train(sample, tokens_per_step)
    mfu = train_toks_per_sec * 6 * n_params / peak_flops(dev)

    # long-context train step (the reference's recipe runs 32k ctx;
    # attention-CORRECTED MFU is the honest long-T efficiency number —
    # param-only MFU mechanically decays as the quadratic term grows)
    mark("train 8k")
    train_long = None
    if on_tpu:
        T_long, n_long = 8192, 4
        s_long = SequenceSample.from_default(
            seqlens=[T_long] * n_long,
            ids=list(range(n_long)),
            data={
                "packed_input_ids": rng.integers(
                    0, cfg.vocab_size, (T_long * n_long,)
                ).astype(np.int64),
                "prompt_mask": np.zeros((T_long * n_long,), bool),
            },
        )
        tps_long = time_train(s_long, T_long * n_long)
        train_long = {
            "seq_len": T_long,
            "n_seqs": n_long,
            "toks_per_sec": round(tps_long, 1),
            "mfu_attn_corrected": round(mfu_attn(tps_long, T_long), 4),
        }

    # generation throughput at 0.5B, batch sweep (tiny shapes off-TPU:
    # a CPU smoke run needs signal, not 512-token decode waves).  The
    # b32 row carries the pipeline-depth A/B (K=1 unpipelined baseline /
    # K=2 default / K=4 deep queue for the tunnel's RTT regime).
    mark("gen 0.5B")
    gen = {}
    gen_shape = {} if on_tpu else {"prompt_len": 32, "max_new": 16}
    for B in gen_batches:
        gen[f"b{B}"] = _section(
            bench_generation, cfg, gen_params, n_reqs=B,
            ring_ab=(1, 2, 4) if (on_tpu and B == 32) else (),
            jit_ratio=on_tpu,
            name=f"generation_b{B}",
            **gen_shape,
        )

    # admission-prefill A/B: jit ceiling vs dense-engine admit vs paged
    # chunked admit (roots the r5 prefill regression — VERDICT #2)
    mark("prefill A/B")
    prefill_ab = (
        _section(bench_prefill_ab, cfg, gen_params, name="prefill_ab")
        if on_tpu
        else None
    )

    # interruption A/B + update-visibility latency
    mark("interruption")
    interruption = (
        _section(bench_interruption, cfg, gen_params, name="interruption")
        if on_tpu
        else None
    )

    # group-prompt KV dedup at admission (prefix-reuse A/B)
    mark("prefix reuse")
    prefix_reuse = (
        _section(bench_prefix_reuse, cfg, gen_params, name="prefix_reuse")
        if on_tpu
        else None
    )

    # flight-recorder overhead A/B (off / sampled / always-on decode
    # tok/s).  Runs off-TPU too — tiny shapes — so the summary always
    # carries the overhead number the <2% acceptance bar tracks.
    mark("trace overhead A/B")
    trace_overhead_ab = _section(
        bench_trace_overhead_ab,
        cfg,
        gen_params,
        name="trace_overhead_ab",
        **(
            {}
            if on_tpu
            else dict(n_reqs=2, prompt_len=32, max_new=16, repeats=1)
        ),
    )

    # HBM-ledger + recompile-sentinel report: per-subsystem device-byte
    # attribution, reconciliation verdict, steady-sentinel silence +
    # forced-recompile fire, leak-free close, ledger-on-vs-off tok/s
    # with the <2% overhead bar.  Runs off-TPU too — tiny shapes — so
    # the summary always carries the acceptance numbers (the reconcile
    # verdict is vacuous without memory_stats, as data).
    mark("obs ledger report")
    obs_ledger_report = _section(
        bench_obs_ledger_report,
        cfg,
        gen_params,
        name="obs_ledger_report",
        **(
            {}
            if on_tpu
            else dict(n_reqs=2, prompt_len=32, max_new=16, repeats=1)
        ),
    )

    # cross-request radix prefix cache: multi-turn conversation replay,
    # cache on vs off (cached-token fraction + replay tok/s).  Runs
    # off-TPU too — tiny shapes — so the summary always carries it.
    mark("prefix cache A/B")
    prefix_cache_ab = _section(
        bench_prefix_cache_ab,
        cfg,
        gen_params,
        name="prefix_cache_ab",
        **(
            {}
            if on_tpu
            else dict(
                n_sessions=2, turns=3, prompt_len=32, user_len=8,
                max_new=8, page=16, chunk=32,
            )
        ),
    )

    # hierarchical prefix cache: cached-token-frac vs conversation-count
    # curves with the host spill tier on vs off, on a sweep that
    # overflows the HBM cache.  Runs off-TPU too — tiny shapes — so the
    # summary always carries the curve pair.
    mark("prefix cache hier")
    prefix_cache_hier = _section(
        bench_prefix_cache_hier,
        cfg,
        gen_params,
        name="prefix_cache_hier",
        **(
            {}
            if on_tpu
            else dict(
                counts=(2, 4), turns=2, prompt_len=48, user_len=8,
                max_new=8, page=16, chunk=16, capacity_frac=0.1,
                pool_rows=3,
            )
        ),
    )

    # fleet-wide KV fabric A/B: session-migration replay on a 2-server
    # in-process fleet, cross-server prefix pull on vs off — fleet
    # cached_token_frac, target re-prefill tokens (>=2x reduction bar),
    # pull bytes, greedy parity as data.  Runs off-TPU too — tiny
    # shapes — so the summary always carries the acceptance numbers.
    mark("kv fabric A/B")
    kv_fabric_ab = _section(
        bench_kv_fabric_ab,
        cfg,
        gen_params,
        name="kv_fabric_ab",
        **(
            {}
            if on_tpu
            else dict(
                counts=(2,), turns=2, prompt_len=48, user_len=8,
                max_new=8, page=16, chunk=16,
            )
        ),
    )

    # quantized KV cache A/B: fp vs int8 paged pools at equal budgets —
    # blocks-per-HBM-byte gain, decode tok/s, max rows at a fixed byte
    # budget, prefix-cache cached_token_frac at equal HBM, and the
    # MEASURED greedy divergence rate per workload (the quality gate).
    # Runs off-TPU too — tiny shapes — so the summary always carries the
    # >=1.8x density + quality-bar acceptance numbers.
    mark("kv quant A/B")
    kv_quant_ab = _section(
        bench_kv_quant_ab,
        cfg,
        gen_params,
        name="kv_quant_ab",
        **(
            {}
            if on_tpu
            else dict(
                n_reqs=2, prompt_len=48, max_new=12, page=16, chunk=8,
                turns=2, sessions=3, user_len=8,
            )
        ),
    )

    # quantized serving weights A/B: model-dtype vs int8 + scales param
    # trees — param-HBM reduction, staged-swap bytes/time per format,
    # decode tok/s, fixed-budget capacity with kv-int8 composed, and
    # the MEASURED greedy divergence rate per workload (quality gate).
    # Runs off-TPU too — tiny shapes — so the summary always carries
    # the >=1.8x staged-bytes + quality-bar acceptance numbers.
    mark("weight quant A/B")
    weight_quant_ab = _section(
        bench_weight_quant_ab,
        cfg,
        gen_params,
        name="weight_quant_ab",
        **(
            {}
            if on_tpu
            else dict(
                n_reqs=2, prompt_len=48, max_new=12, page=16, chunk=8,
                turns=2, sessions=3, user_len=8,
            )
        ),
    )

    # request-level SLO report: fleet-merged TTFT/TPOT percentiles under
    # the multi-turn replay + spec-decode workloads, digest-merge
    # cross-check, and the SLO-tracking on/off overhead A/B (<2% bar).
    # Runs off-TPU too — tiny shapes — so the summary always carries it.
    mark("slo report")
    slo_report = _section(
        bench_slo_report,
        cfg,
        gen_params,
        name="slo_report",
        **(
            {}
            if on_tpu
            else dict(
                n_sessions=2, turns=2, prompt_len=32, user_len=8,
                max_new=12, page=16, chunk=4, overhead_reqs=2,
                overhead_prompt=32, overhead_new=16, overhead_repeats=1,
            )
        ),
    )

    # disaggregated prefill/decode A/B: interactive decode stream + long-
    # prompt prefill wave on unified vs 1P+1D split fleets (same hardware
    # both arms) — fleet-merged p99 TTFT/TPOT per workload, handoff
    # count/bytes/latency, greedy parity as data.  Runs off-TPU too —
    # tiny shapes — so the summary always carries the p99-TTFT verdict.
    mark("pd disagg A/B")
    pd_disagg_ab = _section(
        bench_pd_disagg_ab,
        cfg,
        gen_params,
        name="pd_disagg_ab",
        **(
            {}
            if on_tpu
            else dict(
                n_interactive=3, interactive_prompt=32, interactive_new=8,
                turns=2, n_wave=2, wave_prompt=192, wave_new=4,
                page=32, chunk=4, prefill_chunk=64,
            )
        ),
    )
    # heterogeneous-mesh sub-arm: big-mesh (TP) prefill streaming into a
    # single-chip decode engine — parity + TTFT rows as data (off-TPU it
    # runs in a virtual-CPU-mesh child like sharded_serving)
    if isinstance(pd_disagg_ab, dict):
        mark("pd disagg hetero sub-arm")
        pd_disagg_ab["hetero"] = _section(
            bench_pd_disagg_hetero, name="pd_disagg_hetero",
        )

    # serving gateway A/B: interactive SSE burst vs bulk-rollout storm on
    # a 2-engine fleet, tenant admission on vs off — interactive p99 TTFT
    # (strictly-better bar), typed bulk rejects, SSE/rollout token
    # parity, zero-leak audit.  Runs off-TPU too — tiny shapes — so the
    # summary always carries the acceptance verdict.
    mark("gateway A/B")
    gateway_ab = _section(
        bench_gateway_ab,
        cfg,
        gen_params,
        name="gateway_ab",
        **(
            {}
            if on_tpu
            else dict(
                n_bulk=4, n_interactive=4, prompt_len=32, bulk_new=96,
                inter_new=8, page=16, chunk=8, max_batch=2,
            )
        ),
    )

    # control-plane A/B: the manager's batched ROUTER serve loop +
    # O(log N) routing indexes + batched client RPCs vs the strict REP
    # + O(N)-scan + per-request baseline, at 64 registered fake servers
    # under a mixed rollout+gateway storm.  Pure CPU (real ZMQ, no
    # engine), so the summary always carries the >=5x schedules/sec
    # acceptance verdict and the scan-vs-indexed parity bool.
    mark("control plane A/B")
    control_plane_ab = _section(
        bench_control_plane_ab,
        name="control_plane_ab",
    )

    # self-speculative decoding A/B: n-gram draft + batched paged verify
    # on vs off, on a repetitive-trace workload (decode tok/s + accepted
    # tokens per verify step).  Runs off-TPU too — tiny shapes — so the
    # summary always carries the >=1.3x acceptance bar's number.
    mark("spec decode A/B")
    spec_decode_ab = _section(
        bench_spec_decode_ab,
        cfg,
        gen_params,
        name="spec_decode_ab",
        **(
            {}
            if on_tpu
            else dict(
                batches=(2, 4), prompt_len=48, max_new=160, motif_len=12,
                page=32, chunk=16, max_draft=7,
            )
        ),
    )

    # zero-downtime weight sync A/B: staged (stage-while-decoding ->
    # pointer-flip commit) vs legacy full-reload swap — pause-ms, decode
    # dip around the swap, post-swap fresh-replay parity.  Runs off-TPU
    # too (tiny shapes; mesh arm via a virtual-CPU-mesh child) so the
    # summary always carries the acceptance numbers.
    mark("weight swap A/B")
    weight_swap_ab = _section(
        bench_weight_swap_ab,
        name="weight_swap_ab",
        **(
            {}
            if on_tpu
            else dict(
                n_reqs=2, prompt_len=24, max_new=32, page=16, chunk=4,
                repeats=2,
            )
        ),
    )

    # sharded-serving scaling: decode tok/s at 1 vs N chips, dense-TP +
    # moe-EP arms (ROADMAP item 1).  Runs off-TPU too (child process
    # with a virtual CPU mesh) so the summary always carries it.
    mark("sharded serving")
    sharded_n = min(4, len(devs)) if on_tpu else 2
    sharded_serving = _section(
        bench_sharded_serving,
        n_chips=max(2, sharded_n),
        name="sharded_serving",
        **(
            {}
            if on_tpu
            else dict(n_reqs=2, prompt_len=16, max_new=16, page=16, chunk=4)
        ),
    )

    # train->generation weight publish (sharded raw-param checkpoint,
    # inference dtype; reference budget <3 s)
    mark("publish")
    import shutil
    import tempfile

    from areal_tpu.engine.checkpoint import save_params, wait_for_saves

    # memory-backed dir when available: the CO-HOSTED publish path is a
    # direct device transfer with no disk at all (model_worker._param_realloc),
    # and the reference's <3 s figure is NCCL+GDRDMA, also diskless — this
    # host's ~80 MB/s scratch disk would measure the wrong thing.  The
    # detail still reports it as "commit" (serialize + durable write).
    pub_root = "/dev/shm" if os.path.isdir("/dev/shm") else None
    pub_dir = tempfile.mkdtemp(prefix="areal-bench-pub-", dir=pub_root)
    try:
        save_params(gen_params, pub_dir + "/v0", cast_dtype="bfloat16")
        t0 = time.perf_counter()
        save_params(
            gen_params, pub_dir + "/v1", cast_dtype="bfloat16", wait=False
        )
        publish_block_s = time.perf_counter() - t0  # trainer stall
        wait_for_saves()
        publish_commit_s = time.perf_counter() - t0  # durable + advertised
    finally:
        shutil.rmtree(pub_dir, ignore_errors=True)

    # device->host SINGLE-STREAM link bandwidth: the commit time above is
    # fetch-bound, not disk-bound, when the chip sits behind a slow
    # tunnel (a remote v5e fetches ~1 GB of bf16 params at link speed;
    # a local TPU host does this over PCIe/DMA at GB/s).  Orbax fetches
    # leaves concurrently, so commit throughput ~ n_streams x this.
    big = jax.device_put(  # 64 MiB of incompressible bytes: an all-zeros
        # payload would let transport compression serve the fetch for free
        np.random.default_rng(7)
        .standard_normal((32, 1024, 1024))
        .astype(np.float16)
    )
    scale = jax.jit(lambda x, c: x * c)
    np.asarray(scale(big, jnp.float16(2)))  # compile + warm the path
    t0 = time.perf_counter()
    # same compiled fn, FRESH output buffer: the timed fetch pays only
    # exec + transfer (a repeat fetch of one buffer can hit a host-side
    # cache; a fresh expression re-pays compile under the lazy tunnel)
    np.asarray(scale(big, jnp.float16(3)))
    d2h_gbps = (64 / 1024) / max(time.perf_counter() - t0, 1e-9)

    # effective RL step on one chip AT THE RECIPE REGIME: ~8k-token
    # sequences (prompt 7.5k + 512 generated), gen + train sharing the
    # chip.  The reference baseline below was derived ASSUMING a mean
    # sequence of 8000 tokens — at 8k our sequences match the assumption
    # instead of flattering it (round-4 verdict #3; the old 1k-token row
    # divided by an 8k-denominated baseline).  The 1.5B-arch train state
    # (fp32 adam, 21 GB) exceeds one v5e; the recipe trains it on an
    # 8-chip FSDP mesh (dryrun-validated) — this row keeps the 0.5B
    # model, whose tok/s/TFLOP normalization is size-comparable.
    mark("effective 8k")
    B_eff, new_eff = (8, 512) if on_tpu else (2, 16)
    prompt_eff = 7680 if on_tpu else 32
    eng = make_engine(cfg, gen_params, B_eff, prompt_eff, new_eff)
    submit_wave(eng, cfg, B_eff, prompt_eff, new_eff, "we")
    drain(eng)  # warm
    submit_wave(eng, cfg, B_eff, prompt_eff, new_eff, "te")
    t0 = time.perf_counter()
    drain(eng)
    t_gen = time.perf_counter() - t0
    eff_seq = prompt_eff + new_eff
    eff_tokens = B_eff * eff_seq
    eff_sample = SequenceSample.from_default(
        seqlens=[eff_seq] * B_eff,
        ids=list(range(B_eff)),
        data={
            "packed_input_ids": rng.integers(
                0, cfg.vocab_size, (eff_tokens,)
            ).astype(np.int64),
            "prompt_mask": np.zeros((eff_tokens,), bool),
        },
    )
    engine.train_batch(eff_sample, sft_loss_fn, mb_spec)  # compile
    t0 = time.perf_counter()
    engine.train_batch(eff_sample, sft_loss_fn, mb_spec)
    t_train = time.perf_counter() - t0
    effective_tok_s = eff_tokens / (t_gen + t_train)
    ours_per_tflop = effective_tok_s / (peak_flops(dev) / 1e12)
    del eng, engine, params  # free HBM before the 1.5B section

    # sequence-packing A/B: padded vs FFD segment-packed train steps on a
    # long-tail (lognormal) RL-shaped length distribution — padded-slot
    # count, padding fraction, tok/s, MFU per arm.  Runs off-TPU too
    # (tiny shapes) so the summary always carries the >=2x slot-reduction
    # acceptance number; each arm builds and frees its own engine.
    mark("train packing A/B")
    train_packing_ab = _section(
        bench_train_packing_ab,
        cfg,
        name="train_packing_ab",
        **(
            {}
            if on_tpu
            else dict(
                n_seqs=24,
                len_range=(16, 256),
                max_tokens_per_mb=512,
                timed_steps=1,
            )
        ),
    )

    # chunked-prefill decode-stall A/B (0.5B; the mechanism under test is
    # the engine's admission scheduling, not model-size-dependent)
    mark("chunked prefill")
    chunked_prefill = (
        _section(
            bench_chunked_prefill, cfg, gen_params, name="chunked_prefill"
        )
        if on_tpu
        else None
    )

    # 1.5B architecture (the reference's smallest published scale): the
    # recipe-regime decode A/B (paged vs bucketed-dense at 2k-32k ctx)
    # plus the capacity row.  Init on the HOST CPU and ship straight as
    # bf16 — a device-side fp32 init would spike ~6 GB of HBM next to the
    # other benches' remnants.
    mark("1.5B section")
    gen_15b = None
    decode_ab = None
    if on_tpu:
        import ml_dtypes

        del gen_params
        cfg15 = qwen25_15b_config()
        shapes = jax.eval_shape(
            lambda k: transformer.init_params(cfg15, k),
            jax.random.PRNGKey(1),
        )
        host_rng = np.random.default_rng(1)
        params15 = jax.tree.map(
            lambda s: jax.device_put(
                (0.02 * host_rng.standard_normal(s.shape, dtype=np.float32))
                .astype(ml_dtypes.bfloat16)
            ),
            shapes,
        )
        g15 = _section(
            bench_generation, cfg15, params15, n_reqs=32,
            name="generation_1p5b",
        )
        gen_15b = {**g15, "n_params": param_count(params15)}
        mark("decode A/B")
        decode_ab = _section(
            bench_decode_ab, cfg15, params15, name="decode_ab"
        )
        del params15

    # {remat_policy x moment dtype} train sweep at the bench batch — the
    # MFU-plateau lever set (low-precision optimizer states + graduated
    # remat presets).  Runs LAST: every cell inits fresh 0.5B params +
    # opt state, so it needs the HBM the other sections have released.
    mark("train sweep")
    sweep_cells = (
        DEFAULT_SWEEP_CELLS
        if on_tpu
        else (  # CPU smoke: one cell per mechanism
            ("none", "fp32"),
            ("attn_out", "bf16_mu"),
            ("attn_out", "factored"),
        )
    )
    train_sweep = _section(
        bench_train_sweep,
        cfg,
        seq_len,
        n_seqs,
        dev,
        cells=sweep_cells,
        progress=mark,
        name="train_sweep",
        timeout_s=1800.0,  # many per-cell compiles
    )
    mark("done")

    summary = build_summary(
        gen,
        prefill_ab=prefill_ab,
        prefix_cache_ab=prefix_cache_ab,
        prefix_cache_hier=prefix_cache_hier,
        kv_fabric_ab=kv_fabric_ab,
        kv_quant_ab=kv_quant_ab,
        weight_quant_ab=weight_quant_ab,
        trace_overhead_ab=trace_overhead_ab,
        obs_ledger_report=obs_ledger_report,
        spec_decode_ab=spec_decode_ab,
        slo_report=slo_report,
        pd_disagg_ab=pd_disagg_ab,
        gateway_ab=gateway_ab,
        control_plane_ab=control_plane_ab,
        sharded_serving=sharded_serving,
        weight_swap_ab=weight_swap_ab,
        train_packing_ab=train_packing_ab,
        decode_ab=decode_ab,
    )

    print(
        json.dumps(
            {
                "metric": "effective_rl_toks_per_sec_per_tflop",
                "value": round(ours_per_tflop, 4),
                "unit": "tok/s per bf16-TFLOP/s (1 chip, sync gen+train)",
                "vs_baseline": round(
                    ours_per_tflop / REF_TOK_PER_SEC_PER_TFLOP, 4
                ),
                "summary": summary,
                "detail": {
                    "device": getattr(dev, "device_kind", dev.platform),
                    "baseline_derivation": {
                        "ref_tok_per_sec_per_tflop": round(
                            REF_TOK_PER_SEC_PER_TFLOP, 4
                        ),
                        "ref_seqs_per_step": REF_SEQS_PER_STEP,
                        "ref_mean_seq_len_ASSUMED": REF_MEAN_SEQ_LEN_ASSUMED,
                        "ref_step_seconds": round(REF_STEP_SECONDS, 2),
                        "ref_n_gpus": REF_N_GPUS,
                        "ref_gpu_peak_tflops": REF_GPU_PEAK_TFLOPS,
                        "caveat": "ours: 8k-token seqs (matching the assumed ref mean) on 1 chip sync; ref: 128-GPU async",
                    },
                    "effective": {
                        "toks_per_sec": round(effective_tok_s, 1),
                        "gen_s": round(t_gen, 3),
                        "train_s": round(t_train, 3),
                        "batch": B_eff,
                        "seq_len": eff_seq,
                        "cache_mode": "paged",
                    },
                    "train_step_mfu": round(mfu, 4),
                    "train_mfu_attn_corrected": round(
                        mfu_attn(train_toks_per_sec, seq_len), 4
                    ),
                    "train_long_ctx": train_long,
                    "train_packing_ab": train_packing_ab,
                    "train_remat_moment_sweep": train_sweep,
                    "train_toks_per_sec": round(train_toks_per_sec, 1),
                    "n_params": n_params,
                    "weight_publish_block_s": round(publish_block_s, 4),
                    "weight_publish_commit_s": round(publish_commit_s, 3),
                    "d2h_stream_gb_per_s": round(d2h_gbps, 3),
                    "generation_0p5b": gen,
                    "generation_qwen25_1p5b_arch": gen_15b,
                    "decode_paged_vs_dense_1p5b": decode_ab,
                    "prefill_ab": prefill_ab,
                    "chunked_prefill": chunked_prefill,
                    "interruption": interruption,
                    "prefix_reuse": prefix_reuse,
                    "prefix_cache_ab": prefix_cache_ab,
                    "prefix_cache_hier": prefix_cache_hier,
                    "kv_fabric_ab": kv_fabric_ab,
                    "kv_quant_ab": kv_quant_ab,
                    "weight_quant_ab": weight_quant_ab,
                    "trace_overhead_ab": trace_overhead_ab,
                    "spec_decode_ab": spec_decode_ab,
                    "slo_report": slo_report,
                    "pd_disagg_ab": pd_disagg_ab,
                    "gateway_ab": gateway_ab,
                    "control_plane_ab": control_plane_ab,
                    "sharded_serving": sharded_serving,
                },
            }
        )
    )


if __name__ == "__main__":
    import sys as _sys

    if "--sharded-serving-child" in _sys.argv:
        _sharded_serving_child(
            _sys.argv[_sys.argv.index("--sharded-serving-child") + 1]
        )
    elif "--weight-swap-child" in _sys.argv:
        _weight_swap_child(
            _sys.argv[_sys.argv.index("--weight-swap-child") + 1]
        )
    elif "--pd-hetero-child" in _sys.argv:
        _pd_hetero_child(
            _sys.argv[_sys.argv.index("--pd-hetero-child") + 1]
        )
    else:
        main()

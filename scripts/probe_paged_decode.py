"""Probe: paged vs bucketed-dense decode throughput at long context
(Qwen2.5-1.5B architecture, random weights, synthetic KV).

Isolates the decode hot loop from the engine: fills a dense cache and a
paged pool with random KV at context L, then times W-token decode chunks.
Run on the real chip:  python scripts/probe_paged_decode.py [L ...]
"""

import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, ".")
from bench import qwen25_15b_config  # noqa: E402

from areal_tpu.models import paged, transformer  # noqa: E402
from areal_tpu.models.transformer import KVCache, decode_chunk  # noqa: E402

BS = int(__import__("os").environ.get("PROBE_BS", "256"))
W = 64


def greedy(logits, _rng):
    return (
        jnp.argmax(logits, -1).astype(jnp.int32),
        jnp.max(jax.nn.log_softmax(logits), -1),
    )


def no_stop(toks):
    return jnp.zeros_like(toks, bool)


def bucket(n):
    p = 256
    while p < n:
        p <<= 1
    return p


def run(cfg, params, L, B):
    S = bucket(L + 2 * W + 8)
    MB = S // BS
    NB = B * MB + 4
    Hkv, hd = cfg.n_kv_heads, cfg.head_dim
    key = jax.random.PRNGKey(0)
    kd = jax.random.normal(
        key, (cfg.n_layers, B, Hkv, S, hd), jnp.bfloat16
    ) * 0.05
    lengths = jnp.full((B,), L, jnp.int32)
    cache = KVCache(k=kd, v=kd + 0.0, lengths=lengths)  # no alias: donated
    cur = jnp.full((B,), 7, jnp.int32)
    active = jnp.ones((B,), bool)

    dense_jit = jax.jit(
        decode_chunk,
        static_argnames=(
            "cfg", "chunk_size", "sample_fn", "stop_fn", "attn_len"
        ),
        donate_argnums=(2,),
    )

    def dense_round(cache, cur_in, budgets, rng):
        return dense_jit(
            params, cfg, cache, cur_in, active, budgets, rng,
            chunk_size=W, sample_fn=greedy, stop_fn=no_stop, attn_len=S,
        )

    rng = jax.random.PRNGKey(1)
    budgets = jnp.full((B,), 10_000, jnp.int32)
    times = []
    cur_h = cur
    for it in range(5):
        t0 = time.perf_counter()
        cache, out_t, out_l, em, cur2, act2, budgets, rng = dense_round(
            cache, cur_h, budgets, rng
        )
        # host fetch + feedback: the axon tunnel memoizes repeated
        # identical lazy executions; routing the sampled token back
        # through the host (exactly what the engine does) defeats it
        cur_h = jnp.asarray(np.asarray(out_t[:, -1]))
        times.append(time.perf_counter() - t0)
    dense_times = [round(t, 3) for t in times]
    dense_tps = B * W / min(times[2:])
    del cache, kd
    # paged
    kp = jax.random.normal(
        key, (cfg.n_layers, NB, Hkv, BS, hd), jnp.bfloat16
    ) * 0.05
    # distinct buffer: paged_decode_chunk donates BOTH pools (an aliased
    # buffer donated twice is a runtime error)
    vp = kp + 0.0
    tables = jnp.arange(B * MB, dtype=jnp.int32).reshape(B, MB)
    lengths = jnp.full((B,), L, jnp.int32)
    budgets = jnp.full((B,), 10_000, jnp.int32)
    rng = jax.random.PRNGKey(1)
    times = []
    cur_h = cur
    for it in range(5):
        t0 = time.perf_counter()
        (kp, vp, lengths, out_t, out_l, em, cur2, act2, budgets, rng) = (
            paged.paged_decode_chunk(
                params, kp, vp, cfg, tables, lengths, cur_h, active,
                budgets, rng, W, greedy, no_stop,
                use_kernel=True, max_len=S,
            )
        )
        cur_h = jnp.asarray(np.asarray(out_t[:, -1]))
        times.append(time.perf_counter() - t0)
    paged_times = [round(t, 3) for t in times]
    paged_tps = B * W / min(times[2:])
    kv_per_tok = cfg.n_layers * Hkv * hd * 2 * 2
    roofline = 820e9 / (L * kv_per_tok) * B  # HBM-bound bound per chip
    print(
        f"L={L:6d} B={B:3d}: dense {dense_tps:7.1f} tok/s | paged "
        f"{paged_tps:7.1f} tok/s | ratio {paged_tps/dense_tps:5.2f} | "
        f"KV-roofline {roofline:7.0f}"
    )
    print(f"    dense times {dense_times}  paged times {paged_times}")
    del kp, vp
    return dense_tps, paged_tps


def main():
    cfg = qwen25_15b_config()
    params = transformer.init_params(cfg, jax.random.PRNGKey(0))
    params = jax.tree.map(lambda a: a.astype(jnp.bfloat16), params)
    cases = [(2048, 16), (8192, 16), (16384, 16), (32768, 8)]
    if len(sys.argv) > 1:
        want = {int(a) for a in sys.argv[1:]}
        cases = [c for c in cases if c[0] in want]
    for L, B in cases:
        run(cfg, params, L, B)


if __name__ == "__main__":
    main()

"""Sliding-window bounded-decode measurement at long context.

Compares chunked decode throughput on a mistral-flavor 0.5B config at an
~8k-token cache: the window-GATHER path (sliding_window=4096, per-row reads
bounded to the window) vs the dense full-prefix stream (sliding_window=None,
reads the whole 8k+ prefix every step — what windowed models previously did
with masking).  Same model dims, same cache fill; the delta is the KV bytes
streamed per step."""

import json
import time

import numpy as np


def main():
    import jax
    import jax.numpy as jnp

    from areal_tpu.engine.inference_server import _decode_chunk
    from areal_tpu.engine.sampling import SamplingParams
    from areal_tpu.models import transformer
    from areal_tpu.models.config import TransformerConfig
    from areal_tpu.models.transformer import KVCache

    def cfg_for(window):
        return TransformerConfig(
            n_layers=24,
            hidden_dim=1024,
            n_q_heads=8,
            n_kv_heads=4,
            head_dim=128,
            intermediate_dim=5504,
            vocab_size=32768,
            max_position_embeddings=16384,
            use_attention_bias=True,
            dtype="bfloat16",
            sliding_window=window,
        )

    sampling = SamplingParams()
    B, S, fill, chunk = 8, 8576, 8000, 128  # fill + 4*chunk <= S: every
    # timed token really emits (capacity-deactivated rows would inflate tok/s)
    attn_len = 8576
    results = {}
    for name, window in (("window4096_gather", 4096), ("dense_full_prefix", None)):
        cfg = cfg_for(window)
        params = jax.tree.map(
            lambda x: x.astype(jnp.bfloat16),
            transformer.init_params(cfg, jax.random.PRNGKey(0)),
        )
        cache = KVCache.zeros(cfg, B, S, dtype=jnp.bfloat16)
        cache = KVCache(
            k=cache.k, v=cache.v,
            lengths=jnp.full((B,), fill, jnp.int32),
        )
        cur = jnp.ones((B,), jnp.int32)
        active = jnp.ones((B,), bool)
        budgets = jnp.full((B,), 10_000, jnp.int32)
        rng = jax.random.PRNGKey(1)
        out = _decode_chunk(
            params, cfg, cache, cur, active, budgets,
            jnp.zeros((B,), jnp.int32), rng, chunk, (),
            sampling, attn_len=attn_len,
        )
        cache, out_t, out_l, em, cur, active, budgets, rng = out
        jax.device_get((out_t, active))  # compile + settle
        t0 = time.perf_counter()
        n = 0
        N = 3
        for _ in range(N):
            out = _decode_chunk(
                params, cfg, cache, cur, active, budgets,
                jnp.zeros((B,), jnp.int32), rng, chunk, (),
                sampling, attn_len=attn_len,
            )
            cache, out_t, out_l, em, cur, active, budgets, rng = out
            # immediate fetch bounds live cache generations under lazy
            # execution (OOM guard); also counts what really emitted
            n += int(jax.device_get(em).sum())
        dt = time.perf_counter() - t0
        results[name] = round(n / dt, 1)
        print(json.dumps({name: results[name],
                          "ms_per_step": round(dt / N / chunk * 1e3, 3)}),
              flush=True)
        del params, cache
    results["speedup"] = round(
        results["window4096_gather"] / results["dense_full_prefix"], 3
    )
    print(json.dumps(results))


if __name__ == "__main__":
    main()

"""On-chip decode profiling: where does the missing roofline half go?

Times the jitted decode_chunk in isolation (device-only, no engine host
loop) across batch x attn_len, plus ablations (no-head sampling, bigger
chunks), and compares against the engine's end-to-end loop.  Prints one
JSON line per measurement.  Run with the real TPU visible (no JAX_PLATFORMS
override).
"""

from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp
import numpy as np


def bench_cfg():
    from areal_tpu.models.config import TransformerConfig

    return TransformerConfig(
        n_layers=24,
        hidden_dim=1024,
        n_q_heads=8,
        n_kv_heads=4,
        head_dim=128,
        intermediate_dim=5504,
        vocab_size=32768,
        max_position_embeddings=4096,
        use_attention_bias=True,
        dtype="bfloat16",
    )


def main():
    from functools import partial

    from areal_tpu.engine.sampling import SamplingParams, sample_logits
    from areal_tpu.models import transformer
    from areal_tpu.models.transformer import KVCache, decode_chunk

    cfg = bench_cfg()
    params = jax.tree.map(
        lambda x: x.astype(jnp.bfloat16),
        transformer.init_params(cfg, jax.random.PRNGKey(0)),
    )
    n_params = sum(int(np.prod(x.shape)) for x in jax.tree.leaves(params))
    sampling = SamplingParams()
    dev = jax.devices()[0]
    print(json.dumps({"device": str(dev), "n_params": n_params}))

    def sample_fn(logits, rng):
        return sample_logits(logits, rng, sampling)

    def stop_fn(tok):
        return jnp.zeros_like(tok, dtype=bool)

    @partial(jax.jit, static_argnames=("B", "S", "chunk", "attn_len"))
    def run_chunk(params, cache_len_fill, rng, B, S, chunk, attn_len):
        cache = KVCache.zeros(cfg, B, S, dtype=jnp.bfloat16)
        cache = KVCache(
            k=cache.k, v=cache.v,
            lengths=jnp.full((B,), cache_len_fill, jnp.int32),
        )
        cur = jnp.ones((B,), jnp.int32)
        active = jnp.ones((B,), bool)
        budgets = jnp.full((B,), chunk + 1, jnp.int32)
        out = decode_chunk(
            params, cfg, cache, cur, active, budgets, rng, chunk,
            sample_fn, stop_fn, attn_len=attn_len,
        )
        return out[1]  # tokens [B, chunk]

    results = []
    for B in (16, 32, 64):
        for fill, attn_len in ((512, 1024), (1500, 2048)):
            for chunk in (128, 256):
                S = 4096
                rng = jax.random.PRNGKey(1)
                toks = run_chunk(params, fill, rng, B, S, chunk, attn_len)
                np.asarray(toks)  # compile + real host fetch (tunnel-safe
                # sync: block_until_ready alone returns early under axon)
                t0 = time.perf_counter()
                n_rep = 3
                for i in range(n_rep):
                    toks = run_chunk(
                        params, fill, jax.random.PRNGKey(i), B, S, chunk,
                        attn_len,
                    )
                    np.asarray(toks)
                dt = (time.perf_counter() - t0) / n_rep
                tok_s = B * chunk / dt
                ms_per_step = dt / chunk * 1e3
                # bandwidth model: per step reads weights once + per-row KV
                # prefix attn_len (k+v, bf16)
                kv_bytes = (
                    2 * cfg.n_layers * cfg.n_kv_heads * cfg.head_dim
                    * attn_len * 2 * B
                )
                w_bytes = n_params * 2
                bw_need = (kv_bytes + w_bytes) / (dt / chunk)
                r = {
                    "B": B, "fill": fill, "attn_len": attn_len,
                    "chunk": chunk,
                    "tok_s": round(tok_s, 1),
                    "ms_per_step": round(ms_per_step, 3),
                    "hbm_gbps_implied": round(bw_need / 1e9, 1),
                }
                results.append(r)
                print(json.dumps(r), flush=True)

    print(json.dumps({"summary": sorted(
        results, key=lambda r: -r["tok_s"])[:5]}))


if __name__ == "__main__":
    main()

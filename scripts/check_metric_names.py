#!/usr/bin/env python3
"""Lint: every emitted metric name appears exactly once in the canonical
metric name table (areal_tpu/observability/table.py).

"Emitted" = any string literal passed as the first argument of a
``.counter("...")`` / ``.gauge("...")`` / ``.histogram("...")`` call
anywhere under ``areal_tpu/`` or in ``bench.py``, found by AST walk (so
formatting/aliasing of the registry object doesn't matter, and dynamically
computed names are rejected by construction — metric names must be
literals or the scrape vocabulary becomes unauditable).

Exit code 0 = clean; 1 = violations (each printed, one per line).  Run in
tier-1 via tests/observability/test_metric_names_lint.py.
"""

from __future__ import annotations

import ast
import os
import sys
from typing import Dict, List, Tuple

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_REGISTRY_METHODS = ("counter", "gauge", "histogram")

#: files whose registry-shaped calls are not metric emissions; currently
#: none — even registry.py's own set_stats emission (areal_stats) is real
_SKIP_FILES: Tuple[str, ...] = ()


def _iter_source_files() -> List[str]:
    out = [os.path.join(REPO_ROOT, "bench.py")]
    for dirpath, _, filenames in os.walk(
        os.path.join(REPO_ROOT, "areal_tpu")
    ):
        for f in filenames:
            if f.endswith(".py"):
                out.append(os.path.join(dirpath, f))
    return sorted(out)


def collect_emitted_names() -> Dict[str, List[Tuple[str, int]]]:
    """{metric_name: [(rel_path, lineno), ...]} plus non-literal call sites
    recorded under the sentinel key ``<non-literal>``."""
    emitted: Dict[str, List[Tuple[str, int]]] = {}
    for path in _iter_source_files():
        rel = os.path.relpath(path, REPO_ROOT)
        if rel in _SKIP_FILES:
            continue
        with open(path) as f:
            try:
                tree = ast.parse(f.read(), filename=rel)
            except SyntaxError as e:
                emitted.setdefault("<syntax-error>", []).append(
                    (rel, e.lineno or 0)
                )
                continue
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            if (
                not isinstance(fn, ast.Attribute)
                or fn.attr not in _REGISTRY_METHODS
                or not node.args
            ):
                continue
            arg = node.args[0]
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                emitted.setdefault(arg.value, []).append((rel, node.lineno))
            else:
                emitted.setdefault("<non-literal>", []).append(
                    (rel, node.lineno)
                )
    return emitted


def run_lint() -> List[str]:
    """Returns a list of violation messages (empty = clean)."""
    sys.path.insert(0, REPO_ROOT)
    from areal_tpu.observability.table import METRIC_TABLE

    problems: List[str] = []
    counts: Dict[str, int] = {}
    for spec in METRIC_TABLE:
        counts[spec.name] = counts.get(spec.name, 0) + 1
    for name, n in sorted(counts.items()):
        if n != 1:
            problems.append(
                f"table: {name} appears {n} times in METRIC_TABLE "
                "(must be exactly once)"
            )

    emitted = collect_emitted_names()
    for name, sites in sorted(emitted.items()):
        where = ", ".join(f"{p}:{ln}" for p, ln in sites)
        if name == "<non-literal>":
            problems.append(
                f"non-literal metric name at {where} — metric names must "
                "be string literals so the table lint can see them"
            )
            continue
        if name == "<syntax-error>":
            problems.append(f"unparseable source: {where}")
            continue
        if counts.get(name, 0) == 0:
            problems.append(
                f"emitted metric {name} ({where}) is missing from "
                "areal_tpu/observability/table.py METRIC_TABLE"
            )

    emitted_names = set(emitted) - {"<non-literal>", "<syntax-error>"}
    for name in sorted(set(counts) - emitted_names):
        problems.append(
            f"table entry {name} is never emitted anywhere under "
            "areal_tpu/ or bench.py (dead vocabulary — remove it or wire "
            "the instrument)"
        )
    return problems


def main() -> int:
    problems = run_lint()
    for p in problems:
        print(p)
    if problems:
        print(f"check_metric_names: {len(problems)} problem(s)")
        return 1
    print("check_metric_names: ok")
    return 0


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python3
"""Lint: every emitted metric name appears exactly once in the canonical
metric name table, and every recorded trace span/event name appears
exactly once in the canonical trace table (areal_tpu/observability/
table.py).

"Emitted" = any string literal passed as the first argument of a
``.counter("...")`` / ``.gauge("...")`` / ``.histogram("...")`` call, or
as the SECOND argument (the first is the trace id) of a
``.event(tid, "...")`` / ``.span_begin(...)`` / ``.span_end(...)`` /
``.span(...)`` call, anywhere under ``areal_tpu/`` or in ``bench.py`` /
``__graft_entry__.py`` — found by AST walk (so formatting/aliasing of
the registry/tracer object doesn't matter, and dynamically computed
names are rejected by construction: names must be literals or the
scrape/trace vocabulary becomes unauditable).

The human-facing tables in ``docs/observability.md`` are diffed against
the canonical tables too (both directions): docs cannot silently drift
when a metric or span is added, renamed, or retired.  Metric names are
``areal_*`` identifiers; trace names are dotted ``layer.name`` pairs —
disjoint vocabularies, one doc page.

Exit code 0 = clean; 1 = violations (each printed, one per line).  Run in
tier-1 via tests/observability/test_metric_names_lint.py.
"""

from __future__ import annotations

import ast
import os
import re
import sys
from typing import Dict, List, Set, Tuple

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_REGISTRY_METHODS = ("counter", "gauge", "histogram")
#: tracer recording methods: first arg is the trace id, SECOND is the
#: canonical span/event name
_TRACER_METHODS = ("event", "span_begin", "span_end", "span")

#: files whose registry-shaped calls are not metric emissions; currently
#: none — even registry.py's own set_stats emission (areal_stats) is real
_SKIP_FILES: Tuple[str, ...] = ()


def _iter_source_files() -> List[str]:
    out = [
        os.path.join(REPO_ROOT, "bench.py"),
        os.path.join(REPO_ROOT, "__graft_entry__.py"),
    ]
    for dirpath, _, filenames in os.walk(
        os.path.join(REPO_ROOT, "areal_tpu")
    ):
        for f in filenames:
            if f.endswith(".py"):
                out.append(os.path.join(dirpath, f))
    return sorted(out)


def _collect(methods: Tuple[str, ...], arg_idx: int) -> Dict[str, List[Tuple[str, int]]]:
    """{name: [(rel_path, lineno), ...]} of string literals at position
    ``arg_idx`` of ``.method(...)`` calls, plus non-literal call sites
    under the sentinel key ``<non-literal>``."""
    emitted: Dict[str, List[Tuple[str, int]]] = {}
    for path in _iter_source_files():
        rel = os.path.relpath(path, REPO_ROOT)
        if rel in _SKIP_FILES:
            continue
        with open(path) as f:
            try:
                tree = ast.parse(f.read(), filename=rel)
            except SyntaxError as e:
                emitted.setdefault("<syntax-error>", []).append(
                    (rel, e.lineno or 0)
                )
                continue
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            if (
                not isinstance(fn, ast.Attribute)
                or fn.attr not in methods
                or len(node.args) <= arg_idx
            ):
                continue
            arg = node.args[arg_idx]
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                emitted.setdefault(arg.value, []).append((rel, node.lineno))
            else:
                emitted.setdefault("<non-literal>", []).append(
                    (rel, node.lineno)
                )
    return emitted


def collect_emitted_names() -> Dict[str, List[Tuple[str, int]]]:
    return _collect(_REGISTRY_METHODS, 0)


def collect_trace_names() -> Dict[str, List[Tuple[str, int]]]:
    """Span/event name literals recorded through the tracer API (second
    positional argument — the first is the trace id)."""
    return _collect(_TRACER_METHODS, 1)


DOCS_TABLE = os.path.join(REPO_ROOT, "docs", "observability.md")

#: a documented metric: a backticked `areal_*` name inside a markdown
#: table row.  Rows may document several names at once
#: ("| `areal_host_load1` / `areal_host_load5` | ...") — every backticked
#: name on the row counts.
_DOC_NAME_RE = re.compile(r"`(areal_[a-z0-9_]+)`")

#: a documented trace span/event: a backticked dotted `layer.name` inside
#: a markdown table row (trace names always contain exactly one dot;
#: metric names never do, so the vocabularies cannot collide)
_DOC_TRACE_RE = re.compile(r"`([a-z_]+\.[a-z_]+)`")


def collect_documented_names(path: str = DOCS_TABLE) -> Set[str]:
    """Names documented in docs/observability.md's metric table (markdown
    rows whose first cell is a backticked ``areal_*`` name)."""
    out: Set[str] = set()
    if not os.path.exists(path):
        return out
    with open(path) as f:
        for line in f:
            if not line.lstrip().startswith("| `areal_"):
                continue
            out.update(_DOC_NAME_RE.findall(line))
    return out


def collect_documented_trace_names(path: str = DOCS_TABLE) -> Set[str]:
    """Trace names documented in docs/observability.md: markdown table
    rows whose first cell is EXACTLY one backticked dotted name (prose
    cells that merely mention a dotted identifier don't count)."""
    out: Set[str] = set()
    if not os.path.exists(path):
        return out
    with open(path) as f:
        for line in f:
            stripped = line.lstrip()
            if not stripped.startswith("| `"):
                continue
            cell = stripped.split("|")[1].strip()
            m = _DOC_TRACE_RE.fullmatch(cell)
            if m:
                out.add(m.group(1))
    return out


def slo_vocabulary_problems(families: Dict[str, str], table) -> List[str]:
    """The ``areal_slo_*`` digest vocabulary, linted BOTH ways:

    * every family in ``latency.SLO_FAMILIES`` must exist in
      METRIC_TABLE as a *histogram* labeled exactly ``(workload,)`` —
      the digest merge rebuilds percentiles from scraped histogram
      buckets, so a family declared as any other shape silently breaks
      fleet merging;
    * every ``areal_slo_*`` METRIC_TABLE entry must be in SLO_FAMILIES —
      an SLO-prefixed metric outside the digest plane would LOOK
      mergeable to operators but never reach the fleet rows.

    Split out (pure function of its inputs) so the tier-1 test can feed
    it fabricated mismatches."""
    problems: List[str] = []
    by_name = {spec.name: spec for spec in table}
    for name in sorted(families):
        spec = by_name.get(name)
        if spec is None:
            problems.append(
                f"SLO family {name} (latency.SLO_FAMILIES) is missing "
                "from METRIC_TABLE"
            )
            continue
        if spec.type != "histogram":
            problems.append(
                f"SLO family {name} must be a histogram (digest "
                f"transport), table declares {spec.type!r}"
            )
        if tuple(spec.labels) != ("workload",):
            problems.append(
                f"SLO family {name} must be labeled exactly "
                f"('workload',), table declares {tuple(spec.labels)!r}"
            )
    for spec in table:
        if spec.name.startswith("areal_slo_") and spec.name not in families:
            problems.append(
                f"METRIC_TABLE entry {spec.name} uses the areal_slo_ "
                "prefix but is not in latency.SLO_FAMILIES — it would "
                "never merge into the fleet percentile rows"
            )
    return problems


def collect_stall_kind_sites() -> Dict[str, List[Tuple[str, int]]]:
    """{kind: [(rel_path, lineno), ...]} of stall-``kind`` emission
    sites: a string literal either (a) passed as the ``kind=`` keyword of
    an ``.inc(...)`` call, or (b) passed as the first argument of a
    ``stall_kind(...)`` call (the validate-identity marker emission sites
    wrap computed kinds in).  A non-literal first arg to ``stall_kind``
    is collected under ``<non-literal>`` — computed ``kind=`` keywords on
    ``.inc`` are NOT flagged, because routing them through
    ``stall_kind("literal")`` upstream is exactly the supported pattern
    (runtime membership check + lintable literal)."""
    sites: Dict[str, List[Tuple[str, int]]] = {}
    for path in _iter_source_files():
        rel = os.path.relpath(path, REPO_ROOT)
        if rel in _SKIP_FILES:
            continue
        with open(path) as f:
            try:
                tree = ast.parse(f.read(), filename=rel)
            except SyntaxError:
                continue  # already reported by the metric pass
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            if isinstance(fn, ast.Attribute) and fn.attr == "inc":
                for kw in node.keywords:
                    if kw.arg != "kind":
                        continue
                    if isinstance(kw.value, ast.Constant) and isinstance(
                        kw.value.value, str
                    ):
                        sites.setdefault(kw.value.value, []).append(
                            (rel, node.lineno)
                        )
            is_stall_kind = (
                isinstance(fn, ast.Name) and fn.id == "stall_kind"
            ) or (isinstance(fn, ast.Attribute) and fn.attr == "stall_kind")
            if is_stall_kind and node.args:
                arg = node.args[0]
                if isinstance(arg, ast.Constant) and isinstance(
                    arg.value, str
                ):
                    sites.setdefault(arg.value, []).append(
                        (rel, node.lineno)
                    )
                else:
                    sites.setdefault("<non-literal>", []).append(
                        (rel, node.lineno)
                    )
    return sites


def collect_documented_stall_kinds(path: str = DOCS_TABLE) -> Set[str]:
    """Stall kinds documented in docs/observability.md: every backticked
    lowercase identifier (other than the metric name itself) on the
    ``areal_trace_stall_total`` metric-table row."""
    out: Set[str] = set()
    if not os.path.exists(path):
        return out
    with open(path) as f:
        for line in f:
            stripped = line.lstrip()
            if not stripped.startswith("| `areal_trace_stall_total`"):
                continue
            for m in re.findall(r"`([a-z][a-z0-9_]*)`", stripped):
                if m not in ("areal_trace_stall_total", "kind", "counter"):
                    out.add(m)
    return out


def stall_vocabulary_problems(
    sites: Dict[str, List[Tuple[str, int]]],
    kinds: Tuple[str, ...],
    documented: Set[str],
) -> List[str]:
    """The ``areal_trace_stall_total{kind=}`` vocabulary, linted BOTH
    ways against ``table.STALL_KINDS`` and against the docs row:

    * every literal kind at an emission site must be in STALL_KINDS (an
      unlisted kind would pass the registry's label check — ``kind`` is a
      free-form label value — but be invisible to dashboards keyed on the
      documented vocabulary);
    * every STALL_KINDS entry must be emitted somewhere (dead vocabulary
      otherwise);
    * the docs row for ``areal_trace_stall_total`` must enumerate exactly
      STALL_KINDS.

    Split out (pure function of its inputs) so the tier-1 test can feed
    it fabricated mismatches."""
    problems: List[str] = []
    for kind, where_list in sorted(sites.items()):
        where = ", ".join(f"{p}:{ln}" for p, ln in where_list)
        if kind == "<non-literal>":
            problems.append(
                f"non-literal stall_kind(...) argument at {where} — wrap "
                "each candidate kind literal in stall_kind(\"...\") so "
                "the vocabulary lint can see it"
            )
            continue
        if kind not in kinds:
            problems.append(
                f"stall kind {kind!r} ({where}) is missing from "
                "areal_tpu/observability/table.py STALL_KIND_TABLE"
            )
    emitted = set(sites) - {"<non-literal>"}
    for kind in sorted(set(kinds) - emitted):
        problems.append(
            f"STALL_KIND_TABLE entry {kind!r} is never emitted anywhere "
            "under areal_tpu/, bench.py, or __graft_entry__.py (dead "
            "vocabulary — remove it or wire the emission)"
        )
    for kind in sorted(set(kinds) - documented):
        problems.append(
            f"stall kind {kind!r} is in STALL_KIND_TABLE but missing "
            "from the docs/observability.md areal_trace_stall_total row"
        )
    for kind in sorted(documented - set(kinds)):
        problems.append(
            f"docs/observability.md documents stall kind {kind!r}, which "
            "is not in STALL_KIND_TABLE (stale doc row — remove it or "
            "add the table entry)"
        )
    return problems


def run_lint() -> List[str]:
    """Returns a list of violation messages (empty = clean)."""
    sys.path.insert(0, REPO_ROOT)
    from areal_tpu.observability.table import METRIC_TABLE

    problems: List[str] = []
    counts: Dict[str, int] = {}
    for spec in METRIC_TABLE:
        counts[spec.name] = counts.get(spec.name, 0) + 1
    for name, n in sorted(counts.items()):
        if n != 1:
            problems.append(
                f"table: {name} appears {n} times in METRIC_TABLE "
                "(must be exactly once)"
            )

    emitted = collect_emitted_names()
    for name, sites in sorted(emitted.items()):
        where = ", ".join(f"{p}:{ln}" for p, ln in sites)
        if name == "<non-literal>":
            problems.append(
                f"non-literal metric name at {where} — metric names must "
                "be string literals so the table lint can see them"
            )
            continue
        if name == "<syntax-error>":
            problems.append(f"unparseable source: {where}")
            continue
        if counts.get(name, 0) == 0:
            problems.append(
                f"emitted metric {name} ({where}) is missing from "
                "areal_tpu/observability/table.py METRIC_TABLE"
            )

    emitted_names = set(emitted) - {"<non-literal>", "<syntax-error>"}
    for name in sorted(set(counts) - emitted_names):
        problems.append(
            f"table entry {name} is never emitted anywhere under "
            "areal_tpu/ or bench.py (dead vocabulary — remove it or wire "
            "the instrument)"
        )

    # docs table drift: the markdown table in docs/observability.md must
    # document exactly the canonical vocabulary
    documented = collect_documented_names()
    for name in sorted(set(counts) - documented):
        problems.append(
            f"metric {name} is in METRIC_TABLE but missing from the "
            "docs/observability.md metric table"
        )
    for name in sorted(documented - set(counts)):
        problems.append(
            f"docs/observability.md documents {name}, which is not in "
            "areal_tpu/observability/table.py METRIC_TABLE (stale doc "
            "row — remove it or add the table entry)"
        )

    # -- areal_slo_* digest vocabulary (latency.py <-> table, both ways) ----
    from areal_tpu.observability.latency import SLO_FAMILIES

    problems.extend(slo_vocabulary_problems(SLO_FAMILIES, METRIC_TABLE))

    # -- stall-kind vocabulary (emission sites <-> STALL_KINDS <-> docs) ----
    from areal_tpu.observability.table import STALL_KINDS

    problems.extend(
        stall_vocabulary_problems(
            collect_stall_kind_sites(),
            STALL_KINDS,
            collect_documented_stall_kinds(),
        )
    )

    # -- trace span/event vocabulary (same discipline, second table) --------
    from areal_tpu.observability.table import TRACE_TABLE

    tcounts: Dict[str, int] = {}
    for spec in TRACE_TABLE:
        tcounts[spec.name] = tcounts.get(spec.name, 0) + 1
    for name, n in sorted(tcounts.items()):
        if n != 1:
            problems.append(
                f"trace table: {name} appears {n} times in TRACE_TABLE "
                "(must be exactly once)"
            )
    traced = collect_trace_names()
    for name, sites in sorted(traced.items()):
        where = ", ".join(f"{p}:{ln}" for p, ln in sites)
        if name == "<non-literal>":
            problems.append(
                f"non-literal trace span/event name at {where} — trace "
                "names must be string literals so the table lint can see "
                "them"
            )
            continue
        if name == "<syntax-error>":
            continue  # already reported by the metric pass
        if tcounts.get(name, 0) == 0:
            problems.append(
                f"recorded trace name {name} ({where}) is missing from "
                "areal_tpu/observability/table.py TRACE_TABLE"
            )
    traced_names = set(traced) - {"<non-literal>", "<syntax-error>"}
    for name in sorted(set(tcounts) - traced_names):
        problems.append(
            f"trace table entry {name} is never recorded anywhere under "
            "areal_tpu/, bench.py, or __graft_entry__.py (dead "
            "vocabulary — remove it or wire the instrument)"
        )
    tdocumented = collect_documented_trace_names()
    for name in sorted(set(tcounts) - tdocumented):
        problems.append(
            f"trace name {name} is in TRACE_TABLE but missing from the "
            "docs/observability.md trace table"
        )
    for name in sorted(tdocumented - set(tcounts)):
        problems.append(
            f"docs/observability.md documents trace name {name}, which "
            "is not in TRACE_TABLE (stale doc row — remove it or add "
            "the table entry)"
        )
    return problems


def main() -> int:
    problems = run_lint()
    for p in problems:
        print(p)
    if problems:
        print(f"check_metric_names: {len(problems)} problem(s)")
        return 1
    print("check_metric_names: ok")
    return 0


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python3
"""One-shot support bundle for a live (or half-dead) trial.

Discovers every worker's observability endpoint through name-resolve
(the same ``names.metric_server_root`` subtree the aggregator scrapes),
snapshots ``/metrics``, ``/healthz``, and ``/trace`` from each into a
timestamped directory, records every registered on-demand profiler
capture path (``names.profiler_capture_root``), and writes a
``manifest.json`` summarizing what was captured and what was dead.

Dead endpoints are skip-and-count, never fatal: the whole point of a
debug bundle is that some of the fleet is misbehaving, so one wedged
worker must not block collecting evidence from the others.  Exit code
is 0 as long as the bundle was written; the manifest carries the error
tally.

Usage::

    python scripts/collect_debug_bundle.py EXPERIMENT TRIAL \
        [--output DIR] [--timeout SECONDS] [--profile-seconds N]

``--profile-seconds N`` additionally triggers a bounded
``/profile?seconds=N`` capture on every live worker before snapshotting
(workers already profiling answer 409; that is recorded, not fatal).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
import urllib.request
from typing import Dict, Optional

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

from areal_tpu.base import name_resolve, names  # noqa: E402


def _fetch(url: str, timeout: float) -> bytes:
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.read()


def discover_workers(experiment: str, trial: str) -> Dict[str, str]:
    """{worker_name: host:port} — same subtree the aggregator scrapes."""
    out: Dict[str, str] = {}
    root = names.metric_server_root(experiment, trial)
    for key in name_resolve.find_subtree(root):
        worker = key.rsplit("/", 1)[-1]
        try:
            out[worker] = name_resolve.get(key)
        except name_resolve.NameEntryNotFoundError:
            continue  # unregistered between scan and get
    return out


def discover_profiler_captures(experiment: str, trial: str) -> Dict[str, str]:
    """{worker_name: capture_path} of every registered on-demand
    profiler capture (the ``/profile`` route registers its latest)."""
    out: Dict[str, str] = {}
    root = names.profiler_capture_root(experiment, trial)
    for key in name_resolve.find_subtree(root):
        worker = key.rsplit("/", 1)[-1]
        try:
            out[worker] = name_resolve.get(key)
        except name_resolve.NameEntryNotFoundError:
            continue
    return out


#: endpoint path -> filename inside the per-worker bundle dir
ENDPOINTS = (
    ("/metrics", "metrics.prom"),
    ("/healthz", "healthz.json"),
    ("/trace", "trace.json"),
)


def collect(
    experiment: str,
    trial: str,
    out_dir: str,
    timeout: float = 5.0,
    profile_seconds: Optional[float] = None,
) -> dict:
    """Snapshot the fleet into ``out_dir``; returns the manifest dict
    (also written to ``out_dir/manifest.json``)."""
    os.makedirs(out_dir, exist_ok=True)
    workers = discover_workers(experiment, trial)
    manifest: dict = {
        "experiment": experiment,
        "trial": trial,
        "time": time.time(),
        "workers": sorted(workers),
        "fetched": 0,
        "errors": [],
        "profile_requests": {},
        "profiler_captures": {},
    }
    if profile_seconds is not None:
        for worker, addr in sorted(workers.items()):
            url = f"http://{addr}/profile?seconds={profile_seconds}"
            try:
                manifest["profile_requests"][worker] = json.loads(
                    _fetch(url, timeout)
                )
            except Exception as e:  # noqa: BLE001 - skip-and-count
                manifest["profile_requests"][worker] = {"error": str(e)}
        # a capture needs its wall-clock window before the snapshot can
        # include the registered path
        time.sleep(profile_seconds)
    for worker, addr in sorted(workers.items()):
        wdir = os.path.join(out_dir, worker)
        os.makedirs(wdir, exist_ok=True)
        for path, fname in ENDPOINTS:
            try:
                body = _fetch(f"http://{addr}{path}", timeout)
            except Exception as e:  # noqa: BLE001 - skip-and-count
                manifest["errors"].append(
                    {"worker": worker, "endpoint": path, "error": str(e)}
                )
                continue
            with open(os.path.join(wdir, fname), "wb") as f:
                f.write(body)
            manifest["fetched"] += 1
    for worker, path in sorted(
        discover_profiler_captures(experiment, trial).items()
    ):
        manifest["profiler_captures"][worker] = {
            "path": path,
            # captures live on the worker's host; only claim presence
            # when this process can actually see the directory
            "present_locally": os.path.isdir(path),
        }
    with open(os.path.join(out_dir, "manifest.json"), "w") as f:
        json.dump(manifest, f, indent=2, sort_keys=True)
    return manifest


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n\n")[0])
    ap.add_argument("experiment")
    ap.add_argument("trial")
    ap.add_argument(
        "--output",
        default=None,
        help="bundle directory (default: debug_bundle_<expr>_<trial>_<ts>)",
    )
    ap.add_argument("--timeout", type=float, default=5.0)
    ap.add_argument(
        "--profile-seconds",
        type=float,
        default=None,
        help="also trigger a /profile capture of N seconds on every "
        "live worker before snapshotting",
    )
    args = ap.parse_args(argv)
    out_dir = args.output or "debug_bundle_{}_{}_{}".format(
        args.experiment, args.trial, time.strftime("%Y%m%d-%H%M%S")
    )
    manifest = collect(
        args.experiment,
        args.trial,
        out_dir,
        timeout=args.timeout,
        profile_seconds=args.profile_seconds,
    )
    n_workers = len(manifest["workers"])
    n_errs = len(manifest["errors"])
    print(
        f"collect_debug_bundle: {out_dir} — {n_workers} worker(s), "
        f"{manifest['fetched']} endpoint snapshot(s), {n_errs} error(s), "
        f"{len(manifest['profiler_captures'])} profiler capture(s)"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Time the engine's per-step phases on the real chip: dispatch (jit call
returns), harvest (device_get), admit, misc host work.  Identifies whether
dispatch is truly async under the axon tunnel and where the per-chunk
overhead beyond device time goes."""

import json
import time

import numpy as np


def main():
    import jax

    from areal_tpu.api.model_api import (
        APIGenerateInput,
        GenerationHyperparameters,
    )
    from areal_tpu.engine.inference_server import ContinuousBatchingEngine
    from scripts.profile_decode import bench_cfg
    from areal_tpu.models import transformer
    import jax.numpy as jnp

    cfg = bench_cfg()
    params = jax.tree.map(
        lambda x: x.astype(jnp.bfloat16),
        transformer.init_params(cfg, jax.random.PRNGKey(0)),
    )

    for B, chunk in ((32, 128), (32, 256), (64, 128), (64, 256)):
        eng = ContinuousBatchingEngine(
            cfg, params, max_batch=B, kv_cache_len=2048, chunk_size=chunk
        )
        rng = np.random.default_rng(1)
        gcfg = GenerationHyperparameters(max_new_tokens=512, temperature=1.0)

        def submit_all(tag):
            for i in range(B):
                ids = rng.integers(0, cfg.vocab_size, (512,)).tolist()
                eng.submit(APIGenerateInput(
                    qid=f"{tag}{i}", prompt_ids=ids, input_ids=ids,
                    gconfig=gcfg))

        # warmup drain: compiles every bucket the timed run will touch
        submit_all("w")
        while eng.has_work:
            eng.step()
        eng.drain_results()
        submit_all("t")

        t_dispatch = t_harvest = t_admit = 0.0
        n_steps = 0
        # monkeypatch instrumentation
        orig_dispatch = eng._dispatch_chunk
        orig_harvest = eng._harvest_oldest
        orig_admit = eng._admit

        def dispatch():
            nonlocal t_dispatch
            t0 = time.perf_counter()
            orig_dispatch()
            t_dispatch += time.perf_counter() - t0

        def harvest():
            nonlocal t_harvest
            t0 = time.perf_counter()
            n = orig_harvest()
            t_harvest += time.perf_counter() - t0
            return n

        def admit():
            nonlocal t_admit
            t0 = time.perf_counter()
            orig_admit()
            t_admit += time.perf_counter() - t0

        eng._dispatch_chunk = dispatch
        eng._harvest_oldest = harvest
        eng._admit = admit

        t0 = time.perf_counter()
        n_tok = 0
        while eng.has_work:
            n_tok += eng.step()
            n_steps += 1
        dt = time.perf_counter() - t0
        print(json.dumps({
            "B": B, "chunk": chunk,
            "tok_s": round(n_tok / dt, 1),
            "total_s": round(dt, 2),
            "steps": n_steps,
            "dispatch_s": round(t_dispatch, 2),
            "harvest_s": round(t_harvest, 2),
            "admit_s": round(t_admit, 2),
            "other_s": round(dt - t_dispatch - t_harvest - t_admit, 2),
        }), flush=True)


if __name__ == "__main__":
    main()

// Native sequence-packing kernels for the data plane.
//
// C++ counterpart of areal_tpu/base/datapack.py (the role the reference's
// csrc/ plays for its hot host-side loops). Micro-batch splitting runs
// every train step over thousands of sequence lengths; the balanced
// partition is an O(n^2 k) DP and FFD is O(n * bins) — fine in C++, painful
// in the Python interpreter. Algorithms and outputs are IDENTICAL to the
// Python reference implementations (tests assert bit-for-bit parity).
//
// Build: g++ -O2 -shared -fPIC -o libdatapack.so datapack.cpp
// (done automatically by areal_tpu/base/_native.py).

#include <algorithm>
#include <cstdint>
#include <numeric>
#include <vector>

extern "C" {

// First-fit-decreasing bin packing.
// nums[n]: item sizes; capacity: bin capacity.
// out_bin[n]: bin id per item; returns number of bins.
// Tie-breaking matches numpy argsort(nums)[::-1] on the Python side:
// np.argsort is stable ascending, so the reversed order visits equal sizes
// by DESCENDING original index.
int64_t ffd_pack(const int64_t* nums, int64_t n, int64_t capacity,
                 int64_t* out_bin) {
  std::vector<int64_t> order(n);
  std::iota(order.begin(), order.end(), 0);
  std::stable_sort(order.begin(), order.end(),
                   [&](int64_t a, int64_t b) { return nums[a] < nums[b]; });
  std::reverse(order.begin(), order.end());

  std::vector<int64_t> sums;
  sums.reserve(64);
  for (int64_t idx : order) {
    int64_t x = nums[idx];
    bool placed = false;
    for (size_t b = 0; b < sums.size(); ++b) {
      if (sums[b] + x <= capacity) {
        out_bin[idx] = static_cast<int64_t>(b);
        sums[b] += x;
        placed = true;
        break;
      }
    }
    if (!placed) {
      out_bin[idx] = static_cast<int64_t>(sums.size());
      sums.push_back(x);
    }
  }
  return static_cast<int64_t>(sums.size());
}

// Order-preserving contiguous partition of nums[n] into exactly k groups
// minimizing the maximum group sum (linear-partition DP, same tie-breaks
// as the Python DP: strict '<' improvement keeps the SMALLEST cut t).
// out_cuts[k+1]: boundaries, out_cuts[0]=0, out_cuts[k]=n.
// Returns 0 on success, -1 on invalid input.
int64_t partition_balanced_dp(const int64_t* nums, int64_t n, int64_t k,
                              int64_t* out_cuts) {
  if (k < 1 || k > n) return -1;
  std::vector<int64_t> prefix(n + 1, 0);
  for (int64_t i = 0; i < n; ++i) prefix[i + 1] = prefix[i] + nums[i];

  const double INF = 1e300;
  // dp[j][i]: minimal max-sum splitting first i items into j groups
  std::vector<std::vector<double>> dp(k + 1,
                                      std::vector<double>(n + 1, INF));
  std::vector<std::vector<int64_t>> cut(k + 1,
                                        std::vector<int64_t>(n + 1, 0));
  dp[0][0] = 0.0;
  for (int64_t j = 1; j <= k; ++j) {
    for (int64_t i = j; i <= n; ++i) {
      for (int64_t t = j - 1; t < i; ++t) {
        double last = static_cast<double>(prefix[i] - prefix[t]);
        double cost = std::max(dp[j - 1][t], last);
        if (cost < dp[j][i]) {
          dp[j][i] = cost;
          cut[j][i] = t;
        }
        // dp[j-1][t] is non-decreasing in t and the last-group sum is
        // decreasing; once the last group alone is <= dp[j][i] further t
        // only raises dp[j-1][t] — but matching Python exactly matters
        // more than the constant factor, so no early break.
      }
    }
  }
  out_cuts[k] = n;
  int64_t i = n;
  for (int64_t j = k; j >= 1; --j) {
    int64_t t = cut[j][i];
    out_cuts[j - 1] = t;
    i = t;
  }
  return 0;
}

}  // extern "C"

"""Single-step math agent.

Rebuild of the reference's agent (reference:
realhf/impl/agent/math_single_step_agent.py:23 — puts the prompt on
obs_queue, awaits the sampled group from act_queue, scores via the env,
filters groups by success rate (reject all-right/all-wrong) :94-101, and
builds trajectory SequenceSamples with version/birth_time keys :103-180).
"""

from __future__ import annotations

import asyncio
import time
from typing import List

import numpy as np

from areal_tpu.api import agent_api, model_api
from areal_tpu.api.data import SequenceSample
from areal_tpu.base import logging_

logger = logging_.getLogger("math_single_step_agent")


class MathSingleStepAgent(agent_api.Agent):
    def __init__(
        self,
        gconfig: model_api.GenerationHyperparameters = None,
        answer_save_path: str = None,
        tokenizer_path: str = None,
        success_rate_lb: float = 0.0,
        success_rate_ub: float = 1.0,
        reward_scaling: float = 1.0,
        reward_bias: float = 0.0,
    ):
        self.gconfig = gconfig or model_api.GenerationHyperparameters()
        self.success_rate_lb = success_rate_lb
        self.success_rate_ub = success_rate_ub
        self.reward_scaling = reward_scaling
        self.reward_bias = reward_bias

    async def collect_trajectory(
        self,
        prompt: SequenceSample,
        env,
        obs_queue: asyncio.Queue,
        act_queue: asyncio.Queue,
    ) -> List[SequenceSample]:
        qid = str(prompt.ids[0])
        prompt_ids = prompt.data["packed_prompts"].tolist()
        await obs_queue.put((qid, prompt_ids, self.gconfig.n))

        bundle: model_api.BundledGenerationOutputs = await act_queue.get()

        await env.reset()
        answers = bundle.seqs  # token ids; env decodes/scores
        _, rewards, *_ = await env.step(
            {
                "qid": qid,
                "seqs": answers,
                "prompt_len": len(prompt_ids),
                "task": prompt.metadata.get("task", ["math"])[0],
                "problem": {
                    "query_id": qid,
                    "solutions": prompt.metadata.get("solutions", [[]])[0],
                    "input_output": prompt.metadata.get(
                        "input_output", [None]
                    )[0],
                    **(
                        {"timeout": prompt.metadata["timeout"][0]}
                        if prompt.metadata.get("timeout", [None])[0]
                        is not None
                        else {}
                    ),
                },
            }
        )
        rewards = np.asarray(rewards, np.float32)

        # group filtering: all-correct or all-wrong groups carry no learning
        # signal for group-normalized advantages
        sr = float(np.mean(rewards > 0))
        if not (self.success_rate_lb <= sr <= self.success_rate_ub):
            logger.debug("qid %s filtered (success rate %.2f)", qid, sr)
            return []

        rewards = rewards * self.reward_scaling - self.reward_bias
        now = time.time()  # wall clock: comparable across worker processes
        samples = []
        for j, seq in enumerate(bundle.seqs):
            L = len(seq)
            pmask = np.zeros(L, bool)
            pmask[: len(bundle.prompt_ids)] = True
            samples.append(
                SequenceSample.from_default(
                    seqlens=[L],
                    ids=[f"{qid}-{j}"],
                    data={
                        "packed_input_ids": np.asarray(seq, np.int64),
                        "packed_logprobs": np.asarray(
                            bundle.logprobs[j], np.float32
                        ),
                        "prompt_mask": pmask,
                        "seq_no_eos_mask": np.asarray(
                            [bundle.no_eos[j]], np.float32
                        ),
                        "rewards": np.asarray([rewards[j]], np.float32),
                        "version_start": np.asarray(
                            [bundle.version_start[j]], np.int32
                        ),
                        "version_end": np.asarray(
                            [bundle.version_end[j]], np.int32
                        ),
                        "birth_time": np.asarray([now], np.float64),
                    },
                    # birth_time orders master-buffer dequeues;
                    # version_end rides along for the buffer-age
                    # stall watchdog (flight recorder)
                    metadata={
                        "birth_time": [now],
                        "version_end": [int(bundle.version_end[j])],
                    },
                )
            )
        return samples


agent_api.register_agent("math-single-step", MathSingleStepAgent)

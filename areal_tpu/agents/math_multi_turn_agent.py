"""Multi-turn math agent: retry-with-feedback loop.

Rebuild of the reference's multi-turn agent (reference:
realhf/impl/agent/math_multi_turn_agent.py — per turn: generate one answer,
score it via the env, append a correct/wrong feedback message, continue up
to ``num_turns``; turn rewards are discounted backward through the turn
chain :209-213).

Design divergence from the reference: each turn becomes its OWN trajectory
``SequenceSample`` (id ``{qid}-t{j}``) carrying the discounted
reward-to-go, instead of one multi-sequence sample per id — our data plane
treats per-answer ids as the packing unit.  The training semantics
(per-turn sequences with turn-level discounted rewards) are identical.
"""

from __future__ import annotations

import asyncio
import time
from typing import List

import numpy as np

from areal_tpu.api import agent_api, dataset_api, model_api
from areal_tpu.api.data import SequenceSample
from areal_tpu.base import logging_

logger = logging_.getLogger("math_multi_turn_agent")

FEEDBACK_CORRECT = "\nCongratulations! You are correct!\n"
FEEDBACK_WRONG = "\nUnfortunately your answer is wrong. Let's try again.\n"


class MathMultiTurnAgent(agent_api.Agent):
    def __init__(
        self,
        gconfig: model_api.GenerationHyperparameters = None,
        tokenizer_path: str = None,
        num_turns: int = 5,
        turn_level_discount: float = 1.0,
        reward_scaling: float = 1.0,
        reward_bias: float = 0.0,
    ):
        gconfig = gconfig or model_api.GenerationHyperparameters()
        # one answer per turn; the group dimension is the turn chain
        self.gconfig = gconfig.new(n=1)
        self.tokenizer = (
            dataset_api.load_hf_tokenizer(tokenizer_path)
            if tokenizer_path
            else None
        )
        self.num_turns = num_turns
        self.turn_level_discount = turn_level_discount
        self.reward_scaling = reward_scaling
        self.reward_bias = reward_bias

    def _feedback_ids(self, correct: bool) -> List[int]:
        text = FEEDBACK_CORRECT if correct else FEEDBACK_WRONG
        tok = self.tokenizer
        if tok is None:
            return []
        if getattr(tok, "chat_template", None):
            text = tok.apply_chat_template(
                [dict(content=text.strip(), role="user")],
                add_generation_prompt=True,
                tokenize=False,
            )
        return tok(text, add_special_tokens=False)["input_ids"]

    async def collect_trajectory(
        self,
        prompt: SequenceSample,
        env,
        obs_queue: asyncio.Queue,
        act_queue: asyncio.Queue,
    ) -> List[SequenceSample]:
        qid = str(prompt.ids[0])
        prompt_ids = prompt.data["packed_prompts"].tolist()
        task = prompt.metadata.get("task", ["math"])[0]
        problem = {
            "query_id": qid,
            "solutions": prompt.metadata.get("solutions", [[]])[0],
            "input_output": prompt.metadata.get("input_output", [None])[0],
        }

        token_ids = list(prompt_ids)
        turns = []  # (bundle, prompt_len_this_turn, success)
        await env.reset()
        for turn in range(self.num_turns):
            await obs_queue.put((f"{qid}@t{turn}", token_ids, 1))
            bundle: model_api.BundledGenerationOutputs = await act_queue.get()
            _, rewards, *_ = await env.step(
                {
                    "qid": qid,
                    "seqs": bundle.seqs,
                    "prompt_len": len(token_ids),
                    "task": task,
                    "problem": problem,
                }
            )
            success = float(rewards[0]) > 0
            turns.append((bundle, len(token_ids), success))
            if success:
                break
            # next turn continues from the full transcript + feedback
            token_ids = list(bundle.seqs[0])
            token_ids.extend(self._feedback_ids(success))

        # turn-level discounted reward-to-go (reference :209-213): reward is
        # ±1 per turn, later turns' rewards flow backward
        raw = [
            ((1.0 if s else -1.0) - self.reward_bias) * self.reward_scaling
            for _, _, s in turns
        ]
        for i in reversed(range(len(raw) - 1)):
            raw[i] = raw[i] + raw[i + 1] * self.turn_level_discount

        now = time.time()
        samples = []
        for j, ((bundle, plen, _s), reward) in enumerate(zip(turns, raw)):
            seq = bundle.seqs[0]
            L = len(seq)
            pmask = np.zeros(L, bool)
            pmask[:plen] = True  # everything before this turn's generation
            samples.append(
                SequenceSample.from_default(
                    seqlens=[L],
                    ids=[f"{qid}-t{j}"],
                    data={
                        "packed_input_ids": np.asarray(seq, np.int64),
                        "packed_logprobs": np.asarray(
                            bundle.logprobs[0], np.float32
                        ),
                        "prompt_mask": pmask,
                        "seq_no_eos_mask": np.asarray(
                            [bundle.no_eos[0]], np.float32
                        ),
                        "rewards": np.asarray([reward], np.float32),
                        "version_start": np.asarray(
                            [bundle.version_start[0]], np.int32
                        ),
                        "version_end": np.asarray(
                            [bundle.version_end[0]], np.int32
                        ),
                        "birth_time": np.asarray([now], np.float64),
                    },
                    # birth_time orders master-buffer dequeues;
                    # version_end rides along for the buffer-age
                    # stall watchdog (flight recorder)
                    metadata={
                        "birth_time": [now],
                        "version_end": [int(bundle.version_end[0])],
                    },
                )
            )
        return samples


agent_api.register_agent("math-multi-turn", MathMultiTurnAgent)

"""Pallas flash-decode attention over a contiguous per-row KV cache.

In-house TPU kernel for the rollout engine's decode hot loop (the role the
reference delegates to SGLang/flashinfer paged decode kernels,
realhf/impl/model/backend/sglang.py:369).  One query token per row attends
over that row's cache prefix ``[0, length)``:

* grid ``(B, Hkv, S/block)`` — the minor block axis iterates sequentially on
  TPU, so online-softmax state (m/l/acc) lives in VMEM scratch across blocks
  and the normalized output is emitted at the last block;
* ``lengths`` rides scalar prefetch: the K/V index maps CLAMP the block
  index to the last valid block of each row, so trailing blocks re-address
  the same tile and the pipeline's revisiting logic skips their HBM->VMEM
  copies — short rows stream only the KV they own, which is the entire
  point: decode is HBM-bandwidth-bound on the KV stream;
* GQA is grouped: the query head group ``r = Hq // Hkv`` shares one KV head
  per grid cell, so the cache is read once per KV head (never
  repeat-materialized).

Returns UN-normalized partials ``(acc, m, l)`` so the caller can
online-merge them with attention over KV that is not in the cache yet (the
decode chunk's in-flight window, models/transformer.py:decode_chunk).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from areal_tpu.base.jax_compat import pallas_tpu_compiler_params

DEFAULT_BLOCK = 256
_NEG_INF = -1e30


def softmax_scratch_init(s_acc, s_m, s_l):
    """Reset the online-softmax VMEM scratch at the first grid block
    (shared with ops/paged_attention.py)."""
    s_acc[:] = jnp.zeros_like(s_acc)
    s_m[:] = jnp.full_like(s_m, _NEG_INF)
    s_l[:] = jnp.zeros_like(s_l)


def softmax_block_update(
    q, k, v, s_acc, s_m, s_l, *, base, length, scale
):
    """One KV block's online-softmax update over (rows, hd) queries —
    the SINGLE definition of the decode-attention numerics, used by both
    the contiguous (flash_decode) and paged kernels.  ``q``/``k``/``v``
    are already-loaded VMEM tiles: (rows, hd), (BS, hd), (BS, hd).

    HIGHEST precision on both dots: f32 MXU dots default to single-pass
    bf16 rounding (measured 0.1 abs output error at 4k lengths vs 6e-5
    with 3-pass) and decode is HBM-bound, so the extra passes are free.
    """
    q = q.astype(jnp.float32)  # (rows, hd)
    k = k.astype(jnp.float32)  # (BS, hd)
    s = (
        jax.lax.dot_general(
            q,
            k,
            (((1,), (1,)), ((), ())),
            precision=jax.lax.Precision.HIGHEST,
            preferred_element_type=jnp.float32,
        )
        * scale
    )  # (rows, BS)
    pos = base + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    s = jnp.where(pos < length, s, _NEG_INF)

    m_prev = s_m[:, 0]  # (rows,)
    m_cur = jnp.maximum(m_prev, jnp.max(s, axis=1))
    alpha = jnp.exp(m_prev - m_cur)
    p = jnp.exp(s - m_cur[:, None])  # (rows, BS)
    v = v.astype(jnp.float32)  # (BS, hd)
    pv = jax.lax.dot_general(
        p, v, (((1,), (0,)), ((), ())),
        precision=jax.lax.Precision.HIGHEST,
        preferred_element_type=jnp.float32,
    )  # (rows, hd)
    s_acc[:] = s_acc[:] * alpha[:, None] + pv
    s_l[:] = s_l[:] * alpha[:, None] + jnp.sum(p, axis=1)[:, None]
    s_m[:] = jnp.broadcast_to(m_cur[:, None], s_m.shape)


def softmax_emit(acc_ref, m_ref, l_ref, s_acc, s_m, s_l):
    """Write the scratch state out at the last grid block."""
    acc_ref[0, 0] = s_acc[:]
    m_ref[0, 0] = s_m[:]
    l_ref[0, 0] = s_l[:]


def _kernel(
    lengths_ref,  # scalar prefetch [B]
    q_ref,  # (1, 1, r, hd)
    k_ref,  # (1, 1, BS, hd)
    v_ref,  # (1, 1, BS, hd)
    acc_ref,  # out (1, 1, r, hd) f32
    m_ref,  # out (1, 1, r, 128) f32 (value replicated along lanes)
    l_ref,  # out (1, 1, r, 128) f32
    s_acc,  # scratch (r, hd) f32
    s_m,  # scratch (r, 128) f32
    s_l,  # scratch (r, 128) f32
    *,
    block_size: int,
    scale: float,
):
    b = pl.program_id(0)
    j = pl.program_id(2)
    nb = pl.num_programs(2)

    @pl.when(j == 0)
    def _init():
        softmax_scratch_init(s_acc, s_m, s_l)

    length = lengths_ref[b]
    base = j * block_size

    @pl.when(base < length)
    def _block():
        softmax_block_update(
            q_ref[0, 0], k_ref[0, 0], v_ref[0, 0], s_acc, s_m, s_l,
            base=base, length=length, scale=scale,
        )

    @pl.when(j == nb - 1)
    def _emit():
        softmax_emit(acc_ref, m_ref, l_ref, s_acc, s_m, s_l)


def _clamped_kv_map(b, h, j, lengths_ref, *, block_size):
    # last block that holds any valid KV for row b (>= 0 so length-0 rows
    # still address a real tile; their compute is skipped in the kernel)
    last = jnp.maximum(
        (lengths_ref[b] + block_size - 1) // block_size - 1, 0
    )
    return (b, h, jnp.minimum(j, last), 0)


@functools.partial(
    jax.jit,
    static_argnames=("block_size", "interpret"),
)
def flash_decode(
    q: jax.Array,  # [B, Hq, hd]
    k: jax.Array,  # [B, Hkv, S, hd]
    v: jax.Array,  # [B, Hkv, S, hd]
    lengths: jax.Array,  # [B] int32 — valid cache prefix per row
    block_size: int = DEFAULT_BLOCK,
    interpret: bool = False,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Un-normalized online-softmax attention partials over the cache.

    Returns ``(acc [B,Hq,hd] f32, m [B,Hq] f32, l [B,Hq] f32)`` with
    ``out = acc / l`` the attention output when nothing else is merged.
    Rows with ``length == 0`` return ``acc=0, l=0, m=-inf``.
    """
    B, Hq, hd = q.shape
    Hkv, S = k.shape[1], k.shape[2]
    assert Hq % Hkv == 0, (Hq, Hkv)
    r = Hq // Hkv
    assert S % block_size == 0, (S, block_size)
    nb = S // block_size
    qg = q.reshape(B, Hkv, r, hd)

    grid = (B, Hkv, nb)
    kv_map = functools.partial(_clamped_kv_map, block_size=block_size)
    acc, m, l = pl.pallas_call(
        functools.partial(
            _kernel, block_size=block_size, scale=1.0 / np.sqrt(hd)
        ),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=grid,
            in_specs=[
                pl.BlockSpec((1, 1, r, hd), lambda b, h, j, L: (b, h, 0, 0)),
                pl.BlockSpec((1, 1, block_size, hd), kv_map),
                pl.BlockSpec((1, 1, block_size, hd), kv_map),
            ],
            out_specs=[
                pl.BlockSpec((1, 1, r, hd), lambda b, h, j, L: (b, h, 0, 0)),
                pl.BlockSpec((1, 1, r, 128), lambda b, h, j, L: (b, h, 0, 0)),
                pl.BlockSpec((1, 1, r, 128), lambda b, h, j, L: (b, h, 0, 0)),
            ],
            scratch_shapes=[
                pltpu.VMEM((r, hd), jnp.float32),
                pltpu.VMEM((r, 128), jnp.float32),
                pltpu.VMEM((r, 128), jnp.float32),
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((B, Hkv, r, hd), jnp.float32),
            jax.ShapeDtypeStruct((B, Hkv, r, 128), jnp.float32),
            jax.ShapeDtypeStruct((B, Hkv, r, 128), jnp.float32),
        ],
        compiler_params=pallas_tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(lengths.astype(jnp.int32), qg, k, v)
    return (
        acc.reshape(B, Hq, hd),
        m[..., 0].reshape(B, Hq),
        l[..., 0].reshape(B, Hq),
    )


def reference_decode_partials(q, k, v, lengths):
    """jnp reference for :func:`flash_decode` (same (acc, m, l) contract)."""
    B, Hq, hd = q.shape
    Hkv, S = k.shape[1], k.shape[2]
    r = Hq // Hkv
    qg = q.reshape(B, Hkv, r, hd).astype(jnp.float32)
    s = jnp.einsum(
        "bkrd,bksd->bkrs", qg, k.astype(jnp.float32)
    ) / np.sqrt(hd)
    mask = jnp.arange(S)[None, None, None, :] < lengths[:, None, None, None]
    s = jnp.where(mask, s, _NEG_INF)
    m = jnp.max(s, axis=-1)
    p = jnp.where(mask, jnp.exp(s - m[..., None]), 0.0)
    l = jnp.sum(p, axis=-1)
    acc = jnp.einsum("bkrs,bksd->bkrd", p, v.astype(jnp.float32))
    return (
        acc.reshape(B, Hq, hd),
        m.reshape(B, Hq),
        l.reshape(B, Hq),
    )

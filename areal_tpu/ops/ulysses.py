"""Ulysses-style context parallelism: all-to-all sequence<->head exchange.

The second first-class long-context strategy next to ring attention
(areal_tpu/ops/ring_attention.py).  Where the ring rotates KV blocks around
the ICI with n permute steps, Ulysses (DeepSpeed-Ulysses, Jacobs et al.
2023 — public technique) pays exactly TWO all-to-alls: sequence-sharded
QKV are exchanged into head-sharded full-sequence tensors, each device runs
ordinary full attention over its head subset, and the output is exchanged
back.  Preferable when the head count comfortably exceeds the CP degree
and the interconnect's all-to-all is fast (TPU ICI); the ring wins at very
long sequences where the full [T, T] mask/score blocks no longer fit.

Packing semantics match the rest of the stack: same-segment + causal by
within-segment positions, optional sliding window.  The reference system
has NO context parallelism at all (SURVEY §2.9).
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

_NEG_INF = -1e30


def _full_attention(q, k, v, seg, pos, sliding_window):
    """Dense masked attention over the FULL sequence (q/k/v: [B,T,H,hd],
    same head count — kv already repeated)."""
    hd = q.shape[-1]
    scores = jnp.einsum(
        "bthd,bshd->bhts", q.astype(jnp.float32), k.astype(jnp.float32)
    ) / np.sqrt(hd)
    mask = (
        (seg[:, :, None] == seg[:, None, :])
        & (pos[:, :, None] >= pos[:, None, :])
        & (seg[:, :, None] != 0)
        & (seg[:, None, :] != 0)
    )
    if sliding_window is not None:
        mask &= pos[:, :, None] - pos[:, None, :] < sliding_window
    scores = jnp.where(mask[:, None, :, :], scores, _NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    # fully-masked rows (padding queries) produce uniform probs; zero them
    any_valid = mask.any(axis=-1)[:, None, :, None]
    probs = jnp.where(any_valid, probs, 0.0)
    return jnp.einsum("bhts,bshd->bthd", probs, v.astype(jnp.float32))


def ulysses_attention_local(
    q: jax.Array,  # [B, T_local, Hq, hd]
    k: jax.Array,  # [B, T_local, Hkv, hd]
    v: jax.Array,
    seg: jax.Array,  # [B, T_local]
    pos: jax.Array,  # [B, T_local]
    axis_name: str,
    sliding_window: Optional[int] = None,
) -> jax.Array:
    """Per-device body (inside shard_map over ``axis_name``).

    all-to-all #1: [B, T/n, H, hd] -> [B, T, H/n, hd]; full attention on
    the head subset; all-to-all #2 back.  Requires Hq % n == 0; KV heads
    are exchanged directly when Hkv % n == 0 (then repeated locally — the
    contiguous q-head group g owns exactly kv-head group g) and repeated
    BEFORE the exchange otherwise.
    """
    n = jax.lax.psum(1, axis_name)
    B, Tl, Hq, hd = q.shape
    Hkv = k.shape[2]
    rep = Hq // Hkv

    q_full = jax.lax.all_to_all(
        q, axis_name, split_axis=2, concat_axis=1, tiled=True
    )  # [B, T, Hq/n, hd]
    if rep > 1 and Hkv % n != 0:
        # GQA narrower than the CP degree: replicate kv heads up to Hq
        # before the exchange so every q-head group gets its kv twin
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
        rep = 1
    k_full = jax.lax.all_to_all(
        k, axis_name, split_axis=2, concat_axis=1, tiled=True
    )
    v_full = jax.lax.all_to_all(
        v, axis_name, split_axis=2, concat_axis=1, tiled=True
    )
    if rep > 1:
        # contiguous head groups: q group g = q heads [g*Hq/n, (g+1)*Hq/n),
        # whose kv twins are exactly kv group g when (Hq/n) % rep == 0
        k_full = jnp.repeat(k_full, rep, axis=2)
        v_full = jnp.repeat(v_full, rep, axis=2)
    seg_full = jax.lax.all_gather(seg, axis_name, axis=1, tiled=True)
    pos_full = jax.lax.all_gather(pos, axis_name, axis=1, tiled=True)

    out = _full_attention(
        q_full, k_full, v_full, seg_full, pos_full, sliding_window
    )  # [B, T, Hq/n, hd] f32
    out = jax.lax.all_to_all(
        out.astype(q.dtype), axis_name, split_axis=1, concat_axis=2,
        tiled=True,
    )  # [B, T/n, Hq, hd]
    return out


def ulysses_attention(
    q: jax.Array,  # [B, T, Hq, hd] — T sharded over ``axis``
    k: jax.Array,
    v: jax.Array,
    seg: jax.Array,  # [B, T]
    pos: jax.Array,  # [B, T]
    mesh,
    axis: str = "seq",
    batch_axes: Tuple[str, ...] = ("data", "fsdp"),
    head_axis: Optional[str] = "model",
    sliding_window: Optional[int] = None,
) -> jax.Array:
    """shard_map wrapper mirroring :func:`ring_attention.ring_attention`."""
    from areal_tpu.base.jax_compat import shard_map

    n = mesh.shape.get(axis, 1)
    tp = mesh.shape.get(head_axis, 1) if head_axis else 1
    local_hq = q.shape[2] // max(tp, 1)
    if local_hq % n != 0:
        raise ValueError(
            f"ulysses CP needs per-device q heads ({local_hq}) divisible "
            f"by the seq-parallel degree ({n}); use ring attention instead"
        )
    qkv_spec = P(batch_axes, axis, head_axis, None)
    tok_spec = P(batch_axes, axis)
    fn = partial(
        ulysses_attention_local,
        axis_name=axis,
        sliding_window=sliding_window,
    )
    return shard_map(
        fn,
        mesh=mesh,
        in_specs=(qkv_spec, qkv_spec, qkv_spec, tok_spec, tok_spec),
        out_specs=qkv_spec,
        check_vma=False,
    )(q, k, v, seg, pos)

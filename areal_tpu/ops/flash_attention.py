"""TPU flash attention for packed (segment-id) batches.

Replaces the reference's flash-attn varlen CUDA dependency
(reference: realhf/impl/model/modules/attn.py:24-289 using
``flash_attn_varlen_func``) with the TPU-idiomatic equivalent: a Pallas
flash-attention kernel over padded ``[B, T]`` batches where packing is
expressed via segment ids.  The kernel is fully differentiable (custom VJP
saves only logsumexp, so training memory stays O(T) per layer instead of the
O(T^2) probs matrix).

We dispatch to the tuned Pallas TPU kernel shipped with JAX
(``jax.experimental.pallas.ops.tpu.flash_attention``); GQA is handled by
repeating KV heads (layout-only under XLA).  Constraints: no sliding window
(mistral falls back to the jnp reference path), self-attention only
(decode-time KV-cache attention uses the cache path in the model).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

_BLOCK = 512


def supported(q_len: int, kv_len: int, sliding_window) -> bool:
    if sliding_window is not None or q_len != kv_len or q_len < 128:
        return False
    # the kernel requires seq_len divisible by the block size we pick
    return q_len % min(_BLOCK, q_len) == 0


def flash_attention(
    q: jax.Array,  # [B, T, Hq, hd]
    k: jax.Array,  # [B, T, Hkv, hd]
    v: jax.Array,  # [B, T, Hkv, hd]
    seg_ids: jax.Array,  # [B, T] int32, 0 = padding
) -> jax.Array:
    """Causal, segment-masked flash attention. Returns [B, T, Hq, hd]."""
    from jax.experimental.pallas.ops.tpu.flash_attention import (
        BlockSizes,
        SegmentIds,
    )
    from jax.experimental.pallas.ops.tpu.flash_attention import (
        flash_attention as _fa,
    )

    B, T, Hq, hd = q.shape
    Hkv = k.shape[2]
    rep = Hq // Hkv
    if rep > 1:
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)

    # [B, H, T, hd]
    qt = q.swapaxes(1, 2)
    kt = k.swapaxes(1, 2)
    vt = v.swapaxes(1, 2)

    blk = min(_BLOCK, T)
    sizes = BlockSizes(
        block_q=blk,
        block_k_major=blk,
        block_k=blk,
        block_b=1,
        block_q_major_dkv=blk,
        block_k_major_dkv=blk,
        block_k_dkv=blk,
        block_q_dkv=blk,
        block_k_major_dq=blk,
        block_k_dq=blk,
        block_q_dq=blk,
    )
    out = _fa(
        qt,
        kt,
        vt,
        causal=True,
        segment_ids=SegmentIds(q=seg_ids, kv=seg_ids),
        sm_scale=1.0 / np.sqrt(hd),
        block_sizes=sizes,
    )
    return out.swapaxes(1, 2)

"""Pallas paged flash attention over a block-pool KV cache.

In-house TPU kernel for the serving engine's paged KV cache (the role
SGLang/vLLM paged decode kernels play behind the reference's generation
server, reference: realhf/impl/model/backend/sglang.py:369 + SURVEY §2.8
"splash/paged attention kernels").  KV lives in a shared pool of
fixed-size blocks, PAGE-major ``[NB, Hkv, BS, hd]`` (one page = one
contiguous HBM extent); each batch row owns an ordered list of pool
block ids (its *block table*), so cache capacity is allocated in
BS-token pages instead of dense ``max_len`` rows — the difference
between a handful of 32k rows fitting one chip and dozens.

Kernel shape:

* grid ``(B, QB, ceil(MB/G))`` — MB is the static per-row block
  capacity, G pages stream per step (PAGE_GROUP), QB tiles the query
  axis so VMEM scratch stays bounded at prefill-chunk shapes; the minor
  axis iterates sequentially on TPU so online-softmax state (m/l/acc)
  lives in VMEM scratch across blocks;
* the K/V index maps ride TWO scalar-prefetch operands: ``lengths``
  clamps the block index to each row's last valid block (trailing grid
  steps re-address the same tile and the pipeline skips their HBM->VMEM
  copies — short rows stream only the KV they own), and ``tables``
  translates the clamped logical block index into a pool block id;
* queries are GQA-grouped AND chunk-grouped: ``q`` carries Q query
  tokens per row (Q=1 for decode; Q=chunk for chunked prefill's
  prefix attention) and every query row of a (b, qb) cell shares one
  streamed KV page — all KV heads of a page ride one contiguous DMA.

Returns UN-normalized partials ``(acc, m, l)`` so the caller online-merges
them with attention over KV not in the pool yet (the decode chunk's
in-flight window, or a prefill chunk's causal self-attention).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from areal_tpu.base.jax_compat import pallas_tpu_compiler_params

from areal_tpu.ops.decode_attention import (
    softmax_block_update,
    softmax_emit,
    softmax_scratch_init,
)

DEFAULT_BLOCK = 256
_NEG_INF = -1e30


#: logical pages streamed per grid step.  The kernel is DMA-LATENCY-bound
#: at one small page per step (~1us fixed cost per HBM->VMEM copy caps it
#: at ~200 GB/s on v5e); issuing G page copies per step overlaps their
#: latencies.  Measured on v5e at 8k ctx (1.5B arch, B=16, 256-token
#: pages): G=1 0.70x of the dense-einsum path, G=4 0.78x, and G=4 with
#: 1024-token pages 0.93x — G=8 regresses (0.83x), so 4 it is.
PAGE_GROUP = 4


#: cap on query rows (Q*r) per grid cell: bounds the f32 scratch at
#: ~Hkv * 512 * (hd + 256) * 4 bytes (~1.6 MB at Hkv=2, hd=128) so
#: prefill-chunk shapes (Q up to prefill_chunk_tokens) tile the query
#: axis instead of blowing VMEM (code-review r5 #3)
MAX_Q_ROWS = 512


def _kernel(
    lengths_ref,  # scalar prefetch [B]
    tables_ref,  # scalar prefetch [B, MB]
    layer_ref,  # scalar prefetch [1] (0 when the pool is per-layer)
    q_ref,  # (1, 1, Hkv, QR, hd)
    *refs,  # G k-page refs, G v-page refs, [2G scale refs], 3 outs, 3 scratch
    block_size: int,
    scale: float,
    n_kv_heads: int,
    page_group: int,
    quantized: bool = False,
):
    G = page_group
    k_refs = refs[:G]
    v_refs = refs[G : 2 * G]
    base_idx = 2 * G
    ks_refs = vs_refs = ()
    if quantized:
        ks_refs = refs[2 * G : 3 * G]
        vs_refs = refs[3 * G : 4 * G]
        base_idx = 4 * G
    acc_ref, m_ref, l_ref = refs[base_idx : base_idx + 3]
    s_acc, s_m, s_l = refs[base_idx + 3 :]
    b = pl.program_id(0)
    j = pl.program_id(2)
    nb = pl.num_programs(2)

    @pl.when(j == 0)
    def _init():
        softmax_scratch_init(s_acc, s_m, s_l)

    length = lengths_ref[b]
    hd = k_refs[0].shape[-1]
    for g in range(G):
        base = (j * G + g) * block_size

        @pl.when(base < length)
        def _block(g=g, base=base):
            # each page tile is one CONTIGUOUS (Hkv, BS, hd) copy; all
            # KV heads ride it together
            k_all = k_refs[g][...].reshape(n_kv_heads, block_size, hd)
            v_all = v_refs[g][...].reshape(n_kv_heads, block_size, hd)
            if quantized:
                # in-kernel dequant: multiply the int8 page by its
                # per-(head, slot) scales right after the gather, so the
                # attention dots below run in f32 like the fp path
                ks = ks_refs[g][...].reshape(n_kv_heads, block_size)
                vs = vs_refs[g][...].reshape(n_kv_heads, block_size)
                k_all = k_all.astype(jnp.float32) * ks[:, :, None]
                v_all = v_all.astype(jnp.float32) * vs[:, :, None]
            for h in range(n_kv_heads):
                softmax_block_update(
                    q_ref[0, 0, h], k_all[h], v_all[h],
                    s_acc.at[h], s_m.at[h], s_l.at[h],
                    base=base, length=length, scale=scale,
                )

    @pl.when(j == nb - 1)
    def _emit():
        acc_ref[0, 0] = s_acc[...]
        m_ref[0, 0] = s_m[...]
        l_ref[0, 0] = s_l[...]


def _paged_kv_map(b, qb, j, lengths_ref, tables_ref, layer_ref, *,
                  block_size, layered, group, offset):
    # page ``j * group + offset``, clamped to the last LOGICAL block
    # holding valid KV for row b (trailing steps re-address that tile and
    # the pipeline skips their copies), then translated through the row's
    # block table into a pool block id
    last = jnp.maximum(
        (lengths_ref[b] + block_size - 1) // block_size - 1, 0
    )
    pid = tables_ref[b, jnp.minimum(j * group + offset, last)]
    if layered:
        return (layer_ref[0], pid, 0, 0, 0)
    return (pid, 0, 0, 0)



def _group_queries(q, Hkv, r):
    """Pad + regroup [B, Q, Hq, hd] queries into per-(kv-head) row tiles
    [B, QB, Hkv, QT*r, hd] (QT bounded by MAX_Q_ROWS); returns
    (qg, QT, QB, Qp)."""
    B, Q, Hq, hd = q.shape
    QT = max(1, min(Q, MAX_Q_ROWS // r))
    QB = -(-Q // QT)
    Qp = QB * QT
    q_pad = (
        jnp.pad(q, ((0, 0), (0, Qp - Q), (0, 0), (0, 0)))
        if Qp != Q
        else q
    )
    qg = (
        q_pad.reshape(B, QB, QT, Hkv, r, hd)
        .transpose(0, 1, 3, 2, 4, 5)
        .reshape(B, QB, Hkv, QT * r, hd)
    )
    return qg, QT, QB, Qp


def _ungroup_outputs(acc, m, l, B, QB, QT, Hkv, r, Q, Hq, hd):
    """Invert :func:`_group_queries` on the kernel's (acc, m, l)."""

    def unravel(x, lanes):
        return (
            x.reshape(B, QB, Hkv, QT, r, lanes)
            .transpose(0, 1, 3, 2, 4, 5)
            .reshape(B, QB * QT, Hq, lanes)[:, :Q]
        )

    return (
        unravel(acc, hd),
        unravel(m, 128)[..., 0],
        unravel(l, 128)[..., 0],
    )


def _layer_scalar(layer):
    return (
        jnp.zeros((1,), jnp.int32)
        if layer is None
        else jnp.asarray(layer, jnp.int32).reshape(1)
    )


@functools.partial(jax.jit, static_argnames=("interpret",))
def paged_flash_attention(
    q: jax.Array,  # [B, Q, Hq, hd]
    k_pool: jax.Array,  # [NB, Hkv, BS, hd] or [L, NB, Hkv, BS, hd]
    v_pool: jax.Array,
    tables: jax.Array,  # [B, MB] int32 — pool block id per logical block
    lengths: jax.Array,  # [B] int32 — valid cache prefix per row
    layer: jax.Array | None = None,  # [] or [1] int32, for stacked pools
    interpret: bool = False,
    k_scale: jax.Array | None = None,  # [(L,) NB, Hkv, BS] int8-pool scales
    v_scale: jax.Array | None = None,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Un-normalized online-softmax attention partials over paged KV.

    Every query token attends the FULL prefix ``[0, length)`` of its row
    (decode queries by definition; prefill-chunk queries because the
    prefix precedes the whole chunk — in-chunk causality is the caller's
    self-attention term).  Returns ``(acc [B,Q,Hq,hd] f32, m [B,Q,Hq],
    l [B,Q,Hq])``; rows with ``length == 0`` return ``acc=0, l=0, m=-inf``.

    Pool layout is PAGE-major ``[NB, Hkv, BS, hd]`` so one page's tile is
    one contiguous (Hkv, BS, hd) HBM read, and the grid streams
    ``PAGE_GROUP`` pages per step (their DMAs overlap — see PAGE_GROUP).

    A 5-D ``k_pool``/``v_pool`` is the FULL layer-stacked pool; ``layer``
    (traced scalar) selects the layer inside the kernel's index map, so a
    layer scan never materializes a per-layer pool slice (that slice is
    pool_bytes/L of pure copy traffic per layer — the whole pool per
    forward).

    ``k_scale``/``v_scale`` mark an int8-quantized pool: each page's
    scale tile streams beside its KV tile through the same index map and
    the kernel dequantizes in VMEM right after the gather (the
    storage-only quantization contract).
    """
    B, Q, Hq, hd = q.shape
    layered = k_pool.ndim == 5
    NB, Hkv, BS, _ = k_pool.shape[-4:]
    MB = tables.shape[1]
    assert Hq % Hkv == 0, (Hq, Hkv)
    if layered:
        assert layer is not None, "layer index required for a stacked pool"
    r = Hq // Hkv
    # tile the query axis: QT tokens per grid cell, QT*r rows of scratch
    qg, QT, QB, Qp = _group_queries(q, Hkv, r)
    layer_arr = _layer_scalar(layer)

    G = min(PAGE_GROUP, MB)
    quantized = k_scale is not None
    grid = (B, QB, -(-MB // G))
    kv_block = (1, 1, Hkv, BS, hd) if layered else (1, Hkv, BS, hd)
    kv_specs = [
        pl.BlockSpec(
            kv_block,
            functools.partial(
                _paged_kv_map,
                block_size=BS,
                layered=layered,
                group=G,
                offset=g,
            ),
        )
        for g in range(G)
    ]
    # int8 pools: each page's scale tile (one f32 per head x slot) rides
    # the same clamped index map as its KV tile
    scale_block = (1, 1, Hkv, BS) if layered else (1, Hkv, BS)
    scale_specs = [
        pl.BlockSpec(
            scale_block,
            functools.partial(
                _paged_scale_map,
                block_size=BS,
                layered=layered,
                group=G,
                offset=g,
            ),
        )
        for g in range(G)
    ]
    acc, m, l = pl.pallas_call(
        functools.partial(
            _kernel,
            block_size=BS,
            scale=1.0 / np.sqrt(hd),
            n_kv_heads=Hkv,
            page_group=G,
            quantized=quantized,
        ),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=3,
            grid=grid,
            in_specs=(
                [
                    pl.BlockSpec(
                        (1, 1, Hkv, QT * r, hd),
                        lambda b, qb, j, L, T, Y: (b, qb, 0, 0, 0),
                    )
                ]
                + kv_specs  # G k-page streams
                + kv_specs  # G v-page streams (same maps, v operands)
                + (scale_specs + scale_specs if quantized else [])
            ),
            out_specs=[
                pl.BlockSpec(
                    (1, 1, Hkv, QT * r, hd),
                    lambda b, qb, j, L, T, Y: (b, qb, 0, 0, 0),
                ),
                pl.BlockSpec(
                    (1, 1, Hkv, QT * r, 128),
                    lambda b, qb, j, L, T, Y: (b, qb, 0, 0, 0),
                ),
                pl.BlockSpec(
                    (1, 1, Hkv, QT * r, 128),
                    lambda b, qb, j, L, T, Y: (b, qb, 0, 0, 0),
                ),
            ],
            scratch_shapes=[
                pltpu.VMEM((Hkv, QT * r, hd), jnp.float32),
                pltpu.VMEM((Hkv, QT * r, 128), jnp.float32),
                pltpu.VMEM((Hkv, QT * r, 128), jnp.float32),
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((B, QB, Hkv, QT * r, hd), jnp.float32),
            jax.ShapeDtypeStruct((B, QB, Hkv, QT * r, 128), jnp.float32),
            jax.ShapeDtypeStruct((B, QB, Hkv, QT * r, 128), jnp.float32),
        ],
        compiler_params=pallas_tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(
        lengths.astype(jnp.int32),
        tables.astype(jnp.int32),
        layer_arr,
        qg,
        *([k_pool] * G),
        *([v_pool] * G),
        *(([k_scale] * G + [v_scale] * G) if quantized else []),
    )

    return _ungroup_outputs(acc, m, l, B, QB, QT, Hkv, r, Q, Hq, hd)


def _paged_scale_map(b, qb, j, lengths_ref, tables_ref, layer_ref, *,
                     block_size, layered, group, offset):
    """Scale-pool twin of :func:`_paged_kv_map` (one fewer trailing dim)."""
    last = jnp.maximum(
        (lengths_ref[b] + block_size - 1) // block_size - 1, 0
    )
    pid = tables_ref[b, jnp.minimum(j * group + offset, last)]
    if layered:
        return (layer_ref[0], pid, 0, 0)
    return (pid, 0, 0)


#: in-flight page DMAs of the deep-pipelined kernel (see
#: paged_flash_attention_deep); 8 x ~0.5 MB tiles keep the HBM stream
#: saturated where the BlockSpec pipeline's 1-deep lookahead cannot
DEEP_BUFFERS = 8


def _deep_kernel(
    lengths_ref,  # scalar prefetch [B]
    tables_ref,  # scalar prefetch [B, MB]
    layer_ref,  # scalar prefetch [1]
    q_ref,  # (1, 1, Hkv, QR, hd) VMEM
    *refs,  # k_hbm, v_hbm, [ks_hbm, vs_hbm], 3 outs, bufs, scratch, sems
    block_size: int,
    scale: float,
    n_kv_heads: int,
    layered: bool,
    max_blocks: int,
    n_buffers: int,
    quantized: bool = False,
):
    if quantized:
        (k_hbm, v_hbm, ks_hbm, vs_hbm, acc_ref, m_ref, l_ref,
         kbuf, vbuf, ksbuf, vsbuf, s_acc, s_m, s_l,
         k_sems, v_sems, ks_sems, vs_sems) = refs
    else:
        (k_hbm, v_hbm, acc_ref, m_ref, l_ref, kbuf, vbuf,
         s_acc, s_m, s_l, k_sems, v_sems) = refs
    NBUF = n_buffers
    b = pl.program_id(0)
    length = lengths_ref[b]
    n_blocks = jnp.minimum(
        jnp.maximum((length + block_size - 1) // block_size, 0), max_blocks
    )
    lay = layer_ref[0]

    softmax_scratch_init(s_acc, s_m, s_l)

    def src(j):
        pid = tables_ref[b, jnp.minimum(j, max_blocks - 1)]
        if layered:
            return lambda r: r.at[lay, pid]
        return lambda r: r.at[pid]

    def dma_group(j, slot):
        sel = src(j)
        copies = [
            pltpu.make_async_copy(sel(k_hbm), kbuf.at[slot], k_sems.at[slot]),
            pltpu.make_async_copy(sel(v_hbm), vbuf.at[slot], v_sems.at[slot]),
        ]
        if quantized:
            # the page's scale tiles ride the same DMA ring slot — the
            # in-kernel-dequant half of the int8 storage format
            copies.append(
                pltpu.make_async_copy(
                    sel(ks_hbm), ksbuf.at[slot], ks_sems.at[slot]
                )
            )
            copies.append(
                pltpu.make_async_copy(
                    sel(vs_hbm), vsbuf.at[slot], vs_sems.at[slot]
                )
            )
        return copies

    # warm-up: fill the buffer ring
    def warm(j, _):
        @pl.when(j < n_blocks)
        def _():
            for c in dma_group(j, j % NBUF):
                c.start()
        return 0

    jax.lax.fori_loop(0, NBUF, warm, 0)

    def body(j, _):
        slot = j % NBUF
        for c in dma_group(j, slot):
            c.wait()
        k_all = kbuf[slot]
        v_all = vbuf[slot]
        if quantized:
            k_all = k_all.astype(jnp.float32) * ksbuf[slot][:, :, None]
            v_all = v_all.astype(jnp.float32) * vsbuf[slot][:, :, None]
        for h in range(n_kv_heads):
            softmax_block_update(
                q_ref[0, 0, h], k_all[h], v_all[h],
                s_acc.at[h], s_m.at[h], s_l.at[h],
                base=j * block_size, length=length, scale=scale,
            )
        # refill this slot with the page NBUF ahead
        nxt = j + NBUF

        @pl.when(nxt < n_blocks)
        def _():
            for c in dma_group(nxt, slot):
                c.start()
        return 0

    jax.lax.fori_loop(0, n_blocks, body, 0)

    acc_ref[0, 0] = s_acc[...]
    m_ref[0, 0] = s_m[...]
    l_ref[0, 0] = s_l[...]


@functools.partial(jax.jit, static_argnames=("interpret",))
def paged_flash_attention_deep(
    q: jax.Array,  # [B, Q, Hq, hd]
    k_pool: jax.Array,  # [NB, Hkv, BS, hd] or [L, NB, Hkv, BS, hd]
    v_pool: jax.Array,
    tables: jax.Array,  # [B, MB]
    lengths: jax.Array,  # [B]
    layer: jax.Array | None = None,
    interpret: bool = False,
    k_scale: jax.Array | None = None,  # [(L,) NB, Hkv, BS] int8-pool scales
    v_scale: jax.Array | None = None,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Deep-pipelined variant of :func:`paged_flash_attention`: the pool
    stays in HBM and the kernel issues its own page DMAs with a
    ``DEEP_BUFFERS``-deep ring, so up to 8 page copies are in flight —
    the BlockSpec pipeline's single-step lookahead is what caps the
    default kernel at ~350 GB/s on v5e (DMA-latency-bound).  Same
    (acc, m, l) contract; rows stream only their valid pages.

    EXPERIMENTAL: numerics are parity-tested (interpret mode + TPU), but
    until it is measured FASTER on hardware the engine keeps the default
    kernel (bench.py's decode A/B reports both).
    """
    B, Q, Hq, hd = q.shape
    layered = k_pool.ndim == 5
    NB, Hkv, BS, _ = k_pool.shape[-4:]
    MB = tables.shape[1]
    assert Hq % Hkv == 0
    if layered:
        assert layer is not None
    r = Hq // Hkv
    quantized = k_scale is not None
    qg, QT, QB, Qp = _group_queries(q, Hkv, r)
    layer_arr = _layer_scalar(layer)
    # ring depth bounded by a ~12 MB VMEM budget for the page rings
    # (int8 pools add a small f32 scale tile per page)
    tile_bytes = Hkv * BS * hd * jnp.dtype(k_pool.dtype).itemsize
    if quantized:
        tile_bytes += Hkv * BS * 4
    nbuf = int(max(2, min(DEEP_BUFFERS, (6 << 20) // max(tile_bytes, 1))))
    grid = (B, QB)
    scratch = [
        pltpu.VMEM((nbuf, Hkv, BS, hd), k_pool.dtype),
        pltpu.VMEM((nbuf, Hkv, BS, hd), v_pool.dtype),
    ]
    if quantized:
        scratch += [
            pltpu.VMEM((nbuf, Hkv, BS), jnp.float32),
            pltpu.VMEM((nbuf, Hkv, BS), jnp.float32),
        ]
    scratch += [
        pltpu.VMEM((Hkv, QT * r, hd), jnp.float32),
        pltpu.VMEM((Hkv, QT * r, 128), jnp.float32),
        pltpu.VMEM((Hkv, QT * r, 128), jnp.float32),
    ]
    scratch += [pltpu.SemaphoreType.DMA((nbuf,))] * (4 if quantized else 2)
    acc, m, l = pl.pallas_call(
        functools.partial(
            _deep_kernel,
            block_size=BS,
            scale=1.0 / np.sqrt(hd),
            n_kv_heads=Hkv,
            layered=layered,
            max_blocks=MB,
            n_buffers=nbuf,
            quantized=quantized,
        ),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=3,
            grid=grid,
            in_specs=[
                pl.BlockSpec(
                    (1, 1, Hkv, QT * r, hd),
                    lambda b, qb, L, T, Y: (b, qb, 0, 0, 0),
                ),
            ]
            + [pl.BlockSpec(memory_space=pl.ANY)]
            * (4 if quantized else 2),
            out_specs=[
                pl.BlockSpec(
                    (1, 1, Hkv, QT * r, hd),
                    lambda b, qb, L, T, Y: (b, qb, 0, 0, 0),
                ),
                pl.BlockSpec(
                    (1, 1, Hkv, QT * r, 128),
                    lambda b, qb, L, T, Y: (b, qb, 0, 0, 0),
                ),
                pl.BlockSpec(
                    (1, 1, Hkv, QT * r, 128),
                    lambda b, qb, L, T, Y: (b, qb, 0, 0, 0),
                ),
            ],
            scratch_shapes=scratch,
        ),
        out_shape=[
            jax.ShapeDtypeStruct((B, QB, Hkv, QT * r, hd), jnp.float32),
            jax.ShapeDtypeStruct((B, QB, Hkv, QT * r, 128), jnp.float32),
            jax.ShapeDtypeStruct((B, QB, Hkv, QT * r, 128), jnp.float32),
        ],
        compiler_params=pallas_tpu_compiler_params(
            dimension_semantics=("arbitrary", "arbitrary"),
        ),
        interpret=interpret,
    )(
        lengths.astype(jnp.int32),
        tables.astype(jnp.int32),
        layer_arr,
        qg,
        k_pool,
        v_pool,
        *((k_scale, v_scale) if quantized else ()),
    )

    return _ungroup_outputs(acc, m, l, B, QB, QT, Hkv, r, Q, Hq, hd)


def gather_paged_kv(
    k_pool: jax.Array,  # [NB, Hkv, BS, hd] (or [L, NB, Hkv, BS, hd])
    v_pool: jax.Array,
    tables: jax.Array,  # [B, MB]
) -> Tuple[jax.Array, jax.Array]:
    """Materialize per-row dense KV ``[..., B, Hkv, MB*BS, hd]`` from the
    pool (jnp reference/CPU path; the kernel never does this)."""

    def g(pool):
        gathered = jnp.take(pool, tables, axis=-4)  # [..,B,MB,Hkv,BS,hd]
        gathered = jnp.moveaxis(gathered, -3, -4)  # [..,B,Hkv,MB,BS,hd]
        s = gathered.shape
        return gathered.reshape(*s[:-3], s[-3] * s[-2], s[-1])

    return g(k_pool), g(v_pool)


def reference_paged_partials(
    q, k_pool, v_pool, tables, lengths, k_scale=None, v_scale=None
):
    """jnp reference for :func:`paged_flash_attention` (same contract).

    ``k_scale``/``v_scale`` ([NB, Hkv, BS]) mark an int8 pool: the
    gathered pages are multiplied by their per-(head, slot) scales right
    after the block gather — dequant-on-read, storage-only error."""
    B, Q, Hq, hd = q.shape
    NB, Hkv, BS, _ = k_pool.shape
    r = Hq // Hkv
    k, v = gather_paged_kv(k_pool, v_pool, tables)  # [B,Hkv,S,hd]
    if k_scale is not None:
        ks, vs = gather_paged_kv(
            k_scale[..., None], v_scale[..., None], tables
        )  # [B,Hkv,S,1]
        k = k.astype(jnp.float32) * ks
        v = v.astype(jnp.float32) * vs
    S = k.shape[2]
    qg = q.reshape(B, Q, Hkv, r, hd).astype(jnp.float32)
    s = jnp.einsum(
        "bqkrd,bksd->bqkrs", qg, k.astype(jnp.float32)
    ) / np.sqrt(hd)
    mask = (
        jnp.arange(S)[None, None, None, None, :]
        < lengths[:, None, None, None, None]
    )
    s = jnp.where(mask, s, _NEG_INF)
    m = jnp.max(s, axis=-1)
    p = jnp.where(mask, jnp.exp(s - m[..., None]), 0.0)
    l = jnp.sum(p, axis=-1)
    acc = jnp.einsum("bqkrs,bksd->bqkrd", p, v.astype(jnp.float32))
    return (
        acc.reshape(B, Q, Hq, hd),
        m.reshape(B, Q, Hq),
        l.reshape(B, Q, Hq),
    )

"""Pallas paged flash attention over a block-pool KV cache.

In-house TPU kernel for the serving engine's paged KV cache (the role
SGLang/vLLM paged decode kernels play behind the reference's generation
server, reference: realhf/impl/model/backend/sglang.py:369 + SURVEY §2.8
"splash/paged attention kernels").  KV lives in a shared pool of
fixed-size blocks ``[Hkv, NB, BS, hd]``; each batch row owns an ordered
list of pool block ids (its *block table*), so cache capacity is
allocated in BS-token pages instead of dense ``max_len`` rows — the
difference between a handful of 32k rows fitting one chip and dozens.

Kernel shape:

* grid ``(B, Hkv, MB)`` — MB is the static per-row block capacity; the
  minor axis iterates sequentially on TPU so online-softmax state
  (m/l/acc) lives in VMEM scratch across blocks;
* the K/V index maps ride TWO scalar-prefetch operands: ``lengths``
  clamps the block index to each row's last valid block (trailing grid
  steps re-address the same tile and the pipeline skips their HBM->VMEM
  copies — short rows stream only the KV they own), and ``tables``
  translates the clamped logical block index into a pool block id;
* queries are GQA-grouped AND chunk-grouped: ``q`` carries Q query
  tokens per row (Q=1 for decode; Q=chunk for chunked prefill's
  prefix attention) and all Q*r query rows of a (b, h) cell share one
  streamed KV block — the pool is read once per KV head per block.

Returns UN-normalized partials ``(acc, m, l)`` so the caller online-merges
them with attention over KV not in the pool yet (the decode chunk's
in-flight window, or a prefill chunk's causal self-attention).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from areal_tpu.ops.decode_attention import (
    softmax_block_update,
    softmax_emit,
    softmax_scratch_init,
)

DEFAULT_BLOCK = 256
_NEG_INF = -1e30


def _kernel(
    lengths_ref,  # scalar prefetch [B]
    tables_ref,  # scalar prefetch [B, MB]
    q_ref,  # (1, 1, QR, hd)
    k_ref,  # (1, 1, BS, hd) — pool block selected by the index map
    v_ref,  # (1, 1, BS, hd)
    acc_ref,  # out (1, 1, QR, hd) f32
    m_ref,  # out (1, 1, QR, 128) f32 (value replicated along lanes)
    l_ref,  # out (1, 1, QR, 128) f32
    s_acc,  # scratch (QR, hd) f32
    s_m,  # scratch (QR, 128) f32
    s_l,  # scratch (QR, 128) f32
    *,
    block_size: int,
    scale: float,
):
    b = pl.program_id(0)
    j = pl.program_id(2)
    nb = pl.num_programs(2)

    @pl.when(j == 0)
    def _init():
        softmax_scratch_init(s_acc, s_m, s_l)

    length = lengths_ref[b]
    base = j * block_size

    @pl.when(base < length)
    def _block():
        softmax_block_update(
            q_ref, k_ref, v_ref, s_acc, s_m, s_l,
            base=base, length=length, scale=scale,
        )

    @pl.when(j == nb - 1)
    def _emit():
        softmax_emit(acc_ref, m_ref, l_ref, s_acc, s_m, s_l)


def _paged_kv_map(b, h, j, lengths_ref, tables_ref, *, block_size):
    # clamp to the last LOGICAL block holding valid KV for row b, then
    # translate through the row's block table into a pool block id
    last = jnp.maximum(
        (lengths_ref[b] + block_size - 1) // block_size - 1, 0
    )
    return (h, tables_ref[b, jnp.minimum(j, last)], 0, 0)


@functools.partial(jax.jit, static_argnames=("interpret",))
def paged_flash_attention(
    q: jax.Array,  # [B, Q, Hq, hd]
    k_pool: jax.Array,  # [Hkv, NB, BS, hd]
    v_pool: jax.Array,  # [Hkv, NB, BS, hd]
    tables: jax.Array,  # [B, MB] int32 — pool block id per logical block
    lengths: jax.Array,  # [B] int32 — valid cache prefix per row
    interpret: bool = False,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Un-normalized online-softmax attention partials over paged KV.

    Every query token attends the FULL prefix ``[0, length)`` of its row
    (decode queries by definition; prefill-chunk queries because the
    prefix precedes the whole chunk — in-chunk causality is the caller's
    self-attention term).  Returns ``(acc [B,Q,Hq,hd] f32, m [B,Q,Hq],
    l [B,Q,Hq])``; rows with ``length == 0`` return ``acc=0, l=0, m=-inf``.
    """
    B, Q, Hq, hd = q.shape
    Hkv, NB, BS, _ = k_pool.shape
    MB = tables.shape[1]
    assert Hq % Hkv == 0, (Hq, Hkv)
    r = Hq // Hkv
    qg = (
        q.reshape(B, Q, Hkv, r, hd)
        .transpose(0, 2, 1, 3, 4)
        .reshape(B, Hkv, Q * r, hd)
    )

    grid = (B, Hkv, MB)
    kv_map = functools.partial(_paged_kv_map, block_size=BS)
    acc, m, l = pl.pallas_call(
        functools.partial(_kernel, block_size=BS, scale=1.0 / np.sqrt(hd)),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=grid,
            in_specs=[
                pl.BlockSpec(
                    (1, 1, Q * r, hd), lambda b, h, j, L, T: (b, h, 0, 0)
                ),
                pl.BlockSpec((1, 1, BS, hd), kv_map),
                pl.BlockSpec((1, 1, BS, hd), kv_map),
            ],
            out_specs=[
                pl.BlockSpec(
                    (1, 1, Q * r, hd), lambda b, h, j, L, T: (b, h, 0, 0)
                ),
                pl.BlockSpec(
                    (1, 1, Q * r, 128), lambda b, h, j, L, T: (b, h, 0, 0)
                ),
                pl.BlockSpec(
                    (1, 1, Q * r, 128), lambda b, h, j, L, T: (b, h, 0, 0)
                ),
            ],
            scratch_shapes=[
                pltpu.VMEM((Q * r, hd), jnp.float32),
                pltpu.VMEM((Q * r, 128), jnp.float32),
                pltpu.VMEM((Q * r, 128), jnp.float32),
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((B, Hkv, Q * r, hd), jnp.float32),
            jax.ShapeDtypeStruct((B, Hkv, Q * r, 128), jnp.float32),
            jax.ShapeDtypeStruct((B, Hkv, Q * r, 128), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(
        lengths.astype(jnp.int32),
        tables.astype(jnp.int32),
        qg,
        k_pool,
        v_pool,
    )

    def unravel(x, lanes):
        return (
            x.reshape(B, Hkv, Q, r, lanes)
            .transpose(0, 2, 1, 3, 4)
            .reshape(B, Q, Hq, lanes)
        )

    return (
        unravel(acc, hd),
        unravel(m, 128)[..., 0],
        unravel(l, 128)[..., 0],
    )


def gather_paged_kv(
    k_pool: jax.Array,  # [Hkv, NB, BS, hd] (or [L, Hkv, NB, BS, hd])
    v_pool: jax.Array,
    tables: jax.Array,  # [B, MB]
) -> Tuple[jax.Array, jax.Array]:
    """Materialize per-row dense KV ``[..., B, Hkv, MB*BS, hd]`` from the
    pool (jnp reference/CPU path; the kernel never does this)."""

    def g(pool):
        gathered = jnp.take(pool, tables, axis=-3)  # [..,Hkv,B,MB,BS,hd]
        gathered = jnp.moveaxis(gathered, -4, -5)  # [..,B,Hkv,MB,BS,hd]
        s = gathered.shape
        return gathered.reshape(*s[:-3], s[-3] * s[-2], s[-1])

    return g(k_pool), g(v_pool)


def reference_paged_partials(q, k_pool, v_pool, tables, lengths):
    """jnp reference for :func:`paged_flash_attention` (same contract)."""
    B, Q, Hq, hd = q.shape
    Hkv, NB, BS, _ = k_pool.shape
    r = Hq // Hkv
    k, v = gather_paged_kv(k_pool, v_pool, tables)  # [B,Hkv,S,hd]
    S = k.shape[2]
    qg = q.reshape(B, Q, Hkv, r, hd).astype(jnp.float32)
    s = jnp.einsum(
        "bqkrd,bksd->bqkrs", qg, k.astype(jnp.float32)
    ) / np.sqrt(hd)
    mask = (
        jnp.arange(S)[None, None, None, None, :]
        < lengths[:, None, None, None, None]
    )
    s = jnp.where(mask, s, _NEG_INF)
    m = jnp.max(s, axis=-1)
    p = jnp.where(mask, jnp.exp(s - m[..., None]), 0.0)
    l = jnp.sum(p, axis=-1)
    acc = jnp.einsum("bqkrs,bksd->bqkrd", p, v.astype(jnp.float32))
    return (
        acc.reshape(B, Q, Hq, hd),
        m.reshape(B, Q, Hq),
        l.reshape(B, Q, Hq),
    )

"""Ring attention: context parallelism over the ``seq`` mesh axis.

The reference has NO context parallelism (SURVEY §2.9: long context handled
by packed batches + token-budget micro-batching); this module provides the
TPU-idiomatic long-context answer the rebuild is expected to add: activations
sharded along the sequence dimension over the ICI ring, with KV blocks
rotated via ``lax.ppermute`` while each device accumulates its queries'
attention in online-softmax form (blockwise attention; see RingAttention,
Liu et al. 2023 — public technique).

Pure-jnp blockwise math (autodiff-friendly; XLA fuses the per-block matmuls
onto the MXU), usable standalone inside ``shard_map`` or through
:func:`ring_attention` which wraps the shard_map plumbing.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

_NEG_INF = -1e30


def _block_attn(
    q,  # [B, Tq, H, hd]
    k,  # [B, Tk, H, hd]  (already head-repeated to H = n_q_heads)
    v,  # [B, Tk, H, hd]
    mask,  # [B, Tq, Tk] bool
) -> Tuple[jax.Array, jax.Array]:
    """Unnormalized block attention: returns (weighted values [B,Tq,H,hd],
    row logsumexp [B,H,Tq])."""
    hd = q.shape[-1]
    scores = jnp.einsum(
        "bthd,bshd->bhts", q.astype(jnp.float32), k.astype(jnp.float32)
    ) / np.sqrt(hd)
    scores = jnp.where(mask[:, None, :, :], scores, _NEG_INF)
    lse = jax.nn.logsumexp(scores, axis=-1)  # [B,H,Tq]
    probs = jnp.exp(scores - lse[..., None])
    # rows with no valid key: lse == -inf-ish; zero their probs
    valid_row = lse > _NEG_INF / 2
    probs = jnp.where(valid_row[..., None], probs, 0.0)
    out = jnp.einsum("bhts,bshd->bthd", probs, v.astype(jnp.float32))
    return out, lse


def _combine(out_a, lse_a, out_b, lse_b):
    """Merge two partial attention results in online-softmax form."""
    lse = jnp.logaddexp(lse_a, lse_b)
    wa = jnp.exp(lse_a - lse)[..., None].swapaxes(1, 2)  # [B,Tq,H,1]
    wb = jnp.exp(lse_b - lse)[..., None].swapaxes(1, 2)
    return out_a * wa + out_b * wb, lse


def ring_attention_local(
    q: jax.Array,  # [B, T_local, Hq, hd]
    k: jax.Array,  # [B, T_local, Hkv, hd]
    v: jax.Array,
    seg: jax.Array,  # [B, T_local] int32 (0 = padding)
    pos: jax.Array,  # [B, T_local] int32 within-segment positions
    axis_name: str,
    sliding_window: Optional[int] = None,
) -> jax.Array:
    """Per-device body (call inside shard_map over ``axis_name``).

    Each rotation step r: this device attends its local queries against the
    KV block originally owned by device (i - r) mod n, received over the
    ring.  Packing semantics (same-segment + causal by positions) work
    across blocks because segment ids are globally unique per row.
    """
    n = jax.lax.psum(1, axis_name)
    Hq, Hkv = q.shape[2], k.shape[2]
    rep = Hq // Hkv
    if rep > 1:
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)

    def mask_for(seg_kv, pos_kv):
        m = (
            (seg[:, :, None] == seg_kv[:, None, :])
            & (pos[:, :, None] >= pos_kv[:, None, :])
            & (seg[:, :, None] != 0)
            & (seg_kv[:, None, :] != 0)
        )
        if sliding_window is not None:
            m &= pos[:, :, None] - pos_kv[:, None, :] < sliding_window
        return m

    perm = [(i, (i + 1) % n) for i in range(n)]

    def body(carry, _):
        out, lse, kv_k, kv_v, kv_seg, kv_pos = carry
        o_i, lse_i = _block_attn(q, kv_k, kv_v, mask_for(kv_seg, kv_pos))
        out, lse = _combine(out, lse, o_i, lse_i)
        kv_k = jax.lax.ppermute(kv_k, axis_name, perm)
        kv_v = jax.lax.ppermute(kv_v, axis_name, perm)
        kv_seg = jax.lax.ppermute(kv_seg, axis_name, perm)
        kv_pos = jax.lax.ppermute(kv_pos, axis_name, perm)
        return (out, lse, kv_k, kv_v, kv_seg, kv_pos), None

    B, T, H, hd = q.shape
    out0 = jnp.zeros((B, T, H, hd), jnp.float32)
    lse0 = jnp.full((B, H, T), _NEG_INF, jnp.float32)
    (out, lse, *_), _ = jax.lax.scan(
        body, (out0, lse0, k, v, seg, pos), None, length=n
    )
    return out.astype(q.dtype)


def ring_attention(
    q: jax.Array,  # [B, T, Hq, hd] — T sharded over ``axis``
    k: jax.Array,
    v: jax.Array,
    seg: jax.Array,  # [B, T]
    pos: jax.Array,  # [B, T]
    mesh,
    axis: str = "seq",
    batch_axes: Tuple[str, ...] = ("data", "fsdp"),
    head_axis: Optional[str] = "model",
    sliding_window: Optional[int] = None,
) -> jax.Array:
    """shard_map wrapper: batch over ``batch_axes``, sequence over ``axis``,
    heads over ``head_axis``; XLA only moves KV blocks over the ring."""
    from areal_tpu.base.jax_compat import shard_map

    bspec = P(batch_axes)
    qkv_spec = P(batch_axes, axis, head_axis, None)
    tok_spec = P(batch_axes, axis)
    fn = partial(
        ring_attention_local,
        axis_name=axis,
        sliding_window=sliding_window,
    )
    return shard_map(
        fn,
        mesh=mesh,
        in_specs=(qkv_spec, qkv_spec, qkv_spec, tok_spec, tok_spec),
        out_specs=qkv_spec,
        check_vma=False,
    )(q, k, v, seg, pos)

"""Memory-lean head losses.

For long contexts the [tokens, vocab] logits tensor dominates memory; these
helpers compute cross-entropy / per-token logprobs / entropy in vocab chunks
under ``jax.checkpoint`` so the backward pass recomputes chunk logits instead
of keeping them alive (replaces the reference's vocab-parallel cross entropy,
realhf/impl/model/parallelism/tensor_parallel/modules.py:1060, whose purpose
on GPU was the same memory saving).
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp


def _chunk_logp_ent(h, w, labels):
    """h [C, D], labels [C] -> (logp [C], entropy [C])."""
    logits = (h @ w).astype(jnp.float32)  # [C, V]
    lse = jax.nn.logsumexp(logits, axis=-1)
    logp_all = logits - lse[:, None]
    p = jnp.exp(logp_all)
    entropy = -jnp.sum(p * logp_all, axis=-1)
    logp = jnp.take_along_axis(logp_all, labels[:, None], axis=-1)[:, 0]
    return logp, entropy


def _chunk_logp(h, w, labels):
    """Logprob only — skips the full-vocab entropy passes (saves several
    f32 [C, V] HBM round-trips when the caller discards entropy)."""
    logits = (h @ w).astype(jnp.float32)  # [C, V]
    lse = jax.nn.logsumexp(logits, axis=-1)
    tgt = jnp.take_along_axis(logits, labels[:, None], axis=-1)[:, 0]
    logp = tgt - lse
    return logp, jnp.zeros_like(logp)


def per_token_logprobs_entropy(
    hidden: jax.Array,  # [N, D] hidden states (pre final-head)
    head_w: jax.Array,  # [D, V]
    labels: jax.Array,  # [N]
    chunk_size: int = 1024,
    with_entropy: bool = True,
) -> Tuple[jax.Array, jax.Array]:
    """Chunk-scanned (logprob, entropy) per token; differentiable w.r.t.
    ``hidden`` and ``head_w`` with chunk-local logits rematerialized in the
    backward pass."""
    N, D = hidden.shape
    pad = (-N) % chunk_size
    h = jnp.pad(hidden, ((0, pad), (0, 0)))
    lab = jnp.pad(labels, (0, pad))
    n_chunks = h.shape[0] // chunk_size
    h = h.reshape(n_chunks, chunk_size, D)
    lab = lab.reshape(n_chunks, chunk_size)

    f = jax.checkpoint(_chunk_logp_ent if with_entropy else _chunk_logp)

    def body(_, xs):
        hc, lc = xs
        return None, f(hc, head_w, lc)

    _, (logps, ents) = jax.lax.scan(body, None, (h, lab))
    return logps.reshape(-1)[:N], ents.reshape(-1)[:N]


def masked_cross_entropy(
    hidden: jax.Array,  # [N, D]
    head_w: jax.Array,  # [D, V]
    labels: jax.Array,  # [N]
    mask: jax.Array,  # [N] float/bool
    chunk_size: int = 1024,
) -> Tuple[jax.Array, jax.Array]:
    """(summed NLL over masked tokens, token count).  Mean = sum/count."""
    logp, _ = per_token_logprobs_entropy(
        hidden, head_w, labels, chunk_size, with_entropy=False
    )
    mask = mask.astype(jnp.float32)
    return -jnp.sum(logp * mask), jnp.sum(mask)

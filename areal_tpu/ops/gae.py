"""Generalized Advantage Estimation as a JAX scan.

TPU-native replacement for the reference's CUDA GAE kernel
(reference: csrc/cugae/gae.cu:11-216 ``gae_kernel_1d_nolp_misalign``; python
dispatch realhf/impl/model/utils/ppo_functional.py:292-395).  The reference
runs one CUDA thread per sequence doing the reverse recurrence; on TPU the
same recurrence is a ``lax.scan`` over the time axis of the padded [B, T]
layout — XLA vectorizes across the batch lanes, and the scan is fused into
the surrounding jit.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp


def gae_advantages_returns(
    rewards: jax.Array,  # [B, T] reward on transition t -> t+1
    values: jax.Array,  # [B, T] value at token t
    bootstrap_values: jax.Array,  # [B] value after the last transition (0 if done)
    mask: jax.Array,  # [B, T] 1.0 on valid transitions, 0 elsewhere
    gamma: float,
    lam: float,
) -> Tuple[jax.Array, jax.Array]:
    """Masked reverse-scan GAE.

    For each row, over valid transitions t (mask==1):
        delta_t = r_t + gamma * V_{t+1} - V_t
        A_t     = delta_t + gamma * lam * A_{t+1}
    Values at masked positions are treated as 0; the value after the final
    valid transition is ``bootstrap_values`` (pass 0 for terminated episodes).
    Returns (advantages, returns) with returns = A + V on valid positions.
    """
    B, T = rewards.shape
    mask = mask.astype(jnp.float32)
    values = values.astype(jnp.float32) * mask
    rewards = rewards.astype(jnp.float32) * mask

    # V_{t+1}: next valid value; at the last valid transition use bootstrap.
    next_values = jnp.concatenate(
        [values[:, 1:], jnp.zeros((B, 1), jnp.float32)], axis=1
    )
    next_mask = jnp.concatenate(
        [mask[:, 1:], jnp.zeros((B, 1), jnp.float32)], axis=1
    )
    # position is the LAST valid transition iff mask_t==1 and next_mask==0
    is_last = mask * (1.0 - next_mask)
    next_values = next_values + is_last * bootstrap_values[:, None].astype(
        jnp.float32
    )

    deltas = rewards + gamma * next_values - values  # [B, T]

    def body(adv_next, xs):
        delta_t, mask_t = xs  # [B]
        adv_t = (delta_t + gamma * lam * adv_next) * mask_t
        return adv_t, adv_t

    _, advs_rev = jax.lax.scan(
        body,
        jnp.zeros((B,), jnp.float32),
        (deltas.T[::-1], mask.T[::-1]),
    )
    advantages = advs_rev[::-1].T  # [B, T]
    returns = advantages + values
    return advantages * mask, returns * mask


def gae_packed_numpy(rewards, values, bootstrap, mask, gamma, lam):
    """Pure-numpy reference for tests (mirrors the reference's python
    fallback, realhf/impl/model/utils/ppo_functional.py:292)."""
    import numpy as np

    B, T = rewards.shape
    advs = np.zeros((B, T), np.float64)
    rets = np.zeros((B, T), np.float64)
    for b in range(B):
        valid = np.nonzero(mask[b])[0]
        if len(valid) == 0:
            continue
        adv = 0.0
        nxt = float(bootstrap[b])
        for t in valid[::-1]:
            delta = rewards[b, t] + gamma * nxt - values[b, t]
            adv = delta + gamma * lam * adv
            advs[b, t] = adv
            rets[b, t] = adv + values[b, t]
            nxt = values[b, t]
    return advs, rets

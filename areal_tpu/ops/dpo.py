"""Direct Preference Optimization math.

JAX rebuild of the reference's DPO functional (reference:
realhf/impl/model/utils/dpo_functional.py:11-34 ``dpo_loss`` — sigmoid
preference loss over (chosen, rejected) sequence-logprob pairs, plus
pos/neg score and KL diagnostics).  The reference operates on a dense
``[2k]`` logp vector with chosen/rejected interleaved; here the pairing
is expressed per-pair (the packed-batch interface reduces per-token
logps into per-pair logratios with a segment sum, so variable batch
composition never reshapes a dense vector).
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp


def dpo_pair_loss(
    pi_logratios: jax.Array,  # [P] sum(logp chosen) - sum(logp rejected)
    ref_logratios: jax.Array,  # [P] same under the frozen reference policy
    valid: jax.Array,  # [P] bool; False for padding pairs
    beta: float,
) -> Tuple[jax.Array, jax.Array, Dict[str, jax.Array]]:
    """Returns ``(loss_sum, n_pairs, stats)``.

    loss per pair = -logsigmoid(beta * (pi_logratio - ref_logratio));
    stats carry raw sums so grad-accum can add across micro-batches:
    ``reward_acc_sum`` counts pairs where the implicit reward margin is
    positive (the standard DPO training accuracy).
    """
    validf = valid.astype(jnp.float32)
    delta = beta * (pi_logratios - ref_logratios)
    losses = -jax.nn.log_sigmoid(delta) * validf
    n_pairs = jnp.sum(validf)
    stats = {
        "margin_sum": jnp.sum(jnp.where(valid, delta, 0.0)),
        "reward_acc_sum": jnp.sum((delta > 0) & valid),
    }
    return jnp.sum(losses), n_pairs, stats


def pairwise_logratios(
    per_token: jax.Array,  # [B, T] transition-aligned per-token values
    sign: jax.Array,  # [B, T] +1 chosen / -1 rejected (target-aligned)
    pair_ids: jax.Array,  # [B, T] int32 global pair index (target-aligned)
    mask: jax.Array,  # [B, T] response-transition mask
    n_pairs: int,  # static capacity (bucketed)
) -> jax.Array:
    """Reduce per-token values to per-pair (chosen - rejected) sums."""
    contrib = (per_token * mask * sign).reshape(-1)
    return jax.ops.segment_sum(
        contrib, pair_ids.reshape(-1), num_segments=n_pairs
    )

"""Reward-model training experiment: a single pairwise-BT train MFC over
the paired dataset (the ReaLHF-era ``rw`` quickstart shape; the surveyed
reference keeps the dataset, reference:
realhf/impl/dataset/rw_paired_dataset.py, without the trainer).

Launch by registry name: ``python -m areal_tpu.apps.quickstart rw ...``.
"""

from __future__ import annotations

import dataclasses

from areal_tpu.api import system_api
from areal_tpu.api.config import (
    DatasetAbstraction,
    ModelAbstraction,
    ModelBackendAbstraction,
    ModelInterfaceAbstraction,
    ModelName,
)
from areal_tpu.api.data import MicroBatchSpec
from areal_tpu.api.dfg import MFCDef, ModelInterfaceType
from areal_tpu.api.system_api import ModelShard
from areal_tpu.engine.optimizer import OptimizerConfig
from areal_tpu.experiments.common import CommonExperimentConfig

# interface registration side effect
from areal_tpu.interfaces import rm_interface  # noqa: F401


@dataclasses.dataclass
class RMExperiment(CommonExperimentConfig):
    model: ModelAbstraction = None  # must be a critic (value head)
    dataset: DatasetAbstraction = None
    train_bs_n_seqs: int = 8
    mb_spec: MicroBatchSpec = dataclasses.field(default_factory=MicroBatchSpec)
    optimizer: OptimizerConfig = dataclasses.field(
        default_factory=OptimizerConfig
    )

    def _main_model(self):
        return self.model

    def initial_setup(self) -> system_api.ExperimentConfig:
        self.prepare_common()
        model_name = ModelName("reward")
        iface = ModelInterfaceAbstraction("rw_train")
        rpc = MFCDef(
            name="rw_train",
            model_name=model_name,
            interface_type=ModelInterfaceType.TRAIN_STEP,
            interface_impl=iface,
            input_keys=("packed_input_ids",),
            n_seqs=self.train_bs_n_seqs,
            mb_spec=self.mb_spec,
            log_return_value=True,
        )
        shard = ModelShard(
            model_name=model_name,
            model=self.model,
            backend=ModelBackendAbstraction(
                "train", {"optimizer": self.optimizer}
            ),
            mesh_spec=self.mesh_spec,
        )
        workers = self.build_model_workers(
            [shard], {"rw_train": iface}, [self.dataset]
        )
        return self.make_config([rpc], workers)


system_api.register_experiment("rw", RMExperiment)

"""SFT experiment: a single train MFC
(reference: realhf/experiments/common/sft_exp.py)."""

from __future__ import annotations

import dataclasses
from typing import Optional

from areal_tpu.api import system_api
from areal_tpu.api.config import (
    DatasetAbstraction,
    ModelAbstraction,
    ModelBackendAbstraction,
    ModelInterfaceAbstraction,
    ModelName,
)
from areal_tpu.api.data import MicroBatchSpec
from areal_tpu.api.dfg import MFCDef, ModelInterfaceType
from areal_tpu.api.system_api import ModelShard
from areal_tpu.engine.optimizer import OptimizerConfig
from areal_tpu.experiments.common import CommonExperimentConfig


@dataclasses.dataclass
class SFTExperiment(CommonExperimentConfig):
    model: ModelAbstraction = None
    dataset: DatasetAbstraction = None
    train_bs_n_seqs: int = 8
    mb_spec: MicroBatchSpec = dataclasses.field(default_factory=MicroBatchSpec)
    optimizer: OptimizerConfig = dataclasses.field(
        default_factory=OptimizerConfig
    )

    def _main_model(self):
        return self.model

    def initial_setup(self) -> system_api.ExperimentConfig:
        self.prepare_common()
        model_name = ModelName("default")
        rpc = MFCDef(
            name="trainDefault",
            model_name=model_name,
            interface_type=ModelInterfaceType.TRAIN_STEP,
            interface_impl=ModelInterfaceAbstraction("sft"),
            input_keys=("packed_input_ids", "prompt_mask"),
            n_seqs=self.train_bs_n_seqs,
            mb_spec=self.mb_spec,
            log_return_value=True,
        )
        shard = ModelShard(
            model_name=model_name,
            model=self.model,
            backend=ModelBackendAbstraction(
                "train", {"optimizer": self.optimizer}
            ),
            mesh_spec=self.mesh_spec,
        )
        workers = self.build_model_workers(
            [shard],
            {"trainDefault": ModelInterfaceAbstraction("sft")},
            [self.dataset],
        )
        return self.make_config([rpc], workers)


system_api.register_experiment("sft", SFTExperiment)

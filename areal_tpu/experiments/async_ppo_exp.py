"""Asynchronous PPO experiment: decoupled rollout cluster + trainer.

Rebuild of the reference's async RL experiment (reference:
realhf/experiments/async_exp/async_rl_exp.py:59 — trainer-side graph without
the generate MFC, rollout/generation/gserver-manager worker configs;
realhf/experiments/async_exp/async_ppo_math_exp.py:26 — math agent/env,
rewards computed in the env so the reward MFC is dropped, version keys on
rollout outputs).

The trainer's graph is {ref_inf?, actor_inf?, actor_train (+ critic pair)};
trajectories arrive via the rollout workers' push stream into the trainer's
PullerStreamDataset; after each actor train step the new weights are
published to the realloc dir and the gserver manager hot-swaps every
generation server (interrupting in-flight requests).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from areal_tpu.api import system_api
from areal_tpu.api.config import (
    AgentAbstraction,
    DatasetAbstraction,
    EnvServiceAbstraction,
    ModelAbstraction,
    ModelBackendAbstraction,
    ModelInterfaceAbstraction,
    ModelName,
)
from areal_tpu.api.dfg import MFCDef, ModelInterfaceType
from areal_tpu.api.system_api import (
    GenServerConfig,
    GserverManagerConfig,
    ModelShard,
    RolloutWorkerConfig,
)
from areal_tpu.experiments.ppo_math_exp import PPOMathExperiment


@dataclasses.dataclass
class AsyncPPOMathExperiment(PPOMathExperiment):
    """Extends the sync experiment with the rollout cluster options
    (reference: realhf/api/cli_args.py:1104 ``AsyncRLOptions``)."""

    n_rollout_workers: int = 1
    n_gen_servers: int = 1
    max_head_offpolicyness: int = 0
    # round_robin | least_requests | least_token_usage (KV-pressure-aware;
    # the continuation-refreshed estimate, gserver_manager._schedule)
    gen_schedule_policy: str = "least_requests"
    max_concurrent_rollouts: Optional[int] = None
    new_tokens_per_chunk: int = 1 << 30
    flush_request_timeout: float = 120.0
    gen_kv_cache_len: int = 32768
    gen_max_concurrent_batch: int = 16
    gen_chunk_size: int = 64  # measured on v5e: 3.7k tok/s @64 vs 3.9k @128
    # paged-KV serving knobs (engine/inference_server.py): auto picks the
    # block pool at kv_cache_len >= 2k; pool tokens default to
    # max_batch * kv_cache_len (set smaller for 32k-context serving)
    gen_cache_mode: str = "auto"
    gen_page_size: int = 1024
    gen_kv_pool_tokens: Optional[int] = None
    gen_prefill_chunk_tokens: int = 1024
    # decode-pipeline ring depth (chunks in flight; 1 = unpipelined) and
    # measured dispatch-table overrides (None = engine/dispatch.py
    # defaults; pin values a bench.py decode A/B derived for this chip)
    gen_pipeline_depth: int = 2
    gen_paged_min_cache_len: Optional[int] = None
    gen_deep_kernel_min_context: Optional[int] = None
    # device index hosting each gen server's engine (trainer/gen split)
    gen_device_start: Optional[int] = None
    success_rate_lb: float = 0.0
    success_rate_ub: float = 1.0
    # agent selection (reference: async_ppo_math_exp overrides the agent;
    # "math-multi-turn" enables the retry-with-feedback loop)
    agent_type: str = "math-single-step"
    num_turns: int = 5
    turn_level_discount: float = 1.0

    def _heuristic_gen_fraction(self):
        return 0.25  # reference heuristic carves ~1/4 of devices for gen

    def initial_setup(self) -> system_api.ExperimentConfig:
        # decoupled allocation strings size the rollout cluster before the
        # trainer graph is built (reference: decoupled AllocationMode carving
        # gen devices out of the cluster, experiments/common/utils.py:245)
        am = self.resolve_allocation()
        gen_tp = 1
        if am is not None and am.is_decoupled():
            gen = am.gen_spec
            if gen.fsdp * gen.pipe * gen.seq * gen.expert != 1:
                raise ValueError(
                    "gen specs support data (replica) and model (TP) axes "
                    f"only (got gen.{gen})"
                )
            gen_tp = gen.model
            self.n_gen_servers = gen.data
            if self.gen_device_start is None:
                # gen devices sit after the LARGEST per-MFC trainer mesh,
                # not just the default '*' strategy
                self.gen_device_start = am.train_size
        cfg = super().initial_setup()
        ppo = self.ppo
        actor = ModelName("actor")

        # -- trainer side: strip gen + reward MFCs, switch to stream data ---
        keep = {
            "actor_train",
            "critic_train",
            "critic_inf",
            "ref_inf",
            "actor_inf",
        }
        rpcs = [r for r in cfg.master.model_rpcs if r.name in keep]
        for r in rpcs:
            r._G = None
            # rewards/logprobs/seq masks come with the trajectories now
            if r.name in ("ref_inf", "actor_inf"):
                r.input_keys = ("packed_input_ids", "prompt_mask")
        # publish weights to the generation cluster after each actor step
        actor_train = next(r for r in rpcs if r.name == "actor_train")
        actor_train.post_hooks = list(actor_train.post_hooks) + [
            {"type": "publish_weights", "model_name": str(actor)}
        ]
        cfg.master.model_rpcs = rpcs
        cfg.master.model_groups = {}  # recomputed in lazy_init

        for w in cfg.model_workers:
            w.shards = [s for s in w.shards if s.model_name.role != "reward"]
            w.interfaces = {
                k: v for k, v in w.interfaces.items() if k in keep
            }
            w.use_stream_dataset = True
            w.stream_group_size = self.group_size

        # -- rollout cluster ------------------------------------------------
        gen_gconfig = ppo.gen.new(n=self.group_size)
        from areal_tpu.base.topology import MeshSpec

        cfg.gen_servers = [
            GenServerConfig(
                worker_name=f"gen_server_{i}",
                model=self.actor,
                # each server owns its OWN (usually tiny) mesh: 1 chip per
                # replica, or a model-axis TP span when the allocation's gen
                # spec asks for it — never the trainer's mesh shape
                mesh_spec=MeshSpec(model=gen_tp),
                tokenizer_path=self.tokenizer_path,
                max_concurrent_batch=self.gen_max_concurrent_batch,
                kv_cache_len=self.gen_kv_cache_len,
                chunk_size=self.gen_chunk_size,
                temperature=ppo.gen.temperature,
                cache_mode=self.gen_cache_mode,
                page_size=self.gen_page_size,
                kv_pool_tokens=self.gen_kv_pool_tokens,
                prefill_chunk_tokens=self.gen_prefill_chunk_tokens,
                pipeline_depth=self.gen_pipeline_depth,
                paged_min_cache_len=self.gen_paged_min_cache_len,
                deep_kernel_min_context=self.gen_deep_kernel_min_context,
                device_idx=(
                    self.gen_device_start + i * gen_tp
                    if self.gen_device_start is not None
                    else None
                ),
            )
            for i in range(self.n_gen_servers)
        ]
        # staleness accounting converts rollouts -> sequences via group_size;
        # the multi-turn agent emits ONE answer per turn (1..num_turns seqs
        # per rollout), so counting group_size seqs per rollout would
        # over-count and can gate allocation forever (deadlock: allocations
        # stop before a train batch can fill). Count the guaranteed minimum.
        staleness_group_size = (
            1 if self.agent_type == "math-multi-turn" else self.group_size
        )
        cfg.gserver_manager = GserverManagerConfig(
            n_servers=self.n_gen_servers,
            schedule_policy=self.gen_schedule_policy,
            max_head_offpolicyness=self.max_head_offpolicyness,
            train_batch_size=self.train_bs_n_seqs,
            group_size=staleness_group_size,
            max_concurrent_rollouts=self.max_concurrent_rollouts,
            flush_request_timeout=self.flush_request_timeout,
        )
        if self.agent_type == "math-multi-turn":
            agent_abs = AgentAbstraction(
                "math-multi-turn",
                {
                    "gconfig": gen_gconfig,
                    "tokenizer_path": self.tokenizer_path,
                    "num_turns": self.num_turns,
                    "turn_level_discount": self.turn_level_discount,
                },
            )
        else:
            agent_abs = AgentAbstraction(
                self.agent_type,
                {
                    "gconfig": gen_gconfig,
                    "success_rate_lb": self.success_rate_lb,
                    "success_rate_ub": self.success_rate_ub,
                },
            )
        cfg.rollout_workers = [
            RolloutWorkerConfig(
                worker_name=f"rollout_worker_{i}",
                agent=agent_abs,
                env=EnvServiceAbstraction(
                    "math-code-single-step",
                    {"tokenizer_path": self.tokenizer_path},
                ),
                gconfig=gen_gconfig,
                datasets=[self.dataset],
                tokenizer_path=self.tokenizer_path,
                dataset_shard=(i, self.n_rollout_workers),
                dataset_seed=self.seed,
                new_tokens_per_chunk=self.new_tokens_per_chunk,
            )
            for i in range(self.n_rollout_workers)
        ]
        return cfg.lazy_init()


system_api.register_experiment("async_ppo_math", AsyncPPOMathExperiment)

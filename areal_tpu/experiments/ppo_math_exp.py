"""Sync PPO math experiment: the 7-node MFC graph with pruning options
(reference: realhf/experiments/common/ppo_math_exp.py:29,120-341 —
actor_gen -> {rew_inf, ref_inf, critic_inf, actor_inf} ->
{actor_train, critic_train}; options prune nodes: disable_value drops the
critic pair, kl_ctl=0 drops ref_inf, use_decoupled_loss adds actor_inf;
EMA ref update via ParamReallocHook)."""

from __future__ import annotations

import dataclasses
from typing import Optional

from areal_tpu.api import system_api
from areal_tpu.api.config import (
    DatasetAbstraction,
    ModelAbstraction,
    ModelBackendAbstraction,
    ModelInterfaceAbstraction,
    ModelName,
)
from areal_tpu.api.data import MicroBatchSpec
from areal_tpu.api.dfg import (
    MFCDef,
    ModelInterfaceType,
    ParamReallocHook,
)
from areal_tpu.api.model_api import GenerationHyperparameters
from areal_tpu.api.system_api import ModelShard
from areal_tpu.engine.optimizer import OptimizerConfig
from areal_tpu.experiments.common import CommonExperimentConfig


@dataclasses.dataclass
class PPOHyperparameters:
    """(reference: realhf/api/cli_args.py:597)"""

    gen: GenerationHyperparameters = dataclasses.field(
        default_factory=GenerationHyperparameters
    )
    ppo_n_minibatches: int = 4
    eps_clip: float = 0.2
    c_clip: Optional[float] = None
    value_eps_clip: float = 0.2
    disable_value: bool = False
    reward_output_scaling: float = 1.0
    reward_output_bias: float = 0.0
    max_reward_clip: float = 20.0
    mask_no_eos_with_zero: bool = False
    discount: float = 1.0
    gae_lambda: float = 1.0
    adv_norm: bool = True
    group_adv_norm: bool = False
    kl_ctl: float = 0.1
    adaptive_kl_ctl: bool = False
    use_decoupled_loss: bool = False
    behav_imp_weight_cap: Optional[float] = None
    recompute_logprob: bool = False
    ref_ema_eta: Optional[float] = None  # EMA trainer->ref update


@dataclasses.dataclass
class PPOMathExperiment(CommonExperimentConfig):
    actor: ModelAbstraction = None
    critic: ModelAbstraction = None  # derived from actor if None
    ref: ModelAbstraction = None  # derived from actor if None
    dataset: DatasetAbstraction = None
    ppo: PPOHyperparameters = dataclasses.field(
        default_factory=PPOHyperparameters
    )
    group_size: int = 1
    train_bs_n_seqs: int = 8
    mb_spec: MicroBatchSpec = dataclasses.field(default_factory=MicroBatchSpec)
    actor_optimizer: OptimizerConfig = dataclasses.field(
        default_factory=lambda: OptimizerConfig(lr=1e-6)
    )
    critic_optimizer: OptimizerConfig = dataclasses.field(
        default_factory=lambda: OptimizerConfig(lr=5e-6)
    )
    # collapse rew_inf + ref_inf into one fused MFC on the ref model
    # (reference: fused_interface.py; saves a dispatch + overlaps the CPU
    # verifier with the ref forward). Only takes effect when use_ref.
    fuse_rew_ref: bool = False
    # where PPO rewards come from (the reward-MFC slot, reference:
    # realhf/experiments/common/ppo_math_exp.py:120-341):
    #   "rule"  — the math/code verifier (rw_math interface)
    #   "model" — a TRAINED reward model (rw_train.inference on a frozen
    #             critic-head checkpoint; completes SFT -> RM -> PPO)
    reward_source: str = "rule"
    # the frozen RM for reward_source="model" (e.g. an "hf" abstraction
    # pointing at an rw-experiment checkpoint, is_critic=True); defaults
    # to a critic twin of the actor — useful only for tests
    reward_model: ModelAbstraction = None

    def _main_model(self):
        return self.actor

    def _heuristic_tokens_per_step(self) -> int:
        # prompts + generations for one train batch (upper bound: every
        # sequence at the generation budget)
        per_seq = self.ppo.gen.max_new_tokens + 512
        return self.train_bs_n_seqs * max(1, self.group_size) * per_seq

    @property
    def use_critic(self) -> bool:
        return not self.ppo.disable_value

    @property
    def use_ref(self) -> bool:
        return self.ppo.kl_ctl != 0.0

    def initial_setup(self) -> system_api.ExperimentConfig:
        self.prepare_common()  # allocation_mode -> mesh_spec, tokenizer
        ppo = self.ppo
        actor = ModelName("actor")
        critic = ModelName("critic")
        ref = ModelName("ref")
        reward = ModelName("reward")

        actor_iface_args = dict(
            n_minibatches=ppo.ppo_n_minibatches,
            gconfig=ppo.gen,
            kl_ctl=ppo.kl_ctl,
            adaptive_kl_ctl=ppo.adaptive_kl_ctl,
            eps_clip=ppo.eps_clip,
            c_clip=ppo.c_clip,
            discount=ppo.discount,
            gae_lambda=ppo.gae_lambda,
            max_reward_clip=ppo.max_reward_clip,
            reward_scaling=ppo.reward_output_scaling,
            reward_bias=ppo.reward_output_bias,
            mask_no_eos_with_zero=ppo.mask_no_eos_with_zero,
            adv_norm=ppo.adv_norm,
            group_adv_norm=ppo.group_adv_norm,
            group_size=self.group_size,
            disable_value=ppo.disable_value,
            temperature=ppo.gen.temperature,
            use_decoupled_loss=ppo.use_decoupled_loss,
            behav_imp_weight_cap=ppo.behav_imp_weight_cap,
        )
        actor_iface = ModelInterfaceAbstraction("ppo_actor", actor_iface_args)
        ref_iface = ModelInterfaceAbstraction(
            "ppo_actor",
            {**actor_iface_args, "use_decoupled_loss": False},
        )
        prox_iface = ModelInterfaceAbstraction(
            "ppo_actor",
            {**actor_iface_args, "use_decoupled_loss": True},
        )
        critic_iface = ModelInterfaceAbstraction(
            "ppo_critic",
            dict(
                n_minibatches=ppo.ppo_n_minibatches,
                value_eps_clip=ppo.value_eps_clip,
                kl_ctl=ppo.kl_ctl,
                discount=ppo.discount,
                gae_lambda=ppo.gae_lambda,
                max_reward_clip=ppo.max_reward_clip,
                mask_no_eos_with_zero=ppo.mask_no_eos_with_zero,
            ),
        )
        assert self.reward_source in ("rule", "model"), self.reward_source
        if self.reward_source == "model":
            from areal_tpu.interfaces.rm_interface import (  # noqa: F401
                RewardModelInterface,
            )

            rw_iface = ModelInterfaceAbstraction("rw_train", {})
        else:
            rw_iface = ModelInterfaceAbstraction(
                "rw_math", {"group_size": self.group_size}
            )

        n = self.train_bs_n_seqs
        rpcs = []
        interfaces = {}

        actor_gen = MFCDef(
            name="actor_gen",
            model_name=actor,
            interface_type=ModelInterfaceType.GENERATE,
            interface_impl=actor_iface,
            input_keys=("packed_prompts",),
            output_keys=(
                "packed_input_ids",
                "packed_logprobs",
                "prompt_mask",
                "seq_no_eos_mask",
            ),
            n_seqs=n,
        )
        rpcs.append(actor_gen)
        interfaces["actor_gen"] = actor_iface

        # a model-based reward runs on ITS OWN weights, so it cannot fuse
        # into the ref model's MFC
        fused = (
            self.fuse_rew_ref and self.use_ref
            and self.reward_source == "rule"
        )
        if fused:
            from areal_tpu.interfaces.fused_interface import (  # noqa: F401
                FusedInferenceInterface,
            )

            fused_iface = ModelInterfaceAbstraction(
                "fused-inference",
                {"interfaces": {"rew": rw_iface, "ref": ref_iface}},
            )
            rpcs.append(
                MFCDef(
                    name="rew_ref_inf",
                    model_name=ref,
                    interface_type=ModelInterfaceType.INFERENCE,
                    interface_impl=fused_iface,
                    input_keys=("packed_input_ids", "prompt_mask"),
                    output_keys=("rewards", "packed_ref_logprobs"),
                    n_seqs=n,
                )
            )
            interfaces["rew_ref_inf"] = fused_iface
        else:
            rew_inf = MFCDef(
                name="rew_inf",
                model_name=reward,
                interface_type=ModelInterfaceType.INFERENCE,
                interface_impl=rw_iface,
                input_keys=("packed_input_ids", "prompt_mask"),
                output_keys=("rewards",),
                n_seqs=n,
            )
            rpcs.append(rew_inf)
            interfaces["rew_inf"] = rw_iface

        train_input_keys = [
            "packed_input_ids",
            "packed_logprobs",
            "prompt_mask",
            "rewards",
            "seq_no_eos_mask",
        ]
        if self.use_ref:
            if not fused:
                rpcs.append(
                    MFCDef(
                        name="ref_inf",
                        model_name=ref,
                        interface_type=ModelInterfaceType.INFERENCE,
                        interface_impl=ref_iface,
                        input_keys=("packed_input_ids", "prompt_mask"),
                        output_keys=("packed_ref_logprobs",),
                        n_seqs=n,
                    )
                )
                interfaces["ref_inf"] = ref_iface
            train_input_keys.append("packed_ref_logprobs")
        if self.use_critic:
            rpcs.append(
                MFCDef(
                    name="critic_inf",
                    model_name=critic,
                    interface_type=ModelInterfaceType.INFERENCE,
                    interface_impl=critic_iface,
                    input_keys=("packed_input_ids",),
                    output_keys=("values",),
                    n_seqs=n,
                )
            )
            interfaces["critic_inf"] = critic_iface
            train_input_keys.append("values")
        if ppo.use_decoupled_loss or ppo.recompute_logprob:
            rpcs.append(
                MFCDef(
                    name="actor_inf",
                    model_name=actor,
                    interface_type=ModelInterfaceType.INFERENCE,
                    interface_impl=prox_iface,
                    input_keys=("packed_input_ids", "prompt_mask"),
                    output_keys=("prox_logp",),
                    n_seqs=n,
                )
            )
            interfaces["actor_inf"] = prox_iface
            train_input_keys.append("prox_logp")

        actor_post_hooks = []
        if ppo.ref_ema_eta is not None and self.use_ref:
            actor_post_hooks.append(
                ParamReallocHook(target=ref, eta=ppo.ref_ema_eta)
            )
        actor_train = MFCDef(
            name="actor_train",
            model_name=actor,
            interface_type=ModelInterfaceType.TRAIN_STEP,
            interface_impl=actor_iface,
            input_keys=tuple(train_input_keys),
            n_seqs=n,
            mb_spec=self.mb_spec,
            log_return_value=True,
            post_hooks=actor_post_hooks,
        )
        rpcs.append(actor_train)
        interfaces["actor_train"] = actor_iface
        if self.use_critic:
            rpcs.append(
                MFCDef(
                    name="critic_train",
                    model_name=critic,
                    interface_type=ModelInterfaceType.TRAIN_STEP,
                    interface_impl=critic_iface,
                    input_keys=tuple(train_input_keys),
                    n_seqs=n,
                    mb_spec=self.mb_spec,
                )
            )
            interfaces["critic_train"] = critic_iface

        # -- model shards ---------------------------------------------------
        def critic_model_from(actor_model: ModelAbstraction):
            if actor_model.type_ == "hf":
                return ModelAbstraction(
                    "hf", {**actor_model.args, "is_critic": True}
                )
            args = dict(actor_model.args)
            if "config" in args and hasattr(args["config"], "__dict__"):
                args["config"] = dataclasses.replace(
                    args["config"], is_critic=True, tied_embedding=False
                )
            else:
                args["is_critic"] = True
            return ModelAbstraction(actor_model.type_, args)

        shards = [
            ModelShard(
                model_name=actor,
                model=self.actor,
                backend=ModelBackendAbstraction(
                    "train", {"optimizer": self.actor_optimizer}
                ),
                mesh_spec=self.mesh_spec,
            ),
        ]
        if not fused:
            if self.reward_source == "model":
                # frozen critic-head scorer served by the inference backend
                rm_model = self.reward_model or critic_model_from(self.actor)
                shards.append(
                    ModelShard(
                        model_name=reward,
                        model=rm_model,
                        backend=ModelBackendAbstraction("inference"),
                        mesh_spec=self.mesh_spec,
                    )
                )
            else:
                shards.append(
                    ModelShard(
                        model_name=reward,
                        model=ModelAbstraction("null"),
                        backend=ModelBackendAbstraction("null"),
                        mesh_spec=self.mesh_spec,
                    )
                )
        if self.use_ref:
            shards.append(
                ModelShard(
                    model_name=ref,
                    model=self.ref or self.actor,
                    backend=ModelBackendAbstraction("inference"),
                    mesh_spec=self.mesh_spec,
                )
            )
        if self.use_critic:
            shards.append(
                ModelShard(
                    model_name=critic,
                    model=self.critic or critic_model_from(self.actor),
                    backend=ModelBackendAbstraction(
                        "train", {"optimizer": self.critic_optimizer}
                    ),
                    mesh_spec=self.mesh_spec,
                )
            )

        workers = self.build_model_workers(shards, interfaces, [self.dataset])
        return self.make_config(rpcs, workers)


system_api.register_experiment("ppo_math", PPOMathExperiment)

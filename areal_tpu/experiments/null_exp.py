"""Null experiments: exercise the full master/worker/data plane with no-op
model computation.

Rebuild of the reference's null experiments (reference:
realhf/experiments/common/null_exp.py — ``NullSFTConfig`` one train MFC,
``NullPPOConfig`` reward-inference + train MFCs, both on the ``null``
interface).  Used for plumbing tests, scheduler profiling, and isolating
system overhead from model compute: step time here IS the framework
overhead (dispatch + data plane + host sync), which is exactly what a
profiling run wants to measure.
"""

from __future__ import annotations

import dataclasses

from areal_tpu.api import system_api
from areal_tpu.api.config import (
    DatasetAbstraction,
    ModelAbstraction,
    ModelBackendAbstraction,
    ModelInterfaceAbstraction,
    ModelName,
)
from areal_tpu.api.data import MicroBatchSpec
from areal_tpu.api.dfg import MFCDef, ModelInterfaceType
from areal_tpu.api.system_api import ModelShard
from areal_tpu.experiments.common import CommonExperimentConfig


@dataclasses.dataclass
class NullPPOExperiment(CommonExperimentConfig):
    """reward-inf -> train on null interfaces over a prompt dataset."""

    dataset: DatasetAbstraction = None
    train_bs_n_seqs: int = 8
    mb_spec: MicroBatchSpec = dataclasses.field(default_factory=MicroBatchSpec)

    def initial_setup(self) -> system_api.ExperimentConfig:
        self.resolve_allocation()
        from areal_tpu.interfaces import null_interface  # noqa: F401

        default = ModelName("default")
        null_iface = ModelInterfaceAbstraction("null")
        n = self.train_bs_n_seqs
        rew = MFCDef(
            name="reward",
            model_name=default,
            interface_type=ModelInterfaceType.INFERENCE,
            interface_impl=null_iface,
            input_keys=("packed_prompts",),
            output_keys=("rewards",),
            n_seqs=n,
        )
        train = MFCDef(
            name="trainDefault",
            model_name=default,
            interface_type=ModelInterfaceType.TRAIN_STEP,
            interface_impl=null_iface,
            input_keys=("packed_prompts", "rewards"),
            n_seqs=n,
            mb_spec=self.mb_spec,
            log_return_value=True,
        )
        shards = [
            ModelShard(
                model_name=default,
                model=ModelAbstraction("null"),
                backend=ModelBackendAbstraction("null"),
                mesh_spec=self.mesh_spec,
            )
        ]
        interfaces = {"reward": null_iface, "trainDefault": null_iface}
        workers = self.build_model_workers(shards, interfaces, [self.dataset])
        return self.make_config([rew, train], workers)


system_api.register_experiment("null_ppo", NullPPOExperiment)

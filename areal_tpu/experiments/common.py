"""Shared experiment-building helpers
(reference: realhf/experiments/common/common.py ``CommonExperimentConfig``
:72 — allocation parsing, worker-config building, sanity checks)."""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

from areal_tpu.api import system_api
from areal_tpu.api.config import DatasetAbstraction, ModelAbstraction
from areal_tpu.api.system_api import (
    ExperimentConfig,
    ExperimentSaveEvalControl,
    MasterWorkerConfig,
    ModelWorkerConfig,
)
from areal_tpu.base.topology import MeshSpec


@dataclasses.dataclass
class CommonExperimentConfig(system_api.Experiment):
    """Base options shared by quickstart experiments."""

    experiment_name: str = "test-exp"
    trial_name: str = "test-trial"
    seed: int = 1
    # number of model-worker processes (hosts); each drives its local chips
    n_model_workers: int = 1
    mesh_spec: MeshSpec = dataclasses.field(default_factory=MeshSpec)
    exp_ctrl: ExperimentSaveEvalControl = dataclasses.field(
        default_factory=ExperimentSaveEvalControl
    )
    tokenizer_path: Optional[str] = None
    # run on N virtual CPU devices instead of the accelerator (debug/CI mode,
    # mirrors the reference's CPU test harness realhf/base/testing.py)
    force_cpu_devices: Optional[int] = None

    def apply_device_overrides(self):
        if self.force_cpu_devices:
            import jax

            if (
                jax.devices()[0].platform != "cpu"
                or len(jax.devices()) < self.force_cpu_devices
            ):
                import jax.extend.backend as jeb

                jeb.clear_backends()
                jax.config.update("jax_platforms", "cpu")
                jax.config.update(
                    "jax_num_cpu_devices", self.force_cpu_devices
                )

    def model_worker_names(self) -> List[str]:
        return [f"model_worker_{i}" for i in range(self.n_model_workers)]

    def build_model_workers(
        self,
        shards: List[system_api.ModelShard],
        interfaces: Dict,
        datasets: List[DatasetAbstraction],
    ) -> List[ModelWorkerConfig]:
        names = self.model_worker_names()
        return [
            ModelWorkerConfig(
                worker_name=name,
                shards=shards,
                interfaces=interfaces,
                datasets=datasets,
                tokenizer_path=self.tokenizer_path,
                dataset_seed=self.seed,
                dataset_shard=(i, len(names)),
                seed=self.seed,
            )
            for i, name in enumerate(names)
        ]

    def make_config(self, rpcs, model_workers) -> ExperimentConfig:
        return ExperimentConfig(
            experiment_name=self.experiment_name,
            trial_name=self.trial_name,
            master=MasterWorkerConfig(
                model_rpcs=rpcs,
                exp_ctrl=self.exp_ctrl,
                seed=self.seed,
            ),
            model_workers=model_workers,
        ).lazy_init()

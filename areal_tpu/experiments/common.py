"""Shared experiment-building helpers
(reference: realhf/experiments/common/common.py ``CommonExperimentConfig``
:72 — allocation parsing, worker-config building, sanity checks)."""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

from areal_tpu.api import system_api
from areal_tpu.api.config import DatasetAbstraction, ModelAbstraction
from areal_tpu.api.system_api import (
    ExperimentConfig,
    ExperimentSaveEvalControl,
    MasterWorkerConfig,
    ModelWorkerConfig,
)
from areal_tpu.base.topology import MeshSpec


def model_config_from_abstraction(model: Optional[ModelAbstraction]):
    """TransformerConfig for a model abstraction ('hf' reads config.json
    only, 'random' builds from args), or None when underivable.  Used by
    the heuristic allocation hooks."""
    if model is None:
        return None
    if model.type_ == "hf":
        from areal_tpu.models.hf.registry import load_hf_config

        _, cfg, _ = load_hf_config(model.args["path"])
        return cfg
    if model.type_ == "random":
        from areal_tpu.models.config import TransformerConfig, tiny_config

        args = dict(model.args)
        args.pop("seed", None)
        conf = args.pop("config", None)
        if isinstance(conf, TransformerConfig):
            return conf
        if conf is not None:
            return TransformerConfig(**conf)
        return tiny_config(**args)
    return None


@dataclasses.dataclass
class CommonExperimentConfig(system_api.Experiment):
    """Base options shared by quickstart experiments."""

    experiment_name: str = "test-exp"
    trial_name: str = "test-trial"
    seed: int = 1
    # number of model-worker processes (hosts); each drives its local chips
    n_model_workers: int = 1
    mesh_spec: MeshSpec = dataclasses.field(default_factory=MeshSpec)
    exp_ctrl: ExperimentSaveEvalControl = dataclasses.field(
        default_factory=ExperimentSaveEvalControl
    )
    tokenizer_path: Optional[str] = None
    # compact allocation string ("d2f2m2", "gen.d2m1+d4f2m1", "heuristic")
    # overriding mesh_spec / the gen-device split (reference:
    # CommonExperimentConfig.allocation_mode, experiments/common/common.py:189)
    allocation_mode: str = ""
    # run on N virtual CPU devices instead of the accelerator (debug/CI mode,
    # mirrors the reference's CPU test harness realhf/base/testing.py)
    force_cpu_devices: Optional[int] = None
    # automatic checkpoint evaluator (reference: exp_cfg.evaluator driven by
    # apps/main.py); consumed by the process launcher's monitor loop
    evaluator: Optional[system_api.EvaluatorConfig] = None

    def resolve_allocation(self):
        """Apply ``allocation_mode`` to mesh_spec; returns the parsed mode
        (or None).  Decoupled gen placement is applied by the async
        experiment, which owns the gen-server configs."""
        if not self.allocation_mode:
            return None
        from areal_tpu.api.allocation import AllocationMode, AllocationType

        am = AllocationMode.from_str(self.allocation_mode)
        if am.type_ == AllocationType.HEURISTIC:
            am = self._solve_heuristic_allocation()
        if am.type_ != AllocationType.MANUAL:
            self.mesh_spec = am.train_spec()
        return am

    # -- heuristic allocation hooks (overridden by concrete experiments) ----

    def _main_model(self) -> Optional[ModelAbstraction]:
        """The trained model's abstraction (drives heuristic allocation and
        tokenizer defaulting); None when the experiment has no single one."""
        return None

    def prepare_common(self):
        """Shared initial_setup preamble: resolve the allocation string and
        default the tokenizer to the main model's HF path."""
        self.resolve_allocation()
        main = self._main_model()
        if (
            self.tokenizer_path is None
            and main is not None
            and main.type_ == "hf"
        ):
            self.tokenizer_path = main.args["path"]

    def _heuristic_model_config(self):
        """TransformerConfig of the trained model, or None when the
        experiment cannot derive one."""
        return model_config_from_abstraction(self._main_model())

    def _heuristic_tokens_per_step(self) -> int:
        return 32768

    def _heuristic_gen_fraction(self) -> Optional[float]:
        """Fraction of devices carved out for generation (async RL)."""
        return None

    def _solve_heuristic_allocation(self):
        cfg = self._heuristic_model_config()
        if cfg is None:
            raise ValueError(
                "allocation_mode='heuristic' is not supported by "
                f"{type(self).__name__} (no model footprint); pass an "
                "explicit strategy string like 'd2f2m1'"
            )
        import jax

        from areal_tpu.api.allocation import (
            ModelFootprint,
            search_allocation,
        )

        stats = {}
        try:
            stats = jax.local_devices()[0].memory_stats() or {}
        except Exception:  # noqa: BLE001 - backend-dependent
            pass
        hbm = float(stats.get("bytes_limit", 16e9))
        return search_allocation(
            len(jax.devices()),
            ModelFootprint.from_config(cfg),
            self._heuristic_tokens_per_step(),
            hbm_bytes=hbm,
            decoupled_gen_fraction=self._heuristic_gen_fraction(),
        )

    def apply_device_overrides(self):
        if self.force_cpu_devices:
            import jax

            if (
                jax.devices()[0].platform != "cpu"
                or len(jax.devices()) < self.force_cpu_devices
            ):
                import jax.extend.backend as jeb

                jeb.clear_backends()
                jax.config.update("jax_platforms", "cpu")
                jax.config.update(
                    "jax_num_cpu_devices", self.force_cpu_devices
                )

    def model_worker_names(self) -> List[str]:
        return [f"model_worker_{i}" for i in range(self.n_model_workers)]

    def build_model_workers(
        self,
        shards: List[system_api.ModelShard],
        interfaces: Dict,
        datasets: List[DatasetAbstraction],
    ) -> List[ModelWorkerConfig]:
        names = self.model_worker_names()
        return [
            ModelWorkerConfig(
                worker_name=name,
                shards=shards,
                interfaces=interfaces,
                datasets=datasets,
                tokenizer_path=self.tokenizer_path,
                dataset_seed=self.seed,
                dataset_shard=(i, len(names)),
                seed=self.seed,
            )
            for i, name in enumerate(names)
        ]

    def make_config(self, rpcs, model_workers) -> ExperimentConfig:
        return ExperimentConfig(
            experiment_name=self.experiment_name,
            trial_name=self.trial_name,
            master=MasterWorkerConfig(
                model_rpcs=rpcs,
                exp_ctrl=self.exp_ctrl,
                seed=self.seed,
            ),
            model_workers=model_workers,
            evaluator=self.evaluator,
        ).lazy_init()

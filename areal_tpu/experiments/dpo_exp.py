"""DPO experiment: a two-node graph — frozen-reference inference feeding the
actor's preference train step.

The reference keeps DPO math in
realhf/impl/model/utils/dpo_functional.py without a wired experiment; this
follows its ReaLHF-era quickstart shape (ref_inf -> dpo_train over the
paired dataset, reference: realhf/impl/dataset/rw_paired_dataset.py).
"""

from __future__ import annotations

import dataclasses

from areal_tpu.api import system_api
from areal_tpu.api.config import (
    DatasetAbstraction,
    ModelAbstraction,
    ModelBackendAbstraction,
    ModelInterfaceAbstraction,
    ModelName,
)
from areal_tpu.api.data import MicroBatchSpec
from areal_tpu.api.dfg import MFCDef, ModelInterfaceType
from areal_tpu.api.system_api import ModelShard
from areal_tpu.engine.optimizer import OptimizerConfig
from areal_tpu.experiments.common import CommonExperimentConfig

# interface registration side effect
from areal_tpu.interfaces import dpo_interface  # noqa: F401


@dataclasses.dataclass
class DPOExperiment(CommonExperimentConfig):
    actor: ModelAbstraction = None
    ref: ModelAbstraction = None  # frozen reference; defaults to actor
    dataset: DatasetAbstraction = None
    train_bs_n_seqs: int = 8
    beta: float = 0.1
    mb_spec: MicroBatchSpec = dataclasses.field(default_factory=MicroBatchSpec)
    optimizer: OptimizerConfig = dataclasses.field(
        default_factory=OptimizerConfig
    )

    def _main_model(self):
        return self.actor

    def initial_setup(self) -> system_api.ExperimentConfig:
        self.prepare_common()
        actor = ModelName("actor")
        ref = ModelName("ref")
        iface = ModelInterfaceAbstraction("dpo", {"beta": self.beta})
        n = self.train_bs_n_seqs

        ref_inf = MFCDef(
            name="ref_inf",
            model_name=ref,
            interface_type=ModelInterfaceType.INFERENCE,
            interface_impl=iface,
            input_keys=("packed_input_ids",),
            output_keys=("packed_ref_logprobs",),
            n_seqs=n,
        )
        dpo_train = MFCDef(
            name="dpo_train",
            model_name=actor,
            interface_type=ModelInterfaceType.TRAIN_STEP,
            interface_impl=iface,
            input_keys=(
                "packed_input_ids", "prompt_mask", "packed_ref_logprobs"
            ),
            n_seqs=n,
            mb_spec=self.mb_spec,
            log_return_value=True,
        )
        shards = [
            ModelShard(
                model_name=actor,
                model=self.actor,
                backend=ModelBackendAbstraction(
                    "train", {"optimizer": self.optimizer}
                ),
                mesh_spec=self.mesh_spec,
            ),
            ModelShard(
                model_name=ref,
                model=self.ref or self.actor,
                backend=ModelBackendAbstraction("inference"),
                mesh_spec=self.mesh_spec,
            ),
        ]
        workers = self.build_model_workers(
            shards,
            {"ref_inf": iface, "dpo_train": iface},
            [self.dataset],
        )
        return self.make_config([ref_inf, dpo_train], workers)


system_api.register_experiment("dpo", DPOExperiment)

"""Model/interface/backend abstractions and registries
(reference: realhf/api/core/model_api.py — ``PipelinableEngine`` :514,
``Model`` :652, ``ModelBackend`` :699, ``ModelInterface`` :759, registries
:899-967, generation dataclasses :46-180, ``FinetuneSpec`` :474,
``GenerationHyperparameters`` realhf/api/cli_args.py:531).
"""

from __future__ import annotations

import abc
import dataclasses
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from areal_tpu.api.config import (
    ModelBackendAbstraction,
    ModelInterfaceAbstraction,
    ModelName,
)
from areal_tpu.api.data import MicroBatchSpec, SequenceSample
from areal_tpu.base import logging_

logger = logging_.getLogger("model_api")


# ---------------------------------------------------------------------------
# Generation hyperparameters & request/response dataclasses
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class GenerationHyperparameters:
    n: int = 1  # group size (answers per prompt)
    max_new_tokens: int = 16384
    min_new_tokens: int = 0
    greedy: bool = False
    top_p: float = 1.0
    top_k: int = int(1e8)
    temperature: float = 1.0
    stop_token_ids: List[int] = dataclasses.field(default_factory=list)

    def new(self, **kwargs) -> "GenerationHyperparameters":
        return dataclasses.replace(self, **kwargs)


@dataclasses.dataclass
class GenReqMeta:
    """Metadata for routing a generation request (reference :46)."""

    qid: str
    prompt_len: int
    group_size: int
    new_token_budget: int
    predicted_new_tokens: Optional[int] = None
    previous_server_url: str = ""
    previous_version: int = -1


@dataclasses.dataclass
class APIGenerateInput:
    """One generation call on an inference server (reference :63)."""

    qid: str
    prompt_ids: List[int]
    input_ids: List[int]  # prompt + previously generated (continuation)
    gconfig: GenerationHyperparameters
    stop_token_ids: List[int] = dataclasses.field(default_factory=list)
    return_logprob: bool = True
    metadata: Dict[str, Any] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class APIGenerateOutput:
    """Server reply (reference :88)."""

    qid: str
    prompt_ids: List[int]
    input_ids: List[int]
    output_ids: List[int] = dataclasses.field(default_factory=list)
    output_logprobs: List[float] = dataclasses.field(default_factory=list)
    no_eos: bool = True
    version_start: int = -1
    version_end: int = -1
    latency: float = 0.0

    @classmethod
    def from_input(cls, inp: APIGenerateInput) -> "APIGenerateOutput":
        return cls(qid=inp.qid, prompt_ids=inp.prompt_ids, input_ids=inp.input_ids)

    @property
    def gen_len(self):
        return len(self.output_ids)


@dataclasses.dataclass
class BundledGenerationOutputs:
    """A full group (n answers) for one prompt (reference :180)."""

    qid: str
    prompt_ids: List[int]
    seqs: List[List[int]]  # prompt + answer, per group member
    logprobs: List[List[float]]  # packed logprobs per seq (len = seqlen - 1)
    no_eos: List[bool]
    version_start: List[int]
    version_end: List[int]

    @classmethod
    def from_api_outputs(
        cls, outputs: List[APIGenerateOutput]
    ) -> "BundledGenerationOutputs":
        o0 = outputs[0]
        return cls(
            qid=o0.qid,
            prompt_ids=o0.prompt_ids,
            seqs=[o.prompt_ids + o.output_ids for o in outputs],
            logprobs=[
                [0.0] * (len(o.prompt_ids) - 1) + list(o.output_logprobs)
                for o in outputs
            ],
            no_eos=[o.no_eos for o in outputs],
            version_start=[o.version_start for o in outputs],
            version_end=[o.version_end for o in outputs],
        )


@dataclasses.dataclass
class FinetuneSpec:
    total_train_epochs: int
    dataset_size: int
    train_batch_size: int

    @property
    def steps_per_epoch(self) -> int:
        return max(1, self.dataset_size // self.train_batch_size)

    @property
    def total_train_steps(self) -> int:
        return self.total_train_epochs * self.steps_per_epoch

    def is_new_epoch(self, version) -> bool:
        return version.epoch_step == 0


# ---------------------------------------------------------------------------
# Model bundle + version
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class ModelVersionSteps:
    epoch: int = 0
    epoch_step: int = 0
    global_step: int = 0

    def advance(self, steps_per_epoch: int):
        self.global_step += 1
        self.epoch_step += 1
        if self.epoch_step >= steps_per_epoch:
            self.epoch += 1
            self.epoch_step = 0


@dataclasses.dataclass
class Model:
    """A named model living on a mesh: config + engine + tokenizer
    (reference :652 bundles module/tokenizer/device)."""

    name: ModelName
    engine: Any  # TrainEngine / InferenceEngine (set by backend initialize)
    tokenizer: Any
    mesh: Any
    version: ModelVersionSteps = dataclasses.field(
        default_factory=ModelVersionSteps
    )
    ft_spec: Optional[FinetuneSpec] = None
    backend_name: str = ""
    # set by make_model, consumed by backend initialize
    model_cfg: Any = None
    init_params: Any = None


class ModelBackend(abc.ABC):
    """Wraps a raw model into a trainable/servable engine (reference :699)."""

    @abc.abstractmethod
    def _initialize(self, model: Model, spec: FinetuneSpec) -> Model: ...

    def initialize(self, model: Model, spec: FinetuneSpec) -> Model:
        model = self._initialize(model, spec)
        model.ft_spec = spec
        return model

    def save(self, model: Model, save_dir: str):
        raise NotImplementedError()

    def load(self, model: Model, load_dir: str):
        raise NotImplementedError()


class ModelInterface(abc.ABC):
    """Algorithm interface: stateless handlers executed on model workers
    (reference :759).  All methods consume/produce SequenceSample."""

    def save(self, model: Model, save_dir: str):
        pass

    def evaluate(self, model: Model, eval_dataloader) -> Dict:
        return {}

    def inference(
        self, model: Model, data: SequenceSample, mb_spec: MicroBatchSpec
    ) -> SequenceSample | None:
        raise NotImplementedError()

    def generate(
        self, model: Model, data: SequenceSample, mb_spec: MicroBatchSpec
    ) -> SequenceSample | None:
        raise NotImplementedError()

    def train_step(
        self, model: Model, data: SequenceSample, mb_spec: MicroBatchSpec
    ) -> Dict | List[Dict]:
        raise NotImplementedError()

    # master-side filtering hook (dataset pruning by eval scores)
    def mock(self, type_: str, model: Model, data: SequenceSample):
        raise NotImplementedError()


# ---------------------------------------------------------------------------
# Registries
# ---------------------------------------------------------------------------

_MODEL_INTERFACES: Dict[str, Callable[..., ModelInterface]] = {}
_MODEL_BACKENDS: Dict[str, Callable[..., ModelBackend]] = {}


def register_interface(name: str, cls):
    if name in _MODEL_INTERFACES:
        raise KeyError(f"interface {name} already registered")
    _MODEL_INTERFACES[name] = cls


def register_backend(name: str, cls):
    if name in _MODEL_BACKENDS:
        raise KeyError(f"backend {name} already registered")
    _MODEL_BACKENDS[name] = cls


def make_interface(cfg: ModelInterfaceAbstraction) -> ModelInterface:
    if isinstance(cfg, str):
        cfg = ModelInterfaceAbstraction(cfg)
    return _MODEL_INTERFACES[cfg.type_](**cfg.args)


def make_backend(cfg: ModelBackendAbstraction) -> ModelBackend:
    if isinstance(cfg, str):
        cfg = ModelBackendAbstraction(cfg)
    return _MODEL_BACKENDS[cfg.type_](**cfg.args)

"""Structured-config CLI.

Rebuild of the reference's config system (reference: realhf/api/cli_args.py —
~30 dataclasses with help metadata parsed by hydra/OmegaConf; the resolved
config is dumped to the log dir).  Without hydra in the image, this module
implements the same surface natively: a dataclass tree built from an optional
YAML file plus ``a.b.c=value`` dotted overrides, with ``--help`` flag listing
and resolved-config dump.
"""

from __future__ import annotations

import dataclasses
import json
import sys
import typing
from typing import Any, Dict, List, Optional, Type, Union


def _is_dataclass_type(t) -> bool:
    return isinstance(t, type) and dataclasses.is_dataclass(t)


def _unwrap_optional(t):
    origin = typing.get_origin(t)
    if origin is Union:
        args = [a for a in typing.get_args(t) if a is not type(None)]
        if len(args) == 1:
            return args[0]
    return t


def _coerce(value: Any, t) -> Any:
    """Coerce a YAML/string value to the annotated type."""
    t = _unwrap_optional(t)
    if value is None:
        return None
    if _is_dataclass_type(t):
        return from_dict(t, value)
    origin = typing.get_origin(t)
    if origin in (list, List):
        (et,) = typing.get_args(t) or (str,)
        if isinstance(value, str):
            value = [v for v in value.split(",") if v]
        return [_coerce(v, et) for v in value]
    if origin in (dict, Dict):
        return dict(value)
    if origin in (tuple,):
        ets = typing.get_args(t)
        if isinstance(value, str):
            value = [v for v in value.split(",") if v]
        if ets and ets[-1] is Ellipsis:
            return tuple(_coerce(v, ets[0]) for v in value)
        return tuple(_coerce(v, et) for v, et in zip(value, ets))
    if t is bool:
        if isinstance(value, str):
            return value.lower() in ("1", "true", "yes", "on")
        return bool(value)
    if t is int:
        return int(value)
    if t is float:
        return float(value)
    if t is str:
        return str(value)
    # special-case: MeshSpec accepts compact strings like "d2f2m2"
    from areal_tpu.base.topology import MeshSpec

    if t is MeshSpec and isinstance(value, str):
        return MeshSpec.from_str(value)
    return value


def from_dict(cls: Type, d: Any):
    """Build a (possibly nested) dataclass from a plain dict."""
    if d is None:
        return None
    if isinstance(d, cls):
        return d
    from areal_tpu.base.topology import MeshSpec

    if cls is MeshSpec and isinstance(d, str):
        return MeshSpec.from_str(d)
    if not isinstance(d, dict):
        raise TypeError(f"cannot build {cls.__name__} from {d!r}")
    hints = typing.get_type_hints(cls)
    kwargs = {}
    field_names = {f.name for f in dataclasses.fields(cls)}
    for k, v in d.items():
        if k not in field_names:
            raise KeyError(
                f"{cls.__name__} has no field {k!r} "
                f"(valid: {sorted(field_names)})"
            )
        kwargs[k] = _coerce(v, hints[k])
    return cls(**kwargs)


def _set_dotted(tree: Dict, key: str, value: Any):
    parts = key.split(".")
    node = tree
    for p in parts[:-1]:
        node = node.setdefault(p, {})
        if not isinstance(node, dict):
            raise ValueError(f"override {key}: {p} is not a section")
    node[parts[-1]] = value


def _parse_scalar(s: str) -> Any:
    import yaml

    try:
        return yaml.safe_load(s)
    except Exception:
        return s


def _flag_help(cls: Type, prefix: str = "") -> List[str]:
    lines = []
    hints = typing.get_type_hints(cls)
    for f in dataclasses.fields(cls):
        t = _unwrap_optional(hints[f.name])
        name = f"{prefix}{f.name}"
        if _is_dataclass_type(t):
            lines.extend(_flag_help(t, prefix=name + "."))
        else:
            default = (
                f.default
                if f.default is not dataclasses.MISSING
                else (
                    "<factory>"
                    if f.default_factory is not dataclasses.MISSING
                    else "<required>"
                )
            )
            h = f.metadata.get("help", "") if f.metadata else ""
            tname = getattr(t, "__name__", str(t))
            lines.append(f"  {name}={default!r}  ({tname}) {h}")
    return lines


def parse_cli(
    cls: Type,
    argv: Optional[List[str]] = None,
    defaults: Optional[Dict] = None,
):
    """``prog [--config file.yaml] [a.b.c=value ...]`` -> cls instance."""
    import yaml

    argv = list(sys.argv[1:] if argv is None else argv)
    if "--help" in argv or "-h" in argv:
        print(f"usage: --config FILE.yaml  and/or  dotted.key=value overrides")
        print(f"flags for {cls.__name__}:")
        print("\n".join(_flag_help(cls)))
        sys.exit(0)

    tree: Dict = dict(defaults or {})
    if "--config" in argv:
        i = argv.index("--config")
        path = argv[i + 1]
        del argv[i : i + 2]
        with open(path) as f:
            loaded = yaml.safe_load(f) or {}
        for k, v in loaded.items():
            tree[k] = v
    for arg in argv:
        if "=" not in arg:
            raise ValueError(f"unrecognized argument {arg!r}")
        k, _, v = arg.partition("=")
        _set_dotted(tree, k, _parse_scalar(v))
    return from_dict(cls, tree)


def dump_config(obj, path: str):
    """Write the resolved config as YAML (reference saves config.yaml)."""
    import enum

    import yaml

    def enc(o):
        if dataclasses.is_dataclass(o) and not isinstance(o, type):
            return {f.name: enc(getattr(o, f.name)) for f in dataclasses.fields(o)}
        if isinstance(o, enum.Enum):
            return o.value
        if isinstance(o, (list, tuple)):
            return [enc(v) for v in o]
        if isinstance(o, dict):
            return {k: enc(v) for k, v in o.items()}
        return o

    with open(path, "w") as f:
        yaml.safe_dump(enc(obj), f, sort_keys=False)

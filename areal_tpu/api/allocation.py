"""Allocation modes: how devices are split between generation and training.

TPU-native rebuild of the reference's allocation layer (reference:
realhf/experiments/common/utils.py:245-372 ``AllocationMode`` with
``sglang.d4p1m1+d2p2m1``-style decoupled strings, per-MFC ``key:value``
hybrid strings, and the ``manual``/``heuristic`` modes; plus the allocation
search of realhf/api/quickstart/search.py, an MCMC enumeration over
device-mesh x parallel-strategy assignments driven by a FLOPs/memory cost
model).

Differences by design: parallel strategies are :class:`MeshSpec` axis shapes
(``d``ata/``f``sdp/``m``odel/``p``ipe/``s``eq/``e``xpert) instead of
3D p/m/d tuples — on TPU a strategy IS a mesh shape, XLA inserts the
collectives — and the decoupled prefix is ``gen.`` (the native engine
replaces the vLLM/SGLang server split).  The search enumerates mesh
factorizations and scores them with an analytic HBM + step-time model
rather than profiling runs; it is deterministic and runs in microseconds,
which a TPU can afford because the strategy space is tiny (axis sizes are
powers of two on a fixed chip count).
"""

from __future__ import annotations

import dataclasses
import enum
import re
from typing import Dict, Optional

from areal_tpu.base.topology import MeshSpec

_GEN_PREFIXES = ("gen", "vllm", "sglang", "mock")  # accepted for parity


class AllocationType(enum.Enum):
    DECOUPLED = 1  # separate gen + train device sets (async RL)
    GLOBAL_HYBRID = 2  # one device set, per-MFC (or uniform) strategies
    MANUAL = 3  # caller supplies everything
    HEURISTIC = 4  # search_allocation picks the split


@dataclasses.dataclass
class AllocationMode:
    type_: AllocationType
    # strategy per scope: "*" = every MFC, "gen" = generation cluster,
    # otherwise an MFC name (e.g. "actor_train")
    strategies: Dict[str, MeshSpec] = dataclasses.field(default_factory=dict)

    def is_decoupled(self) -> bool:
        return self.type_ == AllocationType.DECOUPLED

    @property
    def gen_spec(self) -> MeshSpec:
        assert self.is_decoupled(), "gen spec only exists in decoupled mode"
        return self.strategies["gen"]

    @property
    def gen_size(self) -> int:
        return self.gen_spec.world_size

    def train_spec(self, rpc_name: str = "*") -> MeshSpec:
        if rpc_name in self.strategies:
            return self.strategies[rpc_name]
        return self.strategies["*"]

    @property
    def train_size(self) -> int:
        return max(s.world_size for k, s in self.strategies.items() if k != "gen")

    @classmethod
    def from_str(cls, s: str) -> "AllocationMode":
        """Parse an allocation string.

        Forms (mirroring the reference grammar)::

            manual | heuristic
            d2f2m2                      # uniform hybrid
            actor_train:d2f2m2,ref_inf:d4m2   # per-MFC hybrid
            gen.d4m1+d2f2m1             # decoupled: gen cluster + trainer
            gen.d4m1+actor_train:d2m2,ref_inf:d4   # decoupled, per-MFC
        """
        s = s.strip()
        if s == "manual":
            return cls(AllocationType.MANUAL)
        if s == "heuristic":
            return cls(AllocationType.HEURISTIC)
        m = re.match(
            rf"^(?:{'|'.join(_GEN_PREFIXES)})\.([^+]+)\+(.+)$", s
        )
        if m:
            strategies = _parse_hybrid(m.group(2))
            strategies["gen"] = MeshSpec.from_str(m.group(1))
            return cls(AllocationType.DECOUPLED, strategies)
        return cls(AllocationType.GLOBAL_HYBRID, _parse_hybrid(s))

    def __str__(self):
        if self.type_ == AllocationType.MANUAL:
            return "manual"
        if self.type_ == AllocationType.HEURISTIC:
            return "heuristic"
        parts = [
            f"{k}:{v}" if k not in ("*", "gen") else str(v)
            for k, v in self.strategies.items()
            if k != "gen"
        ]
        body = ",".join(parts)
        if self.is_decoupled():
            return f"gen.{self.strategies['gen']}+{body}"
        return body


def _parse_hybrid(s: str) -> Dict[str, MeshSpec]:
    strategies: Dict[str, MeshSpec] = {}
    for part in s.split(","):
        part = part.strip()
        if not part:
            continue
        if ":" in part:
            name, spec = part.split(":", 1)
            strategies[name.strip()] = MeshSpec.from_str(spec.strip())
        else:
            strategies["*"] = MeshSpec.from_str(part)
    if not strategies:
        raise ValueError(f"cannot parse allocation {s!r}")
    if "*" not in strategies:
        # per-MFC-only strings still need a default for unlisted MFCs:
        # use the largest listed strategy
        strategies["*"] = max(
            strategies.values(), key=lambda m: m.world_size
        )
    return strategies


# ---------------------------------------------------------------------------
# Allocation search (reference: realhf/api/quickstart/search.py — ours is an
# analytic enumeration instead of MCMC over profiled costs)
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class ModelFootprint:
    """Inputs to the cost model, derivable from a TransformerConfig."""

    n_params: int
    n_layers: int
    hidden_dim: int
    # bytes per param of train state BEYOND the master weights: bf16 grads
    # + 2x fp32 adam moments = 2 + 4 + 4
    train_state_bytes_per_param: float = 10.0
    param_bytes: float = 4.0  # fp32 master weights

    @classmethod
    def from_config(cls, cfg) -> "ModelFootprint":
        from areal_tpu.models import transformer

        import jax

        # shape-only init is cheap: eval_shape avoids allocating
        shapes = jax.eval_shape(
            lambda: transformer.init_params(cfg, jax.random.PRNGKey(0))
        )
        n = sum(
            int(_prod(x.shape)) for x in jax.tree.leaves(shapes)
        )
        return cls(
            n_params=n, n_layers=cfg.n_layers, hidden_dim=cfg.hidden_dim
        )


def _prod(t):
    out = 1
    for x in t:
        out *= x
    return out


def _pow2_factorizations(n: int):
    """(data, model) splits of n with power-of-two model sizes."""
    m = 1
    while m <= n:
        if n % m == 0:
            yield n // m, m
        m *= 2


def estimate_train_hbm(
    fp: ModelFootprint,
    spec: MeshSpec,
    tokens_per_device_batch: int,
    remat: bool = True,
) -> float:
    """Bytes of HBM needed per chip for one train step.

    Persistent state shards over (fsdp x model); activations scale with the
    per-device token count.  With full remat only ~2 live activations per
    layer boundary survive the forward scan (carry + residual); without it
    every layer's activations are live.
    """
    shards = spec.fsdp * spec.model * spec.pipe * spec.expert
    state = fp.n_params * (fp.param_bytes + fp.train_state_bytes_per_param)
    state_per_chip = state / shards
    act_bytes_per_tok = fp.hidden_dim * 2  # bf16
    live_layers = 4 if remat else fp.n_layers
    acts = tokens_per_device_batch * act_bytes_per_tok * live_layers
    # logits buffer dominates transiently for LM heads; charge one copy
    return state_per_chip + acts * 4  # 4x: grads of acts + workspace


def _comm_penalty(spec: MeshSpec) -> float:
    """Relative step-time penalty of collectives: model-axis collectives are
    per-layer (expensive), fsdp gathers are per-step (cheap), data-axis
    all-reduce is per-step (cheapest)."""
    penalty = 1.0
    if spec.model > 1:
        penalty *= 1.0 + 0.06 * (spec.model - 1)
    if spec.fsdp > 1:
        penalty *= 1.03
    if spec.pipe > 1:
        penalty *= 1.0 + 0.10 * (spec.pipe - 1)  # bubble cost
    return penalty


def search_allocation(
    n_devices: int,
    footprint: ModelFootprint,
    tokens_per_step: int,
    hbm_bytes: float = 16e9,  # v5e default
    decoupled_gen_fraction: Optional[float] = None,
) -> AllocationMode:
    """Pick the best mesh shape(s) for ``n_devices`` chips.

    Enumerates (fsdp, model) power-of-two factorizations, keeps those whose
    estimated HBM fits, and among those picks the one with the smallest
    communication penalty (pure FSDP wins when it fits — the scaling-book
    recipe — model parallelism only buys its cost back when state doesn't
    fit).  With ``decoupled_gen_fraction`` the device set is split
    gen/train first (async RL), mirroring the reference heuristic's
    gen-device carve-out.
    """
    if decoupled_gen_fraction:
        n_gen = max(1, round(n_devices * decoupled_gen_fraction))
        n_train = n_devices - n_gen
        assert n_train >= 1, "no devices left for training"
        train = search_allocation(
            n_train, footprint, tokens_per_step, hbm_bytes
        )
        return AllocationMode(
            AllocationType.DECOUPLED,
            {
                "*": train.strategies["*"],
                # gen replicates the model per server unless it can't fit:
                # bf16 inference state is n_params * 2 bytes
                "gen": _gen_spec(n_gen, footprint, hbm_bytes),
            },
        )

    # NOTE: no pipe tier here — under this cost model a fitting pipe spec is
    # always dominated by folding the pipe factor into fsdp (identical state
    # sharding, smaller per-device batch, lower comm penalty), so enumerating
    # pipe would be dead code.  Pipeline parallelism is a MANUAL choice for
    # the regimes the model doesn't capture (cross-slice DCN, extreme fsdp
    # widths): spell it in the allocation string, e.g. ``d2p2m2``
    # (docs/parallelism.md).
    best = None
    for data, model in _pow2_factorizations(n_devices):
        for fsdp_of_data in _divisors_pow2(data):
            spec = MeshSpec(
                data=data // fsdp_of_data, fsdp=fsdp_of_data, model=model
            )
            per_dev_toks = max(1, tokens_per_step // spec.dp_size)
            need = estimate_train_hbm(footprint, spec, per_dev_toks)
            if need > hbm_bytes * 0.92:  # leave allocator headroom
                continue
            score = _comm_penalty(spec)
            if best is None or score < best[0]:
                best = (score, spec)
    if best is None:
        raise ValueError(
            f"model does not fit on {n_devices} devices with any strategy"
        )
    return AllocationMode(AllocationType.GLOBAL_HYBRID, {"*": best[1]})


def _divisors_pow2(n: int):
    d = 1
    while d <= n:
        if n % d == 0:
            yield d
        d *= 2


def _gen_spec(n_gen: int, fp: ModelFootprint, hbm_bytes: float) -> MeshSpec:
    # smallest model-parallel degree whose bf16 weights + KV budget fit
    m = 1
    while m <= n_gen:
        weights = fp.n_params * 2 / m
        if weights < hbm_bytes * 0.4:  # rest is KV cache
            if n_gen % m == 0:
                return MeshSpec(data=n_gen // m, model=m)
        m *= 2
    raise ValueError(f"generation weights do not fit on {n_gen} devices")

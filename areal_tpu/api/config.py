"""Core named abstractions (reference: realhf/api/core/config.py).

``ModelName`` identifies a model role + replica; ``ModelShardID`` pins one
shard of a model onto a mesh coordinate.  The ``*Abstraction`` dataclasses
are (type_, args) factory references resolved through registries — the
config-file-friendly way the reference wires datasets/models/interfaces/
backends/agents/envs into experiments.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict


@dataclasses.dataclass(frozen=True, order=True)
class ModelName:
    role: str
    replica_id: int = 0

    def __str__(self):
        return f"{self.role}@{self.replica_id}"

    @classmethod
    def from_str(cls, s: str) -> "ModelName":
        role, _, rid = s.partition("@")
        return cls(role=role, replica_id=int(rid or 0))


@dataclasses.dataclass(frozen=True, order=True)
class ModelFamily:
    """HF model family tag, e.g. qwen2 / llama / gemma."""

    _class: str
    is_critic: bool = False

    def __str__(self):
        return f"{self._class}{'-critic' if self.is_critic else ''}"


@dataclasses.dataclass(frozen=True)
class ModelShardID:
    """One shard of a model: mesh coordinates of the owning chip.

    The reference uses (dp, tp, pp) ranks (realhf/api/core/config.py);
    we keep the same identification for the system layer, where ``dp``
    indexes the combined data×fsdp axes, ``tp`` the model axis, and ``pp``
    the pipe axis of the MeshSpec.
    """

    model_name: ModelName
    dp_rank: int = 0
    tp_rank: int = 0
    pp_rank: int = 0

    @classmethod
    def from_parallelism_rank(cls, model_name: ModelName, spec, rank: int):
        """Map a flat chip rank in a MeshSpec to shard coordinates."""
        from areal_tpu.base.topology import worker_topology

        topo = worker_topology(spec)
        coord = topo.get_coord(rank)
        dp = coord["data"] * spec.fsdp + coord["fsdp"]
        return cls(
            model_name=model_name,
            dp_rank=dp,
            tp_rank=coord["model"],
            pp_rank=coord["pipe"],
        )

    def __str__(self):
        return (
            f"{self.model_name}-d{self.dp_rank}t{self.tp_rank}p{self.pp_rank}"
        )


def _abstraction(name: str):
    @dataclasses.dataclass
    class _Abstraction:
        type_: str
        args: Dict[str, Any] = dataclasses.field(default_factory=dict)

    _Abstraction.__name__ = name
    _Abstraction.__qualname__ = name
    return _Abstraction


DatasetAbstraction = _abstraction("DatasetAbstraction")
ModelAbstraction = _abstraction("ModelAbstraction")
ModelInterfaceAbstraction = _abstraction("ModelInterfaceAbstraction")
ModelBackendAbstraction = _abstraction("ModelBackendAbstraction")
AgentAbstraction = _abstraction("AgentAbstraction")
EnvServiceAbstraction = _abstraction("EnvServiceAbstraction")
RewardAbstraction = _abstraction("RewardAbstraction")

"""Environment API (reference: realhf/api/core/env_api.py:9 — gym-like async
``EnvironmentService.step/reset`` + registry)."""

from __future__ import annotations

import abc
from typing import Any, Callable, Dict, Tuple


class EnvironmentService(abc.ABC):
    @abc.abstractmethod
    async def reset(self, seed=None, options=None) -> Tuple[Any, Dict]: ...

    @abc.abstractmethod
    async def step(self, action) -> Tuple[Any, float, bool, bool, Dict]: ...


ALL_ENVIRONMENTS: Dict[str, Callable[..., EnvironmentService]] = {}


def register_environment(name: str, cls):
    if name in ALL_ENVIRONMENTS:
        raise KeyError(f"environment {name} already registered")
    ALL_ENVIRONMENTS[name] = cls


def make_env(cfg) -> EnvironmentService:
    from areal_tpu.api.config import EnvServiceAbstraction

    if isinstance(cfg, str):
        cfg = EnvServiceAbstraction(cfg)
    return ALL_ENVIRONMENTS[cfg.type_](**cfg.args)

"""Experiment/worker configuration dataclasses.

Rebuild of the reference's system API (reference:
realhf/api/core/system_api.py — ``ModelWorker`` :95, ``GenerationServer``
:124, ``GserverManager`` :134, ``RolloutWorker`` :146, ``MasterWorker``
:159, ``ExperimentConfig`` :190 with DFG lazy-init, ``Experiment`` ABC +
registry :457-488).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Tuple

from areal_tpu.api.config import (
    AgentAbstraction,
    DatasetAbstraction,
    EnvServiceAbstraction,
    ModelAbstraction,
    ModelBackendAbstraction,
    ModelInterfaceAbstraction,
    ModelName,
)
from areal_tpu.api.dfg import MFCDef, build_graph
from areal_tpu.api.model_api import GenerationHyperparameters
from areal_tpu.base.topology import MeshSpec
from areal_tpu.observability.tracing import TraceConfig


@dataclasses.dataclass
class ExperimentSaveEvalControl:
    """Frequency control for save/eval/recover-ckpt
    (reference: realhf/api/cli_args.py:702)."""

    total_train_epochs: int = 1
    save_freq_epochs: Optional[int] = None
    save_freq_steps: Optional[int] = None
    save_freq_secs: Optional[int] = None
    ckpt_freq_epochs: Optional[int] = None
    ckpt_freq_steps: Optional[int] = None
    ckpt_freq_secs: Optional[int] = None
    eval_freq_epochs: Optional[int] = None
    eval_freq_steps: Optional[int] = None
    eval_freq_secs: Optional[int] = None
    benchmark_steps: Optional[int] = None  # early exit for profiling runs


@dataclasses.dataclass
class ModelShard:
    """One model role hosted by a model worker (reference: system_api.py
    ``StandaloneModelShard``)."""

    model_name: ModelName
    model: ModelAbstraction
    backend: ModelBackendAbstraction
    mesh_spec: MeshSpec = dataclasses.field(default_factory=MeshSpec)
    eval_dataset: Optional[DatasetAbstraction] = None


@dataclasses.dataclass
class ModelWorkerConfig:
    worker_name: str
    shards: List[ModelShard] = dataclasses.field(default_factory=list)
    # interfaces per MFC name (the worker instantiates them lazily)
    interfaces: Dict[str, ModelInterfaceAbstraction] = dataclasses.field(
        default_factory=dict
    )
    datasets: List[DatasetAbstraction] = dataclasses.field(
        default_factory=list
    )
    tokenizer_path: Optional[str] = None
    dataset_seed: int = 1
    # which DP shard of the dataset this worker loads (dp_rank, dp_size)
    dataset_shard: Tuple[int, int] = (0, 1)
    use_stream_dataset: bool = False  # async mode: data arrives by push
    stream_group_size: int = 1  # trajectories per prompt (epoch accounting)
    # publish an int8 serving tree (matmul weights quantized to int8 +
    # per-output-channel f32 scales, sibling v{N}-int8 snapshot dir)
    # next to every full-precision weight publish and advertise it in
    # the manifest.  Servers that set serving_weight_dtype="int8"
    # negotiate onto it (half the staged-swap bytes, half the serving
    # weight HBM); everyone else ignores it.  Costs ~50% extra publish
    # IO — turn off for trainers whose fleet never serves quantized.
    publish_quantized_int8: bool = True
    seed: int = 1
    # flight-recorder knobs (None = ambient process defaults)
    trace: Optional[TraceConfig] = None


@dataclasses.dataclass
class MasterWorkerConfig:
    worker_name: str = "master"
    model_rpcs: List[MFCDef] = dataclasses.field(default_factory=list)
    model_worker_names: List[str] = dataclasses.field(default_factory=list)
    # worker names hosting each model role (requests broadcast to the group)
    model_groups: Dict[str, List[str]] = dataclasses.field(
        default_factory=dict
    )
    exp_ctrl: ExperimentSaveEvalControl = dataclasses.field(
        default_factory=ExperimentSaveEvalControl
    )
    # the MFC whose n_seqs defines one train iteration
    train_rpc_name: str = ""
    seed: int = 1
    trace: Optional[TraceConfig] = None


@dataclasses.dataclass
class RolloutWorkerConfig:
    worker_name: str
    agent: AgentAbstraction = None
    env: EnvServiceAbstraction = None
    gconfig: GenerationHyperparameters = dataclasses.field(
        default_factory=GenerationHyperparameters
    )
    datasets: List[DatasetAbstraction] = dataclasses.field(
        default_factory=list
    )
    tokenizer_path: Optional[str] = None
    dataset_shard: Tuple[int, int] = (0, 1)
    dataset_seed: int = 1
    rollout_request_timeout: float = 600.0
    new_tokens_per_chunk: int = 1 << 30  # interruptible-generation chunking
    # schedule all group siblings' first chunks in ONE manager RPC
    # (affinity co-locates them anyway); falls back per-qid against an
    # old manager that does not know the batched command
    batch_schedule: bool = True
    # SLO/tenant label this worker's traffic carries end-to-end: it
    # lands in LatencyRecord.workload (fleet-merged per-workload
    # percentile rows) and charges the matching admission-plane tenant.
    # Default: the bulk rollout tenant.
    workload: str = "rollout"
    trace: Optional[TraceConfig] = None


@dataclasses.dataclass
class SpecDecodeConfig:
    """Self-speculative decoding on the paged serving path (default OFF).

    Each row drafts its own continuation by n-gram / prompt-lookup over
    its prompt+output history (no draft model — RL math/code traces are
    repetitive enough), and one batched paged-prefill pass verifies up
    to ``max_draft_tokens`` drafts per step.  Output is token-identical
    to plain GREEDY decode (the engine silently disables the feature
    under non-greedy sampling or a dense cache); rows whose
    acceptance-rate EMA falls below ``min_accept_rate`` drop back to
    plain chunked decode, bounding the worst case.  See
    ``engine/spec_decode.py`` and docs/async_pipeline.md."""

    enabled: bool = False
    # drafts proposed per verify step (the verify window is this + 1:
    # the pending token rides along); each step emits 1..this+1 tokens.
    # Keep at 2^n - 1: windows bucket to powers of two, so 8 drafts pad
    # every verify to 16 positions and double its compute for nothing
    max_draft_tokens: int = 7
    # n-gram sizes tried for the history lookup (longest first)
    ngram_max: int = 3
    ngram_min: int = 1
    # acceptance-rate EMA below which a row falls back to plain decode;
    # None = the measured default in engine/dispatch.py (bench.py's
    # spec_decode_ab derives the break-even rate for the hardware)
    min_accept_rate: Optional[float] = None
    ema_decay: float = 0.9
    # verifies before the fallback threshold may fire
    warmup_verifies: int = 4
    # measured cost of one verify pass in plain-decode-step units (the
    # per-step batch vote's c); None = engine/dispatch.py default.  Pin
    # it from bench.py spec_decode_ab's verify_cost_over_decode_step
    verify_cost_over_decode_step: Optional[float] = None


@dataclasses.dataclass
class GenServerConfig:
    worker_name: str
    model: ModelAbstraction = None
    mesh_spec: MeshSpec = dataclasses.field(default_factory=MeshSpec)
    tokenizer_path: Optional[str] = None
    max_concurrent_batch: int = 64
    kv_cache_len: int = 32768
    # tokens generated fully device-side between host syncs; larger chunks
    # amortize dispatch (measured on v5e: 3.7k tok/s @64 -> 3.9k @128 for
    # the 0.5B bench model) at the cost of coarser interrupt/admission
    # granularity
    chunk_size: int = 64
    temperature: float = 1.0
    # greedy (argmax) decoding server-wide; required for spec_decode's
    # exactness guarantee (eval servers, deterministic replay)
    greedy: bool = False
    # KV layout: "auto" uses the paged block pool at kv_cache_len >= 2k
    # (global-attention models), dense per-row cache below; see
    # engine/inference_server.py.  kv_pool_tokens sizes the paged pool
    # (None = dense-equivalent max_batch * kv_cache_len — set smaller to
    # serve 32k contexts a dense cache could never reserve);
    # prefill_chunk_tokens bounds the per-step admission prefill so long
    # prompts never stall decode for a whole wave (chunked prefill)
    cache_mode: str = "auto"
    page_size: int = 1024
    kv_pool_tokens: Optional[int] = None
    # paged KV storage dtype (the SGLang/vLLM --kv-cache-dtype knob):
    # "auto" stores blocks at model dtype (bit-for-bit today's
    # behavior); "int8" stores quantized pools with per-(block, head,
    # slot) f32 scales alongside — ~half the HBM per cached token (~2x
    # live rows / prefix-cache capacity / half-cost host spills at the
    # same budget), reads dequantize inline so the error is
    # storage-only.  Quality is MEASURED, not assumed: bench.py's
    # kv_quant_ab section reports the greedy divergence rate per
    # workload and the fleet exports areal_inference_kv_quant_* series.
    kv_cache_dtype: str = "auto"
    # serving WEIGHT storage dtype (the SGLang --quantization / vLLM
    # quantized-weight-loading knob): "auto" serves the model-dtype
    # param tree (bit-for-bit today's behavior — quantized snapshots a
    # publisher advertises are simply ignored); "int8" holds matmul
    # weights as int8 + per-output-channel f32 absmax scales
    # (models/quantize.py) — ~half the weight HBM (freed for paged
    # blocks / prefix cache) and ~half the bytes a staged weight swap
    # restores.  The format is NEGOTIATED through the publish manifest:
    # a publisher that wrote the v{N}-int8 sibling tree serves it to
    # int8 servers; one that didn't triggers a logged fall-back to the
    # full-precision tree (restored full, quantized on arrival), never
    # a crash.  Dequantization happens at use inside each projection,
    # so matmul math stays model dtype and the error is storage-only —
    # measured, not assumed: bench.py weight_quant_ab reports the
    # greedy divergence rate per workload and the fleet exports the
    # areal_inference_weight_quant_* series.
    serving_weight_dtype: str = "auto"
    prefill_chunk_tokens: int = 1024
    # cross-request radix prefix cache over the paged pool (default on
    # for paged mode; engine/prefix_cache.py): finished/parked sequences'
    # blocks stay indexed by token prefix so multi-turn continuations,
    # retries, and late group members prefill only their new suffix.
    # capacity_frac bounds the pool fraction the cache may hold
    # references to; min_match_tokens suppresses matches too short to
    # pay for their pin + tail copy — a tail match costs a full
    # page_size-block COW device copy, so reusing a handful of tokens
    # (every prompt shares a BOS/template head) costs more than the
    # prefill it saves.  64 keeps multi-turn/retry reuse (hundreds+ of
    # tokens) while rejecting the degenerate matches.
    prefix_cache: bool = True
    prefix_cache_capacity_frac: float = 0.5
    prefix_cache_min_match_tokens: int = 64
    # host spill tier below the HBM radix cache (the SGLang
    # hierarchical-cache / HiCache direction): evicted full-block
    # entries copy their KV into host buffers (batched device_get per
    # reclamation round) instead of dying, and a match on a spilled
    # prefix swaps the blocks back in on an async dispatch riding the
    # decode ring's overlap (admission requeued until the step after
    # dispatch — SPMD-deterministic).  Bytes-budgeted: effective cache
    # capacity multiplies by roughly host-RAM/HBM.  0 = off (default);
    # weight swaps always flush both tiers.  Single-process engines
    # only (multi-host SPMD serving auto-disables with a warning).
    prefix_cache_host_bytes: int = 0
    # P/D disaggregation: the serving role this server registers under
    # (the SGLang/vLLM prefill/decode-disaggregation deployment knob).
    # "unified" (default) serves both stages exactly as before.  With
    # both "prefill" and "decode" servers registered, the gserver
    # manager routes every NEW request to a prefill server, which runs
    # chunked prefill + first token, exports the row's paged KV blocks
    # as a handoff unit, and pushes them to the decode server that owns
    # the request; continuations sticky-route to the decode server and
    # resume with zero prefill.  Version skew across a weight swap
    # fails the handoff closed (the decode server re-prefills — stale
    # KV is never decoded).  Single-process servers only.
    role: str = "unified"
    # per-handoff timeout for the import_handoff RPC to the decode peer
    # (a dead peer must not wedge the prefill server's poll loop; on
    # timeout the continuation re-prefills on the decode server)
    handoff_request_timeout: float = 60.0
    # STREAMED handoff (default on): export each fill chunk's finalized
    # blocks as a numbered segment the moment the chunk lands — one
    # coalesced buffer per segment over the import_handoff_segment RPC,
    # pushed while later chunks still fill — and the decode server
    # pre-allocates the row's blocks on segment 0 and async-scatters
    # each segment under its own decode chunks, so the decode-side
    # resume gap is O(one chunk) instead of O(prompt).  Every segment
    # is version-checked fail-closed (skew, sequence gaps, aborts, and
    # dead peers all release the partial blocks; the continuation
    # re-prefills).  False = the PR-13 monolithic handoff unit.
    handoff_streaming: bool = True
    # how KV segments travel between servers (streamed handoffs AND
    # fleet prefix pulls).  "host-numpy" (the default and the only
    # backend in this build) materializes segment payloads on host and
    # ships them over the worker ZMQ RPC; "tpu-d2d" is the reserved
    # capability token for a device-to-device ICI/DMA backend (a server
    # registering it today fails at startup — the token exists so the
    # registration protocol and mixed-fleet negotiation are already
    # wire-stable).  The manager reads each server's token from its
    # registration value and only fabric-routes between servers whose
    # transports match.
    segment_transport: str = "host-numpy"
    # fleet KV fabric, pull side: a kv_source schedule hint triggers a
    # peer prefix pull only when the pull would cover at least this
    # many tokens beyond the local radix match (an RPC + scatter round
    # trip costs more than re-prefilling a short suffix).  The
    # manager's kv_fabric_min_prefix_tokens gates the hint fleet-side;
    # this is the engine's own floor.
    prefix_pull_min_tokens: int = 256
    # self-speculative n-gram decoding on the paged path (default off);
    # maps SGLang's ngram speculative mode / vLLM's ngram
    # speculative_config — see SpecDecodeConfig + docs
    spec_decode: SpecDecodeConfig = dataclasses.field(
        default_factory=SpecDecodeConfig
    )
    # request-level SLO plane (observability/latency.py): per-request
    # latency decomposition (schedule/admission wait, TTFT, TPOT,
    # swap/preempt stall) streamed into mergeable percentile digests and
    # exported as the areal_slo_* families.  Off = the bench A/B's
    # baseline arm; overhead is a few clock stamps per request.
    slo_tracking: bool = True
    # decode-pipeline depth: max chunks dispatched-but-unharvested (the
    # engine's in-flight ring).  2 overlaps each chunk's output fetch
    # with the next chunk's device time; raise it when the fetch RTT
    # exceeds a chunk's device time (high-latency tunnels).  1 =
    # unpipelined baseline.
    pipeline_depth: int = 2
    # measured dispatch-table overrides for cache_mode="auto" (None =
    # builtin defaults / bench-derived values from engine/dispatch.py):
    # paged_min_cache_len switches dense->paged by kv_cache_len;
    # deep_kernel_min_context switches the paged decode kernel to the
    # deep DMA-ring variant once the batch's longest context crosses it
    paged_min_cache_len: Optional[int] = None
    deep_kernel_min_context: Optional[int] = None
    # recompile sentinel (observability/compile_watch.py): engine steps
    # after which the serving loop is declared steady-state — any
    # decode/fill-path XLA compile from then on fires
    # areal_trace_stall_total{kind="recompile"} once per episode and
    # force-samples the in-flight trace roots.  0 disables the sentinel
    # (compile COUNTING always runs); size it past the bucket-ladder
    # warm-up for the deployment's longest prompts.
    compile_quiet_after_steps: int = 0
    # staged weight sync: transient HBM headroom knob for the staged
    # restore (update_weights mode="stage").  The snapshot restores in
    # layer chunks of at most this many bytes, placed directly at the
    # engine's serving shardings, so peak footprint during a stage is
    # old tree + staged-so-far + ONE chunk of restore buffers — not old
    # tree + a full host copy + a full device copy like the legacy
    # full-reload path.  None = one-shot restore (small models).
    stage_chunk_bytes: Optional[int] = 256 * 1024 * 1024
    # which local device hosts this server's engine (trainer/generation
    # device split on one host; None = default device)
    device_idx: Optional[int] = None
    # multi-host serving: when num_processes > 1 this worker is one SPMD
    # controller of a TP mesh spanning jax.distributed processes (the role
    # of the reference's multi-node SGLang servers).  Process 0 is the
    # leader: it owns the client-facing socket and broadcasts the command
    # stream; followers replay it in lockstep so every controller issues
    # identical device programs.
    coordinator: str = ""  # jax.distributed coordinator host:port
    num_processes: int = 1
    process_id: int = 0
    trace: Optional[TraceConfig] = None


@dataclasses.dataclass
class GserverManagerConfig:
    worker_name: str = "gserver_manager"
    n_servers: int = 1
    schedule_policy: str = "round_robin"
    # control-plane serve loop: "router" (default) drains a batch of
    # pending requests per tick off a ZMQ ROUTER socket, processes them
    # under one lock pass, and replies out of order — a gateway storm
    # never queues behind rollout traffic, and slow work (weight-update
    # fan-out) runs off the serve thread.  "rep" restores the legacy
    # strict-lockstep REP loop.  Wire format is identical either way:
    # legacy REQ clients speak to both.
    serve_mode: str = "router"
    # max requests drained per ROUTER serve tick (bounds the time one
    # lock pass can hold the scheduling state)
    serve_batch_max: int = 256
    # O(log N) routing: per-chip load/token min-heaps maintained
    # incrementally on the deltas scheduling already applies, plus a
    # precomputed weighted round-robin cycle rebuilt only when pool
    # membership or mesh shapes change.  False = the O(N) scans
    # (pick-for-pick identical; kept for A/B and paranoia).
    routing_index: bool = True
    max_head_offpolicyness: int = 0
    train_batch_size: int = 1  # in sequences (train_bs_n_seqs)
    group_size: int = 1  # sequences per rollout (staleness unit conversion)
    max_concurrent_rollouts: Optional[int] = None
    flush_request_timeout: float = 120.0
    # cache-aware routing: a session's turns follow the server whose
    # prefix cache is hottest for it (longest prefix served so far),
    # UNLESS that server's estimated resident tokens exceed the least-
    # loaded server's by more than imbalance_factor x + slack — then the
    # affinity breaks (the new server re-prefills; latency beats a hot
    # cache on an overloaded box).  False = the pre-cache behavior
    # (unconditional group affinity + the configured schedule_policy).
    cache_aware_routing: bool = True
    affinity_imbalance_factor: float = 1.5
    affinity_imbalance_slack_tokens: float = 4096.0
    # per-server update_weights retries before the round is declared
    # failed (one flaky server must not block the fleet's version bump)
    update_weights_retries: int = 3
    update_weights_retry_backoff_s: float = 0.5
    # zero-downtime weight sync (default on for published sharded
    # snapshots): servers restore the new snapshot into a device-resident
    # STAGING tree while decode continues (update_weights mode="stage",
    # issued to the whole fleet concurrently), then the fleet pauses only
    # for the pointer-flip commit — pause becomes max(commit) instead of
    # sum(load + transfer + apply).  A server whose stage fails falls
    # back to the legacy full reload inside the pause window, so the
    # fleet always converges on one version.  False = legacy full
    # reloads (still fanned out concurrently).
    staged_weight_updates: bool = True
    # per-server timeout for the stage RPC — generous, because staging
    # runs OFF the paused critical path (decode continues throughout)
    stage_request_timeout: float = 600.0
    # load-aware prefill admission (two-stage P/D fleets): prefill
    # servers report their in-flight prefill-token backlog through the
    # metrics RPC (scraped at most every prefill_backlog_refresh_s,
    # with optimistic local increments between scrapes) and a NEW
    # request's prefill stage goes to the least-backlog-per-chip server
    # instead of the load-blind chip-weighted rotation.  When EVERY
    # prefill server's backlog-per-chip exceeds
    # prefill_saturation_tokens_per_chip, the request is SHED: it
    # routes straight to its decode owner and serves unified-style
    # there (prefill + decode on one server) — admission pressure never
    # queues unboundedly on a saturated prefill pool.  0 disables
    # shedding; prefill_load_aware=False restores the PR-13 rotation.
    prefill_load_aware: bool = True
    prefill_backlog_refresh_s: float = 0.5
    prefill_saturation_tokens_per_chip: int = 65536
    # fleet KV fabric (cross-server prefix reuse): the manager's
    # per-session hot-prefix map doubles as a fleet prefix DIRECTORY —
    # when a session's request routes to a server other than its
    # longest-prefix owner, the schedule response carries a kv_source
    # hint and the routed engine peer-pulls the cached prefix instead
    # of re-prefilling it.  Directory entries are stamped with the
    # owner's (model version, cache flush epoch) and invalidated on
    # weight updates, server cache flushes (reported through the
    # existing metrics scrape), and server death — the directory never
    # advertises dropped prefixes.  Hints only pair servers whose
    # segment transports match.  False = hot-prefix tracking behaves
    # exactly as before (affinity only, no hints).
    kv_fabric: bool = True
    # minimum advertised prefix length (tokens) worth a pull hint — the
    # fleet-side floor mirroring the engine's prefix_pull_min_tokens
    kv_fabric_min_prefix_tokens: int = 256
    # per-tenant admission policies (gateway/admission.TenantPolicy rows
    # or plain dicts of their fields): priority class, token-bucket rate
    # limit, cumulative token budget.  Unknown tenants run under the
    # permissive interactive default; rollout traffic charges the
    # "rollout" tenant.  Empty = admit everything.
    tenants: List = dataclasses.field(default_factory=list)
    trace: Optional[TraceConfig] = None


@dataclasses.dataclass
class GatewayConfig:
    """The OpenAI-style HTTP/SSE serving gateway (gateway/server.py):
    one front-door worker per fleet, scheduling through the gserver
    manager and streaming tokens off the gen servers' harvest
    streams."""

    worker_name: str = "gateway"
    host: str = "0.0.0.0"
    port: int = 8081
    # tenant attributed to requests carrying neither an x-tenant header
    # nor a body ``user`` field
    default_tenant: str = "anonymous"
    # byte-codec vocab for string prompts (see gateway/sse.py); set to
    # the serving model's vocab size
    vocab_size: int = 256
    # real tokenizer for string prompts/completions: a HF tokenizer path
    # loaded via dataset_api.load_hf_tokenizer.  Empty = the byte-level
    # codec (token-id prompts are native either way).
    tokenizer_path: str = ""
    max_new_tokens_cap: int = 1024
    request_timeout_s: float = 600.0
    poll_interval_s: float = 0.002
    # manager RPC timeout for the gateway's admission/schedule calls
    manager_timeout_s: float = 60.0
    trace: Optional[TraceConfig] = None


@dataclasses.dataclass
class EvaluatorConfig:
    """Automatic-evaluator knobs (reference: cli_args AutomaticEvaluator —
    ours points the watcher at the saved-checkpoint tree and an eval
    dataset instead of a slurm image)."""

    dataset_path: str
    model_name: str = "actor"
    max_prompts: int = 64
    max_new_tokens: int = 256
    interval: float = 5.0
    # JAX platform policy for the eval subprocess (scheduler/evaluator.py
    # resolve_eval_env).  "auto" (default): run ON a spare local
    # accelerator whenever the experiment's workers leave one free
    # (pinned via TPU_VISIBLE_DEVICES — the reference's dedicated eval
    # partition, realhf/scheduler/evaluator.py:34), falling back to CPU
    # only when every chip is claimed.  A platform string forces it;
    # "" inherits the host platform unconditionally.
    device: str = "auto"


@dataclasses.dataclass
class ExperimentConfig:
    experiment_name: str
    trial_name: str
    master: MasterWorkerConfig
    model_workers: List[ModelWorkerConfig] = dataclasses.field(
        default_factory=list
    )
    rollout_workers: List[RolloutWorkerConfig] = dataclasses.field(
        default_factory=list
    )
    gen_servers: List[GenServerConfig] = dataclasses.field(
        default_factory=list
    )
    gserver_manager: Optional[GserverManagerConfig] = None
    gateway: Optional[GatewayConfig] = None
    evaluator: Optional[EvaluatorConfig] = None
    # experiment-wide flight-recorder config, propagated to every worker
    # that does not set its own (None = leave workers on ambient defaults)
    trace: Optional[TraceConfig] = None

    def lazy_init(self):
        """Build the MFC graph and sanity-check worker wiring
        (reference: system_api.py ExperimentConfig.lazy_init :190)."""
        build_graph(self.master.model_rpcs)
        if self.trace is not None:
            workers = [self.master, self.gserver_manager, self.gateway]
            workers += self.model_workers + self.rollout_workers
            workers += self.gen_servers
            for w in workers:
                if w is not None and w.trace is None:
                    w.trace = self.trace
        if self.gateway is not None and self.gserver_manager is None:
            raise ValueError(
                "gateway worker requires a gserver_manager (it schedules "
                "and admits through the manager's control plane)"
            )
        self.master.model_worker_names = [
            w.worker_name for w in self.model_workers
        ]
        if not self.master.model_groups:
            groups: Dict[str, List[str]] = {}
            for w in self.model_workers:
                for s in w.shards:
                    groups.setdefault(str(s.model_name), []).append(
                        w.worker_name
                    )
            self.master.model_groups = groups
        for rpc in self.master.model_rpcs:
            if str(rpc.model_name) not in self.master.model_groups:
                raise ValueError(
                    f"MFC {rpc.name}: no worker hosts {rpc.model_name}"
                )
        if not self.master.train_rpc_name:
            from areal_tpu.api.dfg import ModelInterfaceType

            trains = [
                r
                for r in self.master.model_rpcs
                if r.interface_type == ModelInterfaceType.TRAIN_STEP
            ]
            if trains:
                self.master.train_rpc_name = trains[0].name
        return self


# ---------------------------------------------------------------------------
# Experiment registry (reference :457-488)
# ---------------------------------------------------------------------------


class Experiment:
    """User-facing experiment: produces an ExperimentConfig."""

    def initial_setup(self) -> ExperimentConfig:
        raise NotImplementedError()


_EXPERIMENTS: Dict[str, Callable[[], Experiment]] = {}


def register_experiment(name: str, cls: Callable[[], Experiment]):
    if name in _EXPERIMENTS:
        raise KeyError(f"experiment {name} already registered")
    _EXPERIMENTS[name] = cls


def make_experiment(name: str, *args, **kwargs) -> Experiment:
    return _EXPERIMENTS[name](*args, **kwargs)


def experiment_cls(name: str) -> Callable[[], Experiment]:
    if name not in _EXPERIMENTS:
        raise KeyError(
            f"unknown experiment {name!r}; registered: {sorted(_EXPERIMENTS)}"
        )
    return _EXPERIMENTS[name]


def list_experiments() -> List[str]:
    return sorted(_EXPERIMENTS)

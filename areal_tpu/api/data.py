"""SequenceSample — THE data currency of the framework.

Rebuild of the reference's packed-batch abstraction
(reference: realhf/api/core/data_api.py:105 ``SequenceSample``, :289 gather,
:398 split, :483 meta, :683 json codec; ``MicroBatchSpec``
realhf/api/cli_args.py:16).

TPU-native design notes:

* Data lives on host as **numpy** arrays.  Everything between workers is
  packed 1-D varlen; padding to static shapes happens only at the jit
  boundary inside engines (XLA needs static shapes, the data plane doesn't).
* The JSON codec uses base64 raw bytes (fast, compact) — it is the wire
  format of the rollout->trainer push stream.
* Each *id* may own multiple sequences per key (e.g. one prompt id with n
  sampled answers), hence ``seqlens[key]`` is a list (per id) of lists (per
  sequence).
"""

from __future__ import annotations

import base64
import dataclasses
from typing import Any, Dict, Hashable, List, Optional, Sequence, Set, Tuple

import numpy as np

from areal_tpu.base import datapack

# ---------------------------------------------------------------------------
# Micro-batch splitting spec.
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class MicroBatchSpec:
    """``n_mbs`` is the (minimum) number of micro-batches;
    ``max_tokens_per_mb`` bounds tokens per micro-batch."""

    n_mbs: int = 1
    max_tokens_per_mb: int = int(1e12)

    @classmethod
    def new(cls, mb_spec: "MicroBatchSpec", **kwargs) -> "MicroBatchSpec":
        fields = dict(
            n_mbs=mb_spec.n_mbs, max_tokens_per_mb=mb_spec.max_tokens_per_mb
        )
        fields.update(kwargs)
        return cls(**fields)


@dataclasses.dataclass
class SequenceSplitSpec:
    """Contiguous partition of a batch: either ``partitions`` [(start,end)...]
    or ``sizes`` may be given; the other is derived."""

    partitions: Optional[List[Tuple[int, int]]] = None
    sizes: Optional[List[int]] = None

    def __post_init__(self):
        if self.partitions is None and self.sizes is None:
            raise ValueError("either sizes or partitions required")
        if self.partitions is not None:
            bound = 0
            for start, end in self.partitions:
                if start >= end:
                    raise ValueError(f"empty partition {start}-{end}")
                if start != bound:
                    raise ValueError(f"non-contiguous partition at {start}")
                bound = end
            derived = [e - s for s, e in self.partitions]
            if self.sizes is None:
                self.sizes = derived
            elif self.sizes != derived:
                raise ValueError("sizes inconsistent with partitions")
        else:
            offsets = np.cumsum([0] + list(self.sizes))
            self.partitions = [
                (int(offsets[i]), int(offsets[i + 1]))
                for i in range(len(self.sizes))
            ]


# Keys whose per-sequence length is 1 (scalars).
_SCALAR_KEYS = frozenset(
    [
        "seq_no_eos_mask",
        "loss_mask",
        "rewards",
        "base_scores",
        "task_ids",
        "version",
        "version_start",
        "version_end",
        "birth_time",
    ]
)
# Keys whose length equals the main sequence length.
_FULL_LEN_KEYS = frozenset(
    [
        "input_ids",
        "packed_input_ids",
        "packed_prompts",
        "prompt_mask",
        "values",
        "seq",
        "packed_seq",
    ]
)
# Keys with length seqlen - 1 (per-transition quantities).
_SHIFTED_KEYS = frozenset(
    [
        "packed_logprobs",
        "packed_ref_logprobs",
        "prox_logp",
        "logprobs",
        "ref_logprobs",
        "old_logp",
        "ref_logp",
        "advantages",
        "ppo_loss_mask",
        "kl_rewards",
        "returns",
    ]
)


def _resolve_seqlen_from_key(key: str, seqlens: List[int]) -> List[List[int]]:
    if key in _SCALAR_KEYS:
        return [[1] for _ in seqlens]
    if key in _FULL_LEN_KEYS:
        return [[int(s)] for s in seqlens]
    if key in _SHIFTED_KEYS:
        return [[int(s) - 1] for s in seqlens]
    raise NotImplementedError(
        f"cannot resolve seqlens for key {key!r}; construct SequenceSample "
        "explicitly instead of via from_default"
    )


@dataclasses.dataclass
class SequenceSample:
    keys: Set[str]
    trailing_shapes: Dict[str, Optional[Tuple[int, ...]]]
    dtypes: Dict[str, Optional[np.dtype]]
    ids: List[str]
    seqlens: Dict[str, List[List[int]]]
    data: Optional[Dict[str, Optional[np.ndarray]]] = None
    metadata: Dict[str, List[Any]] = dataclasses.field(default_factory=dict)

    def __post_init__(self):
        self.keys = set(self.keys)
        self.ids = [str(i) for i in self.ids]
        if len(self.ids) != len(set(self.ids)):
            raise ValueError(f"duplicate ids: {self.ids}")
        for k in self.keys:
            lens = self.seqlens[k]
            if len(lens) != len(self.ids):
                raise ValueError(
                    f"seqlens[{k}] has {len(lens)} entries for {len(self.ids)} ids"
                )
            if self.data is not None and self.data.get(k) is not None:
                total = sum(sum(l) for l in lens)
                if self.data[k].shape[0] != total:
                    raise ValueError(
                        f"data[{k}] first dim {self.data[k].shape[0]} != "
                        f"total seqlen {total}"
                    )

    # -- construction -------------------------------------------------------

    @classmethod
    def from_default(
        cls,
        seqlens: Sequence[int],
        ids: Sequence[Hashable],
        data: Dict[str, Optional[np.ndarray]],
        metadata: Optional[Dict[str, List[Any]]] = None,
    ) -> "SequenceSample":
        """Build a sample where every id has a single sequence of the given
        main length; per-key lengths are derived from the key-name registry."""
        metadata = dict(metadata or {})
        for k, v in metadata.items():
            if not isinstance(v, list) or len(v) != len(seqlens):
                raise ValueError(
                    f"metadata {k!r} must be a list of len {len(seqlens)}"
                )
        if len(seqlens) and isinstance(seqlens[0], (list, tuple)):
            assert all(len(s) == 1 for s in seqlens)
            seqlens = [s[0] for s in seqlens]
        seqlens = [int(s) for s in seqlens]
        keys = set(data.keys())
        data = {
            k: (np.asarray(v) if v is not None else None) for k, v in data.items()
        }
        return cls(
            keys=keys,
            ids=list(ids),
            seqlens={k: _resolve_seqlen_from_key(k, seqlens) for k in keys},
            trailing_shapes={
                k: (tuple(v.shape[1:]) if v is not None else None)
                for k, v in data.items()
            },
            dtypes={
                k: (v.dtype if v is not None else None) for k, v in data.items()
            },
            data=data,
            metadata=metadata,
        )

    # -- basic properties ---------------------------------------------------

    @property
    def bs(self) -> int:
        return len(self.ids)

    def total_seqlen(self, key: str) -> int:
        return sum(sum(l) for l in self.seqlens[key])

    def _get_split_key(self) -> str:
        return max(self.keys, key=lambda k: self.total_seqlen(k))

    # -- gather / split -----------------------------------------------------

    @classmethod
    def gather(
        cls,
        samples: List["SequenceSample"],
        keys: Optional[Sequence[str]] = None,
    ) -> "SequenceSample":
        keys = set(keys) if keys is not None else set(samples[0].keys)
        seqlens = {k: sum((s.seqlens[k] for s in samples), []) for k in keys}
        if samples[0].data is not None:
            data = {
                k: (
                    np.concatenate([s.data[k] for s in samples], axis=0)
                    if samples[0].data[k] is not None
                    else None
                )
                for k in keys
            }
        else:
            data = None
        metadata = {
            k: sum((s.metadata[k] for s in samples), [])
            for k in samples[0].metadata
        }
        return cls(
            keys=keys,
            dtypes={k: samples[0].dtypes[k] for k in keys},
            trailing_shapes={k: samples[0].trailing_shapes[k] for k in keys},
            ids=sum((s.ids for s in samples), []),
            seqlens=seqlens,
            data=data,
            metadata=metadata,
        )

    def split_with_spec(self, spec: SequenceSplitSpec) -> List["SequenceSample"]:
        out = []
        data_offset = {k: 0 for k in self.keys}
        for start, end in spec.partitions:
            new_seqlens = {k: v[start:end] for k, v in self.seqlens.items()}
            chunk_len = {
                k: sum(sum(l) for l in v) for k, v in new_seqlens.items()
            }
            if self.data is not None:
                new_data = {
                    k: (
                        v[data_offset[k] : data_offset[k] + chunk_len[k]]
                        if v is not None
                        else None
                    )
                    for k, v in self.data.items()
                }
            else:
                new_data = None
            for k in self.keys:
                data_offset[k] += chunk_len[k]
            out.append(
                SequenceSample(
                    keys=self.keys,
                    dtypes=self.dtypes,
                    trailing_shapes=self.trailing_shapes,
                    ids=self.ids[start:end],
                    seqlens=new_seqlens,
                    data=new_data,
                    metadata={
                        k: v[start:end] for k, v in self.metadata.items()
                    },
                )
            )
        return out

    def split_with_lengths(
        self, mb_spec: MicroBatchSpec, lens: List[int]
    ) -> Tuple[List["SequenceSample"], np.ndarray, np.ndarray]:
        """Split into micro-batches bounded by ``max_tokens_per_mb`` with at
        least ``n_mbs`` groups.  Returns (micro_batches, forward_indices,
        backward_indices); use :meth:`reorder_output` to restore original
        order of per-token outputs."""
        groups = datapack.ffd_allocate(
            lens, mb_spec.max_tokens_per_mb, min_groups=mb_spec.n_mbs
        )
        groups = sorted(sorted(g) for g in groups)
        forward_indices = np.array(datapack.flat2d(groups), dtype=np.int64)
        sample = SequenceSample.reorder(self, forward_indices)
        backward_indices = np.zeros(self.bs, dtype=np.int64)
        backward_indices[forward_indices] = np.arange(self.bs)
        spec = SequenceSplitSpec(sizes=[len(g) for g in groups])
        return sample.split_with_spec(spec), forward_indices, backward_indices

    def split(
        self, mb_spec: MicroBatchSpec
    ) -> Tuple[List["SequenceSample"], np.ndarray, np.ndarray]:
        lens = [sum(l) for l in self.seqlens[self._get_split_key()]]
        return self.split_with_lengths(mb_spec, lens)

    @staticmethod
    def reorder(
        sample: "SequenceSample", indices: Sequence[int]
    ) -> "SequenceSample":
        assert set(int(i) for i in indices) == set(range(sample.bs))
        pieces = sample.unpack()
        return SequenceSample.gather([pieces[int(i)] for i in indices])

    @staticmethod
    def reorder_output(
        x: np.ndarray,
        expected_seqlens: List[List[int]],
        forward_indices: Sequence[int],
        backward_indices: Sequence[int],
    ) -> np.ndarray:
        """Restore original batch order for a packed per-token output ``x``
        produced from the reordered (micro-batched) sample."""
        actual = [expected_seqlens[int(i)] for i in forward_indices]
        group_lens = [sum(s) for s in actual]
        assert x.shape[0] == sum(group_lens)
        offsets = np.concatenate([[0], np.cumsum(group_lens)])
        chunks = [
            x[offsets[i] : offsets[i + 1]] for i in range(len(group_lens))
        ]
        return np.concatenate(
            [chunks[int(i)] for i in backward_indices], axis=0
        )

    def unpack(self) -> List["SequenceSample"]:
        return self.split_with_spec(
            SequenceSplitSpec(partitions=[(i, i + 1) for i in range(self.bs)])
        )

    @staticmethod
    def shuffled(
        sample: "SequenceSample", seed: Optional[int] = None
    ) -> "SequenceSample":
        rng = np.random.RandomState(seed)
        indices = np.arange(sample.bs)
        rng.shuffle(indices)
        return SequenceSample.reorder(sample, indices)

    # -- mutation -----------------------------------------------------------

    def meta(self) -> "SequenceSample":
        return SequenceSample(
            keys=self.keys,
            trailing_shapes=self.trailing_shapes,
            dtypes=self.dtypes,
            ids=self.ids,
            data=None,
            seqlens=self.seqlens,
            metadata=self.metadata,
        )

    def select(self, keys: Sequence[str]) -> "SequenceSample":
        keys = set(keys)
        return SequenceSample(
            keys=keys,
            dtypes={k: self.dtypes[k] for k in keys},
            trailing_shapes={k: self.trailing_shapes[k] for k in keys},
            ids=self.ids,
            seqlens={k: self.seqlens[k] for k in keys},
            data=(
                None if self.data is None else {k: self.data[k] for k in keys}
            ),
            metadata=self.metadata,
        )

    def update_(self, other: "SequenceSample"):
        """Merge ``other``'s keys into self (ids must match)."""
        assert self.ids == other.ids, (self.ids, other.ids)
        self.keys = self.keys | other.keys
        self.trailing_shapes.update(other.trailing_shapes)
        self.dtypes.update(other.dtypes)
        self.seqlens.update(other.seqlens)
        if self.data is not None and other.data is not None:
            self.data.update(other.data)
        self.metadata.update(other.metadata)

    def remap_keys_(self, remap: Dict[str, str]):
        for k in list(self.keys):
            if k in remap:
                nk = remap[k]
                self.seqlens[nk] = self.seqlens.pop(k)
                self.trailing_shapes[nk] = self.trailing_shapes.pop(k)
                self.dtypes[nk] = self.dtypes.pop(k)
                if self.data is not None:
                    self.data[nk] = self.data.pop(k)
        self.keys = set(remap.get(k, k) for k in self.keys)

    # -- wire format --------------------------------------------------------

    def as_json_compatible(self) -> Dict:
        data = None
        if self.data is not None:
            data = {}
            for k, v in self.data.items():
                if v is None:
                    data[k] = None
                else:
                    v = np.ascontiguousarray(v)
                    data[k] = {
                        "b64": base64.b64encode(v.tobytes()).decode("ascii"),
                        "dtype": str(v.dtype),
                        "shape": list(v.shape),
                    }
        return dict(
            ids=self.ids,
            keys=sorted(self.keys),
            trailing_shapes={
                k: (list(v) if v is not None else None)
                for k, v in self.trailing_shapes.items()
            },
            dtypes={
                k: (str(v) if v is not None else None)
                for k, v in self.dtypes.items()
            },
            seqlens=self.seqlens,
            data=data,
            metadata=self.metadata,
        )

    @classmethod
    def from_json_compatible(cls, d: Dict) -> "SequenceSample":
        dtypes = {
            k: (np.dtype(v) if v is not None else None)
            for k, v in d["dtypes"].items()
        }
        data = None
        if d["data"] is not None:
            data = {}
            for k, v in d["data"].items():
                if v is None:
                    data[k] = None
                else:
                    arr = np.frombuffer(
                        base64.b64decode(v["b64"]), dtype=np.dtype(v["dtype"])
                    ).reshape(v["shape"])
                    data[k] = arr.copy()  # writable
        return cls(
            ids=d["ids"],
            keys=set(d["keys"]),
            trailing_shapes={
                k: (tuple(v) if v is not None else None)
                for k, v in d["trailing_shapes"].items()
            },
            dtypes=dtypes,
            seqlens=d["seqlens"],
            data=data,
            metadata=d.get("metadata", {}),
        )

    def __repr__(self):
        return (
            f"SequenceSample(bs={self.bs}, keys={sorted(self.keys)}, "
            f"has_data={self.data is not None})"
        )

"""Dataflow graph of Model Function Calls.

Rebuild of the reference's MFC graph layer (reference: realhf/api/core/dfg.py
— ``MFCDef`` :56, ``build_graph`` :237): one experiment = a DAG of
generate / inference / train_step calls on named models; edges are derived
by matching producers' output keys to consumers' input keys.

Hooks mirror the reference's ``ParamReallocHook``/``OffloadHook``; on TPU a
param-realloc hook is a resharding request (``jax.device_put`` onto the
target NamedSharding) rather than an NCCL bcast plan.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Any, Dict, List, Optional, Tuple

from areal_tpu.api.config import (
    ModelAbstraction,
    ModelInterfaceAbstraction,
    ModelName,
)
from areal_tpu.api.data import MicroBatchSpec


class ModelInterfaceType(enum.Enum):
    GENERATE = "generate"
    TRAIN_STEP = "train_step"
    EVALUATE = "evaluate"
    INFERENCE = "inference"


@dataclasses.dataclass
class MFCHook:
    """Base class for pre/post hooks attached to an MFC."""


@dataclasses.dataclass
class ParamReallocHook(MFCHook):
    """Re-host weights under a different model name / layout before or after
    the call (reference: dfg.py ``ParamReallocHook``; used for trainer->ref
    EMA updates and train<->gen layout moves)."""

    source: Optional[ModelName] = None
    target: Optional[ModelName] = None
    eta: float = 1.0  # target = eta * source + (1 - eta) * target

    def __post_init__(self):
        assert (self.source is None) != (self.target is None), (
            "exactly one of source/target must be set"
        )


@dataclasses.dataclass
class OffloadHook(MFCHook):
    """Drop device copies of the model after the call (host copy kept)."""


@dataclasses.dataclass
class MFCDef:
    """One node of the experiment dataflow graph.

    ``n_seqs`` is the number of sequences the master accumulates in the
    buffer before this call fires; ``input_keys``/``output_keys`` define the
    graph edges by name matching.
    """

    name: str
    model_name: ModelName
    interface_type: ModelInterfaceType
    interface_impl: ModelInterfaceAbstraction
    input_keys: Tuple[str, ...] = ()
    output_keys: Tuple[str, ...] = ()
    n_seqs: int = 1
    mb_spec: MicroBatchSpec = dataclasses.field(default_factory=MicroBatchSpec)
    balanced_dp: bool = False
    log_return_value: bool = False
    model_type: Optional[Any] = None
    model_path: Optional[str] = None
    pre_hooks: List[MFCHook] = dataclasses.field(default_factory=list)
    post_hooks: List[MFCHook] = dataclasses.field(default_factory=list)

    # filled by build_graph
    _G: Any = None

    def __post_init__(self):
        self.input_keys = tuple(self.input_keys)
        self.output_keys = tuple(self.output_keys)
        dup = set(self.input_keys) & set(self.output_keys)
        if dup:
            raise ValueError(
                f"MFC {self.name}: keys {dup} are both input and output"
            )

    @property
    def role(self) -> str:
        return self.model_name.role

    @property
    def G(self):
        assert self._G is not None, "call build_graph first"
        return self._G

    @property
    def parents(self) -> List["MFCDef"]:
        return [self.G.nodes[p]["object"] for p in self.G.predecessors(self.name)]

    @property
    def children(self) -> List["MFCDef"]:
        return [self.G.nodes[c]["object"] for c in self.G.successors(self.name)]

    @property
    def is_src(self) -> bool:
        return self.G.in_degree(self.name) == 0

    @property
    def is_dst(self) -> bool:
        return self.G.out_degree(self.name) == 0

    @property
    def data_producers(self) -> Dict[str, Optional[str]]:
        """key -> producing MFC name (None if from the dataset)."""
        out = {}
        for k in self.input_keys:
            out[k] = None
            for _, node in self.G.nodes(data="object"):
                if node.name != self.name and k in node.output_keys:
                    out[k] = node.name
        return out

    def __repr__(self):
        return f"MFCDef[{self.name}:{self.model_name}:{self.interface_type.value}]"


def build_graph(rpcs: List[MFCDef], verbose: bool = False):
    """Wire MFCs into a networkx DiGraph by output->input key matching.
    Attaches the graph to every node and returns it."""
    import networkx as nx

    names = [r.name for r in rpcs]
    if len(set(names)) != len(names):
        raise ValueError(f"duplicate MFC names: {names}")

    G = nx.DiGraph()
    for r in rpcs:
        G.add_node(r.name, object=r)
    for dst in rpcs:
        for key in dst.input_keys:
            for src in rpcs:
                if src.name != dst.name and key in src.output_keys:
                    if G.has_edge(src.name, dst.name):
                        G.edges[src.name, dst.name]["keys"].append(key)
                    else:
                        G.add_edge(src.name, dst.name, keys=[key])
    if not nx.is_directed_acyclic_graph(G):
        raise ValueError("MFC graph has a cycle")
    for r in rpcs:
        r._G = G
    if verbose:
        from areal_tpu.base import logging_

        logging_.getLogger("dfg").info(
            "MFC graph: nodes=%s edges=%s",
            list(G.nodes),
            [(u, v, d["keys"]) for u, v, d in G.edges(data=True)],
        )
    return G


def topological_levels(G) -> List[List[MFCDef]]:
    """Nodes grouped by topological generation (calls in one level have no
    data dependencies between them and may run concurrently)."""
    import networkx as nx

    return [
        [G.nodes[n]["object"] for n in gen]
        for gen in nx.topological_generations(G)
    ]

"""Dataset utilities and registry
(reference: realhf/api/core/data_api.py — ``DatasetUtility``,
``load_shuffle_split_dataset``, ``make_dataset``, ``load_hf_tokenizer``).

Datasets are host-side torch ``Dataset``s yielding :class:`SequenceSample`s
(numpy-backed); the TPU engines pad/shard at the jit boundary.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Callable, Dict, List, Optional

import numpy as np
import torch.utils.data

from areal_tpu.base import logging_, seeding

logger = logging_.getLogger("dataset_api")


def load_hf_tokenizer(
    model_name_or_path: str,
    fast_tokenizer: bool = True,
    padding_side: Optional[str] = None,
):
    import transformers

    kwargs = {}
    if padding_side is not None:
        kwargs["padding_side"] = padding_side
    tokenizer = transformers.AutoTokenizer.from_pretrained(
        model_name_or_path,
        use_fast=fast_tokenizer,
        trust_remote_code=True,
        **kwargs,
    )
    if tokenizer.pad_token_id is None:
        tokenizer.pad_token_id = tokenizer.eos_token_id
    return tokenizer


@dataclasses.dataclass
class DatasetUtility:
    """Per-DP-shard dataset context: this worker's rank/world_size determine
    which slice of the dataset it owns."""

    seed: int
    dp_rank: int
    world_size: int
    tokenizer: Any

    def __post_init__(self):
        if self.tokenizer is not None and self.tokenizer.pad_token_id is None:
            raise ValueError("tokenizer must have a pad token id")


def load_shuffle_split_dataset(
    util: DatasetUtility,
    dataset_path: Optional[str] = None,
    dataset_builder: Optional[Callable[[], List[Dict]]] = None,
) -> List[Dict]:
    """Load a json/jsonl list-of-dicts, deterministically shuffle, and return
    this DP rank's contiguous shard."""
    if dataset_path is not None:
        if dataset_path.endswith(".jsonl"):
            with open(dataset_path) as f:
                data = [json.loads(line) for line in f if line.strip()]
        elif dataset_path.endswith(".json"):
            with open(dataset_path) as f:
                data = json.load(f)
        else:
            raise NotImplementedError(f"unknown dataset format: {dataset_path}")
    else:
        assert dataset_builder is not None
        data = dataset_builder()

    # Assign stable unique ids if absent.
    for i, d in enumerate(data):
        if "id" not in d:
            d["id"] = d.get("query_id", str(i))

    rng = np.random.RandomState(util.seed)
    indices = np.arange(len(data))
    rng.shuffle(indices)
    # contiguous per-rank shard of the shuffled order
    shards = np.array_split(indices, util.world_size)
    shard = shards[util.dp_rank]
    return [data[int(i)] for i in shard]


# ---------------------------------------------------------------------------
# Registry
# ---------------------------------------------------------------------------

_DATASETS: Dict[str, Callable] = {}


def register_dataset(name: str, cls: Callable):
    if name in _DATASETS:
        raise KeyError(f"dataset {name} already registered")
    _DATASETS[name] = cls


def make_dataset(
    cfg,
    seed: int,
    dp_rank: int,
    world_size: int,
    tokenizer_or_path: Any,
) -> torch.utils.data.Dataset:
    """``cfg`` is a DatasetAbstraction (type_ + args) or a plain name."""
    from areal_tpu.api.config import DatasetAbstraction

    if isinstance(cfg, str):
        cfg = DatasetAbstraction(type_=cfg)
    if isinstance(tokenizer_or_path, str):
        tokenizer = load_hf_tokenizer(tokenizer_or_path)
    else:
        tokenizer = tokenizer_or_path
    util = DatasetUtility(
        seed=seed, dp_rank=dp_rank, world_size=world_size, tokenizer=tokenizer
    )
    return _DATASETS[cfg.type_](util=util, **cfg.args)


def gather_sequence_samples(samples):
    """Default collate: list of SequenceSample -> one gathered batch."""
    from areal_tpu.api.data import SequenceSample

    return SequenceSample.gather(samples)


class SequenceSampleDataLoader(torch.utils.data.DataLoader):
    """DataLoader yielding gathered SequenceSample batches."""

    def __init__(self, dataset, batch_size: int, shuffle: bool = True, seed: int = 0):
        g = torch.Generator()
        g.manual_seed(seed)
        super().__init__(
            dataset,
            batch_size=batch_size,
            shuffle=shuffle,
            generator=g,
            collate_fn=gather_sequence_samples,
            num_workers=0,
        )

"""Agent API (reference: realhf/api/core/agent_api.py:16 —
``Agent.collect_trajectory(prompt, env, obs_queue, act_queue)`` coroutine +
registry)."""

from __future__ import annotations

import abc
import asyncio
from typing import Any, Callable, Dict, List

from areal_tpu.api.data import SequenceSample


class Agent(abc.ABC):
    """Collects one trajectory by exchanging observations/actions with the
    generation infrastructure through asyncio queues: the agent puts token
    prompts into ``obs_queue`` and awaits sampled generations from
    ``act_queue``."""

    @abc.abstractmethod
    async def collect_trajectory(
        self,
        prompt: SequenceSample,
        env,
        obs_queue: asyncio.Queue,
        act_queue: asyncio.Queue,
    ) -> List[SequenceSample]: ...


ALL_AGENTS: Dict[str, Callable[..., Agent]] = {}


def register_agent(name: str, cls):
    if name in ALL_AGENTS:
        raise KeyError(f"agent {name} already registered")
    ALL_AGENTS[name] = cls


def make_agent(cfg) -> Agent:
    from areal_tpu.api.config import AgentAbstraction

    if isinstance(cfg, str):
        cfg = AgentAbstraction(cfg)
    return ALL_AGENTS[cfg.type_](**cfg.args)

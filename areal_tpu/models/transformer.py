"""TPU-native transformer.

This is the rebuild of the reference's ``ReaLModel``
(reference: realhf/impl/model/nn/real_llm_api.py:100 and the modules under
realhf/impl/model/modules/) as a *pure-functional* JAX model:

* Params are a plain pytree (nested dicts of jnp arrays).  Per-layer params
  are **stacked along a leading layer axis** and the forward pass runs
  ``lax.scan`` over layers — fast compiles, and the layer axis is the natural
  shard target for pipeline parallelism.
* Batches are padded ``[B, T]`` with **segment ids** (0 = padding): packed
  varlen sequences are bins of concatenated segments, replacing the
  reference's flash-attn varlen 1-D packing (realhf/impl/model/modules/attn.py)
  with the TPU-idiomatic static-shape equivalent.
* Attention dispatches to a Pallas flash kernel on TPU
  (areal_tpu/ops/flash_attention.py) and a jnp reference path elsewhere.
* Sharding is expressed as a PartitionSpec pytree (:func:`param_pspecs`)
  over the canonical mesh axes (areal_tpu/base/topology.py) — megatron-style
  tensor parallelism over ``model``, ZeRO-style over ``fsdp`` — and XLA
  inserts all collectives.

Supported features mirroring the reference model zoo: GQA, RoPE, RMS/LayerNorm,
qkv bias (qwen2), per-head q/k norm (qwen3), tied embeddings, absolute position
embeddings (gpt2), embedding scale (gemma), sliding window (mistral), MoE
(mixtral-style top-k router; see areal_tpu/models/moe.py), and a critic value
head (reference: realhf/impl/model/nn/real_llm_base.py:358-451).
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.ad_checkpoint import checkpoint_name
from jax.sharding import PartitionSpec as P

from areal_tpu.base import logging_
from areal_tpu.engine.sampling import call_sample_fn
from areal_tpu.models import quantize
from areal_tpu.models.config import TransformerConfig

logger = logging_.getLogger("transformer")

Params = Dict[str, Any]

# ---------------------------------------------------------------------------
# Initialization
# ---------------------------------------------------------------------------


def _dense_init(key, shape, scale_axis=0):
    scale = 1.0 / np.sqrt(shape[scale_axis])
    return jax.random.uniform(
        key, shape, minval=-scale, maxval=scale, dtype=jnp.float32
    )


def init_params(cfg: TransformerConfig, key: jax.Array) -> Params:
    """Random init (HF-load overwrites this; used by tests and from-scratch)."""
    keys = iter(jax.random.split(key, 32))
    L, D, F = cfg.n_layers, cfg.hidden_dim, cfg.intermediate_dim
    Hq, Hkv, hd = cfg.n_q_heads, cfg.n_kv_heads, cfg.head_dim

    def stack_init(shape, scale_axis=0):
        k = next(keys)
        return jax.vmap(
            lambda kk: _dense_init(kk, shape, scale_axis=scale_axis)
        )(jax.random.split(k, L))

    attn: Params = {
        "q": {"w": stack_init((D, Hq * hd))},
        "k": {"w": stack_init((D, Hkv * hd))},
        "v": {"w": stack_init((D, Hkv * hd))},
        "o": {"w": stack_init((Hq * hd, D))},
    }
    if cfg.use_attention_bias:
        attn["q"]["b"] = jnp.zeros((L, Hq * hd), jnp.float32)
        attn["k"]["b"] = jnp.zeros((L, Hkv * hd), jnp.float32)
        attn["v"]["b"] = jnp.zeros((L, Hkv * hd), jnp.float32)
    if cfg.use_qk_norm:
        attn["q_norm"] = {"scale": jnp.ones((L, hd), jnp.float32)}
        attn["k_norm"] = {"scale": jnp.ones((L, hd), jnp.float32)}

    if cfg.is_moe:
        from areal_tpu.models.moe import init_moe_params

        mlp = init_moe_params(cfg, next(keys))
    else:
        mlp = {
            "gate": {"w": stack_init((D, F))},
            "down": {"w": stack_init((F, D), scale_axis=0)},
        }
        if cfg.gated_mlp:
            mlp["up"] = {"w": stack_init((D, F))}
        if cfg.use_mlp_bias:
            mlp["gate"]["b"] = jnp.zeros((L, F), jnp.float32)
            if cfg.gated_mlp:
                mlp["up"]["b"] = jnp.zeros((L, F), jnp.float32)
            mlp["down"]["b"] = jnp.zeros((L, D), jnp.float32)

    def norm_params(shape):
        p = {"scale": jnp.ones(shape, jnp.float32)}
        if cfg.norm_type == "layer":
            p["bias"] = jnp.zeros(shape, jnp.float32)
        return p

    params: Params = {
        "embed": {"weight": _dense_init(next(keys), (cfg.vocab_size, D))},
        "layers": {
            "attn_norm": norm_params((L, D)),
            "attn": attn,
            "mlp_norm": norm_params((L, D)),
            "mlp": mlp,
        },
        "final_norm": norm_params((D,)),
    }
    if cfg.abs_position_embedding:
        params["pos_embed"] = {
            "weight": _dense_init(
                next(keys), (cfg.max_position_embeddings, D)
            )
        }
    if cfg.is_critic:
        params["value_head"] = {"w": _dense_init(next(keys), (D, 1))}
    elif not cfg.tied_embedding:
        params["lm_head"] = {"w": _dense_init(next(keys), (D, cfg.vocab_size))}
    return params


# ---------------------------------------------------------------------------
# Sharding rules
# ---------------------------------------------------------------------------


def param_pspecs(
    cfg: TransformerConfig, params: Params, pipe: bool = False
) -> Params:
    """PartitionSpec pytree derived from the actual param tree by path.

    Megatron-style TP over the ``model`` axis (reference:
    realhf/impl/model/parallelism/tensor_parallel/modules.py — column/row
    parallel linears), ZeRO-sharding over ``fsdp``; with ``pipe=True`` the
    stacked layer axis shards over the ``pipe`` mesh axis and the forward
    runs the shard_map pipeline (areal_tpu/parallel/pipeline.py) instead of
    the plain layer scan.
    """
    lp = "pipe" if pipe else None  # stacked layer axis

    def spec_for(path: Tuple, leaf) -> P:
        keys = tuple(
            k.key if hasattr(k, "key") else str(k) for k in path
        )
        if keys[0] == "embed":
            return P("model", "fsdp")
        if keys[0] == "pos_embed":
            return P(None, "fsdp")
        if keys[0] == "lm_head":
            # quantized serving tree: the [V] per-output-channel scale
            # shards like the weight's output (vocab) axis
            if keys[-1] == "scale":
                return P("model")
            return P("fsdp", "model")
        if keys[0] == "value_head":
            return P("fsdp", None)
        if keys[0] == "final_norm":
            return P(None)
        # inside "layers": leading dim is the stacked layer axis
        if "router" in keys or "experts" in keys:
            if "router" in keys:
                return P(lp, None, None)
            # [L, E, D, F]: expert dim shards over the ``expert`` mesh axis
            # (expert parallelism; SURVEY §2.9 EP row — beyond the
            # reference's local-only MoE), matmul dims over fsdp/model.
            # Quantized trees nest {"qw", "scale"} one level deeper; the
            # [L, E, out] scale keeps the expert shard plus the weight's
            # output-axis shard.
            name = keys[-1] if keys[-1] in ("gate", "up", "down") else keys[-2]
            if keys[-1] == "scale":
                return (
                    P(lp, "expert", "fsdp")
                    if name == "down"
                    else P(lp, "expert", "model")
                )
            if name == "down":
                return P(lp, "expert", "model", "fsdp")
            return P(lp, "expert", "fsdp", "model")
        if "attn" in keys or "mlp" in keys:
            name = keys[-2]  # q/k/v/o/gate/up/down/q_norm/...
            leafname = keys[-1]  # w / qw / b / scale
            if leafname == "scale" and name in ("q_norm", "k_norm"):
                return P(lp, None)
            is_row = name in ("o", "down")
            if leafname == "b":
                return P(lp, None) if is_row else P(lp, "model")
            if leafname == "scale":
                # int8 per-output-channel scale [L, out]: shard like the
                # weight's output axis (fsdp for row-parallel o/down,
                # model for column-parallel)
                return P(lp, "fsdp") if is_row else P(lp, "model")
            return (
                P(lp, "model", "fsdp") if is_row else P(lp, "fsdp", "model")
            )
        # norms inside layers
        return P(lp, None)

    return jax.tree_util.tree_map_with_path(spec_for, params)


def serving_param_pspecs(cfg: TransformerConfig, params: Params) -> Params:
    """PartitionSpec pytree for the SERVING engine's mesh.

    Identical to :func:`param_pspecs` except MoE expert weights shard
    over the ``expert`` mesh axis ONLY (replicated across model/fsdp):
    the serving EP path computes local-expert groups under an explicit
    shard_map (models/moe.py) whose in_specs must match the physical
    layout exactly — sharding the D/F matmul dims over ``model`` too
    would force an all-gather of every expert weight inside each
    layer's shard_map, re-paying the traffic EP exists to avoid.  Dense
    (attention/embedding/head) weights keep the megatron TP layout."""
    specs = param_pspecs(cfg, params)
    if not cfg.is_moe:
        return specs

    def fix(path, spec):
        keys = tuple(k.key if hasattr(k, "key") else str(k) for k in path)
        if "experts" in keys:
            # quantized trees: the [L, E, out] scale is one rank shorter
            # than its [L, E, in, out] weight but shards the same E axis
            if keys[-1] == "scale":
                return P(None, "expert", None)
            return P(None, "expert", None, None)
        return spec

    return jax.tree_util.tree_map_with_path(fix, specs)


# ---------------------------------------------------------------------------
# Core ops
# ---------------------------------------------------------------------------


def _norm(x, p, cfg: TransformerConfig):
    dt = x.dtype
    x = x.astype(jnp.float32)
    if cfg.norm_type == "rms":
        x = x * jax.lax.rsqrt(jnp.mean(x * x, axis=-1, keepdims=True) + cfg.norm_eps)
        out = x * p["scale"].astype(jnp.float32)
    else:
        mean = jnp.mean(x, axis=-1, keepdims=True)
        var = jnp.var(x, axis=-1, keepdims=True)
        out = (x - mean) * jax.lax.rsqrt(var + cfg.norm_eps)
        out = out * p["scale"].astype(jnp.float32) + p["bias"].astype(jnp.float32)
    return out.astype(dt)


def _head_norm(x, scale, eps):
    # per-head RMSNorm over head_dim (qwen3)
    dt = x.dtype
    x = x.astype(jnp.float32)
    x = x * jax.lax.rsqrt(jnp.mean(x * x, axis=-1, keepdims=True) + eps)
    return (x * scale.astype(jnp.float32)).astype(dt)


def rope_tables(
    positions: jax.Array, base: float, head_dim: int
) -> Tuple[jax.Array, jax.Array]:
    """(cos, sin) [B, T, 1, hd/2] f32.  Computed ONCE per forward and shared
    by every layer's q/k application (hoisting the transcendentals out of the
    layer scan is a measurable win on TPU)."""
    half = head_dim // 2
    freqs = 1.0 / (base ** (jnp.arange(0, half, dtype=jnp.float32) / half))
    angles = positions[..., None].astype(jnp.float32) * freqs  # [B,T,half]
    return jnp.cos(angles)[:, :, None, :], jnp.sin(angles)[:, :, None, :]


def rope_apply(x: jax.Array, cos: jax.Array, sin: jax.Array) -> jax.Array:
    """Rotary embedding with precomputed tables. x: [B, T, H, hd]."""
    half = x.shape[-1] // 2
    x1, x2 = x[..., :half], x[..., half:]
    out = jnp.concatenate(
        [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1
    )
    return out.astype(x.dtype)


def rope(x: jax.Array, positions: jax.Array, base: float) -> jax.Array:
    """Rotary embedding. x: [B, T, H, hd]; positions: [B, T]."""
    cos, sin = rope_tables(positions, base, x.shape[-1])
    return rope_apply(x, cos, sin)


def _activation(x, kind: str):
    if kind == "silu":
        return jax.nn.silu(x)
    return jax.nn.gelu(x)


def make_attention_mask(
    seg_q: jax.Array,
    pos_q: jax.Array,
    seg_kv: jax.Array,
    pos_kv: jax.Array,
    sliding_window: Optional[int] = None,
) -> jax.Array:
    """[B, Tq, Tkv] bool mask: same segment, causal, non-pad; optional
    sliding window."""
    same = seg_q[:, :, None] == seg_kv[:, None, :]
    causal = pos_q[:, :, None] >= pos_kv[:, None, :]
    valid = (seg_q[:, :, None] != 0) & (seg_kv[:, None, :] != 0)
    mask = same & causal & valid
    if sliding_window is not None:
        mask &= pos_q[:, :, None] - pos_kv[:, None, :] < sliding_window
    return mask


def cache_attention(q, k, v, mask):
    """Decode/prefill attention over a KV cache, GQA-grouped so the cache is
    never ``repeat``-materialized, in the cache's native head-major layout so
    no [S, H] transpose of the cache ever materializes (both were measured
    whole-cache copies per step in rounds 1-2).
    q [B,T,Hq,hd]; k/v [B,Hkv,S,hd]; mask [B,T,S] -> [B,T,Hq,hd]."""
    B, T, Hq, hd = q.shape
    Hkv = k.shape[1]
    rep = Hq // Hkv
    qg = q.reshape(B, T, Hkv, rep, hd)
    # preferred_element_type accumulates in f32 WITHOUT materializing f32
    # copies of the (large) cache operands
    scores = jnp.einsum(
        "btkrd,bksd->bkrts",
        qg,
        k.astype(qg.dtype),
        preferred_element_type=jnp.float32,
    ) / np.sqrt(hd)
    scores = jnp.where(mask[:, None, None, :, :], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum(
        "bkrts,bksd->btkrd", probs.astype(v.dtype), v
    )
    return out.reshape(B, T, Hq, hd)


def reference_attention(q, k, v, mask, logits_dtype=jnp.float32):
    """jnp attention: q [B,T,Hq,hd], k/v [B,S,Hkv,hd], mask [B,T,S]."""
    B, T, Hq, hd = q.shape
    Hkv = k.shape[2]
    rep = Hq // Hkv
    k = jnp.repeat(k, rep, axis=2)
    v = jnp.repeat(v, rep, axis=2)
    scores = jnp.einsum(
        "bthd,bshd->bhts", q.astype(logits_dtype), k.astype(logits_dtype)
    ) / np.sqrt(hd)
    scores = jnp.where(mask[:, None, :, :], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhts,bshd->bthd", probs.astype(v.dtype), v)
    return out


# Mesh used for context-parallel (ring) attention inside jitted forwards.
# Set by the train engine at trace time; None disables the ring path.
_AMBIENT_MESH = None


def set_ambient_mesh(mesh):
    global _AMBIENT_MESH
    _AMBIENT_MESH = mesh


def _seq_parallel_mesh():
    m = _AMBIENT_MESH
    if m is not None and m.shape.get("seq", 1) > 1:
        return m
    return None


def _pipe_mesh():
    m = _AMBIENT_MESH
    if m is not None and m.shape.get("pipe", 1) > 1:
        return m
    return None


def _attention_dispatch(
    q, k, v, mask, cfg: TransformerConfig, seg_ids=None, positions=None
):
    """Pick the attention implementation: ring attention when the engine's
    mesh shards the sequence axis (context parallelism — a capability the
    reference lacks, SURVEY §2.9); Pallas flash on TPU for the dense
    self-attention path; jnp reference elsewhere."""
    from areal_tpu.ops import flash_attention as fa

    mesh = _seq_parallel_mesh()
    if mesh is not None and seg_ids is not None and positions is not None:
        head_axis = (
            "model"
            if cfg.n_kv_heads % mesh.shape.get("model", 1) == 0
            else None
        )
        if cfg.cp_impl == "ulysses":
            from areal_tpu.ops.ulysses import ulysses_attention

            return ulysses_attention(
                q,
                k,
                v,
                seg_ids,
                positions,
                mesh=mesh,
                head_axis=head_axis,
                sliding_window=cfg.sliding_window,
            )
        from areal_tpu.ops.ring_attention import ring_attention

        return ring_attention(
            q,
            k,
            v,
            seg_ids,
            positions,
            mesh=mesh,
            head_axis=head_axis,
            sliding_window=cfg.sliding_window,
        )
    if (
        seg_ids is not None
        and jax.default_backend() == "tpu"
        and fa.supported(q.shape[1], k.shape[1], cfg.sliding_window)
    ):
        return fa.flash_attention(q, k, v, seg_ids)
    _warn_dense_fallback(
        q.shape[1], k.shape[1], cfg.sliding_window, seg_ids is None
    )
    return reference_attention(q, k, v, mask)


_warned_dense = set()


def _warn_dense_fallback(
    q_len: int, kv_len: int, sliding_window, no_seg_ids: bool
):
    """One warning per (cause, compile) when a long sequence pays the
    O(T^2) dense path on TPU — round-1 review found these fallbacks silent
    (mistral's sliding window, odd lengths, CP's block math).  Reports the
    ACTUAL failing flash-attention constraints, in ``fa.supported`` order."""
    T = q_len
    if jax.default_backend() != "tpu" or T < 1024:
        return
    causes = []
    if no_seg_ids:
        causes.append("no segment ids")
    if sliding_window is not None:
        causes.append("sliding window")
    if q_len != kv_len:
        causes.append(f"q_len {q_len} != kv_len {kv_len}")
    from areal_tpu.ops import flash_attention as fa

    if q_len % min(fa._BLOCK, max(q_len, 1)) != 0:
        causes.append(f"length {q_len} not block-aligned")
    cause = ", ".join(causes) or f"unsupported length {T}"
    key = (cause, T)
    if key in _warned_dense:
        return
    _warned_dense.add(key)
    logger.warning(
        "attention falling back to the dense O(T^2) path at T=%d (%s): "
        "expect quadratic memory/time; consider pad-to-block or removing "
        "the constraint",
        T,
        cause,
    )


# ---------------------------------------------------------------------------
# Layer + model forward
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class KVCache:
    """Decode-time KV cache: stacked over layers.

    k/v: [L, B, Hkv, S, hd] — HEAD-major so decode attention reads the cache
    in its stored layout (seq-major forced a whole-cache transpose copy per
    step); ``lengths``: [B] current per-row lengths (also the insertion
    offset for the next token); rows are independent so the cache natively
    supports continuous batching.
    """

    k: jax.Array
    v: jax.Array
    lengths: jax.Array  # [B] int32

    @classmethod
    def zeros(cls, cfg: TransformerConfig, batch: int, max_len: int, dtype=None):
        dtype = dtype or jnp.dtype(cfg.dtype)
        shape = (cfg.n_layers, batch, cfg.n_kv_heads, max_len, cfg.head_dim)
        return cls(
            k=jnp.zeros(shape, dtype),
            v=jnp.zeros(shape, dtype),
            lengths=jnp.zeros((batch,), jnp.int32),
        )

    @property
    def max_len(self) -> int:
        return self.k.shape[3]


jax.tree_util.register_dataclass(
    KVCache, data_fields=["k", "v", "lengths"], meta_fields=[]
)


def _proj(p, y):
    # leaf_weight serves both formats: plain {"w"} arrays and the int8
    # serving format's {"qw", "scale"} leaves (dequantized at use, so
    # the matmul below is identical math at the activation dtype and
    # storage rounding is the only delta — models/quantize.py)
    out = y @ quantize.leaf_weight(p, y.dtype)
    if "b" in p:
        out = out + p["b"].astype(y.dtype)
    return out


def _attn_qkv(cfg: TransformerConfig, lp: Params, h, positions, rope_cs):
    """Shared q/k/v head math (projection + qk-norm + rope) for the training
    forward, step decode, and chunk decode — ONE definition so the rollout
    and trainer forwards can never silently diverge."""
    B, T, _ = h.shape
    q = _proj(lp["attn"]["q"], h).reshape(B, T, cfg.n_q_heads, cfg.head_dim)
    k = _proj(lp["attn"]["k"], h).reshape(B, T, cfg.n_kv_heads, cfg.head_dim)
    v = _proj(lp["attn"]["v"], h).reshape(B, T, cfg.n_kv_heads, cfg.head_dim)
    q = checkpoint_name(q, "q_proj")
    k = checkpoint_name(k, "k_proj")
    v = checkpoint_name(v, "v_proj")
    if cfg.use_qk_norm:
        q = _head_norm(q, lp["attn"]["q_norm"]["scale"], cfg.norm_eps)
        k = _head_norm(k, lp["attn"]["k_norm"]["scale"], cfg.norm_eps)
    if not cfg.abs_position_embedding:
        if rope_cs is None:
            rope_cs = rope_tables(positions, cfg.rotary_base, cfg.head_dim)
        q = rope_apply(q, *rope_cs)
        k = rope_apply(k, *rope_cs)
    return q, k, v


def _mlp_block(cfg: TransformerConfig, lp: Params, h, seg_ids=None,
               mesh=None):
    """Shared MLP/MoE block (post-attention half of every layer).
    Returns (out, aux): aux carries the router's load-balancing/z losses
    for MoE (coefficient-scaled, reference moe/router.py; padding masked
    out of the statistics via ``seg_ids``) and is None for dense layers.

    ``mesh`` is the SERVING mesh (None for training): a mesh with an
    ``expert`` axis > 1 routes MoE through the explicit expert-parallel
    shard_map so per-chip expert residency is E/ep (see models/moe.py)."""
    if cfg.is_moe:
        from areal_tpu.models.moe import moe_mlp

        valid = None if seg_ids is None else (seg_ids != 0)
        return moe_mlp(cfg, h, lp["mlp"], valid=valid, mesh=mesh)
    gate = _activation(_proj(lp["mlp"]["gate"], h), cfg.activation)
    if cfg.gated_mlp:
        gate = gate * _proj(lp["mlp"]["up"], h)
    return _proj(lp["mlp"]["down"], gate), None


def _layer(
    cfg: TransformerConfig,
    x: jax.Array,
    lp: Params,
    positions: jax.Array,
    mask: jax.Array,
    kv: Optional[Tuple[jax.Array, jax.Array]] = None,
    kv_write_pos: Optional[jax.Array] = None,
    seg_ids: Optional[jax.Array] = None,
    rope_cs: Optional[Tuple[jax.Array, jax.Array]] = None,
    mesh=None,
):
    """One transformer block. Returns (y, (k_full, v_full), aux) where
    k/v_full include cached history when provided and aux carries MoE
    router losses (None for dense)."""
    B, T, D = x.shape
    h = _norm(x, lp["attn_norm"], cfg)
    proj = _proj
    q, k, v = _attn_qkv(cfg, lp, h, positions, rope_cs)

    if kv is not None:
        # write new k/v into cache at per-row offsets, attend over full cache
        k_cache, v_cache = kv  # [B, Hkv, S, hd]

        def write_row(cache_row, new_row, off):
            # cache_row [Hkv, S, hd]; new_row [T, Hkv, hd]
            return jax.lax.dynamic_update_slice(
                cache_row,
                new_row.swapaxes(0, 1).astype(cache_row.dtype),
                (0, off, 0),
            )

        k_full = jax.vmap(write_row)(k_cache, k, kv_write_pos)
        v_full = jax.vmap(write_row)(v_cache, v, kv_write_pos)
        attn_out = cache_attention(q, k_full, v_full, mask)
    else:
        k_full = v_full = None
        attn_out = _attention_dispatch(
            q, k, v, mask, cfg, seg_ids=seg_ids, positions=positions
        )

    attn_out = attn_out.reshape(B, T, cfg.n_q_heads * cfg.head_dim)
    attn_out = checkpoint_name(attn_out, "attn_out")
    x = x + proj(lp["attn"]["o"], attn_out)

    h = _norm(x, lp["mlp_norm"], cfg)
    mlp_out, aux = _mlp_block(cfg, lp, h, seg_ids=seg_ids, mesh=mesh)
    mlp_out = checkpoint_name(mlp_out, "mlp_out")
    x = x + mlp_out
    return x, (k_full, v_full), aux


def _scan_layers(cfg: TransformerConfig, stacked_lp, x, positions, mask,
                 seg_ids, rope_cs):
    """``lax.scan`` of :func:`_layer` over stacked layer params (with the
    configured rematerialisation).  Returns ``(y, aux_layers)`` where
    aux_layers is the per-layer MoE loss stack (None for dense)."""

    def body(carry, lp):
        y, _, aux = _layer(
            cfg, carry, lp, positions, mask, seg_ids=seg_ids, rope_cs=rope_cs
        )
        return y, aux if cfg.is_moe else None

    if cfg.remat:
        # graduated policy table over the checkpoint_name tags planted
        # above (q_proj/k_proj/v_proj/attn_out/mlp_out) — see
        # areal_tpu/models/remat.py for the per-preset memory/FLOP trade
        from areal_tpu.models import remat as remat_policies

        policy = remat_policies.policy_for(cfg.remat_policy)
        if policy is None:
            body = jax.checkpoint(body)
        else:
            body = jax.checkpoint(body, policy=policy)
    return jax.lax.scan(body, x, stacked_lp)


def _run_layers_pipelined(
    params, cfg: TransformerConfig, x, positions, mask, seg_ids, rope_cs, mesh
):
    """Pipeline-parallel layer run: stages = ``pipe``-axis slices of the
    stacked layers, micro-batches = row groups (see
    areal_tpu/parallel/pipeline.py; replaces the reference's 1F1B pipe VM,
    reference: realhf/impl/model/backend/pipe_runner.py:989)."""
    from jax.sharding import NamedSharding
    from areal_tpu.parallel import pipeline

    B = x.shape[0]
    p = mesh.shape["pipe"]
    assert cfg.n_layers % p == 0, (
        f"n_layers {cfg.n_layers} not divisible by pipe {p}"
    )
    m = pipeline.pick_microbatches(B, p, cfg.pipe_microbatches)
    pad = (-B) % m
    if pad:
        # zero rows (seg 0) contribute nothing; dropped after the pipeline
        def padr(a, one=False):
            width = ((0, pad),) + ((0, 0),) * (a.ndim - 1)
            return jnp.pad(a, width, constant_values=1 if one else 0)

        x, positions, seg_ids, mask = (
            padr(x), padr(positions), padr(seg_ids), padr(mask)
        )
        if rope_cs is not None:
            rope_cs = (padr(rope_cs[0], one=True), padr(rope_cs[1]))

    sides = {"positions": positions, "seg_ids": seg_ids, "mask": mask}
    if rope_cs is not None:
        sides["cos"], sides["sin"] = rope_cs
    zero = jnp.zeros((), jnp.float32)
    aux_zero = {"moe_aux_loss": zero, "moe_z_loss": zero}

    def stage_fn(local_layers, mb):
        cs = (mb["cos"], mb["sin"]) if "cos" in mb else None
        y, aux_layers = _scan_layers(
            cfg, local_layers, mb["x"], mb["positions"], mb["mask"],
            mb["seg_ids"], cs,
        )
        if aux_layers is None:
            aux = aux_zero
        else:
            # per-micro-batch router means, weighted by the micro-batch's
            # valid-token count; the division below turns the pipeline sum
            # into the token-weighted mean over micro-batches — the same
            # grad-accum semantics as per-micro-batch aux in the engine's
            # accumulation loop (a full-batch router statistic is not
            # computable per stage)
            w = jnp.sum((mb["seg_ids"] != 0).astype(jnp.float32))
            aux = jax.tree.map(lambda a: jnp.sum(a) * w, aux_layers)
        return y, aux

    if cfg.pipe_schedule == "1f1b":
        if cfg.is_moe:
            raise ValueError(
                "pipe_schedule='1f1b' does not differentiate MoE router "
                "aux losses; use 'gpipe' for MoE models"
            )
        y = pipeline.pipeline_apply_1f1b(
            mesh, params["layers"], stage_fn, x, sides, m
        )
        aux_total = aux_zero
    else:
        y, aux_total = pipeline.pipeline_apply(
            mesh, params["layers"], stage_fn, x, sides, m, aux_zero=aux_zero
        )
    if cfg.is_moe:
        W = jnp.maximum(jnp.sum((seg_ids != 0).astype(jnp.float32)), 1.0)
        aux_total = jax.tree.map(lambda a: a / W, aux_total)
    if pad:
        y = y[:-pad]
    # head/loss work shards over the pipe axis too (otherwise every stage
    # group would redundantly compute the [B,T,V] logits matmul)
    y = jax.lax.with_sharding_constraint(
        y, NamedSharding(mesh, P(("data", "fsdp", "pipe"), None, None))
    )
    return y, aux_total


def _run_layers(
    params,
    cfg: TransformerConfig,
    x,
    positions,
    mask,
    seg_ids,
    with_aux: bool = False,
):
    """Run the stacked layers (self-attention path, no cache): a plain layer
    scan, or the shard_map pipeline when the ambient mesh has a ``pipe``
    axis of size > 1.

    ``with_aux=True`` also returns the MoE router losses summed over layers
    (zeros for dense models) — the round-1 review found these computed then
    dropped inside the scan (VERDICT weak #7)."""

    rope_cs = (
        None
        if cfg.abs_position_embedding
        else rope_tables(positions, cfg.rotary_base, cfg.head_dim)
    )
    pmesh = _pipe_mesh()
    if pmesh is not None:
        x, aux_total = _run_layers_pipelined(
            params, cfg, x, positions, mask, seg_ids, rope_cs, pmesh
        )
        return (x, aux_total) if with_aux else x
    x, aux_layers = _scan_layers(
        cfg, params["layers"], x, positions, mask, seg_ids, rope_cs
    )
    if not with_aux:
        return x
    if aux_layers is None:
        zero = jnp.zeros((), jnp.float32)
        aux_total = {"moe_aux_loss": zero, "moe_z_loss": zero}
    else:
        aux_total = jax.tree.map(lambda a: jnp.sum(a), aux_layers)
    return x, aux_total


def _embed(params, cfg: TransformerConfig, tokens, positions):
    x = params["embed"]["weight"].astype(jnp.dtype(cfg.dtype))[tokens]
    if cfg.embed_scale is not None:
        x = x * jnp.asarray(cfg.embed_scale, x.dtype)
    if cfg.abs_position_embedding:
        x = x + params["pos_embed"]["weight"].astype(x.dtype)[positions]
    return x


def _head(params, cfg: TransformerConfig, x):
    x = _norm(x, params["final_norm"], cfg)
    if cfg.is_critic:
        w = params["value_head"]["w"].astype(x.dtype)
        return (x @ w)[..., 0].astype(jnp.dtype(cfg.logits_dtype))
    if cfg.tied_embedding:
        w = params["embed"]["weight"].astype(x.dtype).T
    else:
        w = quantize.leaf_weight(params["lm_head"], x.dtype)
    return (x @ w).astype(jnp.dtype(cfg.logits_dtype))


def forward(
    params: Params,
    cfg: TransformerConfig,
    tokens: jax.Array,  # [B, T] int32
    positions: jax.Array,  # [B, T] int32 (within-segment positions)
    seg_ids: jax.Array,  # [B, T] int32, 0 = padding
) -> jax.Array:
    """Full forward over a packed padded batch.

    Returns logits [B, T, V] (or values [B, T] for critics).
    """
    x = _embed(params, cfg, tokens, positions)
    mask = make_attention_mask(
        seg_ids, positions, seg_ids, positions, cfg.sliding_window
    )
    x = _run_layers(params, cfg, x, positions, mask, seg_ids)
    return _head(params, cfg, x)


def prefill(
    params: Params,
    cfg: TransformerConfig,
    tokens: jax.Array,  # [B, T]
    positions: jax.Array,
    seg_ids: jax.Array,
    cache: KVCache,
    last_pos: Optional[jax.Array] = None,  # [B] index of each row's last tok
    mesh=None,  # serving mesh (EP MoE dispatch); None elsewhere
) -> Tuple[jax.Array, KVCache]:
    """Run the prompt through the model, filling the KV cache.

    Each batch row is ONE sequence (seg_ids: 1 for real tokens, 0 for right
    padding).  Returns (logits [B, T, V], cache) — or (logits [B, 1, V],
    cache) when ``last_pos`` is given: admission only samples the next
    token, and materializing [B, T, V] full-sequence logits at a 152k
    vocab is ~10 GB of HBM for nothing (measured OOM at 1.5B, B=32,
    T=512 on v5e).
    """
    B, T = tokens.shape
    S = cache.max_len
    x = _embed(params, cfg, tokens, positions)
    # Cache slot s holds the token at absolute position s; a query at
    # absolute position p attends to slots <= p.  (``positions`` must be
    # absolute, i.e. offset by cache.lengths when continuing a sequence.)
    kv_pos = jnp.arange(S)[None, None, :]  # [1,1,S]
    mask = (kv_pos <= positions[:, :, None]) & (seg_ids != 0)[:, :, None]
    if cfg.sliding_window is not None:
        mask &= positions[:, :, None] - kv_pos < cfg.sliding_window
    write_pos = cache.lengths  # [B]
    rope_cs = (
        None
        if cfg.abs_position_embedding
        else rope_tables(positions, cfg.rotary_base, cfg.head_dim)
    )

    def body(carry, xs):
        lp, kc, vc = xs
        y, (k_full, v_full), _aux = _layer(
            cfg,
            carry,
            lp,
            positions,
            mask,
            kv=(kc, vc),
            kv_write_pos=write_pos,
            rope_cs=rope_cs,
            mesh=mesh,
        )
        return y, (k_full, v_full)

    x, (new_k, new_v) = jax.lax.scan(
        body, x, (params["layers"], cache.k, cache.v)
    )
    new_lengths = cache.lengths + jnp.sum(seg_ids != 0, axis=1).astype(jnp.int32)
    if last_pos is not None:
        x = jnp.take_along_axis(x, last_pos[:, None, None], axis=1)  # [B,1,D]
    logits = _head(params, cfg, x)
    return logits, KVCache(k=new_k, v=new_v, lengths=new_lengths)


def decode_step(
    params: Params,
    cfg: TransformerConfig,
    tokens: jax.Array,  # [B] int32 — next token per row
    cache: KVCache,
    active: Optional[jax.Array] = None,  # [B] bool; inactive rows don't advance
    mesh=None,  # serving mesh (EP MoE dispatch); None elsewhere
) -> Tuple[jax.Array, KVCache]:
    """One decode step for all rows. Returns (logits [B, V], new cache).

    The full [L, B, Hkv, S, hd] cache rides the layer scan as CARRY with
    per-row scatter writes, so XLA updates it in place.  (Round 1 stacked
    fresh per-layer outputs via scan ys — a whole-cache copy per token.)
    Inactive rows do not advance ``lengths``; the garbage token written at
    their current slot sits beyond the valid region [0, length) and is
    overwritten on any later write, so no whole-cache select is needed.
    For high-throughput chunked decoding use :func:`decode_chunk`, which
    buffers in-chunk KV in a write-friendly window.
    """
    B = tokens.shape[0]
    S = cache.max_len
    if active is None:
        active = jnp.ones((B,), bool)
    positions = cache.lengths[:, None]  # [B,1]
    x = _embed(params, cfg, tokens[:, None], positions)
    kv_pos = jnp.arange(S)[None, :]  # [1,S]
    mask = kv_pos <= positions  # [B, S]
    if cfg.sliding_window is not None:
        mask &= positions - kv_pos < cfg.sliding_window
    mask = mask[:, None, :]  # [B, 1(Tq), S]
    rope_cs = (
        None
        if cfg.abs_position_embedding
        else rope_tables(positions, cfg.rotary_base, cfg.head_dim)
    )
    rows = jnp.arange(B)

    def body(carry, xs):
        x, k_all, v_all = carry
        lp, l = xs
        h = _norm(x, lp["attn_norm"], cfg)
        q, k, v = _attn_qkv(cfg, lp, h, positions, rope_cs)
        kv_heads = jnp.arange(cfg.n_kv_heads)
        k_all = k_all.at[
            l, rows[:, None], kv_heads[None, :], cache.lengths[:, None]
        ].set(k[:, 0].astype(k_all.dtype))
        v_all = v_all.at[
            l, rows[:, None], kv_heads[None, :], cache.lengths[:, None]
        ].set(v[:, 0].astype(v_all.dtype))
        attn_out = cache_attention(q, k_all[l], v_all[l], mask)
        attn_out = attn_out.reshape(B, 1, cfg.n_q_heads * cfg.head_dim)
        x = x + _proj(lp["attn"]["o"], attn_out)

        h = _norm(x, lp["mlp_norm"], cfg)
        mlp_out, _ = _mlp_block(cfg, lp, h, mesh=mesh)
        x = x + mlp_out
        return (x, k_all, v_all), None

    (x, new_k, new_v), _ = jax.lax.scan(
        body,
        (x, cache.k, cache.v),
        (params["layers"], jnp.arange(cfg.n_layers)),
    )
    logits = _head(params, cfg, x)[:, 0]
    new_lengths = cache.lengths + active.astype(jnp.int32)
    return logits, KVCache(k=new_k, v=new_v, lengths=new_lengths)


def decode_chunk(
    params: Params,
    cfg: TransformerConfig,
    cache: KVCache,
    cur_tokens: jax.Array,  # [B] pending token per row (KV not yet cached)
    active: jax.Array,  # [B] bool
    budgets: jax.Array,  # [B] remaining new tokens (incl. pending cur)
    rng: jax.Array,
    chunk_size: int,
    sample_fn,  # (logits_f32 [B,V], rng[, positions[, row_seeds]])
    stop_fn,  # (tokens [B]) -> [B] bool
    attn_len: Optional[int] = None,
    row_seeds: Optional[jax.Array] = None,  # [B] per-request sampler keys
    mesh=None,  # serving mesh (EP MoE dispatch); None elsewhere
):
    """Generate up to ``chunk_size`` tokens for all active rows device-side.

    In-chunk KV goes to a small [L, W, B, Hkv, hd] WINDOW written at scalar
    offsets (contiguous, in-place), and attention runs over main-cache +
    window jointly; the window merges into the per-row cache slots ONCE per
    chunk.  This removes the per-token per-row scatter that dominated the
    round-2 step-wise decode (measured ~3.4 ms/token at B=32 on v5e).

    ``attn_len`` (static) bounds the cache prefix attention actually reads:
    decode is HBM-bound on the KV stream, so reading ``max_len`` slots when
    every row is shorter wastes the bandwidth the kernel lives on.  The
    caller must guarantee every row stays below ``attn_len`` through the
    whole chunk (engine buckets max in-flight length + chunk_size).

    Sliding-window models with a long cache take the WINDOW-GATHER path:
    each row's last ``window`` cache slots are gathered into a compact
    [L, B, Hkv, Ww, hd] buffer ONCE per chunk, and every decode step streams
    only that buffer — per-row bounded KV reads (the role flash-attn's
    windowed kvcache path plays in the reference,
    realhf/impl/model/modules/attn.py flash_attn_with_kvcache) instead of
    masked full-prefix streaming.

    Returns (cache, out_tokens [B,W], out_logps [B,W], emitted [B,W] bool,
    cur_tokens, active, budgets, rng).
    """
    if cfg.sliding_window is not None and chunk_size > cfg.sliding_window:
        raise ValueError(
            "chunked decode requires chunk_size <= sliding_window "
            f"({chunk_size} > {cfg.sliding_window}); in-chunk KV must stay "
            "inside every query's attention window"
        )
    B = cur_tokens.shape[0]
    S = cache.max_len
    Sa = S if attn_len is None else min(attn_len, S)
    W = chunk_size
    L, Hkv, hd = cfg.n_layers, cfg.n_kv_heads, cfg.head_dim
    base_lens = cache.lengths  # frozen: main-cache valid region per row

    # window-gather dispatch: pays 2x window of copy traffic once per chunk
    # to save (Sa - Ww) of streaming on EVERY step — wins whenever the
    # bucketed prefix exceeds the (padded) window
    Ww = 0
    if cfg.sliding_window is not None:
        Ww = -(-min(cfg.sliding_window, Sa) // 128) * 128  # round up to tile
    use_window_gather = 0 < Ww < Sa
    if use_window_gather:
        # absolute cache slots gathered per row: the last Ww below base_len
        gidx = base_lens[:, None] - Ww + jnp.arange(Ww)[None, :]  # [B,Ww]
        gclamped = jnp.clip(gidx, 0, S - 1)
        attn_k = jnp.take_along_axis(
            cache.k, gclamped[None, :, None, :, None], axis=3
        )  # [L,B,Hkv,Ww,hd]
        attn_v = jnp.take_along_axis(
            cache.v, gclamped[None, :, None, :, None], axis=3
        )
        Seff = Ww
    else:
        gidx = None
        attn_k, attn_v = cache.k, cache.v
        Seff = Sa
    mask_base = (jnp.arange(Sa)[None, :] < base_lens[:, None])  # [B,Sa]
    # NOTE on kernel dispatch: this dense path intentionally has NO Pallas
    # kernel branch.  The measured crossover on v5e is structural, not a
    # flag: below ~2k cache the XLA-fused einsum over the bucketed prefix
    # wins every regime tested (round 2-4), and at >=2k the ENGINE switches
    # to the paged pool + paged_flash_attention (cache_mode="auto",
    # engine/inference_server.py) whose cost scales with each row's true
    # length.  The former AREAL_FLASH_DECODE env opt-in is gone
    # (round-4 verdict #7).

    wk = jnp.zeros((L, W, B, Hkv, hd), cache.k.dtype)
    wv = jnp.zeros((L, W, B, Hkv, hd), cache.v.dtype)
    wvalid0 = jnp.zeros((W, B), bool)

    def step(i, st):
        (lengths, cur, active, budgets, wk, wv, wvalid,
         out_t, out_l, emitted, rng) = st
        positions = lengths[:, None]
        x = _embed(params, cfg, cur[:, None], positions)
        rope_cs = (
            None
            if cfg.abs_position_embedding
            else rope_tables(positions, cfg.rotary_base, cfg.head_dim)
        )
        wvalid = wvalid.at[i].set(active)
        mask_win = wvalid.T[:, None, None, None, :]  # [B,1,1,1,W]
        # per-step cache mask: base prefix, plus the sliding-window lower
        # bound relative to the CURRENT query position (cache slot s holds
        # absolute position s). Window entries are always in range because
        # chunk_size <= sliding_window (checked above).
        if use_window_gather:
            # gathered slots carry their absolute position in gidx;
            # clamped (out-of-range) entries have gidx < 0
            mask_main = (gidx >= 0) & (
                gidx > positions - cfg.sliding_window
            )  # [B,Ww]
        elif cfg.sliding_window is not None:
            mask_main = mask_base & (
                jnp.arange(Sa)[None, :] > positions - cfg.sliding_window
            )
        else:
            mask_main = mask_base

        def body(carry, xs):
            x, wk, wv = carry
            lp, l, kc, vc = xs  # kc/vc [B,Hkv,Seff|S,hd]
            if not use_window_gather and Sa < S:
                # static prefix slice: fuses into the dot's HBM->VMEM read
                # (no materialized copy), so attention streams only the
                # slots rows can actually occupy this chunk
                kc = jax.lax.slice_in_dim(kc, 0, Sa, axis=2)
                vc = jax.lax.slice_in_dim(vc, 0, Sa, axis=2)
            h = _norm(x, lp["attn_norm"], cfg)
            q, k, v = _attn_qkv(cfg, lp, h, positions, rope_cs)
            # contiguous window write at scalar offsets (l, i)
            wk = jax.lax.dynamic_update_slice(
                wk, k.swapaxes(0, 1)[None].astype(wk.dtype), (l, i, 0, 0, 0)
            )
            wv = jax.lax.dynamic_update_slice(
                wv, v.swapaxes(0, 1)[None].astype(wv.dtype), (l, i, 0, 0, 0)
            )
            wk_l = jax.lax.dynamic_index_in_dim(wk, l, 0, keepdims=False)
            wv_l = jax.lax.dynamic_index_in_dim(wv, l, 0, keepdims=False)
            r = cfg.n_q_heads // Hkv
            qg = q.reshape(B, 1, Hkv, r, hd)
            s_win = jnp.einsum(
                "btkrd,wbkd->bkrtw", qg, wk_l.astype(qg.dtype),
                preferred_element_type=jnp.float32,
            ) / np.sqrt(hd)
            s_win = jnp.where(mask_win, s_win, -1e30)  # [B,Hkv,r,1,W]
            s_main = jnp.einsum(
                "btkrd,bksd->bkrts", qg, kc.astype(qg.dtype),
                preferred_element_type=jnp.float32,
            ) / np.sqrt(hd)
            s_main = jnp.where(
                mask_main[:, None, None, None, :], s_main, -1e30
            )
            s = jnp.concatenate([s_main, s_win], axis=-1)
            p = jax.nn.softmax(s, axis=-1)
            p_main, p_win = p[..., :Seff], p[..., Seff:]
            attn = jnp.einsum(
                "bkrts,bksd->btkrd", p_main.astype(vc.dtype), vc
            ) + jnp.einsum(
                "bkrtw,wbkd->btkrd", p_win.astype(wv_l.dtype), wv_l
            )
            attn = attn.reshape(B, 1, cfg.n_q_heads * hd)
            x = x + _proj(lp["attn"]["o"], attn)
            h = _norm(x, lp["mlp_norm"], cfg)
            mlp_out, _ = _mlp_block(cfg, lp, h, mesh=mesh)
            x = x + mlp_out
            return (x, wk, wv), None

        (x, wk, wv), _ = jax.lax.scan(
            body,
            (x, wk, wv),
            (params["layers"], jnp.arange(L), attn_k, attn_v),
        )
        logits = _head(params, cfg, x)[:, 0]
        rng, sub = jax.random.split(rng)
        # position-aware samplers receive each sampled token's absolute
        # position (cur sits at ``lengths``; its successor at lengths+1)
        tok, logp = call_sample_fn(
            sample_fn, logits.astype(jnp.float32), sub, lengths + 1,
            row_seeds,
        )
        tok = jnp.where(active, tok, 0)
        out_t = out_t.at[:, i].set(tok)
        out_l = out_l.at[:, i].set(jnp.where(active, logp, 0.0))
        emitted = emitted.at[:, i].set(active)
        new_lengths = lengths + active.astype(jnp.int32)
        budgets = budgets - active.astype(jnp.int32)
        active = active & ~stop_fn(tok) & (budgets > 0) & (new_lengths < S)
        return (new_lengths, tok, active, budgets, wk, wv, wvalid,
                out_t, out_l, emitted, rng)

    out_t = jnp.zeros((B, W), jnp.int32)
    out_l = jnp.zeros((B, W), jnp.float32)
    emitted = jnp.zeros((B, W), bool)
    st = (base_lens, cur_tokens, active, budgets, wk, wv, wvalid0,
          out_t, out_l, emitted, rng)
    (lengths, cur, active, budgets, wk, wv, wvalid,
     out_t, out_l, emitted, rng) = jax.lax.fori_loop(0, W, step, st)

    # merge the window into per-row cache slots: ONE scatter per chunk
    offs = base_lens[None, :] + jnp.cumsum(
        wvalid.astype(jnp.int32), axis=0
    ) - wvalid.astype(jnp.int32)  # [W,B] target slot per window entry
    slot = jnp.where(wvalid, offs, S)  # invalid -> OOB -> dropped
    b_idx = jnp.broadcast_to(jnp.arange(B)[None, :], (W, B))
    val_k = wk.transpose(1, 2, 0, 3, 4)  # [W,B,L,Hkv,hd]
    val_v = wv.transpose(1, 2, 0, 3, 4)
    new_k = cache.k.at[:, b_idx, :, slot].set(val_k, mode="drop")
    new_v = cache.v.at[:, b_idx, :, slot].set(val_v, mode="drop")
    new_cache = KVCache(k=new_k, v=new_v, lengths=lengths)
    return new_cache, out_t, out_l, emitted, cur, active, budgets, rng


# ---------------------------------------------------------------------------
# Memory-lean logprob computation (no [B,T,V] materialization)
# ---------------------------------------------------------------------------


def hidden_states(
    params: Params,
    cfg: TransformerConfig,
    tokens: jax.Array,
    positions: jax.Array,
    seg_ids: jax.Array,
    with_aux: bool = False,
):
    """Final-norm hidden states [B, T, D] (pre-head), for chunked losses.

    ``with_aux=True`` additionally returns the MoE router losses summed over
    layers ({"moe_aux_loss", "moe_z_loss"}, zeros for dense) so training
    losses can include them."""
    x = _embed(params, cfg, tokens, positions)
    mask = make_attention_mask(
        seg_ids, positions, seg_ids, positions, cfg.sliding_window
    )
    if with_aux:
        x, aux = _run_layers(
            params, cfg, x, positions, mask, seg_ids, with_aux=True
        )
        return _norm(x, params["final_norm"], cfg), aux
    x = _run_layers(params, cfg, x, positions, mask, seg_ids)
    return _norm(x, params["final_norm"], cfg)


def head_weight(params: Params, cfg: TransformerConfig) -> jax.Array:
    """[D, V] output head weight (tied or untied)."""
    if cfg.tied_embedding:
        return params["embed"]["weight"].T
    if quantize.is_quant_leaf(params["lm_head"]):
        return quantize.leaf_weight(params["lm_head"], jnp.float32)
    return params["lm_head"]["w"]


def logprobs_of_labels(
    params: Params,
    cfg: TransformerConfig,
    tokens: jax.Array,  # [B,T]
    positions: jax.Array,
    seg_ids: jax.Array,
) -> jax.Array:
    """log p(tokens[t+1] | tokens[<=t]) — shape [B, T-1].

    Used by PPO inference passes (reference recomputes logprobs at
    realhf/impl/model/interface/ppo_interface.py:474); computes the head in
    chunks so the full-vocab logits for long contexts never materialize.
    """
    x = _embed(params, cfg, tokens, positions)
    mask = make_attention_mask(
        seg_ids, positions, seg_ids, positions, cfg.sliding_window
    )
    x = _run_layers(params, cfg, x, positions, mask, seg_ids)
    x = _norm(x, params["final_norm"], cfg)
    if cfg.tied_embedding:
        w = params["embed"]["weight"].astype(x.dtype).T
    else:
        w = quantize.leaf_weight(params["lm_head"], x.dtype)

    labels = tokens[:, 1:]
    hs = x[:, :-1]  # hidden predicting next token

    chunk = 1024

    B, Tm1, D = hs.shape
    pad = (-Tm1) % chunk
    hs = jnp.pad(hs, ((0, 0), (0, pad), (0, 0)))
    labels_p = jnp.pad(labels, ((0, 0), (0, pad)))
    n_chunks = hs.shape[1] // chunk
    hs = hs.reshape(B, n_chunks, chunk, D)
    labels_p = labels_p.reshape(B, n_chunks, chunk)

    def chunk_body(_, xs):
        h, lab = xs  # [B,chunk,D], [B,chunk]
        logits = (h @ w).astype(jnp.float32)  # [B,chunk,V]
        lse = jax.nn.logsumexp(logits, axis=-1)
        tgt = jnp.take_along_axis(logits, lab[..., None], axis=-1)[..., 0]
        return None, tgt - lse

    _, lps = jax.lax.scan(
        chunk_body, None, (hs.swapaxes(0, 1), labels_p.swapaxes(0, 1))
    )
    lps = lps.swapaxes(0, 1).reshape(B, -1)[:, :Tm1]
    return lps

"""TPU-native transformer.

This is the rebuild of the reference's ``ReaLModel``
(reference: realhf/impl/model/nn/real_llm_api.py:100 and the modules under
realhf/impl/model/modules/) as a *pure-functional* JAX model:

* Params are a plain pytree (nested dicts of jnp arrays).  Per-layer params
  are **stacked along a leading layer axis** and the forward pass runs
  ``lax.scan`` over layers — fast compiles, and the layer axis is the natural
  shard target for pipeline parallelism.
* Batches are padded ``[B, T]`` with **segment ids** (0 = padding): packed
  varlen sequences are bins of concatenated segments, replacing the
  reference's flash-attn varlen 1-D packing (realhf/impl/model/modules/attn.py)
  with the TPU-idiomatic static-shape equivalent.
* Attention dispatches to a Pallas flash kernel on TPU
  (areal_tpu/ops/flash_attention.py) and a jnp reference path elsewhere.
* Sharding is expressed as a PartitionSpec pytree (:func:`param_pspecs`)
  over the canonical mesh axes (areal_tpu/base/topology.py) — megatron-style
  tensor parallelism over ``model``, ZeRO-style over ``fsdp`` — and XLA
  inserts all collectives.

Supported features mirroring the reference model zoo: GQA, RoPE, RMS/LayerNorm,
qkv bias (qwen2), per-head q/k norm (qwen3), tied embeddings, absolute position
embeddings (gpt2), embedding scale (gemma), sliding window (mistral), MoE
(mixtral-style top-k router; see areal_tpu/models/moe.py), and a critic value
head (reference: realhf/impl/model/nn/real_llm_base.py:358-451).
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from areal_tpu.models.config import TransformerConfig

Params = Dict[str, Any]

# ---------------------------------------------------------------------------
# Initialization
# ---------------------------------------------------------------------------


def _dense_init(key, shape, scale_axis=0):
    scale = 1.0 / np.sqrt(shape[scale_axis])
    return jax.random.uniform(
        key, shape, minval=-scale, maxval=scale, dtype=jnp.float32
    )


def init_params(cfg: TransformerConfig, key: jax.Array) -> Params:
    """Random init (HF-load overwrites this; used by tests and from-scratch)."""
    keys = iter(jax.random.split(key, 32))
    L, D, F = cfg.n_layers, cfg.hidden_dim, cfg.intermediate_dim
    Hq, Hkv, hd = cfg.n_q_heads, cfg.n_kv_heads, cfg.head_dim

    def stack_init(shape, scale_axis=0):
        k = next(keys)
        return jax.vmap(
            lambda kk: _dense_init(kk, shape, scale_axis=scale_axis)
        )(jax.random.split(k, L))

    attn: Params = {
        "q": {"w": stack_init((D, Hq * hd))},
        "k": {"w": stack_init((D, Hkv * hd))},
        "v": {"w": stack_init((D, Hkv * hd))},
        "o": {"w": stack_init((Hq * hd, D))},
    }
    if cfg.use_attention_bias:
        attn["q"]["b"] = jnp.zeros((L, Hq * hd), jnp.float32)
        attn["k"]["b"] = jnp.zeros((L, Hkv * hd), jnp.float32)
        attn["v"]["b"] = jnp.zeros((L, Hkv * hd), jnp.float32)
    if cfg.use_qk_norm:
        attn["q_norm"] = {"scale": jnp.ones((L, hd), jnp.float32)}
        attn["k_norm"] = {"scale": jnp.ones((L, hd), jnp.float32)}

    if cfg.is_moe:
        from areal_tpu.models.moe import init_moe_params

        mlp = init_moe_params(cfg, next(keys))
    else:
        mlp = {
            "gate": {"w": stack_init((D, F))},
            "down": {"w": stack_init((F, D), scale_axis=0)},
        }
        if cfg.gated_mlp:
            mlp["up"] = {"w": stack_init((D, F))}
        if cfg.use_mlp_bias:
            mlp["gate"]["b"] = jnp.zeros((L, F), jnp.float32)
            if cfg.gated_mlp:
                mlp["up"]["b"] = jnp.zeros((L, F), jnp.float32)
            mlp["down"]["b"] = jnp.zeros((L, D), jnp.float32)

    def norm_params(shape):
        p = {"scale": jnp.ones(shape, jnp.float32)}
        if cfg.norm_type == "layer":
            p["bias"] = jnp.zeros(shape, jnp.float32)
        return p

    params: Params = {
        "embed": {"weight": _dense_init(next(keys), (cfg.vocab_size, D))},
        "layers": {
            "attn_norm": norm_params((L, D)),
            "attn": attn,
            "mlp_norm": norm_params((L, D)),
            "mlp": mlp,
        },
        "final_norm": norm_params((D,)),
    }
    if cfg.abs_position_embedding:
        params["pos_embed"] = {
            "weight": _dense_init(
                next(keys), (cfg.max_position_embeddings, D)
            )
        }
    if cfg.is_critic:
        params["value_head"] = {"w": _dense_init(next(keys), (D, 1))}
    elif not cfg.tied_embedding:
        params["lm_head"] = {"w": _dense_init(next(keys), (D, cfg.vocab_size))}
    return params


# ---------------------------------------------------------------------------
# Sharding rules
# ---------------------------------------------------------------------------


def param_pspecs(cfg: TransformerConfig, params: Params) -> Params:
    """PartitionSpec pytree derived from the actual param tree by path.

    Megatron-style TP over the ``model`` axis (reference:
    realhf/impl/model/parallelism/tensor_parallel/modules.py — column/row
    parallel linears), ZeRO-sharding over ``fsdp``; the stacked layer axis is
    reserved for the ``pipe`` axis when pipeline parallelism is enabled.
    """
    lp = None  # layer axis: unsharded under SPMD (pipe uses shard_map)

    def spec_for(path: Tuple, leaf) -> P:
        keys = tuple(
            k.key if hasattr(k, "key") else str(k) for k in path
        )
        if keys[0] == "embed":
            return P("model", "fsdp")
        if keys[0] == "pos_embed":
            return P(None, "fsdp")
        if keys[0] == "lm_head":
            return P("fsdp", "model")
        if keys[0] == "value_head":
            return P("fsdp", None)
        if keys[0] == "final_norm":
            return P(None)
        # inside "layers": leading dim is the stacked layer axis
        if "router" in keys or "experts" in keys:
            if "router" in keys:
                return P(lp, None, None)
            if keys[-1] == "down":
                return P(lp, None, "model", "fsdp")
            return P(lp, None, "fsdp", "model")
        if "attn" in keys or "mlp" in keys:
            name = keys[-2]  # q/k/v/o/gate/up/down/q_norm/...
            leafname = keys[-1]  # w or b or scale
            if leafname == "scale":  # q_norm/k_norm
                return P(lp, None)
            is_row = name in ("o", "down")
            if leafname == "b":
                return P(lp, None) if is_row else P(lp, "model")
            return (
                P(lp, "model", "fsdp") if is_row else P(lp, "fsdp", "model")
            )
        # norms inside layers
        return P(lp, None)

    return jax.tree_util.tree_map_with_path(spec_for, params)


# ---------------------------------------------------------------------------
# Core ops
# ---------------------------------------------------------------------------


def _norm(x, p, cfg: TransformerConfig):
    dt = x.dtype
    x = x.astype(jnp.float32)
    if cfg.norm_type == "rms":
        x = x * jax.lax.rsqrt(jnp.mean(x * x, axis=-1, keepdims=True) + cfg.norm_eps)
        out = x * p["scale"].astype(jnp.float32)
    else:
        mean = jnp.mean(x, axis=-1, keepdims=True)
        var = jnp.var(x, axis=-1, keepdims=True)
        out = (x - mean) * jax.lax.rsqrt(var + cfg.norm_eps)
        out = out * p["scale"].astype(jnp.float32) + p["bias"].astype(jnp.float32)
    return out.astype(dt)


def _head_norm(x, scale, eps):
    # per-head RMSNorm over head_dim (qwen3)
    dt = x.dtype
    x = x.astype(jnp.float32)
    x = x * jax.lax.rsqrt(jnp.mean(x * x, axis=-1, keepdims=True) + eps)
    return (x * scale.astype(jnp.float32)).astype(dt)


def rope(x: jax.Array, positions: jax.Array, base: float) -> jax.Array:
    """Rotary embedding. x: [B, T, H, hd]; positions: [B, T]."""
    hd = x.shape[-1]
    half = hd // 2
    freqs = 1.0 / (base ** (jnp.arange(0, half, dtype=jnp.float32) / half))
    angles = positions[..., None].astype(jnp.float32) * freqs  # [B,T,half]
    cos = jnp.cos(angles)[:, :, None, :]  # [B,T,1,half]
    sin = jnp.sin(angles)[:, :, None, :]
    x1, x2 = x[..., :half], x[..., half:]
    out = jnp.concatenate(
        [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1
    )
    return out.astype(x.dtype)


def _activation(x, kind: str):
    if kind == "silu":
        return jax.nn.silu(x)
    return jax.nn.gelu(x)


def make_attention_mask(
    seg_q: jax.Array,
    pos_q: jax.Array,
    seg_kv: jax.Array,
    pos_kv: jax.Array,
    sliding_window: Optional[int] = None,
) -> jax.Array:
    """[B, Tq, Tkv] bool mask: same segment, causal, non-pad; optional
    sliding window."""
    same = seg_q[:, :, None] == seg_kv[:, None, :]
    causal = pos_q[:, :, None] >= pos_kv[:, None, :]
    valid = (seg_q[:, :, None] != 0) & (seg_kv[:, None, :] != 0)
    mask = same & causal & valid
    if sliding_window is not None:
        mask &= pos_q[:, :, None] - pos_kv[:, None, :] < sliding_window
    return mask


def reference_attention(q, k, v, mask, logits_dtype=jnp.float32):
    """jnp attention: q [B,T,Hq,hd], k/v [B,S,Hkv,hd], mask [B,T,S]."""
    B, T, Hq, hd = q.shape
    Hkv = k.shape[2]
    rep = Hq // Hkv
    k = jnp.repeat(k, rep, axis=2)
    v = jnp.repeat(v, rep, axis=2)
    scores = jnp.einsum(
        "bthd,bshd->bhts", q.astype(logits_dtype), k.astype(logits_dtype)
    ) / np.sqrt(hd)
    scores = jnp.where(mask[:, None, :, :], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhts,bshd->bthd", probs.astype(v.dtype), v)
    return out


# Mesh used for context-parallel (ring) attention inside jitted forwards.
# Set by the train engine at trace time; None disables the ring path.
_AMBIENT_MESH = None


def set_ambient_mesh(mesh):
    global _AMBIENT_MESH
    _AMBIENT_MESH = mesh


def _seq_parallel_mesh():
    m = _AMBIENT_MESH
    if m is not None and m.shape.get("seq", 1) > 1:
        return m
    return None


def _attention_dispatch(
    q, k, v, mask, cfg: TransformerConfig, seg_ids=None, positions=None
):
    """Pick the attention implementation: ring attention when the engine's
    mesh shards the sequence axis (context parallelism — a capability the
    reference lacks, SURVEY §2.9); Pallas flash on TPU for the dense
    self-attention path; jnp reference elsewhere."""
    from areal_tpu.ops import flash_attention as fa

    mesh = _seq_parallel_mesh()
    if mesh is not None and seg_ids is not None and positions is not None:
        from areal_tpu.ops.ring_attention import ring_attention

        head_axis = (
            "model"
            if cfg.n_kv_heads % mesh.shape.get("model", 1) == 0
            else None
        )
        return ring_attention(
            q,
            k,
            v,
            seg_ids,
            positions,
            mesh=mesh,
            head_axis=head_axis,
            sliding_window=cfg.sliding_window,
        )
    if (
        seg_ids is not None
        and jax.default_backend() == "tpu"
        and fa.supported(q.shape[1], k.shape[1], cfg.sliding_window)
    ):
        return fa.flash_attention(q, k, v, seg_ids)
    return reference_attention(q, k, v, mask)


# ---------------------------------------------------------------------------
# Layer + model forward
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class KVCache:
    """Decode-time KV cache: stacked over layers.

    k/v: [L, B, S, Hkv, hd]; ``lengths``: [B] current per-row lengths (also
    the insertion offset for the next token); rows are independent so the
    cache natively supports continuous batching.
    """

    k: jax.Array
    v: jax.Array
    lengths: jax.Array  # [B] int32

    @classmethod
    def zeros(cls, cfg: TransformerConfig, batch: int, max_len: int, dtype=None):
        dtype = dtype or jnp.dtype(cfg.dtype)
        shape = (cfg.n_layers, batch, max_len, cfg.n_kv_heads, cfg.head_dim)
        return cls(
            k=jnp.zeros(shape, dtype),
            v=jnp.zeros(shape, dtype),
            lengths=jnp.zeros((batch,), jnp.int32),
        )


jax.tree_util.register_dataclass(
    KVCache, data_fields=["k", "v", "lengths"], meta_fields=[]
)


def _layer(
    cfg: TransformerConfig,
    x: jax.Array,
    lp: Params,
    positions: jax.Array,
    mask: jax.Array,
    kv: Optional[Tuple[jax.Array, jax.Array]] = None,
    kv_write_pos: Optional[jax.Array] = None,
    seg_ids: Optional[jax.Array] = None,
):
    """One transformer block. Returns (y, (k_full, v_full)) where k/v_full
    include cached history when provided."""
    B, T, D = x.shape
    h = _norm(x, lp["attn_norm"], cfg)

    def proj(p, y):
        out = y @ p["w"].astype(y.dtype)
        if "b" in p:
            out = out + p["b"].astype(y.dtype)
        return out

    q = proj(lp["attn"]["q"], h).reshape(B, T, cfg.n_q_heads, cfg.head_dim)
    k = proj(lp["attn"]["k"], h).reshape(B, T, cfg.n_kv_heads, cfg.head_dim)
    v = proj(lp["attn"]["v"], h).reshape(B, T, cfg.n_kv_heads, cfg.head_dim)
    if cfg.use_qk_norm:
        q = _head_norm(q, lp["attn"]["q_norm"]["scale"], cfg.norm_eps)
        k = _head_norm(k, lp["attn"]["k_norm"]["scale"], cfg.norm_eps)
    if not cfg.abs_position_embedding:
        q = rope(q, positions, cfg.rotary_base)
        k = rope(k, positions, cfg.rotary_base)

    if kv is not None:
        # write new k/v into cache at per-row offsets, attend over full cache
        k_cache, v_cache = kv

        def write_row(cache_row, new_row, off):
            return jax.lax.dynamic_update_slice(
                cache_row, new_row.astype(cache_row.dtype), (off, 0, 0)
            )

        k_full = jax.vmap(write_row)(k_cache, k, kv_write_pos)
        v_full = jax.vmap(write_row)(v_cache, v, kv_write_pos)
        attn_out = reference_attention(q, k_full, v_full, mask)
    else:
        k_full = v_full = None
        attn_out = _attention_dispatch(
            q, k, v, mask, cfg, seg_ids=seg_ids, positions=positions
        )

    attn_out = attn_out.reshape(B, T, cfg.n_q_heads * cfg.head_dim)
    x = x + proj(lp["attn"]["o"], attn_out)

    h = _norm(x, lp["mlp_norm"], cfg)
    if cfg.is_moe:
        from areal_tpu.models.moe import moe_mlp

        mlp_out, _aux = moe_mlp(cfg, h, lp["mlp"])
    else:
        gate = _activation(proj(lp["mlp"]["gate"], h), cfg.activation)
        if cfg.gated_mlp:
            gate = gate * proj(lp["mlp"]["up"], h)
        mlp_out = proj(lp["mlp"]["down"], gate)
    x = x + mlp_out
    return x, (k_full, v_full)


def _run_layers(params, cfg: TransformerConfig, x, positions, mask, seg_ids):
    """Scan over stacked layers (self-attention path, no cache)."""

    def body(carry, lp):
        y, _ = _layer(cfg, carry, lp, positions, mask, seg_ids=seg_ids)
        return y, None

    if cfg.remat:
        body = jax.checkpoint(body)
    x, _ = jax.lax.scan(body, x, params["layers"])
    return x


def _embed(params, cfg: TransformerConfig, tokens, positions):
    x = params["embed"]["weight"].astype(jnp.dtype(cfg.dtype))[tokens]
    if cfg.embed_scale is not None:
        x = x * jnp.asarray(cfg.embed_scale, x.dtype)
    if cfg.abs_position_embedding:
        x = x + params["pos_embed"]["weight"].astype(x.dtype)[positions]
    return x


def _head(params, cfg: TransformerConfig, x):
    x = _norm(x, params["final_norm"], cfg)
    if cfg.is_critic:
        w = params["value_head"]["w"].astype(x.dtype)
        return (x @ w)[..., 0].astype(jnp.dtype(cfg.logits_dtype))
    if cfg.tied_embedding:
        w = params["embed"]["weight"].astype(x.dtype).T
    else:
        w = params["lm_head"]["w"].astype(x.dtype)
    return (x @ w).astype(jnp.dtype(cfg.logits_dtype))


def forward(
    params: Params,
    cfg: TransformerConfig,
    tokens: jax.Array,  # [B, T] int32
    positions: jax.Array,  # [B, T] int32 (within-segment positions)
    seg_ids: jax.Array,  # [B, T] int32, 0 = padding
) -> jax.Array:
    """Full forward over a packed padded batch.

    Returns logits [B, T, V] (or values [B, T] for critics).
    """
    x = _embed(params, cfg, tokens, positions)
    mask = make_attention_mask(
        seg_ids, positions, seg_ids, positions, cfg.sliding_window
    )
    x = _run_layers(params, cfg, x, positions, mask, seg_ids)
    return _head(params, cfg, x)


def prefill(
    params: Params,
    cfg: TransformerConfig,
    tokens: jax.Array,  # [B, T]
    positions: jax.Array,
    seg_ids: jax.Array,
    cache: KVCache,
) -> Tuple[jax.Array, KVCache]:
    """Run the prompt through the model, filling the KV cache.

    Each batch row is ONE sequence (seg_ids: 1 for real tokens, 0 for right
    padding).  Returns (logits [B, T, V], cache).
    """
    B, T = tokens.shape
    S = cache.k.shape[2]
    x = _embed(params, cfg, tokens, positions)
    # Cache slot s holds the token at absolute position s; a query at
    # absolute position p attends to slots <= p.  (``positions`` must be
    # absolute, i.e. offset by cache.lengths when continuing a sequence.)
    kv_pos = jnp.arange(S)[None, None, :]  # [1,1,S]
    mask = (kv_pos <= positions[:, :, None]) & (seg_ids != 0)[:, :, None]
    if cfg.sliding_window is not None:
        mask &= positions[:, :, None] - kv_pos < cfg.sliding_window
    write_pos = cache.lengths  # [B]

    def body(carry, xs):
        lp, kc, vc = xs
        y, (k_full, v_full) = _layer(
            cfg, carry, lp, positions, mask, kv=(kc, vc), kv_write_pos=write_pos
        )
        return y, (k_full, v_full)

    x, (new_k, new_v) = jax.lax.scan(
        body, x, (params["layers"], cache.k, cache.v)
    )
    new_lengths = cache.lengths + jnp.sum(seg_ids != 0, axis=1).astype(jnp.int32)
    logits = _head(params, cfg, x)
    return logits, KVCache(k=new_k, v=new_v, lengths=new_lengths)


def decode_step(
    params: Params,
    cfg: TransformerConfig,
    tokens: jax.Array,  # [B] int32 — next token per row
    cache: KVCache,
    active: Optional[jax.Array] = None,  # [B] bool; inactive rows don't advance
) -> Tuple[jax.Array, KVCache]:
    """One decode step for all rows. Returns (logits [B, V], new cache)."""
    B = tokens.shape[0]
    S = cache.k.shape[2]
    if active is None:
        active = jnp.ones((B,), bool)
    positions = cache.lengths[:, None]  # [B,1]
    x = _embed(params, cfg, tokens[:, None], positions)
    # mask over cache: attend to slots < length+1 for active rows
    kv_pos = jnp.arange(S)[None, :]  # [1,S]
    mask = kv_pos <= positions  # [B, S]
    if cfg.sliding_window is not None:
        mask &= positions - kv_pos < cfg.sliding_window
    mask = mask[:, None, :]  # [B, 1(Tq), S]

    def body(carry, xs):
        lp, kc, vc = xs
        y, (k_full, v_full) = _layer(
            cfg,
            carry,
            lp,
            positions,
            mask,
            kv=(kc, vc),
            kv_write_pos=cache.lengths,
        )
        return y, (k_full, v_full)

    x, (new_k, new_v) = jax.lax.scan(
        body, x, (params["layers"], cache.k, cache.v)
    )
    logits = _head(params, cfg, x)[:, 0]
    # freeze inactive rows: keep old cache content & lengths
    new_k = jnp.where(active[None, :, None, None, None], new_k, cache.k)
    new_v = jnp.where(active[None, :, None, None, None], new_v, cache.v)
    new_lengths = cache.lengths + active.astype(jnp.int32)
    return logits, KVCache(k=new_k, v=new_v, lengths=new_lengths)


# ---------------------------------------------------------------------------
# Memory-lean logprob computation (no [B,T,V] materialization)
# ---------------------------------------------------------------------------


def hidden_states(
    params: Params,
    cfg: TransformerConfig,
    tokens: jax.Array,
    positions: jax.Array,
    seg_ids: jax.Array,
) -> jax.Array:
    """Final-norm hidden states [B, T, D] (pre-head), for chunked losses."""
    x = _embed(params, cfg, tokens, positions)
    mask = make_attention_mask(
        seg_ids, positions, seg_ids, positions, cfg.sliding_window
    )
    x = _run_layers(params, cfg, x, positions, mask, seg_ids)
    return _norm(x, params["final_norm"], cfg)


def head_weight(params: Params, cfg: TransformerConfig) -> jax.Array:
    """[D, V] output head weight (tied or untied)."""
    if cfg.tied_embedding:
        return params["embed"]["weight"].T
    return params["lm_head"]["w"]


def logprobs_of_labels(
    params: Params,
    cfg: TransformerConfig,
    tokens: jax.Array,  # [B,T]
    positions: jax.Array,
    seg_ids: jax.Array,
) -> jax.Array:
    """log p(tokens[t+1] | tokens[<=t]) — shape [B, T-1].

    Used by PPO inference passes (reference recomputes logprobs at
    realhf/impl/model/interface/ppo_interface.py:474); computes the head in
    chunks so the full-vocab logits for long contexts never materialize.
    """
    x = _embed(params, cfg, tokens, positions)
    mask = make_attention_mask(
        seg_ids, positions, seg_ids, positions, cfg.sliding_window
    )
    x = _run_layers(params, cfg, x, positions, mask, seg_ids)
    x = _norm(x, params["final_norm"], cfg)
    if cfg.tied_embedding:
        w = params["embed"]["weight"].astype(x.dtype).T
    else:
        w = params["lm_head"]["w"].astype(x.dtype)

    labels = tokens[:, 1:]
    hs = x[:, :-1]  # hidden predicting next token

    chunk = 1024

    B, Tm1, D = hs.shape
    pad = (-Tm1) % chunk
    hs = jnp.pad(hs, ((0, 0), (0, pad), (0, 0)))
    labels_p = jnp.pad(labels, ((0, 0), (0, pad)))
    n_chunks = hs.shape[1] // chunk
    hs = hs.reshape(B, n_chunks, chunk, D)
    labels_p = labels_p.reshape(B, n_chunks, chunk)

    def chunk_body(_, xs):
        h, lab = xs  # [B,chunk,D], [B,chunk]
        logits = (h @ w).astype(jnp.float32)  # [B,chunk,V]
        lse = jax.nn.logsumexp(logits, axis=-1)
        tgt = jnp.take_along_axis(logits, lab[..., None], axis=-1)[..., 0]
        return None, tgt - lse

    _, lps = jax.lax.scan(
        chunk_body, None, (hs.swapaxes(0, 1), labels_p.swapaxes(0, 1))
    )
    lps = lps.swapaxes(0, 1).reshape(B, -1)[:, :Tm1]
    return lps

"""Graduated rematerialisation policies for the layer scan.

Round 1-5 shipped an all-or-nothing choice: full remat ("none") or
``qkv_attn`` (save q/k/v projections + attention output), and the latter
OOMs v5e at the bench batch (measured 17.0G peak temp vs 15.75G HBM, r4).
This module replaces the two hardcoded branches in
``models/transformer._scan_layers`` with a POLICY TABLE built from the
``checkpoint_name`` tags the forward already plants (q_proj/k_proj/v_proj/
attn_out/mlp_out), graduated by per-layer saved bytes so a config can buy
back backward-recompute FLOPs in steps instead of one 4x jump:

  name         saves per layer (per token)          role
  ----------   ----------------------------------   -------------------------
  none         nothing                              full recompute (max mem headroom)
  attn_out     attn_out                [D]          skips the whole attention-block
                                                    recompute for the o-proj/residual
                                                    backward at 1 activation/layer
  mlp          attn_out + mlp_out      [2D]         both block boundaries saved:
                                                    backward recomputes only INSIDE
                                                    a block, never across it
  qkv_attn     q,k,v,attn_out          [~4D]        also skips qkv-projection
                                                    recompute (the v5p policy)
  offload_qkv  q,k,v,attn_out -> HOST  [0 on HBM]   qkv_attn's FLOP savings at
                                                    none's device footprint, paying
                                                    d2h/h2d DMA instead
  dots         every matmul output                  cheapest backward, most memory

This is the JAX-native equivalent of Megatron's
``--recompute-granularity/--recompute-method/--recompute-num-layers`` knobs
the reference drives through its ``MegatronConfig`` (AReaL leans on them for
exactly this memory/throughput trade; realhf/api/cli_args.py).

``compile_train_step`` AOT-compiles one full train step (grad + optimizer
update) WITHOUT materializing params, so "fits v5e at the bench batch" is a
checkable property of every (policy, moment-dtype) cell via XLA's
``memory_analysis`` — asserted in tests at tiny shapes and reported per cell
by the bench sweep (bench.py ``bench_train_sweep``).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

# tensor-name tags planted by models/transformer.py (_attn_qkv / _layer)
QKV_NAMES = ("q_proj", "k_proj", "v_proj")
ATTN_OUT = "attn_out"
MLP_OUT = "mlp_out"


def _none() -> None:
    return None  # plain jax.checkpoint: save nothing, recompute everything


def _attn_out():
    import jax

    return jax.checkpoint_policies.save_only_these_names(ATTN_OUT)


def _mlp():
    import jax

    return jax.checkpoint_policies.save_only_these_names(ATTN_OUT, MLP_OUT)


def _qkv_attn():
    import jax

    return jax.checkpoint_policies.save_only_these_names(*QKV_NAMES, ATTN_OUT)


def _offload_qkv():
    import jax

    return jax.checkpoint_policies.save_and_offload_only_these_names(
        names_which_can_be_saved=[],
        names_which_can_be_offloaded=[*QKV_NAMES, ATTN_OUT],
        offload_src="device",
        offload_dst="pinned_host",
    )


def _dots():
    import jax

    return jax.checkpoint_policies.dots_with_no_batch_dims_saveable


# ordered roughly by device-activation footprint, smallest first
POLICIES: Dict[str, Callable[[], Any]] = {
    "none": _none,
    "offload_qkv": _offload_qkv,
    "attn_out": _attn_out,
    "mlp": _mlp,
    "qkv_attn": _qkv_attn,
    "dots": _dots,
}

POLICY_NAMES: Tuple[str, ...] = tuple(POLICIES)


def policy_for(name: str):
    """The jax.checkpoint policy for a preset name (None = save nothing)."""
    try:
        return POLICIES[name]()
    except KeyError:
        raise ValueError(
            f"unknown remat_policy {name!r} (valid: {POLICY_NAMES})"
        ) from None


# ---------------------------------------------------------------------------
# AOT train-step compilation + memory analysis
# ---------------------------------------------------------------------------


def compile_train_step(
    cfg,
    optimizer_cfg=None,
    n_seqs: int = 16,
    seq_len: int = 2048,
    total_train_steps: int = 100,
    donate: bool = True,
):
    """AOT-compile one SFT train step (value_and_grad + clip + adamw apply)
    at batch [n_seqs, seq_len] and return ``(compiled, abstract_state)``.

    Compilation is from ``jax.ShapeDtypeStruct``s only — no params are
    materialized, so a 0.5B cell costs compile time, not HBM.  The returned
    ``compiled`` executable IS callable (``compiled(params, opt_state,
    batch)``) and donates params/opt_state like the engine's fused step;
    ``abstract_state`` is ``{"params", "opt_state", "batch"}`` shape trees
    for building concrete inputs.  ``compiled.memory_analysis()`` gives the
    XLA peak-temp/argument/output byte accounting per cell.
    """
    import jax
    import jax.numpy as jnp

    from areal_tpu.engine.optimizer import OptimizerConfig, make_optimizer
    from areal_tpu.interfaces.sft_interface import sft_loss_fn
    from areal_tpu.models import transformer

    optimizer_cfg = optimizer_cfg or OptimizerConfig()
    tx = make_optimizer(optimizer_cfg, total_train_steps)

    def step(params, opt_state, batch):
        def scalar_loss(p):
            loss_sum, denom, _stats = sft_loss_fn(p, cfg, batch)
            return loss_sum, denom

        (loss_sum, denom), grads = jax.value_and_grad(
            scalar_loss, has_aux=True
        )(params)
        grads = jax.tree.map(
            lambda g: g / jnp.maximum(denom, 1e-8).astype(g.dtype), grads
        )
        updates, opt_state = tx.update(grads, opt_state, params)
        params = jax.tree.map(
            lambda p, u: p + u.astype(p.dtype), params, updates
        )
        return params, opt_state, loss_sum / jnp.maximum(denom, 1e-8)

    params_s = jax.eval_shape(
        lambda k: transformer.init_params(cfg, k), jax.random.PRNGKey(0)
    )
    opt_s = jax.eval_shape(tx.init, params_s)
    batch_s = {
        "tokens": jax.ShapeDtypeStruct((n_seqs, seq_len), jnp.int32),
        "positions": jax.ShapeDtypeStruct((n_seqs, seq_len), jnp.int32),
        "seg_ids": jax.ShapeDtypeStruct((n_seqs, seq_len), jnp.int32),
        "prompt_mask": jax.ShapeDtypeStruct((n_seqs, seq_len), jnp.bool_),
    }
    jitted = jax.jit(step, donate_argnums=(0, 1) if donate else ())
    compiled = jitted.lower(params_s, opt_s, batch_s).compile()
    return compiled, {"params": params_s, "opt_state": opt_s, "batch": batch_s}


def memory_summary(compiled) -> Optional[Dict[str, float]]:
    """{peak_temp_gb, argument_gb, output_gb, host_temp_gb} from an AOT
    executable's XLA memory analysis; None when the backend reports none."""
    try:
        ma = compiled.memory_analysis()
    except Exception:  # noqa: BLE001 - backend-dependent surface
        return None
    if ma is None:
        return None
    gb = float(2**30)
    try:
        return {
            "peak_temp_gb": ma.temp_size_in_bytes / gb,
            "argument_gb": ma.argument_size_in_bytes / gb,
            "output_gb": ma.output_size_in_bytes / gb,
            "host_temp_gb": getattr(ma, "host_temp_size_in_bytes", 0) / gb,
        }
    except AttributeError:
        return None

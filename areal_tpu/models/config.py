"""Transformer architecture config.

TPU-native analogue of the reference's ``ReaLModelConfig``
(reference: realhf/api/core/model_api.py — model config consumed by
realhf/impl/model/nn/real_llm_api.py:100).  One config dataclass covers all
supported HF families (llama/qwen2/qwen3/mistral/gemma/gpt2/mixtral); family
specific conversion lives in ``areal_tpu/models/hf/``.
"""

from __future__ import annotations

import dataclasses
from typing import Optional


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    n_layers: int
    hidden_dim: int
    n_q_heads: int
    n_kv_heads: int
    head_dim: int
    intermediate_dim: int
    vocab_size: int
    max_position_embeddings: int = 32768

    # architecture knobs
    activation: str = "silu"  # silu | gelu
    norm_type: str = "rms"  # rms | layer
    norm_eps: float = 1e-6
    rotary_base: float = 10000.0
    use_attention_bias: bool = False  # qwen2-style qkv bias
    use_mlp_bias: bool = False
    gated_mlp: bool = True  # SwiGLU-style; False = plain fc->act->proj (gpt2)
    tied_embedding: bool = False
    use_qk_norm: bool = False  # qwen3-style per-head q/k RMSNorm
    embed_scale: Optional[float] = None  # gemma multiplies embeddings
    abs_position_embedding: bool = False  # gpt2
    sliding_window: Optional[int] = None  # mistral

    # MoE (mixtral / qwen3-moe); n_experts=0 disables
    n_experts: int = 0
    n_experts_per_tok: int = 2
    moe_intermediate_dim: Optional[int] = None
    moe_aux_loss_coef: float = 0.001
    moe_z_loss_coef: float = 0.0
    # renormalize the top-k routing probs to sum to 1 (mixtral: yes;
    # qwen3-moe: per-config ``norm_topk_prob``)
    moe_norm_topk_prob: bool = True

    # head
    is_critic: bool = False  # value head (dim 1) instead of lm head

    # numerics
    dtype: str = "bfloat16"  # activation/param dtype on device
    logits_dtype: str = "float32"
    # rematerialize each layer in backward (jax.checkpoint over the layer
    # scan) — trades FLOPs for activation memory, standard for training.
    remat: bool = False
    # what the layer-checkpoint keeps — a graduated preset table
    # (areal_tpu/models/remat.py), smallest device footprint first:
    # "none" = full recompute; "offload_qkv" = save q/k/v + attn output to
    # HOST memory (qkv_attn's FLOP savings at none's HBM footprint);
    # "attn_out" = save the attention-block output only; "mlp" = save both
    # block boundaries (attn_out + mlp_out); "qkv_attn" = save q/k/v
    # projections + attention output (v5p-class memory); "dots" = save
    # every matmul output (cheapest backward, most memory).
    remat_policy: str = "none"
    # context-parallel attention over a sharded `seq` mesh axis:
    # "ring" rotates KV blocks with n ppermutes (scales to any length);
    # "ulysses" pays two all-to-alls and runs full attention on a head
    # subset (fewer collectives; needs per-device q heads % cp degree == 0)
    cp_impl: str = "ring"
    # pipeline micro-batches per forward when the mesh has a ``pipe`` axis
    # (row groups rotated stage-to-stage; areal_tpu/parallel/pipeline.py).
    # 0 = auto (2 x pipe stages, capped by the row count).
    pipe_microbatches: int = 0
    # pipeline schedule: "gpipe" (differentiate through the forward scan;
    # saves ~m micro-batch boundary activations) or "1f1b" (custom-VJP
    # interleaved backward; live activations bound by ~2p micro-batches at
    # the cost of one extra forward sweep — the memory-bounded schedule
    # for large micro-batch counts).  MoE models require "gpipe" (router
    # aux losses are not differentiated under 1f1b).
    pipe_schedule: str = "gpipe"

    def __post_init__(self):
        assert self.n_q_heads % self.n_kv_heads == 0
        assert self.activation in ("silu", "gelu")
        assert self.norm_type in ("rms", "layer")
        assert self.pipe_schedule in ("gpipe", "1f1b"), (
            f"unknown pipe_schedule {self.pipe_schedule!r}"
        )
        from areal_tpu.models.remat import POLICY_NAMES

        assert self.remat_policy in POLICY_NAMES, (
            f"unknown remat_policy {self.remat_policy!r} "
            f"(valid: {POLICY_NAMES})"
        )
        assert self.cp_impl in ("ring", "ulysses"), (
            f"unknown cp_impl {self.cp_impl!r}"
        )

    @property
    def q_dim(self) -> int:
        return self.n_q_heads * self.head_dim

    @property
    def kv_dim(self) -> int:
        return self.n_kv_heads * self.head_dim

    @property
    def is_moe(self) -> bool:
        return self.n_experts > 0


def tiny_config(
    vocab_size: int = 256, is_critic: bool = False, **kwargs
) -> TransformerConfig:
    """Small config for tests."""
    defaults = dict(
        n_layers=2,
        hidden_dim=32,
        n_q_heads=4,
        n_kv_heads=2,
        head_dim=8,
        intermediate_dim=64,
        vocab_size=vocab_size,
        max_position_embeddings=128,
        dtype="float32",
        is_critic=is_critic,
    )
    defaults.update(kwargs)
    return TransformerConfig(**defaults)

"""Mixtral (MoE) HF adapter (reference: realhf/api/from_hf/mixtral.py).

HF expert weights are per-expert Linears ``block_sparse_moe.experts.{e}.w1/w2/w3``
(w1=gate [F,D], w2=down [D,F], w3=up [F,D]); we stack them to [L, E, D, F]
for the ragged-dot MoE path (areal_tpu/models/moe.py).
"""

from __future__ import annotations

from typing import Any, Dict

import jax.numpy as jnp
import numpy as np

from areal_tpu.models.config import TransformerConfig
from areal_tpu.models.hf.registry import (
    HFFamily,
    StateDict,
    register_hf_family,
    stack_layers,
    to_np,
)


def _config_from_hf(hf: Dict[str, Any]) -> TransformerConfig:
    return TransformerConfig(
        n_layers=hf["num_hidden_layers"],
        hidden_dim=hf["hidden_size"],
        n_q_heads=hf["num_attention_heads"],
        n_kv_heads=hf.get("num_key_value_heads", hf["num_attention_heads"]),
        head_dim=hf["hidden_size"] // hf["num_attention_heads"],
        intermediate_dim=hf["intermediate_size"],
        moe_intermediate_dim=hf["intermediate_size"],
        vocab_size=hf["vocab_size"],
        max_position_embeddings=hf.get("max_position_embeddings", 32768),
        norm_eps=hf.get("rms_norm_eps", 1e-5),
        rotary_base=hf.get("rope_theta", 1e6),
        n_experts=hf["num_local_experts"],
        n_experts_per_tok=hf["num_experts_per_tok"],
        moe_aux_loss_coef=hf.get("router_aux_loss_coef", 0.001),
        sliding_window=hf.get("sliding_window"),
    )


def _config_to_hf(cfg: TransformerConfig) -> Dict[str, Any]:
    return dict(
        architectures=["MixtralForCausalLM"],
        model_type="mixtral",
        hidden_size=cfg.hidden_dim,
        intermediate_size=cfg.moe_intermediate_dim or cfg.intermediate_dim,
        num_hidden_layers=cfg.n_layers,
        num_attention_heads=cfg.n_q_heads,
        num_key_value_heads=cfg.n_kv_heads,
        vocab_size=cfg.vocab_size,
        max_position_embeddings=cfg.max_position_embeddings,
        rms_norm_eps=cfg.norm_eps,
        rope_theta=cfg.rotary_base,
        num_local_experts=cfg.n_experts,
        num_experts_per_tok=cfg.n_experts_per_tok,
        router_aux_loss_coef=cfg.moe_aux_loss_coef,
        sliding_window=cfg.sliding_window,
        torch_dtype="bfloat16",
    )


def _params_from_hf(state: StateDict, cfg: TransformerConfig) -> Dict[str, Any]:
    L, E = cfg.n_layers, cfg.n_experts
    g = lambda n: to_np(state[n])

    def layer_stack(fmt, transpose=True):
        mats = [g(fmt.format(i=i)) for i in range(L)]
        if transpose:
            mats = [m.T for m in mats]
        return jnp.asarray(stack_layers(mats))

    def expert_stack(w_name):  # -> [L, E, in, out]
        per_layer = []
        for i in range(L):
            per_exp = [
                g(
                    f"model.layers.{i}.block_sparse_moe.experts.{e}.{w_name}.weight"
                ).T
                for e in range(E)
            ]
            per_layer.append(np.stack(per_exp, axis=0))
        return jnp.asarray(np.stack(per_layer, axis=0))

    params: Dict[str, Any] = {
        "embed": {"weight": jnp.asarray(g("model.embed_tokens.weight"))},
        "layers": {
            "attn_norm": {
                "scale": layer_stack(
                    "model.layers.{i}.input_layernorm.weight", transpose=False
                )
            },
            "attn": {
                "q": {"w": layer_stack("model.layers.{i}.self_attn.q_proj.weight")},
                "k": {"w": layer_stack("model.layers.{i}.self_attn.k_proj.weight")},
                "v": {"w": layer_stack("model.layers.{i}.self_attn.v_proj.weight")},
                "o": {"w": layer_stack("model.layers.{i}.self_attn.o_proj.weight")},
            },
            "mlp_norm": {
                "scale": layer_stack(
                    "model.layers.{i}.post_attention_layernorm.weight",
                    transpose=False,
                )
            },
            "mlp": {
                "router": {
                    "w": layer_stack(
                        "model.layers.{i}.block_sparse_moe.gate.weight"
                    )
                },
                "experts": {
                    "gate": expert_stack("w1"),
                    "down": expert_stack("w2"),
                    "up": expert_stack("w3"),
                },
            },
        },
        "final_norm": {"scale": jnp.asarray(g("model.norm.weight"))},
    }
    if not cfg.is_critic:
        params["lm_head"] = {"w": jnp.asarray(g("lm_head.weight").T)}
    return params


def _params_to_hf(params: Dict[str, Any], cfg: TransformerConfig) -> StateDict:
    out: StateDict = {}
    np_ = lambda x: np.asarray(x, np.float32)
    lay = params["layers"]
    out["model.embed_tokens.weight"] = np_(params["embed"]["weight"])
    for i in range(cfg.n_layers):
        pre = f"model.layers.{i}."
        out[pre + "input_layernorm.weight"] = np_(lay["attn_norm"]["scale"][i])
        out[pre + "post_attention_layernorm.weight"] = np_(
            lay["mlp_norm"]["scale"][i]
        )
        for ours, theirs in (
            ("q", "q_proj"),
            ("k", "k_proj"),
            ("v", "v_proj"),
            ("o", "o_proj"),
        ):
            out[pre + f"self_attn.{theirs}.weight"] = np_(
                lay["attn"][ours]["w"][i]
            ).T
        out[pre + "block_sparse_moe.gate.weight"] = np_(
            lay["mlp"]["router"]["w"][i]
        ).T
        for e in range(cfg.n_experts):
            base = pre + f"block_sparse_moe.experts.{e}."
            out[base + "w1.weight"] = np_(lay["mlp"]["experts"]["gate"][i, e]).T
            out[base + "w2.weight"] = np_(lay["mlp"]["experts"]["down"][i, e]).T
            out[base + "w3.weight"] = np_(lay["mlp"]["experts"]["up"][i, e]).T
    out["model.norm.weight"] = np_(params["final_norm"]["scale"])
    if "lm_head" in params:
        out["lm_head.weight"] = np_(params["lm_head"]["w"]).T
    return out


register_hf_family(
    HFFamily(
        name="mixtral",
        hf_architecture="MixtralForCausalLM",
        config_from_hf=_config_from_hf,
        config_to_hf=_config_to_hf,
        params_from_hf=_params_from_hf,
        params_to_hf=_params_to_hf,
    )
)

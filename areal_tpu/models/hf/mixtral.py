"""Mixtral (MoE) HF adapter (reference: realhf/api/from_hf/mixtral.py).

HF expert weights are per-expert Linears ``block_sparse_moe.experts.{e}.w1/w2/w3``
(w1=gate [F,D], w2=down [D,F], w3=up [F,D]); we stack them to [L, E, D, F]
for the ragged-dot MoE path (areal_tpu/models/moe.py).
"""

from __future__ import annotations

from typing import Any, Dict

from areal_tpu.models.config import TransformerConfig
from areal_tpu.models.hf.moe_common import moe_params_from_hf, moe_params_to_hf
from areal_tpu.models.hf.registry import HFFamily, StateDict, register_hf_family


def _config_from_hf(hf: Dict[str, Any]) -> TransformerConfig:
    return TransformerConfig(
        n_layers=hf["num_hidden_layers"],
        hidden_dim=hf["hidden_size"],
        n_q_heads=hf["num_attention_heads"],
        n_kv_heads=hf.get("num_key_value_heads", hf["num_attention_heads"]),
        head_dim=hf["hidden_size"] // hf["num_attention_heads"],
        intermediate_dim=hf["intermediate_size"],
        moe_intermediate_dim=hf["intermediate_size"],
        vocab_size=hf["vocab_size"],
        max_position_embeddings=hf.get("max_position_embeddings", 32768),
        norm_eps=hf.get("rms_norm_eps", 1e-5),
        rotary_base=hf.get("rope_theta", 1e6),
        n_experts=hf["num_local_experts"],
        n_experts_per_tok=hf["num_experts_per_tok"],
        moe_aux_loss_coef=hf.get("router_aux_loss_coef", 0.001),
        sliding_window=hf.get("sliding_window"),
    )


def _config_to_hf(cfg: TransformerConfig) -> Dict[str, Any]:
    return dict(
        architectures=["MixtralForCausalLM"],
        model_type="mixtral",
        hidden_size=cfg.hidden_dim,
        intermediate_size=cfg.moe_intermediate_dim or cfg.intermediate_dim,
        num_hidden_layers=cfg.n_layers,
        num_attention_heads=cfg.n_q_heads,
        num_key_value_heads=cfg.n_kv_heads,
        vocab_size=cfg.vocab_size,
        max_position_embeddings=cfg.max_position_embeddings,
        rms_norm_eps=cfg.norm_eps,
        rope_theta=cfg.rotary_base,
        num_local_experts=cfg.n_experts,
        num_experts_per_tok=cfg.n_experts_per_tok,
        router_aux_loss_coef=cfg.moe_aux_loss_coef,
        sliding_window=cfg.sliding_window,
        torch_dtype="bfloat16",
    )


def _params_from_hf(state: StateDict, cfg: TransformerConfig) -> Dict[str, Any]:
    return moe_params_from_hf(
        state,
        cfg,
        router_fmt="model.layers.{i}.block_sparse_moe.gate.weight",
        expert_fmt="model.layers.{i}.block_sparse_moe.experts.{e}.{w}.weight",
        expert_names=("w1", "w2", "w3"),  # (gate, down, up)
    )


def _params_to_hf(params: Dict[str, Any], cfg: TransformerConfig) -> StateDict:
    return moe_params_to_hf(
        params,
        cfg,
        router_key="block_sparse_moe.gate.weight",
        expert_base="block_sparse_moe.experts.{e}.",
        expert_names=("w1", "w2", "w3"),
    )


register_hf_family(
    HFFamily(
        name="mixtral",
        hf_architecture="MixtralForCausalLM",
        config_from_hf=_config_from_hf,
        config_to_hf=_config_to_hf,
        params_from_hf=_params_from_hf,
        params_to_hf=_params_to_hf,
    )
)

"""HF checkpoint import/export registry.

Rebuild of the reference's bidirectional ReaL<->HF conversion
(reference: realhf/impl/model/conversion/hf_registry.py:33 ``HFModelRegistry``,
family adapters realhf/api/from_hf/*.py registered via ``register_hf_family``).

Each family provides: config conversion (HF config.json <-> TransformerConfig)
and param-tree conversion (HF state dict of numpy arrays <-> our stacked-layer
pytree).  Loading reads sharded safetensors; saving writes safetensors +
config.json that ``transformers`` can load back.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any, Callable, Dict, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from areal_tpu.base import logging_
from areal_tpu.models.config import TransformerConfig

logger = logging_.getLogger("hf_registry")

StateDict = Dict[str, np.ndarray]


@dataclasses.dataclass
class HFFamily:
    name: str
    hf_architecture: str
    config_from_hf: Callable[[Dict[str, Any]], TransformerConfig]
    config_to_hf: Callable[[TransformerConfig], Dict[str, Any]]
    params_from_hf: Callable[[StateDict, TransformerConfig], Dict[str, Any]]
    params_to_hf: Callable[[Dict[str, Any], TransformerConfig], StateDict]


_FAMILIES: Dict[str, HFFamily] = {}
_BY_ARCH: Dict[str, str] = {}


def register_hf_family(family: HFFamily):
    if family.name in _FAMILIES:
        raise KeyError(f"hf family {family.name} already registered")
    _FAMILIES[family.name] = family
    _BY_ARCH[family.hf_architecture] = family.name


def get_hf_family(name: str) -> HFFamily:
    import areal_tpu.models.hf  # noqa: F401 ensure registration

    return _FAMILIES[name]


def family_from_architecture(arch: str) -> HFFamily:
    return _FAMILIES[_BY_ARCH[arch]]


def _read_hf_state_dict(path: str) -> StateDict:
    """Load all safetensors shards under ``path`` into numpy arrays."""
    from safetensors.numpy import load_file

    index_file = os.path.join(path, "model.safetensors.index.json")
    state: StateDict = {}
    if os.path.isfile(index_file):
        with open(index_file) as f:
            index = json.load(f)
        shards = sorted(set(index["weight_map"].values()))
        for shard in shards:
            state.update(load_file(os.path.join(path, shard)))
    else:
        single = os.path.join(path, "model.safetensors")
        if os.path.isfile(single):
            state.update(load_file(single))
        else:
            # torch .bin fallback
            import torch

            for fn in sorted(os.listdir(path)):
                if fn.startswith("pytorch_model") and fn.endswith(".bin"):
                    sd = torch.load(
                        os.path.join(path, fn), map_location="cpu", weights_only=True
                    )
                    state.update(
                        {k: v.float().numpy() for k, v in sd.items()}
                    )
            if not state:
                raise FileNotFoundError(f"no model weights found in {path}")
    return state


def load_hf_config(path: str) -> Tuple[HFFamily, TransformerConfig, Dict]:
    import areal_tpu.models.hf  # noqa: F401

    with open(os.path.join(path, "config.json")) as f:
        hf_cfg = json.load(f)
    arch = (hf_cfg.get("architectures") or ["?"])[0]
    family = family_from_architecture(arch)
    return family, family.config_from_hf(hf_cfg), hf_cfg


def load_hf_model(
    path: str,
    is_critic: bool = False,
    dtype: Optional[str] = None,
) -> Tuple[TransformerConfig, Dict[str, Any]]:
    """Load an HF checkpoint directory into (config, param pytree).

    ``is_critic=True`` drops the LM head and attaches a zero-init value head
    (the reference's critic bootstrap from an LM checkpoint).
    """
    family, cfg, _ = load_hf_config(path)
    if dtype is not None:
        cfg = dataclasses.replace(cfg, dtype=dtype)
    if is_critic:
        cfg = dataclasses.replace(cfg, is_critic=True, tied_embedding=False)
    state = _read_hf_state_dict(path)
    params = family.params_from_hf(state, cfg)
    if is_critic:
        params.pop("lm_head", None)
        if "value_head.weight" in state:
            # an RM/critic checkpoint exported by save_hf_model carries its
            # TRAINED scorer; zero-initing here would silently discard it
            # (the SFT->RM->PPO chain reloads exactly this head)
            params["value_head"] = {
                "w": jnp.asarray(
                    np.asarray(state["value_head.weight"], np.float32).T
                )
            }
        else:
            params["value_head"] = {
                "w": jnp.zeros((cfg.hidden_dim, 1), jnp.float32)
            }
    logger.info(
        "loaded %s (%d layers, %d hidden) from %s",
        family.name,
        cfg.n_layers,
        cfg.hidden_dim,
        path,
    )
    return cfg, params


MAX_SHARD_BYTES = 4 * 1024**3


def save_hf_model(
    path: str,
    family_name: str,
    cfg: TransformerConfig,
    params: Dict[str, Any],
    tokenizer=None,
):
    """Export to an HF checkpoint dir (config.json + sharded safetensors)."""
    from safetensors.numpy import save_file

    family = get_hf_family(family_name)
    os.makedirs(path, exist_ok=True)
    state = family.params_to_hf(params, cfg)
    # transposed views must be materialized before safetensors writes bytes
    state = {k: np.ascontiguousarray(v) for k, v in state.items()}
    with open(os.path.join(path, "config.json"), "w") as f:
        json.dump(family.config_to_hf(cfg), f, indent=2)

    # shard by size (reference: realhf/impl/model/conversion/hf_registry.py:214)
    shards = []
    cur: StateDict = {}
    cur_bytes = 0
    for k, v in state.items():
        if cur and cur_bytes + v.nbytes > MAX_SHARD_BYTES:
            shards.append(cur)
            cur, cur_bytes = {}, 0
        cur[k] = v
        cur_bytes += v.nbytes
    if cur:
        shards.append(cur)

    if len(shards) == 1:
        save_file(shards[0], os.path.join(path, "model.safetensors"))
    else:
        weight_map = {}
        total = sum(v.nbytes for v in state.values())
        for i, shard in enumerate(shards):
            fn = f"model-{i + 1:05d}-of-{len(shards):05d}.safetensors"
            save_file(shard, os.path.join(path, fn))
            for k in shard:
                weight_map[k] = fn
        with open(
            os.path.join(path, "model.safetensors.index.json"), "w"
        ) as f:
            json.dump(
                {
                    "metadata": {"total_size": total},
                    "weight_map": weight_map,
                },
                f,
            )
    if tokenizer is not None:
        tokenizer.save_pretrained(path)


# -- helpers shared by family adapters --------------------------------------


def to_np(x) -> np.ndarray:
    return np.asarray(x, dtype=np.float32)


def stack_layers(per_layer: list) -> np.ndarray:
    return np.stack(per_layer, axis=0)

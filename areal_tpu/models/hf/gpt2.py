"""GPT-2 HF adapter (reference: realhf/api/from_hf/gpt2.py).

GPT-2 quirks: LayerNorm with bias, absolute position embeddings, fused qkv
``c_attn`` stored in Conv1D layout ([in, out] — NOT transposed like Linear),
gelu, tied LM head.
"""

from __future__ import annotations

from typing import Any, Dict

import jax.numpy as jnp
import numpy as np

from areal_tpu.models.config import TransformerConfig
from areal_tpu.models.hf.registry import (
    HFFamily,
    StateDict,
    register_hf_family,
    stack_layers,
    to_np,
)


def _config_from_hf(hf: Dict[str, Any]) -> TransformerConfig:
    return TransformerConfig(
        n_layers=hf["n_layer"],
        hidden_dim=hf["n_embd"],
        n_q_heads=hf["n_head"],
        n_kv_heads=hf["n_head"],
        head_dim=hf["n_embd"] // hf["n_head"],
        intermediate_dim=hf.get("n_inner") or 4 * hf["n_embd"],
        vocab_size=hf["vocab_size"],
        max_position_embeddings=hf.get("n_positions", 1024),
        activation="gelu",
        norm_type="layer",
        norm_eps=hf.get("layer_norm_epsilon", 1e-5),
        use_attention_bias=True,
        use_mlp_bias=True,
        gated_mlp=False,
        tied_embedding=True,
        abs_position_embedding=True,
    )


def _config_to_hf(cfg: TransformerConfig) -> Dict[str, Any]:
    return dict(
        architectures=["GPT2LMHeadModel"],
        model_type="gpt2",
        n_layer=cfg.n_layers,
        n_embd=cfg.hidden_dim,
        n_head=cfg.n_q_heads,
        n_inner=cfg.intermediate_dim,
        vocab_size=cfg.vocab_size,
        n_positions=cfg.max_position_embeddings,
        n_ctx=cfg.max_position_embeddings,
        layer_norm_epsilon=cfg.norm_eps,
        activation_function="gelu_new",
    )


def _strip_prefix(state: StateDict) -> StateDict:
    if any(k.startswith("transformer.") for k in state):
        return {
            k[len("transformer.") :]: v
            for k, v in state.items()
            if k.startswith("transformer.")
        }
    return state


def _params_from_hf(state: StateDict, cfg: TransformerConfig) -> Dict[str, Any]:
    state = _strip_prefix(state)
    L, D = cfg.n_layers, cfg.hidden_dim
    g = lambda n: to_np(state[n])

    qw, kw, vw, qb, kb, vb = [], [], [], [], [], []
    for i in range(L):
        w = g(f"h.{i}.attn.c_attn.weight")  # [D, 3D] Conv1D layout
        b = g(f"h.{i}.attn.c_attn.bias")  # [3D]
        qw.append(w[:, :D]); kw.append(w[:, D : 2 * D]); vw.append(w[:, 2 * D :])
        qb.append(b[:D]); kb.append(b[D : 2 * D]); vb.append(b[2 * D :])

    def conv_stack(fmt):
        return jnp.asarray(stack_layers([g(fmt.format(i=i)) for i in range(L)]))

    params: Dict[str, Any] = {
        "embed": {"weight": jnp.asarray(g("wte.weight"))},
        "pos_embed": {"weight": jnp.asarray(g("wpe.weight"))},
        "layers": {
            "attn_norm": {
                "scale": conv_stack("h.{i}.ln_1.weight"),
                "bias": conv_stack("h.{i}.ln_1.bias"),
            },
            "attn": {
                "q": {"w": jnp.asarray(stack_layers(qw)), "b": jnp.asarray(stack_layers(qb))},
                "k": {"w": jnp.asarray(stack_layers(kw)), "b": jnp.asarray(stack_layers(kb))},
                "v": {"w": jnp.asarray(stack_layers(vw)), "b": jnp.asarray(stack_layers(vb))},
                "o": {
                    "w": conv_stack("h.{i}.attn.c_proj.weight"),
                    "b": conv_stack("h.{i}.attn.c_proj.bias"),
                },
            },
            "mlp_norm": {
                "scale": conv_stack("h.{i}.ln_2.weight"),
                "bias": conv_stack("h.{i}.ln_2.bias"),
            },
            "mlp": {
                # non-gated mlp: "gate" is the fc layer (cfg.gated_mlp=False)
                "gate": {
                    "w": conv_stack("h.{i}.mlp.c_fc.weight"),
                    "b": conv_stack("h.{i}.mlp.c_fc.bias"),
                },
                "down": {
                    "w": conv_stack("h.{i}.mlp.c_proj.weight"),
                    "b": conv_stack("h.{i}.mlp.c_proj.bias"),
                },
            },
        },
        "final_norm": {
            "scale": jnp.asarray(g("ln_f.weight")),
            "bias": jnp.asarray(g("ln_f.bias")),
        },
    }
    return params


def _params_to_hf(params: Dict[str, Any], cfg: TransformerConfig) -> StateDict:
    out: StateDict = {}
    np_ = lambda x: np.asarray(x, np.float32)
    lay = params["layers"]
    out["wte.weight"] = np_(params["embed"]["weight"])
    out["wpe.weight"] = np_(params["pos_embed"]["weight"])
    for i in range(cfg.n_layers):
        pre = f"h.{i}."
        out[pre + "ln_1.weight"] = np_(lay["attn_norm"]["scale"][i])
        out[pre + "ln_1.bias"] = np_(lay["attn_norm"]["bias"][i])
        out[pre + "ln_2.weight"] = np_(lay["mlp_norm"]["scale"][i])
        out[pre + "ln_2.bias"] = np_(lay["mlp_norm"]["bias"][i])
        out[pre + "attn.c_attn.weight"] = np.concatenate(
            [
                np_(lay["attn"]["q"]["w"][i]),
                np_(lay["attn"]["k"]["w"][i]),
                np_(lay["attn"]["v"]["w"][i]),
            ],
            axis=1,
        )
        out[pre + "attn.c_attn.bias"] = np.concatenate(
            [
                np_(lay["attn"]["q"]["b"][i]),
                np_(lay["attn"]["k"]["b"][i]),
                np_(lay["attn"]["v"]["b"][i]),
            ]
        )
        out[pre + "attn.c_proj.weight"] = np_(lay["attn"]["o"]["w"][i])
        out[pre + "attn.c_proj.bias"] = np_(lay["attn"]["o"]["b"][i])
        out[pre + "mlp.c_fc.weight"] = np_(lay["mlp"]["gate"]["w"][i])
        out[pre + "mlp.c_fc.bias"] = np_(lay["mlp"]["gate"]["b"][i])
        out[pre + "mlp.c_proj.weight"] = np_(lay["mlp"]["down"]["w"][i])
        out[pre + "mlp.c_proj.bias"] = np_(lay["mlp"]["down"]["b"][i])
    out["ln_f.weight"] = np_(params["final_norm"]["scale"])
    out["ln_f.bias"] = np_(params["final_norm"]["bias"])
    return out


register_hf_family(
    HFFamily(
        name="gpt2",
        hf_architecture="GPT2LMHeadModel",
        config_from_hf=_config_from_hf,
        config_to_hf=_config_to_hf,
        params_from_hf=_params_from_hf,
        params_to_hf=_params_to_hf,
    )
)

"""Qwen3-MoE HF adapter — a family BEYOND the reference's seven
(reference: realhf/api/from_hf/ has no qwen3moe converter).

Qwen3 attention (per-head q/k RMSNorm, explicit ``head_dim``, no qkv bias)
plus mixtral-style sparse MLP with qwen naming: router at
``model.layers.{i}.mlp.gate``, experts at
``model.layers.{i}.mlp.experts.{e}.gate_proj/up_proj/down_proj``.
Expert weights stack to [L, E, in, out] for the ragged-dot MoE path
(areal_tpu/models/moe.py); ``norm_topk_prob`` maps to
``TransformerConfig.moe_norm_topk_prob``.

Dense-interleaved variants (``decoder_sparse_step != 1`` or non-empty
``mlp_only_layers``) are rejected: the stacked-layer scan assumes a
homogeneous per-layer structure.
"""

from __future__ import annotations

from typing import Any, Dict

from areal_tpu.models.config import TransformerConfig
from areal_tpu.models.hf.moe_common import moe_params_from_hf, moe_params_to_hf
from areal_tpu.models.hf.registry import HFFamily, StateDict, register_hf_family


def _config_from_hf(hf: Dict[str, Any]) -> TransformerConfig:
    if hf.get("decoder_sparse_step", 1) != 1 or hf.get("mlp_only_layers"):
        raise NotImplementedError(
            "qwen3_moe with dense-interleaved layers (decoder_sparse_step "
            "!= 1 or mlp_only_layers) is not supported: the layer scan "
            "requires homogeneous layers"
        )
    if hf.get("attention_bias", False):
        raise NotImplementedError(
            "qwen3_moe with attention_bias=True is not supported: the "
            "adapter would silently drop the q/k/v/o bias tensors"
        )
    if hf.get("use_sliding_window") and hf.get(
        "max_window_layers", 0
    ) not in (0, hf["num_hidden_layers"]):
        # HF applies SWA only to layers >= max_window_layers; our stacked
        # scan applies one window to EVERY layer — heterogeneous configs
        # would silently diverge
        raise NotImplementedError(
            "qwen3_moe with per-layer sliding-window gating "
            "(max_window_layers) is not supported: the layer scan applies "
            "a uniform window"
        )
    head_dim = hf.get("head_dim") or hf["hidden_size"] // hf["num_attention_heads"]
    return TransformerConfig(
        sliding_window=(
            hf.get("sliding_window") if hf.get("use_sliding_window") else None
        ),
        n_layers=hf["num_hidden_layers"],
        hidden_dim=hf["hidden_size"],
        n_q_heads=hf["num_attention_heads"],
        n_kv_heads=hf.get("num_key_value_heads", hf["num_attention_heads"]),
        head_dim=head_dim,
        intermediate_dim=hf["intermediate_size"],
        moe_intermediate_dim=hf["moe_intermediate_size"],
        vocab_size=hf["vocab_size"],
        max_position_embeddings=hf.get("max_position_embeddings", 32768),
        norm_eps=hf.get("rms_norm_eps", 1e-6),
        rotary_base=hf.get("rope_theta", 10000.0),
        tied_embedding=hf.get("tie_word_embeddings", False),
        use_qk_norm=True,
        n_experts=hf["num_experts"],
        n_experts_per_tok=hf["num_experts_per_tok"],
        moe_aux_loss_coef=hf.get("router_aux_loss_coef", 0.001),
        moe_norm_topk_prob=hf.get("norm_topk_prob", False),
    )


def _config_to_hf(cfg: TransformerConfig) -> Dict[str, Any]:
    return dict(
        architectures=["Qwen3MoeForCausalLM"],
        model_type="qwen3_moe",
        hidden_size=cfg.hidden_dim,
        intermediate_size=cfg.intermediate_dim,
        moe_intermediate_size=cfg.moe_intermediate_dim or cfg.intermediate_dim,
        num_hidden_layers=cfg.n_layers,
        num_attention_heads=cfg.n_q_heads,
        num_key_value_heads=cfg.n_kv_heads,
        head_dim=cfg.head_dim,
        vocab_size=cfg.vocab_size,
        max_position_embeddings=cfg.max_position_embeddings,
        rms_norm_eps=cfg.norm_eps,
        rope_theta=cfg.rotary_base,
        tie_word_embeddings=cfg.tied_embedding,
        num_experts=cfg.n_experts,
        num_experts_per_tok=cfg.n_experts_per_tok,
        router_aux_loss_coef=cfg.moe_aux_loss_coef,
        norm_topk_prob=cfg.moe_norm_topk_prob,
        decoder_sparse_step=1,
        mlp_only_layers=[],
        sliding_window=cfg.sliding_window,
        use_sliding_window=cfg.sliding_window is not None,
        torch_dtype="bfloat16",
    )


def _params_from_hf(state: StateDict, cfg: TransformerConfig) -> Dict[str, Any]:
    return moe_params_from_hf(
        state,
        cfg,
        router_fmt="model.layers.{i}.mlp.gate.weight",
        expert_fmt="model.layers.{i}.mlp.experts.{e}.{w}.weight",
        expert_names=("gate_proj", "down_proj", "up_proj"),
        qk_norm=True,
    )


def _params_to_hf(params: Dict[str, Any], cfg: TransformerConfig) -> StateDict:
    return moe_params_to_hf(
        params,
        cfg,
        router_key="mlp.gate.weight",
        expert_base="mlp.experts.{e}.",
        expert_names=("gate_proj", "down_proj", "up_proj"),
        qk_norm=True,
    )


register_hf_family(
    HFFamily(
        name="qwen3_moe",
        hf_architecture="Qwen3MoeForCausalLM",
        config_from_hf=_config_from_hf,
        config_to_hf=_config_to_hf,
        params_from_hf=_params_from_hf,
        params_to_hf=_params_to_hf,
    )
)

"""Llama-family HF adapters: llama, qwen2, qwen3, mistral, gemma.

(reference: realhf/api/from_hf/{llama,qwen2,qwen3,mistral,gemma}.py — each
registers config+param converters via register_hf_family.)

These share the ``model.layers.{i}.self_attn.*`` naming; family differences
are bias flags, qk-norm, sliding window, tied embeddings, norm offset
(gemma stores RMSNorm scale as weight+1) and embedding scaling.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict

import jax.numpy as jnp
import numpy as np

from areal_tpu.models.config import TransformerConfig
from areal_tpu.models.hf.registry import (
    HFFamily,
    StateDict,
    register_hf_family,
    stack_layers,
    to_np,
)


def _llama_like_config_from_hf(hf: Dict[str, Any], **overrides) -> TransformerConfig:
    head_dim = hf.get("head_dim") or hf["hidden_size"] // hf["num_attention_heads"]
    kwargs = dict(
        n_layers=hf["num_hidden_layers"],
        hidden_dim=hf["hidden_size"],
        n_q_heads=hf["num_attention_heads"],
        n_kv_heads=hf.get("num_key_value_heads", hf["num_attention_heads"]),
        head_dim=head_dim,
        intermediate_dim=hf["intermediate_size"],
        vocab_size=hf["vocab_size"],
        max_position_embeddings=hf.get("max_position_embeddings", 32768),
        norm_eps=hf.get("rms_norm_eps", 1e-6),
        rotary_base=hf.get("rope_theta", 10000.0),
        tied_embedding=hf.get("tie_word_embeddings", False),
        sliding_window=(
            hf.get("sliding_window")
            if hf.get("use_sliding_window", True)
            else None
        ),
    )
    kwargs.update(overrides)
    return TransformerConfig(**kwargs)


def _llama_like_config_to_hf(
    cfg: TransformerConfig, model_type: str, architecture: str, **extra
) -> Dict[str, Any]:
    hf = dict(
        architectures=[architecture],
        model_type=model_type,
        hidden_size=cfg.hidden_dim,
        intermediate_size=cfg.intermediate_dim,
        num_hidden_layers=cfg.n_layers,
        num_attention_heads=cfg.n_q_heads,
        num_key_value_heads=cfg.n_kv_heads,
        head_dim=cfg.head_dim,
        vocab_size=cfg.vocab_size,
        max_position_embeddings=cfg.max_position_embeddings,
        rms_norm_eps=cfg.norm_eps,
        rope_theta=cfg.rotary_base,
        tie_word_embeddings=cfg.tied_embedding,
        hidden_act="silu" if cfg.activation == "silu" else "gelu_pytorch_tanh",
        torch_dtype="bfloat16",
    )
    if cfg.sliding_window is not None:
        hf["sliding_window"] = cfg.sliding_window
        hf["use_sliding_window"] = True
    hf.update(extra)
    return hf


def _params_from_hf(
    state: StateDict,
    cfg: TransformerConfig,
    qkv_bias: bool = False,
    qk_norm: bool = False,
    gemma_norm: bool = False,
) -> Dict[str, Any]:
    L = cfg.n_layers
    g = lambda name: to_np(state[name])

    def layer_stack(fmt: str, transpose: bool = True):
        mats = [g(fmt.format(i=i)) for i in range(L)]
        if transpose:
            mats = [m.T for m in mats]  # torch [out,in] -> ours [in,out]
        return jnp.asarray(stack_layers(mats))

    def norm_stack(fmt: str):
        mats = [g(fmt.format(i=i)) for i in range(L)]
        if gemma_norm:
            mats = [m + 1.0 for m in mats]
        return jnp.asarray(stack_layers(mats))

    attn: Dict[str, Any] = {
        "q": {"w": layer_stack("model.layers.{i}.self_attn.q_proj.weight")},
        "k": {"w": layer_stack("model.layers.{i}.self_attn.k_proj.weight")},
        "v": {"w": layer_stack("model.layers.{i}.self_attn.v_proj.weight")},
        "o": {"w": layer_stack("model.layers.{i}.self_attn.o_proj.weight")},
    }
    if qkv_bias:
        attn["q"]["b"] = layer_stack(
            "model.layers.{i}.self_attn.q_proj.bias", transpose=False
        )
        attn["k"]["b"] = layer_stack(
            "model.layers.{i}.self_attn.k_proj.bias", transpose=False
        )
        attn["v"]["b"] = layer_stack(
            "model.layers.{i}.self_attn.v_proj.bias", transpose=False
        )
    if qk_norm:
        attn["q_norm"] = {
            "scale": norm_stack("model.layers.{i}.self_attn.q_norm.weight")
        }
        attn["k_norm"] = {
            "scale": norm_stack("model.layers.{i}.self_attn.k_norm.weight")
        }

    final_norm = to_np(state["model.norm.weight"])
    if gemma_norm:
        final_norm = final_norm + 1.0

    params: Dict[str, Any] = {
        "embed": {"weight": jnp.asarray(to_np(state["model.embed_tokens.weight"]))},
        "layers": {
            "attn_norm": {
                "scale": norm_stack("model.layers.{i}.input_layernorm.weight")
            },
            "attn": attn,
            "mlp_norm": {
                "scale": norm_stack(
                    "model.layers.{i}.post_attention_layernorm.weight"
                )
            },
            "mlp": {
                "gate": {
                    "w": layer_stack("model.layers.{i}.mlp.gate_proj.weight")
                },
                "up": {"w": layer_stack("model.layers.{i}.mlp.up_proj.weight")},
                "down": {
                    "w": layer_stack("model.layers.{i}.mlp.down_proj.weight")
                },
            },
        },
        "final_norm": {"scale": jnp.asarray(final_norm)},
    }
    if not cfg.tied_embedding and not cfg.is_critic:
        params["lm_head"] = {
            "w": jnp.asarray(to_np(state["lm_head.weight"]).T)
        }
    return params


def _params_to_hf(
    params: Dict[str, Any],
    cfg: TransformerConfig,
    qkv_bias: bool = False,
    qk_norm: bool = False,
    gemma_norm: bool = False,
) -> StateDict:
    out: StateDict = {}
    np_ = lambda x: np.asarray(x, dtype=np.float32)
    out["model.embed_tokens.weight"] = np_(params["embed"]["weight"])
    lay = params["layers"]
    L = cfg.n_layers
    for i in range(L):
        pre = f"model.layers.{i}."
        norm_off = -1.0 if gemma_norm else 0.0
        out[pre + "input_layernorm.weight"] = (
            np_(lay["attn_norm"]["scale"][i]) + norm_off
        )
        out[pre + "post_attention_layernorm.weight"] = (
            np_(lay["mlp_norm"]["scale"][i]) + norm_off
        )
        for ours, theirs in (("q", "q_proj"), ("k", "k_proj"), ("v", "v_proj"), ("o", "o_proj")):
            out[pre + f"self_attn.{theirs}.weight"] = np_(
                lay["attn"][ours]["w"][i]
            ).T
            if qkv_bias and ours != "o":
                out[pre + f"self_attn.{theirs}.bias"] = np_(
                    lay["attn"][ours]["b"][i]
                )
        if qk_norm:
            out[pre + "self_attn.q_norm.weight"] = (
                np_(lay["attn"]["q_norm"]["scale"][i]) + norm_off
            )
            out[pre + "self_attn.k_norm.weight"] = (
                np_(lay["attn"]["k_norm"]["scale"][i]) + norm_off
            )
        for ours, theirs in (("gate", "gate_proj"), ("up", "up_proj"), ("down", "down_proj")):
            out[pre + f"mlp.{theirs}.weight"] = np_(
                lay["mlp"][ours]["w"][i]
            ).T
    out["model.norm.weight"] = np_(params["final_norm"]["scale"]) + (
        -1.0 if gemma_norm else 0.0
    )
    if "lm_head" in params:
        out["lm_head.weight"] = np_(params["lm_head"]["w"]).T
    if "value_head" in params:
        out["value_head.weight"] = np_(params["value_head"]["w"]).T
    return out


register_hf_family(
    HFFamily(
        name="llama",
        hf_architecture="LlamaForCausalLM",
        config_from_hf=lambda hf: _llama_like_config_from_hf(hf),
        config_to_hf=lambda cfg: _llama_like_config_to_hf(
            cfg, "llama", "LlamaForCausalLM"
        ),
        params_from_hf=lambda s, c: _params_from_hf(s, c),
        params_to_hf=lambda p, c: _params_to_hf(p, c),
    )
)

register_hf_family(
    HFFamily(
        name="qwen2",
        hf_architecture="Qwen2ForCausalLM",
        config_from_hf=lambda hf: _llama_like_config_from_hf(
            hf, use_attention_bias=True
        ),
        config_to_hf=lambda cfg: _llama_like_config_to_hf(
            cfg, "qwen2", "Qwen2ForCausalLM"
        ),
        params_from_hf=lambda s, c: _params_from_hf(s, c, qkv_bias=True),
        params_to_hf=lambda p, c: _params_to_hf(p, c, qkv_bias=True),
    )
)

register_hf_family(
    HFFamily(
        name="qwen3",
        hf_architecture="Qwen3ForCausalLM",
        config_from_hf=lambda hf: _llama_like_config_from_hf(
            hf, use_qk_norm=True
        ),
        config_to_hf=lambda cfg: _llama_like_config_to_hf(
            cfg, "qwen3", "Qwen3ForCausalLM"
        ),
        params_from_hf=lambda s, c: _params_from_hf(s, c, qk_norm=True),
        params_to_hf=lambda p, c: _params_to_hf(p, c, qk_norm=True),
    )
)

register_hf_family(
    HFFamily(
        name="mistral",
        hf_architecture="MistralForCausalLM",
        config_from_hf=lambda hf: _llama_like_config_from_hf(hf),
        config_to_hf=lambda cfg: _llama_like_config_to_hf(
            cfg, "mistral", "MistralForCausalLM"
        ),
        params_from_hf=lambda s, c: _params_from_hf(s, c),
        params_to_hf=lambda p, c: _params_to_hf(p, c),
    )
)


def _gemma_config_from_hf(hf: Dict[str, Any]) -> TransformerConfig:
    cfg = _llama_like_config_from_hf(
        hf,
        activation="gelu",
        tied_embedding=True,
        embed_scale=float(np.sqrt(hf["hidden_size"])),
    )
    return cfg


register_hf_family(
    HFFamily(
        name="gemma",
        hf_architecture="GemmaForCausalLM",
        config_from_hf=_gemma_config_from_hf,
        config_to_hf=lambda cfg: _llama_like_config_to_hf(
            cfg,
            "gemma",
            "GemmaForCausalLM",
            hidden_act="gelu_pytorch_tanh",
        ),
        params_from_hf=lambda s, c: _params_from_hf(s, c, gemma_norm=True),
        params_to_hf=lambda p, c: _params_to_hf(p, c, gemma_norm=True),
    )
)

"""Shared converter core for sparse-MLP (MoE) HF families.

mixtral and qwen3_moe differ only in weight-key naming and qk-norm; one
parameterized pair of converters keeps them in lockstep (the same shape
llama_like.py uses for its five dense families).

``expert_names`` maps our (gate, down, up) order to the family's
per-expert Linear names; expert weights stack to [L, E, in, out] for the
ragged-dot MoE path (areal_tpu/models/moe.py).
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax.numpy as jnp
import numpy as np

from areal_tpu.models.config import TransformerConfig
from areal_tpu.models.hf.registry import StateDict, stack_layers, to_np


def moe_params_from_hf(
    state: StateDict,
    cfg: TransformerConfig,
    *,
    router_fmt: str,
    expert_fmt: str,
    expert_names: Tuple[str, str, str],  # (gate, down, up)
    qk_norm: bool = False,
) -> Dict[str, Any]:
    L, E = cfg.n_layers, cfg.n_experts
    g = lambda n: to_np(state[n])

    def layer_stack(fmt, transpose=True):
        mats = [g(fmt.format(i=i)) for i in range(L)]
        if transpose:
            mats = [m.T for m in mats]
        return jnp.asarray(stack_layers(mats))

    def expert_stack(w_name):  # -> [L, E, in, out]
        per_layer = []
        for i in range(L):
            per_exp = [
                g(expert_fmt.format(i=i, e=e, w=w_name)).T for e in range(E)
            ]
            per_layer.append(np.stack(per_exp, axis=0))
        return jnp.asarray(np.stack(per_layer, axis=0))

    attn: Dict[str, Any] = {
        "q": {"w": layer_stack("model.layers.{i}.self_attn.q_proj.weight")},
        "k": {"w": layer_stack("model.layers.{i}.self_attn.k_proj.weight")},
        "v": {"w": layer_stack("model.layers.{i}.self_attn.v_proj.weight")},
        "o": {"w": layer_stack("model.layers.{i}.self_attn.o_proj.weight")},
    }
    if qk_norm:
        attn["q_norm"] = {
            "scale": layer_stack(
                "model.layers.{i}.self_attn.q_norm.weight", transpose=False
            )
        }
        attn["k_norm"] = {
            "scale": layer_stack(
                "model.layers.{i}.self_attn.k_norm.weight", transpose=False
            )
        }

    gate_n, down_n, up_n = expert_names
    params: Dict[str, Any] = {
        "embed": {"weight": jnp.asarray(g("model.embed_tokens.weight"))},
        "layers": {
            "attn_norm": {
                "scale": layer_stack(
                    "model.layers.{i}.input_layernorm.weight", transpose=False
                )
            },
            "attn": attn,
            "mlp_norm": {
                "scale": layer_stack(
                    "model.layers.{i}.post_attention_layernorm.weight",
                    transpose=False,
                )
            },
            "mlp": {
                "router": {"w": layer_stack(router_fmt)},
                "experts": {
                    "gate": expert_stack(gate_n),
                    "down": expert_stack(down_n),
                    "up": expert_stack(up_n),
                },
            },
        },
        "final_norm": {"scale": jnp.asarray(g("model.norm.weight"))},
    }
    if not cfg.is_critic and not cfg.tied_embedding:
        params["lm_head"] = {"w": jnp.asarray(g("lm_head.weight").T)}
    return params


def moe_params_to_hf(
    params: Dict[str, Any],
    cfg: TransformerConfig,
    *,
    router_key: str,  # relative to "model.layers.{i}."
    expert_base: str,  # e.g. "block_sparse_moe.experts.{e}."
    expert_names: Tuple[str, str, str],  # (gate, down, up)
    qk_norm: bool = False,
) -> StateDict:
    out: StateDict = {}
    np_ = lambda x: np.asarray(x, np.float32)
    lay = params["layers"]
    gate_n, down_n, up_n = expert_names
    out["model.embed_tokens.weight"] = np_(params["embed"]["weight"])
    for i in range(cfg.n_layers):
        pre = f"model.layers.{i}."
        out[pre + "input_layernorm.weight"] = np_(lay["attn_norm"]["scale"][i])
        out[pre + "post_attention_layernorm.weight"] = np_(
            lay["mlp_norm"]["scale"][i]
        )
        for ours, theirs in (
            ("q", "q_proj"),
            ("k", "k_proj"),
            ("v", "v_proj"),
            ("o", "o_proj"),
        ):
            out[pre + f"self_attn.{theirs}.weight"] = np_(
                lay["attn"][ours]["w"][i]
            ).T
        if qk_norm:
            out[pre + "self_attn.q_norm.weight"] = np_(
                lay["attn"]["q_norm"]["scale"][i]
            )
            out[pre + "self_attn.k_norm.weight"] = np_(
                lay["attn"]["k_norm"]["scale"][i]
            )
        out[pre + router_key] = np_(lay["mlp"]["router"]["w"][i]).T
        for e in range(cfg.n_experts):
            base = pre + expert_base.format(e=e)
            out[base + f"{gate_n}.weight"] = np_(
                lay["mlp"]["experts"]["gate"][i, e]
            ).T
            out[base + f"{down_n}.weight"] = np_(
                lay["mlp"]["experts"]["down"][i, e]
            ).T
            out[base + f"{up_n}.weight"] = np_(
                lay["mlp"]["experts"]["up"][i, e]
            ).T
    out["model.norm.weight"] = np_(params["final_norm"]["scale"])
    if "lm_head" in params:
        out["lm_head.weight"] = np_(params["lm_head"]["w"]).T
    return out

"""HF family adapters.  Importing registers all families."""

from areal_tpu.models.hf import gpt2, llama_like, mixtral, qwen3_moe  # noqa: F401
from areal_tpu.models.hf.registry import (  # noqa: F401
    get_hf_family,
    load_hf_config,
    load_hf_model,
    save_hf_model,
)

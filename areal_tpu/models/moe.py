"""Mixture-of-Experts layer (mixtral-style top-k routing).

Rebuild of the reference's MoE stack (reference:
realhf/impl/model/modules/moe/router.py ``TopKRouter`` with aux/z losses,
moe/experts.py:21-131 grouped GEMM experts, moe/token_dispatcher.py
permute/unpermute) the TPU way: tokens are sorted by expert and the expert
matmuls run as a single ``jax.lax.ragged_dot`` — the MXU-native equivalent of
the CUDA ``grouped_gemm`` dependency.  Expert parallelism shards the [E, ...]
expert-weight dimension over the ``expert`` mesh axis (transformer.param_pspecs;
SURVEY §2.9 EP — a capability beyond the reference's local-only MoE).

Two EP regimes:

* Training leaves the partitioning to XLA's SPMD partitioner over the
  pspecs (the engine jits over the whole mesh and the partitioner keeps
  the [E, D, F] weights sharded through the backward pass).
* SERVING passes ``mesh`` explicitly: the expert compute runs under a
  fully-manual ``shard_map`` over the ``expert`` axis — each shard
  computes only the (token, k) pairs routed to ITS local experts from
  its local ``[E/ep, D, F]`` weight shard and a ``psum`` combines the
  partial outputs.  The router stays replicated (it is [D, E]-small);
  non-local pairs contribute exact zeros (their inputs are masked to
  zero, so silu(0)·0 → 0 flows through the down projection), which
  keeps the combine bitwise-faithful to the replicated layout for the
  usual K <= 2.  This is what lets a qwen3-moe-style model whose expert
  weights don't fit one chip SERVE at all: per-chip expert residency is
  E/ep, not E (the role Megatron's expert parallelism plays for the
  reference's training side, here on the decode/prefill hot path).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from areal_tpu.base import jax_compat
from areal_tpu.models import quantize
from areal_tpu.models.config import TransformerConfig


def init_moe_params(cfg: TransformerConfig, key: jax.Array) -> Dict[str, Any]:
    L, D, E = cfg.n_layers, cfg.hidden_dim, cfg.n_experts
    F = cfg.moe_intermediate_dim or cfg.intermediate_dim
    ks = jax.random.split(key, 4)

    def init(k, shape, fan_in):
        scale = 1.0 / np.sqrt(fan_in)
        return jax.random.uniform(
            k, shape, minval=-scale, maxval=scale, dtype=jnp.float32
        )

    return {
        "router": {"w": init(ks[0], (L, D, E), D)},
        "experts": {
            "gate": init(ks[1], (L, E, D, F), D),
            "up": init(ks[2], (L, E, D, F), D),
            "down": init(ks[3], (L, E, F, D), F),
        },
    }


def ep_axis_size(mesh) -> int:
    """Expert-parallel degree of a (possibly None) mesh."""
    if mesh is None:
        return 1
    return int(mesh.shape.get("expert", 1))


def _ep_expert_compute(
    cfg: TransformerConfig,
    mesh,
    x: jax.Array,  # [N, D] (compute dtype)
    topk_idx: jax.Array,  # [N, K] global expert ids
    gate_w: jax.Array,  # [E, D, F] sharded P("expert", None, None)
    up_w: jax.Array,
    down_w: jax.Array,  # [E, F, D]
) -> jax.Array:
    """Expert-parallel grouped compute: returns ``expert_out`` [N*K, D]
    in canonical (token, k) order, identical to the replicated path's
    unsorted output.

    Runs as a fully-manual ``shard_map`` over the serving mesh (the same
    pattern as the TP paged-attention kernel in
    ``models/paged._prefix_partials``): activations and routing are
    replicated in, expert weights arrive pre-sharded over ``expert``
    (the engine's serving pspecs shard the E axis ONLY, so no weight
    gather happens here), and each shard sorts its LOCAL (token, k)
    pairs by local expert id for one ragged_dot per projection.
    Non-local pairs are clamped into group 0 with their inputs zeroed —
    they flow exact zeros through silu/mul/down — and the final ``psum``
    over ``expert`` reassembles every pair from the one shard that owns
    its expert."""
    E, K = cfg.n_experts, cfg.n_experts_per_tok
    ep = ep_axis_size(mesh)
    assert E % ep == 0, (
        f"n_experts {E} not divisible by expert-parallel degree {ep}"
    )
    act_kind = cfg.activation
    from jax.sharding import PartitionSpec as P

    def local_fn(x, topk_idx, gate_w, up_w, down_w):
        e_local = gate_w.shape[0]  # E / ep
        e0 = jax.lax.axis_index("expert") * e_local
        flat = topk_idx.reshape(-1) - e0  # [N*K] local expert ids
        is_local = (flat >= 0) & (flat < e_local)
        key = jnp.where(is_local, flat, 0)
        order = jnp.argsort(key)
        inv_order = jnp.argsort(order)
        xs = jnp.repeat(x, K, axis=0)[order]
        # zeroed non-local rows ride group 0: their gate/up are exact
        # zeros, so the whole pair contributes 0 to the psum below
        xs = jnp.where(is_local[order][:, None], xs, 0)
        group_sizes = jnp.bincount(key, length=e_local).astype(jnp.int32)
        gate = jax.lax.ragged_dot(xs, gate_w, group_sizes)
        up = jax.lax.ragged_dot(xs, up_w, group_sizes)
        act = (
            jax.nn.silu(gate) if act_kind == "silu" else jax.nn.gelu(gate)
        )
        out = jax.lax.ragged_dot(act * up, down_w, group_sizes)
        return jax.lax.psum(out[inv_order], "expert")

    w_spec = P("expert", None, None)
    fn = jax_compat.shard_map(
        local_fn,
        mesh=mesh,
        in_specs=(P(None, None), P(None, None), w_spec, w_spec, w_spec),
        out_specs=P(None, None),
        check_vma=False,
    )
    return fn(x, topk_idx, gate_w, up_w, down_w)


def moe_mlp(
    cfg: TransformerConfig,
    h: jax.Array,
    p: Dict[str, Any],
    valid: jax.Array = None,
    mesh=None,
) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """h: [B, T, D] (per-layer params, no leading L).  Returns (out, aux)
    where aux carries the load-balancing and z losses
    (reference: realhf/impl/model/modules/moe/router.py aux-loss/z-loss).

    ``valid`` [B, T] bool masks padding out of the aux statistics — the
    reference router sees packed pad-free tokens, so including pads here
    would distort the load-balancing objective toward pad-token routing.

    ``mesh`` (serving only): a mesh whose ``expert`` axis is > 1 routes
    the expert compute through the explicit EP shard_map
    (:func:`_ep_expert_compute`) over locally-resident [E/ep, D, F]
    weight shards; None (training) leaves sharding to XLA's partitioner
    over the pspecs."""
    B, T, D = h.shape
    E, K = cfg.n_experts, cfg.n_experts_per_tok
    x = h.reshape(-1, D)
    N = x.shape[0]

    router_logits = (x.astype(jnp.float32)) @ p["router"]["w"].astype(
        jnp.float32
    )  # [N, E]
    probs = jax.nn.softmax(router_logits, axis=-1)
    topk_probs, topk_idx = jax.lax.top_k(probs, K)  # [N, K]
    if cfg.moe_norm_topk_prob:
        topk_probs = topk_probs / jnp.sum(topk_probs, axis=-1, keepdims=True)

    # aux losses over VALID tokens only
    if valid is None:
        vmask = jnp.ones((N,), jnp.float32)
    else:
        vmask = valid.reshape(-1).astype(jnp.float32)
    n_valid = jnp.maximum(jnp.sum(vmask), 1.0)
    me = jnp.sum(probs * vmask[:, None], axis=0) / n_valid  # [E]
    ce = (
        jnp.sum(
            jax.nn.one_hot(topk_idx, E).sum(axis=1) * vmask[:, None], axis=0
        )
        / n_valid
    )  # fraction routed per expert * K
    aux_loss = cfg.moe_aux_loss_coef * E * jnp.sum(me * ce) / K
    z_loss = cfg.moe_z_loss_coef * jnp.sum(
        jax.nn.logsumexp(router_logits, axis=-1) ** 2 * vmask
    ) / n_valid

    # leaf_weight serves both formats: plain arrays and the int8 serving
    # format's {"qw", "scale"} leaves.  Dequant happens at use, OUTSIDE
    # the EP shard_map: the qw/scale leaves are sharded over the same
    # ``expert`` axis (transformer.serving_param_pspecs), so the
    # partitioner dequantizes each shard's resident [E/ep, ...] slice
    # locally and the shard_map's in_specs see the layout they expect —
    # no gather, and per-chip residency stays E/ep at int8 bytes.
    gate_w = quantize.leaf_weight(p["experts"]["gate"], h.dtype)
    up_w = quantize.leaf_weight(p["experts"]["up"], h.dtype)
    down_w = quantize.leaf_weight(p["experts"]["down"], h.dtype)

    xd = x.astype(h.dtype)
    if ep_axis_size(mesh) > 1:
        # serving EP: explicit shard_map over the expert axis (already in
        # canonical (token, k) order — no global unsort needed)
        expert_out = _ep_expert_compute(
            cfg, mesh, xd, topk_idx, gate_w, up_w, down_w
        ).reshape(N, K, D)
    else:
        # dispatch: sort token-expert pairs by expert id
        flat_expert = topk_idx.reshape(-1)  # [N*K]
        order = jnp.argsort(flat_expert)
        inv_order = jnp.argsort(order)
        xs = jnp.repeat(xd, K, axis=0)[order]  # [N*K, D] grouped by expert
        group_sizes = jnp.bincount(flat_expert, length=E).astype(jnp.int32)

        gate = jax.lax.ragged_dot(xs, gate_w, group_sizes)
        up = jax.lax.ragged_dot(xs, up_w, group_sizes)
        act = (
            jax.nn.silu(gate)
            if cfg.activation == "silu"
            else jax.nn.gelu(gate)
        )
        expert_out = jax.lax.ragged_dot(
            act * up, down_w, group_sizes
        )  # [N*K, D]
        # combine: unsort, weight, sum over K
        expert_out = expert_out[inv_order].reshape(N, K, D)
    out = jnp.sum(expert_out * topk_probs[..., None].astype(h.dtype), axis=1)
    aux = {"moe_aux_loss": aux_loss, "moe_z_loss": z_loss}
    return out.reshape(B, T, D), aux

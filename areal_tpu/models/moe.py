"""Mixture-of-Experts layer (mixtral-style top-k routing).

Rebuild of the reference's MoE stack (reference:
realhf/impl/model/modules/moe/router.py ``TopKRouter`` with aux/z losses,
moe/experts.py:21-131 grouped GEMM experts, moe/token_dispatcher.py
permute/unpermute) the TPU way: tokens are sorted by expert and the expert
matmuls run as a single ``jax.lax.ragged_dot`` — the MXU-native equivalent of
the CUDA ``grouped_gemm`` dependency.  Expert parallelism shards the [E, ...]
expert-weight dimension over the ``expert`` mesh axis (transformer.param_pspecs;
SURVEY §2.9 EP — a capability beyond the reference's local-only MoE).
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from areal_tpu.models.config import TransformerConfig


def init_moe_params(cfg: TransformerConfig, key: jax.Array) -> Dict[str, Any]:
    L, D, E = cfg.n_layers, cfg.hidden_dim, cfg.n_experts
    F = cfg.moe_intermediate_dim or cfg.intermediate_dim
    ks = jax.random.split(key, 4)

    def init(k, shape, fan_in):
        scale = 1.0 / np.sqrt(fan_in)
        return jax.random.uniform(
            k, shape, minval=-scale, maxval=scale, dtype=jnp.float32
        )

    return {
        "router": {"w": init(ks[0], (L, D, E), D)},
        "experts": {
            "gate": init(ks[1], (L, E, D, F), D),
            "up": init(ks[2], (L, E, D, F), D),
            "down": init(ks[3], (L, E, F, D), F),
        },
    }


def moe_mlp(
    cfg: TransformerConfig,
    h: jax.Array,
    p: Dict[str, Any],
    valid: jax.Array = None,
) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """h: [B, T, D] (per-layer params, no leading L).  Returns (out, aux)
    where aux carries the load-balancing and z losses
    (reference: realhf/impl/model/modules/moe/router.py aux-loss/z-loss).

    ``valid`` [B, T] bool masks padding out of the aux statistics — the
    reference router sees packed pad-free tokens, so including pads here
    would distort the load-balancing objective toward pad-token routing."""
    B, T, D = h.shape
    E, K = cfg.n_experts, cfg.n_experts_per_tok
    x = h.reshape(-1, D)
    N = x.shape[0]

    router_logits = (x.astype(jnp.float32)) @ p["router"]["w"].astype(
        jnp.float32
    )  # [N, E]
    probs = jax.nn.softmax(router_logits, axis=-1)
    topk_probs, topk_idx = jax.lax.top_k(probs, K)  # [N, K]
    if cfg.moe_norm_topk_prob:
        topk_probs = topk_probs / jnp.sum(topk_probs, axis=-1, keepdims=True)

    # aux losses over VALID tokens only
    if valid is None:
        vmask = jnp.ones((N,), jnp.float32)
    else:
        vmask = valid.reshape(-1).astype(jnp.float32)
    n_valid = jnp.maximum(jnp.sum(vmask), 1.0)
    me = jnp.sum(probs * vmask[:, None], axis=0) / n_valid  # [E]
    ce = (
        jnp.sum(
            jax.nn.one_hot(topk_idx, E).sum(axis=1) * vmask[:, None], axis=0
        )
        / n_valid
    )  # fraction routed per expert * K
    aux_loss = cfg.moe_aux_loss_coef * E * jnp.sum(me * ce) / K
    z_loss = cfg.moe_z_loss_coef * jnp.sum(
        jax.nn.logsumexp(router_logits, axis=-1) ** 2 * vmask
    ) / n_valid

    # dispatch: sort token-expert pairs by expert id
    flat_expert = topk_idx.reshape(-1)  # [N*K]
    order = jnp.argsort(flat_expert)
    inv_order = jnp.argsort(order)
    xs = jnp.repeat(x, K, axis=0)[order]  # [N*K, D] grouped by expert
    group_sizes = jnp.bincount(flat_expert, length=E).astype(jnp.int32)

    gate_w = p["experts"]["gate"].astype(h.dtype)
    up_w = p["experts"]["up"].astype(h.dtype)
    down_w = p["experts"]["down"].astype(h.dtype)

    gate = jax.lax.ragged_dot(xs, gate_w, group_sizes)
    up = jax.lax.ragged_dot(xs, up_w, group_sizes)
    act = jax.nn.silu(gate) if cfg.activation == "silu" else jax.nn.gelu(gate)
    expert_out = jax.lax.ragged_dot(act * up, down_w, group_sizes)  # [N*K, D]

    # combine: unsort, weight, sum over K
    expert_out = expert_out[inv_order].reshape(N, K, D)
    out = jnp.sum(expert_out * topk_probs[..., None].astype(h.dtype), axis=1)
    aux = {"moe_aux_loss": aux_loss, "moe_z_loss": z_loss}
    return out.reshape(B, T, D), aux

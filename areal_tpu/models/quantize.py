"""int8 serving-weight quantization (per-output-channel absmax).

The serving fleet's second-largest HBM consumer after the paged KV pool
is the full-precision param tree.  This module defines the storage
format that halves it (and halves the bytes a staged weight swap must
restore): every MATMUL weight — attention q/k/v/o, dense MLP
gate/up/down, MoE expert stacks, the untied lm_head — is stored as an
``int8`` tensor plus one float32 symmetric absmax scale per OUTPUT
channel (the weight's last axis; for stacked/expert weights the scale
keeps every leading axis, so a ``[L, D, F]`` weight carries a ``[L, F]``
scale).  Norm scales/biases, embeddings, the router, and the critic
value head stay at model dtype — they are tiny, and their error
sensitivity is disproportionate.

In the param tree a quantized leaf replaces its weight array with a
``{"qw": int8, "scale": f32}`` dict (biases ride alongside unchanged),
so one tree walks through ``lax.scan`` layer stacking, orbax
checkpointing, and the staged-restore chunker exactly like the
full-precision tree.  Consumers dequantize AT USE — ``w = qw * scale``
fused in front of each projection (transformer._proj, moe.moe_mlp) — so
the matmul math runs at the activation dtype like the full-precision
path and the only error is storage rounding.  This is the role SGLang's
``--quantization`` / vLLM's int8 weight loading play for AReaL's
serving side.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

#: storage bits per quantized weight element (the metrics gauge)
STORAGE_BITS = 8

#: projection names whose "w" (or expert stack) leaves quantize
_PROJ_NAMES = ("q", "k", "v", "o", "gate", "up", "down")


def quantizable(keys: Tuple[str, ...]) -> bool:
    """True iff the leaf at key path ``keys`` is a matmul weight the int8
    serving format quantizes.  Everything else (norms, biases,
    embeddings, the MoE router, the critic value head) stays model
    dtype."""
    if (
        len(keys) >= 3
        and keys[-1] == "w"
        and keys[-2] in _PROJ_NAMES
        and ("attn" in keys or "mlp" in keys)
    ):
        return True
    if keys == ("lm_head", "w"):
        return True
    # MoE expert stacks are bare [L, E, D, F] leaves named gate/up/down
    if len(keys) >= 2 and keys[-2] == "experts" and keys[-1] in _PROJ_NAMES:
        return True
    return False


def quantize_weight(w: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """``(qw int8, scale f32)`` with one symmetric absmax scale per
    output channel: scale shape is ``w.shape`` minus the input axis
    (``-2``).  All-zero channels get a tiny scale so the divide is
    finite and dequantizes back to exact zeros."""
    w32 = jnp.asarray(w).astype(jnp.float32)
    absmax = jnp.max(jnp.abs(w32), axis=-2)
    scale = jnp.maximum(absmax, 1e-12) / 127.0
    q = jnp.clip(
        jnp.round(w32 / scale[..., None, :]), -127, 127
    ).astype(jnp.int8)
    return q, scale


def dequant_weight(qw: jax.Array, scale: jax.Array, dtype) -> jax.Array:
    """``qw * scale`` at use.  The multiply runs in f32 (the scale's
    dtype) and casts to the activation dtype AFTER, so the storage
    rounding is the only error a reduced-precision activation path adds
    on top of its own."""
    return (
        qw.astype(jnp.float32) * scale[..., None, :].astype(jnp.float32)
    ).astype(dtype)


def is_quant_leaf(p) -> bool:
    """True for a ``{"qw", "scale"}`` quantized-projection dict."""
    return isinstance(p, dict) and "qw" in p


def leaf_weight(p, dtype) -> jax.Array:
    """The compute-dtype weight of a projection leaf that is either a
    plain array, a ``{"w": ...}`` dict, or a quantized ``{"qw",
    "scale"}`` dict — ONE accessor so every forward path serves both
    formats."""
    if isinstance(p, dict):
        if "qw" in p:
            return dequant_weight(p["qw"], p["scale"], dtype)
        p = p["w"]
    return p.astype(dtype)


def _transform(tree, quant_fn, other_fn):
    """Structure-preserving walk that rewrites quantizable weights: a
    ``{"w": ...}`` projection's weight entry is REPLACED in its parent
    dict by whatever ``quant_fn`` returns (so ``qw``/``scale`` sit next
    to an existing bias), while bare expert-stack leaves are replaced in
    place (``{"gate": arr}`` -> ``{"gate": {"qw", "scale"}}``)."""

    def walk(d, prefix):
        out = {}
        for k, v in d.items():
            kp = prefix + (str(k),)
            if isinstance(v, dict):
                out[k] = walk(v, kp)
            elif quantizable(kp):
                rep = quant_fn(kp, v)
                if k == "w":
                    out.update(rep)
                else:
                    out[k] = rep
            else:
                out[k] = other_fn(kp, v)
        return out

    return walk(tree, ())


def quantize_param_tree(params: Dict[str, Any]) -> Dict[str, Any]:
    """The int8 serving tree of a full-precision param tree: quantizable
    weights become ``{"qw", "scale"}`` pairs (``{"w"}`` projections lose
    the ``w`` entry, biases/norms ride along unchanged), everything else
    is the original leaf (same object — no copy).  Idempotent on an
    already-quantized tree (its ``qw``/``scale`` leaves are not
    quantizable paths)."""

    def quant(keys, leaf):
        qw, scale = quantize_weight(leaf)
        return {"qw": qw, "scale": scale}

    return _transform(params, quant, lambda keys, leaf: leaf)


def quant_tree_struct(params: Dict[str, Any]) -> Dict[str, Any]:
    """Abstract (ShapeDtypeStruct) int8-serving-tree template derived
    from a params tree of arrays OR structs (full-precision or already
    quantized) — no compute, no transfer.  The staged-restore path uses
    this as its placement template when the engine negotiated the
    quantized snapshot format."""

    def quant(keys, leaf):
        shape = tuple(leaf.shape)
        return {
            "qw": jax.ShapeDtypeStruct(shape, jnp.int8),
            "scale": jax.ShapeDtypeStruct(
                shape[:-2] + shape[-1:], jnp.float32
            ),
        }

    def other(keys, leaf):
        return jax.ShapeDtypeStruct(tuple(leaf.shape), jnp.dtype(leaf.dtype))

    return _transform(params, quant, other)


def is_quantized_tree(params) -> bool:
    """True iff ``params`` holds at least one int8 ``{"qw", "scale"}``
    leaf (i.e. it is a serving tree in the quantized format)."""
    found = False

    def walk(tree):
        nonlocal found
        if found or not isinstance(tree, dict):
            return
        if "qw" in tree:
            found = True
            return
        for v in tree.values():
            walk(v)

    walk(params)
    return found


def quantized_leaf_count(params) -> int:
    """Number of ``{"qw", "scale"}`` projection leaves in the tree (the
    metrics gauge; 0 for a full-precision tree)."""
    n = 0

    def walk(tree):
        nonlocal n
        if not isinstance(tree, dict):
            return
        if "qw" in tree:
            n += 1
            return
        for v in tree.values():
            walk(v)

    walk(params)
    return n


def tree_bytes(params) -> int:
    """Total leaf bytes of a param tree (HBM footprint of the serving
    weights; int8 trees come out at roughly half the model-dtype tree)."""
    return sum(
        int(np.prod(leaf.shape)) * jnp.dtype(leaf.dtype).itemsize
        if hasattr(leaf, "shape")
        else 0
        for leaf in jax.tree_util.tree_leaves(params)
    )

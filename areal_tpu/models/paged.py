"""Paged-KV forward paths: chunked prefill + chunked decode over a block
pool.

TPU-native replacement for the paged/radix KV machinery the reference gets
from SGLang (reference: realhf/impl/model/backend/sglang.py:369 and the
server patched by patch/sglang/v0.4.6.post2.patch; SURVEY §2.8 names
"splash/paged attention kernels" as the TPU equivalent).  The serving
engine (areal_tpu/engine/inference_server.py) owns the host-side block
allocator; this module owns the device-side compute:

* the KV pool is ``[L, NB, Hkv, BS, hd]`` (PAGE-major: one page is one
  contiguous HBM extent) — NB fixed-size blocks shared by all rows; a
  row's cache is the ordered block list in its table row ``[MB]`` (pool
  block id per logical block);
* :func:`paged_fill_chunk` runs ONE chunk of prompt prefill for a batch of
  filling rows: in-chunk causal self-attention merged online with
  paged-kernel partials over each row's already-cached prefix — so a 16k
  prompt admits as 16 × 1k chunks interleaved with decode steps instead of
  one decode-stalling wave (chunked prefill, the round-4 verdict's #1/#2);
* :func:`paged_decode_chunk` mirrors ``transformer.decode_chunk``'s
  window design (in-chunk KV in a small contiguous window, ONE pool
  scatter per chunk) with the paged kernel streaming each row's valid
  blocks — cost scales with the row's true length, not a padded bucket.

Every function threads the pool through donated jit args; the layered
kernel entry reads blocks straight from the stacked pool so no per-layer
pool slice is ever materialized.

**Quantized KV storage** (``kv_cache_dtype="int8"``): the k/v pools
store int8 with a float32 scale pool ``[L, NB, Hkv, BS]`` alongside —
one absmax scale per (block, head, page slot).  The slot axis is what
makes append-only pages exact: a single per-(block, head) scale would
need a read-modify-write requantization of the whole block every time
decode appends one token to the tail page, while per-slot scales let
every write path quantize just the values it scatters.  Writes quantize
at insert (:func:`quantize_kv` before the pool scatter in
:func:`paged_window_forward` / :func:`paged_decode_chunk`'s chunk-end
merge); reads dequantize inline right after the block gather (the jnp
reference path and both Pallas kernels multiply by scales before the
attention dots), so attention math stays in model dtype and the
accuracy loss is storage-only.  Every function below accepts optional
``k_scale``/``v_scale`` operands (None = unquantized, today's
behavior) and returns them updated whenever it returns the pools.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from areal_tpu.engine.sampling import call_sample_fn
from areal_tpu.models.config import TransformerConfig
from areal_tpu.models.transformer import (
    Params,
    _attn_qkv,
    _embed,
    _head,
    _mlp_block,
    _norm,
    _proj,
    rope_tables,
)
from areal_tpu.ops.paged_attention import (
    paged_flash_attention,
    paged_flash_attention_deep,
    reference_paged_partials,
)

_NEG_INF = -1e30


def pool_zeros(
    cfg: TransformerConfig, n_blocks: int, block_size: int, dtype=None
) -> Tuple[jax.Array, jax.Array]:
    """Allocate the (k, v) block pools ``[L, NB, Hkv, BS, hd]`` —
    PAGE-major so one page is one contiguous HBM extent (the kernel reads
    a page's every head in a single DMA)."""
    dtype = dtype or jnp.dtype(cfg.dtype)
    shape = (
        cfg.n_layers, n_blocks, cfg.n_kv_heads, block_size, cfg.head_dim
    )
    return jnp.zeros(shape, dtype), jnp.zeros(shape, dtype)


#: int8 symmetric absmax range (one sign bit + 7 magnitude bits; -128 is
#: never produced so quantize/dequantize round-trips are symmetric)
KV_QUANT_MAX = 127.0


def alloc_kv_pool(
    cfg: TransformerConfig,
    n_blocks: int,
    block_size: int,
    kv_cache_dtype: str = "auto",
    dtype=None,
) -> Tuple[jax.Array, jax.Array, Optional[jax.Array], Optional[jax.Array]]:
    """Allocate the paged KV storage: ``(k_pool, v_pool, k_scale,
    v_scale)``.

    ``kv_cache_dtype="auto"`` keeps today's model-dtype pools (scales are
    None); ``"int8"`` allocates int8 pools plus float32 scale pools
    ``[L, NB, Hkv, BS]`` — one absmax scale per (block, head, page slot),
    so the storage cost per cached token-head drops from ``2 * hd *
    itemsize(model dtype)`` to ``2 * (hd + 4)`` bytes."""
    if kv_cache_dtype == "auto":
        k, v = pool_zeros(cfg, n_blocks, block_size, dtype=dtype)
        return k, v, None, None
    if kv_cache_dtype != "int8":
        raise ValueError(
            f"kv_cache_dtype must be 'auto' or 'int8', got {kv_cache_dtype!r}"
        )
    shape = (
        cfg.n_layers, n_blocks, cfg.n_kv_heads, block_size, cfg.head_dim
    )
    sshape = shape[:-1]
    return (
        jnp.zeros(shape, jnp.int8),
        jnp.zeros(shape, jnp.int8),
        jnp.zeros(sshape, jnp.float32),
        jnp.zeros(sshape, jnp.float32),
    )


def kv_pool_layout_bytes(
    cfg: TransformerConfig,
    n_blocks: int,
    block_size: int,
    kv_cache_dtype: str = "auto",
    dtype=None,
) -> Tuple[int, int]:
    """``(pool_bytes, scale_bytes)`` that :func:`alloc_kv_pool` with the
    same arguments will allocate — pure arithmetic, no device memory.
    The HBM ledger sizes its ``kv_pool``/``kv_scales`` attributions from
    this (the allocation itself runs under jit, where a host-side ledger
    call cannot live); ``scale_bytes`` is 0 for fp pools."""
    shape = (
        cfg.n_layers, n_blocks, cfg.n_kv_heads, block_size, cfg.head_dim
    )
    n = 1
    for d in shape:
        n *= int(d)
    if kv_cache_dtype == "int8":
        # k + v int8 data, k + v float32 scale pools [L, NB, Hkv, BS]
        return 2 * n, 2 * (n // cfg.head_dim) * 4
    itemsize = jnp.dtype(dtype or cfg.dtype).itemsize
    return 2 * n * itemsize, 0


def quantize_kv(vals: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Symmetric absmax int8 quantization over the trailing head_dim
    axis: returns ``(int8 values, float32 scales)`` with scales shaped
    like ``vals`` minus its last axis.  All-zero vectors quantize to
    zeros with scale 0 (the dequant multiply reproduces them exactly)."""
    v32 = vals.astype(jnp.float32)
    scale = jnp.max(jnp.abs(v32), axis=-1) / KV_QUANT_MAX
    q = v32 / jnp.maximum(scale, 1e-30)[..., None]
    q = jnp.clip(
        jnp.round(q), -KV_QUANT_MAX, KV_QUANT_MAX
    ).astype(jnp.int8)
    return q, scale


def _prefix_partials(
    q, k_pool, v_pool, tables, lengths, layer, use_kernel,
    mesh=None, kv_axis=None, deep=False, k_scale=None, v_scale=None,
):
    """Paged-attention partials over each row's cached prefix.  ``q`` is
    [B, Q, Hq, hd]; returns (acc, m, l) with Q query tokens per row.

    ``k_scale``/``v_scale`` mark an int8-quantized pool: both the kernel
    and the jnp reference dequantize (multiply by the per-(block, head,
    slot) scales) right after the block gather, so attention math is
    identical to the unquantized path up to storage rounding.

    On a TP serving mesh the Pallas kernel has no SPMD partitioning rule,
    so it runs under an explicit ``shard_map``: the pool's kv-head axis
    and q's head axis split over ``kv_axis`` (or fully replicated when
    the head count doesn't divide), each shard streaming only its own
    heads' pages (code-review r5 #2)."""
    if use_kernel:
        kernel_fn = (
            paged_flash_attention_deep if deep else paged_flash_attention
        )
        interp = jax.default_backend() != "tpu"
        if mesh is not None:
            from jax.experimental.shard_map import shard_map
            from jax.sharding import PartitionSpec as P

            layered = k_pool.ndim == 5
            pool_spec = (
                P(None, None, kv_axis, None, None)
                if layered
                else P(None, kv_axis, None, None)
            )
            scale_spec = (
                P(None, None, kv_axis, None)
                if layered
                else P(None, kv_axis, None)
            )
            out_specs = (
                P(None, None, kv_axis, None),
                P(None, None, kv_axis),
                P(None, None, kv_axis),
            )
            common = dict(mesh=mesh, out_specs=out_specs, check_rep=False)
            if k_scale is None:

                def kern(qq, kk, vv, tb, ln, ly):
                    return kernel_fn(
                        qq, kk, vv, tb, ln, layer=ly, interpret=interp
                    )

                fn = shard_map(
                    kern,
                    in_specs=(
                        P(None, None, kv_axis, None),
                        pool_spec,
                        pool_spec,
                        P(None, None),
                        P(None),
                        P(None),
                    ),
                    **common,
                )
                return fn(
                    q, k_pool, v_pool, tables, lengths,
                    jnp.asarray(layer, jnp.int32).reshape(1),
                )

            def kern_q(qq, kk, vv, ks, vs, tb, ln, ly):
                return kernel_fn(
                    qq, kk, vv, tb, ln, layer=ly, interpret=interp,
                    k_scale=ks, v_scale=vs,
                )

            fn = shard_map(
                kern_q,
                in_specs=(
                    P(None, None, kv_axis, None),
                    pool_spec,
                    pool_spec,
                    scale_spec,
                    scale_spec,
                    P(None, None),
                    P(None),
                    P(None),
                ),
                **common,
            )
            return fn(
                q, k_pool, v_pool, k_scale, v_scale, tables, lengths,
                jnp.asarray(layer, jnp.int32).reshape(1),
            )
        return kernel_fn(
            q, k_pool, v_pool, tables, lengths, layer=layer,
            interpret=interp, k_scale=k_scale, v_scale=v_scale,
        )
    kl = jax.lax.dynamic_index_in_dim(k_pool, layer, 0, keepdims=False)
    vl = jax.lax.dynamic_index_in_dim(v_pool, layer, 0, keepdims=False)
    ksl = vsl = None
    if k_scale is not None:
        ksl = jax.lax.dynamic_index_in_dim(k_scale, layer, 0, keepdims=False)
        vsl = jax.lax.dynamic_index_in_dim(v_scale, layer, 0, keepdims=False)
    return reference_paged_partials(
        q, kl, vl, tables, lengths, k_scale=ksl, v_scale=vsl
    )


def paged_window_forward(
    params: Params,
    k_pool: jax.Array,  # [L, NB, Hkv, BS, hd]
    v_pool: jax.Array,
    cfg: TransformerConfig,
    tokens: jax.Array,  # [F, C] window tokens (right-padded)
    starts: jax.Array,  # [F] tokens already cached per row (window offset)
    valid: jax.Array,  # [F, C] bool: positions to compute + scatter
    tables: jax.Array,  # [F, MB] pool block ids
    use_kernel: bool,
    mesh=None,
    kv_axis=None,
    k_scale: Optional[jax.Array] = None,  # [L, NB, Hkv, BS] (int8 pool)
    v_scale: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array, jax.Array, Optional[jax.Array],
           Optional[jax.Array]]:
    """Forward a short token WINDOW for F rows over their cached paged
    prefixes: in-window causal self-attention merged online with the
    paged kernel's partials over ``[0, start)``, window KV scattered into
    the rows' pool blocks (invalid positions dropped).  Shared core of
    chunked prefill (:func:`paged_fill_chunk`) and the speculative-decode
    verify step (engine/spec_decode.py) — verify IS a batched paged
    prefill of the draft window, so both paths ride the same attention
    math and the same pool scatter.  Returns ``(x [F, C, D], k_pool,
    v_pool, k_scale, v_scale)`` with ``x`` the final hidden states
    (pre-head); the scales pass through as None on unquantized pools.

    On an int8 pool the window KV is computed in model dtype, quantized
    per (token, head) right before the scatter, and its scales land in
    the scale pools through the same (pid, off) coordinates.

    Callers jit this (it is not jitted itself); the pools thread through
    donated args of the enclosing jit."""
    F, C = tokens.shape
    L, NB, Hkv, BS, hd = k_pool.shape
    r = cfg.n_q_heads // Hkv
    positions = starts[:, None] + jnp.arange(C, dtype=jnp.int32)[None, :]
    # masked rows must stream zero prefix blocks (their ``starts`` may be
    # any live length — e.g. non-participant rows of a verify window)
    read_lens = jnp.where(valid[:, 0], starts, 0)
    x = _embed(params, cfg, tokens, positions)
    rope_cs = (
        None
        if cfg.abs_position_embedding
        else rope_tables(positions, cfg.rotary_base, cfg.head_dim)
    )
    iot = jnp.arange(C)
    mask_chunk = (
        valid[:, None, :]
        & valid[:, :, None]
        & (iot[:, None] >= iot[None, :])
    )  # [F, Cq, Ckv] causal
    # pool write coordinates for every chunk token
    pid_log = jnp.clip(positions // BS, 0, tables.shape[1] - 1)
    pid = jnp.take_along_axis(tables, pid_log, axis=1)
    pid = jnp.where(valid, pid, NB)  # invalid -> OOB -> dropped
    off = positions % BS
    seg_ids = valid.astype(jnp.int32)
    scale = 1.0 / np.sqrt(hd)

    def body(carry, xs):
        x, k_pool, v_pool, k_scale, v_scale = carry
        lp, l = xs
        h = _norm(x, lp["attn_norm"], cfg)
        q, k, v = _attn_qkv(cfg, lp, h, positions, rope_cs)
        acc_p, m_p, l_p = _prefix_partials(
            q, k_pool, v_pool, tables, read_lens, l, use_kernel,
            mesh=mesh, kv_axis=kv_axis, k_scale=k_scale, v_scale=v_scale,
        )
        # in-chunk causal scores (C <= ~1k keeps [F,Hq,C,C] small)
        qg = q.reshape(F, C, Hkv, r, hd)
        s_c = (
            jnp.einsum(
                "fikrd,fjkd->fkrij",
                qg.astype(jnp.float32),
                k.astype(jnp.float32),
            )
            * scale
        )  # [F, Hkv, r, Cq, Ckv]
        s_c = jnp.where(mask_chunk[:, None, None, :, :], s_c, _NEG_INF)
        accp = acc_p.reshape(F, C, Hkv, r, hd).transpose(0, 2, 3, 1, 4)
        mp = m_p.reshape(F, C, Hkv, r).transpose(0, 2, 3, 1)
        lpp = l_p.reshape(F, C, Hkv, r).transpose(0, 2, 3, 1)
        # online merge of prefix partials with the in-chunk scores
        m_tot = jnp.maximum(mp, jnp.max(s_c, axis=-1))
        p_c = jnp.exp(s_c - m_tot[..., None])
        alpha = jnp.exp(mp - m_tot)
        num = accp * alpha[..., None] + jnp.einsum(
            "fkrij,fjkd->fkrid", p_c, v.astype(jnp.float32)
        )
        den = lpp * alpha + jnp.sum(p_c, axis=-1)
        attn = (num / jnp.maximum(den, 1e-30)[..., None]).astype(x.dtype)
        attn = (
            attn.transpose(0, 3, 1, 2, 4)
            .reshape(F, C, cfg.n_q_heads * hd)
        )
        x = x + _proj(lp["attn"]["o"], attn)
        h2 = _norm(x, lp["mlp_norm"], cfg)
        mlp_out, _ = _mlp_block(cfg, lp, h2, seg_ids=seg_ids, mesh=mesh)
        x = x + mlp_out
        # scatter chunk KV into the pool (in-place on the donated carry);
        # advanced indices split by the Hkv slice -> result [F, C, Hkv, hd]
        if k_scale is not None:
            kq, ks = quantize_kv(k)
            vq, vs = quantize_kv(v)
            k_pool = k_pool.at[l, pid, :, off].set(kq, mode="drop")
            v_pool = v_pool.at[l, pid, :, off].set(vq, mode="drop")
            # scale pools [L, NB, Hkv, BS]: same (pid, off) coordinates,
            # advanced indices split by the Hkv slice -> [F, C, Hkv]
            k_scale = k_scale.at[l, pid, :, off].set(ks, mode="drop")
            v_scale = v_scale.at[l, pid, :, off].set(vs, mode="drop")
        else:
            k_pool = k_pool.at[l, pid, :, off].set(
                k.astype(k_pool.dtype), mode="drop"
            )
            v_pool = v_pool.at[l, pid, :, off].set(
                v.astype(v_pool.dtype), mode="drop"
            )
        return (x, k_pool, v_pool, k_scale, v_scale), None

    (x, k_pool, v_pool, k_scale, v_scale), _ = jax.lax.scan(
        body,
        (x, k_pool, v_pool, k_scale, v_scale),
        (params["layers"], jnp.arange(L)),
    )
    return x, k_pool, v_pool, k_scale, v_scale


@partial(
    jax.jit,
    static_argnames=("cfg", "use_kernel", "mesh", "kv_axis"),
    donate_argnums=(1, 2),
    donate_argnames=("k_scale", "v_scale"),
)
def paged_fill_chunk(
    params: Params,
    k_pool: jax.Array,  # [L, NB, Hkv, BS, hd]
    v_pool: jax.Array,
    cfg: TransformerConfig,
    tokens: jax.Array,  # [F, C] this chunk's tokens (right-padded)
    starts: jax.Array,  # [F] tokens already cached per row (chunk offset)
    chunk_lens: jax.Array,  # [F] valid tokens in this chunk
    tables: jax.Array,  # [F, MB] pool block ids
    use_kernel: bool,
    mesh=None,
    kv_axis=None,
    k_scale: Optional[jax.Array] = None,  # [L, NB, Hkv, BS] (int8 pool)
    v_scale: Optional[jax.Array] = None,
):
    """One prefill chunk for F filling rows.

    Each row's chunk tokens attend causally within the chunk AND over the
    row's already-cached prefix ``[0, start)`` via paged partials — an
    exact continuation of the row's prefill no matter how the prompt was
    split into chunks.  Chunk KV is scattered into the rows' pool blocks
    (the engine pre-allocated blocks covering ``start + chunk_len``);
    int8 pools quantize at the scatter and land scales alongside.

    Returns ``(last_logits [F, V], k_pool, v_pool)`` — plus ``(k_scale,
    v_scale)`` when the pool is quantized — logits at each row's LAST
    valid chunk position (only meaningful on a row's final chunk, where
    the engine samples the first generated token).
    """
    C = tokens.shape[1]
    valid = jnp.arange(C)[None, :] < chunk_lens[:, None]  # [F, C]
    x, k_pool, v_pool, k_scale, v_scale = paged_window_forward(
        params, k_pool, v_pool, cfg, tokens, starts, valid, tables,
        use_kernel=use_kernel, mesh=mesh, kv_axis=kv_axis,
        k_scale=k_scale, v_scale=v_scale,
    )
    last_idx = jnp.maximum(chunk_lens - 1, 0)
    x_last = jnp.take_along_axis(x, last_idx[:, None, None], axis=1)
    logits = _head(params, cfg, x_last)[:, 0]  # [F, V]
    if k_scale is None:
        return logits, k_pool, v_pool
    return logits, k_pool, v_pool, k_scale, v_scale


@partial(
    jax.jit,
    static_argnames=(
        "cfg", "chunk_size", "use_kernel", "max_len", "sample_fn",
        "stop_fn", "mesh", "kv_axis", "deep_kernel",
    ),
    donate_argnums=(1, 2),
    donate_argnames=("k_scale", "v_scale"),
)
def paged_decode_chunk(
    params: Params,
    k_pool: jax.Array,  # [L, NB, Hkv, BS, hd]
    v_pool: jax.Array,
    cfg: TransformerConfig,
    tables: jax.Array,  # [B, MB]
    lengths: jax.Array,  # [B] valid cache prefix per row
    cur_tokens: jax.Array,  # [B] pending token per row (KV not yet cached)
    active: jax.Array,  # [B] bool
    budgets: jax.Array,  # [B] remaining new tokens (incl. pending cur)
    rng: jax.Array,
    chunk_size: int,
    sample_fn,  # (logits_f32 [B,V], rng[, positions[, row_seeds]])
    stop_fn,  # (tokens [B]) -> [B] bool
    use_kernel: bool,
    max_len: int,
    mesh=None,
    kv_axis=None,
    deep_kernel: bool = False,
    row_seeds: Optional[jax.Array] = None,  # [B] per-request sampler keys
    k_scale: Optional[jax.Array] = None,  # [L, NB, Hkv, BS] (int8 pool)
    v_scale: Optional[jax.Array] = None,
):
    """Generate up to ``chunk_size`` tokens for all active rows device-side
    over the paged pool (the paged twin of ``transformer.decode_chunk``).

    In-chunk KV goes to a [L, W, B, Hkv, hd] window written at scalar
    offsets — always in MODEL dtype, even over an int8 pool, so in-chunk
    attention pays zero quantization error; prefix attention streams each
    row's valid blocks through the paged kernel (inactive rows read ZERO
    blocks — their read length is masked, unlike the dense path whose
    cost scaled with the padded bucket); the window merges into pool
    blocks ONCE per chunk through the block tables (int8 pools quantize
    at that merge, scales landing through the same coordinates).  The
    engine guarantees every active row's table covers ``length +
    chunk_size`` slots before dispatch.

    Returns (k_pool, v_pool, lengths, out_t [B,W], out_l [B,W],
    emitted [B,W], cur_tokens, active, budgets, rng) — with
    ``(k_scale, v_scale)`` appended when the pool is quantized.
    """
    assert cfg.sliding_window is None, (
        "paged decode serves global-attention models; sliding-window "
        "models use the dense window-gather path"
    )
    B = cur_tokens.shape[0]
    W = chunk_size
    L, NB, Hkv, BS, hd = k_pool.shape
    r = cfg.n_q_heads // Hkv
    base_lens = lengths  # frozen: pool-resident prefix per row
    # dead rows stream nothing (parked/freed rows keep their lengths)
    read_lens = jnp.where(active, base_lens, 0)
    scale = 1.0 / np.sqrt(hd)

    win_dtype = (
        jnp.dtype(cfg.dtype) if k_scale is not None else k_pool.dtype
    )
    wk = jnp.zeros((L, W, B, Hkv, hd), win_dtype)
    wv = jnp.zeros((L, W, B, Hkv, hd), win_dtype)
    wvalid0 = jnp.zeros((W, B), bool)

    def step(i, st):
        (lengths_, cur, active, budgets, k_pool, v_pool, wk, wv, wvalid,
         out_t, out_l, emitted, rng) = st
        positions = lengths_[:, None]
        x = _embed(params, cfg, cur[:, None], positions)
        rope_cs = (
            None
            if cfg.abs_position_embedding
            else rope_tables(positions, cfg.rotary_base, cfg.head_dim)
        )
        wvalid = wvalid.at[i].set(active)
        mask_win = wvalid.T[:, None, None, None, :]  # [B,1,1,1,W]

        def body(carry, xs):
            x, wk, wv = carry
            lp, l = xs
            h = _norm(x, lp["attn_norm"], cfg)
            q, k, v = _attn_qkv(cfg, lp, h, positions, rope_cs)
            wk = jax.lax.dynamic_update_slice(
                wk, k.swapaxes(0, 1)[None].astype(wk.dtype), (l, i, 0, 0, 0)
            )
            wv = jax.lax.dynamic_update_slice(
                wv, v.swapaxes(0, 1)[None].astype(wv.dtype), (l, i, 0, 0, 0)
            )
            wk_l = jax.lax.dynamic_index_in_dim(wk, l, 0, keepdims=False)
            wv_l = jax.lax.dynamic_index_in_dim(wv, l, 0, keepdims=False)
            qg = q.reshape(B, 1, Hkv, r, hd)
            s_win = (
                jnp.einsum(
                    "btkrd,wbkd->bkrtw", qg, wk_l.astype(qg.dtype),
                    preferred_element_type=jnp.float32,
                )
                * scale
            )
            s_win = jnp.where(mask_win, s_win, _NEG_INF)  # [B,Hkv,r,1,W]
            acc, m_main, l_main = _prefix_partials(
                q, k_pool, v_pool, tables, read_lens, l, use_kernel,
                mesh=mesh, kv_axis=kv_axis, deep=deep_kernel,
                k_scale=k_scale, v_scale=v_scale,
            )
            acc = acc.reshape(B, Hkv, r, hd)
            m_main = m_main.reshape(B, Hkv, r)
            l_main = l_main.reshape(B, Hkv, r)
            sw = s_win[:, :, :, 0, :]  # [B,Hkv,r,W]
            m_tot = jnp.maximum(m_main, jnp.max(sw, axis=-1))
            p_win = jnp.exp(sw - m_tot[..., None])
            alpha = jnp.exp(m_main - m_tot)
            num = acc * alpha[..., None] + jnp.einsum(
                "bkrw,wbkd->bkrd", p_win, wv_l.astype(jnp.float32)
            )
            den = l_main * alpha + jnp.sum(p_win, axis=-1)
            attn = (num / jnp.maximum(den, 1e-30)[..., None]).astype(
                x.dtype
            )
            attn = attn.reshape(B, 1, cfg.n_q_heads * hd)
            x = x + _proj(lp["attn"]["o"], attn)
            h2 = _norm(x, lp["mlp_norm"], cfg)
            mlp_out, _ = _mlp_block(cfg, lp, h2, mesh=mesh)
            x = x + mlp_out
            return (x, wk, wv), None

        (x, wk, wv), _ = jax.lax.scan(
            body, (x, wk, wv), (params["layers"], jnp.arange(L))
        )
        logits = _head(params, cfg, x)[:, 0]
        rng, sub = jax.random.split(rng)
        tok, logp = call_sample_fn(
            sample_fn, logits.astype(jnp.float32), sub, lengths_ + 1,
            row_seeds,
        )
        tok = jnp.where(active, tok, 0)
        out_t = out_t.at[:, i].set(tok)
        out_l = out_l.at[:, i].set(jnp.where(active, logp, 0.0))
        emitted = emitted.at[:, i].set(active)
        new_lengths = lengths_ + active.astype(jnp.int32)
        budgets = budgets - active.astype(jnp.int32)
        active = (
            active & ~stop_fn(tok) & (budgets > 0) & (new_lengths < max_len)
        )
        return (new_lengths, tok, active, budgets, k_pool, v_pool, wk, wv,
                wvalid, out_t, out_l, emitted, rng)

    out_t = jnp.zeros((B, W), jnp.int32)
    out_l = jnp.zeros((B, W), jnp.float32)
    emitted = jnp.zeros((B, W), bool)
    st = (base_lens, cur_tokens, active, budgets, k_pool, v_pool, wk, wv,
          wvalid0, out_t, out_l, emitted, rng)
    (lengths_, cur, active, budgets, k_pool, v_pool, wk, wv, wvalid,
     out_t, out_l, emitted, rng) = jax.lax.fori_loop(0, W, step, st)

    # merge the window into pool blocks: ONE scatter per chunk
    offs = base_lens[None, :] + jnp.cumsum(
        wvalid.astype(jnp.int32), axis=0
    ) - wvalid.astype(jnp.int32)  # [W, B] absolute slot per window entry
    b_idx = jnp.broadcast_to(jnp.arange(B)[None, :], (W, B))
    pid_log = jnp.clip(offs // BS, 0, tables.shape[1] - 1)
    pid = tables[b_idx, pid_log]  # [W, B]
    pid = jnp.where(wvalid, pid, NB)  # invalid -> OOB -> dropped
    off = offs % BS
    # advanced indices split by the Hkv slice -> result [W, B, L, Hkv, hd]
    val_k = wk.transpose(1, 2, 0, 3, 4)
    val_v = wv.transpose(1, 2, 0, 3, 4)
    if k_scale is not None:
        kq, ks = quantize_kv(val_k)
        vq, vs = quantize_kv(val_v)
        k_pool = k_pool.at[:, pid, :, off].set(kq, mode="drop")
        v_pool = v_pool.at[:, pid, :, off].set(vq, mode="drop")
        # scale pools [L, NB, Hkv, BS]: same coordinates -> [W, B, L, Hkv]
        k_scale = k_scale.at[:, pid, :, off].set(ks, mode="drop")
        v_scale = v_scale.at[:, pid, :, off].set(vs, mode="drop")
        return (k_pool, v_pool, lengths_, out_t, out_l, emitted, cur,
                active, budgets, rng, k_scale, v_scale)
    k_pool = k_pool.at[:, pid, :, off].set(val_k, mode="drop")
    v_pool = v_pool.at[:, pid, :, off].set(val_v, mode="drop")
    return (k_pool, v_pool, lengths_, out_t, out_l, emitted, cur, active,
            budgets, rng)


@jax.jit
def gather_blocks(
    k_pool: jax.Array,
    v_pool: jax.Array,
    src: jax.Array,  # [n] pool block ids to gather (pad with any valid id)
    k_scale: Optional[jax.Array] = None,
    v_scale: Optional[jax.Array] = None,
):
    """Gather whole blocks out of the pool as ``[n, L, Hkv, BS, hd]``
    pairs — the device half of a host-tier SPILL (the engine
    ``device_get``s the result into host buffers, one batched fetch per
    reclamation round).  Quantized pools also gather the blocks' scale
    slices ``[n, L, Hkv, BS]`` (appended to the returned tuple), so a
    spilled prefix costs its true int8+scale bytes in host RAM — half
    or less of the model-dtype footprint.  NOT donated: the pool stays
    live."""
    src = jnp.clip(src, 0, k_pool.shape[1] - 1)
    out = (
        jnp.take(k_pool, src, axis=1).swapaxes(0, 1),
        jnp.take(v_pool, src, axis=1).swapaxes(0, 1),
    )
    if k_scale is None:
        return out
    return out + (
        jnp.take(k_scale, src, axis=1).swapaxes(0, 1),
        jnp.take(v_scale, src, axis=1).swapaxes(0, 1),
    )


@partial(
    jax.jit, donate_argnums=(0, 1), donate_argnames=("k_scale", "v_scale")
)
def restore_blocks(
    k_pool: jax.Array,
    v_pool: jax.Array,
    k_host: jax.Array,  # [n, L, Hkv, BS, hd] spilled payloads (host-built)
    v_host: jax.Array,
    dst: jax.Array,  # [n] destination pool block ids (NB entries drop)
    k_scale: Optional[jax.Array] = None,
    v_scale: Optional[jax.Array] = None,
    k_scale_host: Optional[jax.Array] = None,  # [n, L, Hkv, BS]
    v_scale_host: Optional[jax.Array] = None,
):
    """Scatter host-spilled block KV back into freshly allocated pool
    blocks — the device half of a host-tier swap-in.  Quantized pools
    restore the spilled int8 bytes AND their scales bit-identically (no
    requantization round trip).  Dispatched async like every pool op:
    the host->device transfer and scatter ride under the decode chunks
    queued behind it in the in-flight ring, and any later op consuming
    the (donated) pool is sequenced after it by data dependence."""
    k_pool = k_pool.at[:, dst].set(
        k_host.swapaxes(0, 1).astype(k_pool.dtype), mode="drop"
    )
    v_pool = v_pool.at[:, dst].set(
        v_host.swapaxes(0, 1).astype(v_pool.dtype), mode="drop"
    )
    if k_scale is None:
        return k_pool, v_pool
    k_scale = k_scale.at[:, dst].set(
        k_scale_host.swapaxes(0, 1).astype(k_scale.dtype), mode="drop"
    )
    v_scale = v_scale.at[:, dst].set(
        v_scale_host.swapaxes(0, 1).astype(v_scale.dtype), mode="drop"
    )
    return k_pool, v_pool, k_scale, v_scale


def gather_blocks_host(
    k_pool: jax.Array,
    v_pool: jax.Array,
    blocks: Sequence[int],
    k_scale: Optional[jax.Array] = None,
    v_scale: Optional[jax.Array] = None,
) -> Tuple[np.ndarray, ...]:
    """Batched device->host copy of whole pool blocks: one jitted
    :func:`gather_blocks` + one blocking ``device_get``, power-of-two
    padded so repeated calls reuse a handful of compiled shapes.
    Returns host numpy components indexed ``[i] -> blocks[i]`` —
    ``(k, v)`` for model-dtype pools, ``(k, v, k_scale, v_scale)`` for
    int8 pools (the quantized bytes and their scales travel together,
    so a round trip through :func:`restore_blocks_from_host` is
    bit-identical, no requantization).

    The ONE host-copy implementation for every whole-block exporter:
    the prefix cache's host spill tier and the P/D-disaggregation
    handoff unit both ride it."""
    n = len(blocks)
    n_pad = 1 << (n - 1).bit_length()
    idx = np.zeros((n_pad,), np.int32)
    idx[:n] = blocks
    out = gather_blocks(
        k_pool, v_pool, jnp.asarray(idx), k_scale=k_scale, v_scale=v_scale
    )
    out = jax.device_get(out)
    return tuple(np.asarray(a)[:n] for a in out)


def restore_blocks_from_host(
    k_pool: jax.Array,
    v_pool: jax.Array,
    payloads: Sequence[Tuple[np.ndarray, ...]],
    dst: Sequence[int],
    k_scale: Optional[jax.Array] = None,
    v_scale: Optional[jax.Array] = None,
):
    """Batched host->device scatter of per-block payload tuples (each as
    produced by :func:`gather_blocks_host`, one tuple per destination
    block): stacks the components into one padded transfer buffer and
    dispatches ONE async :func:`restore_blocks` — the copy rides under
    whatever decode chunks are queued behind it, and any later op
    consuming the (donated) pools is sequenced after it by data
    dependence.  Returns the updated pools: ``(k_pool, v_pool)`` or
    ``(k_pool, v_pool, k_scale, v_scale)`` matching the pool format.

    Component shapes/dtypes come from the payloads themselves, so int8
    + scale spills restore bit-identically on quantized pools."""
    n = len(payloads)
    assert n == len(dst) and n > 0
    n_pad = 1 << (n - 1).bit_length()
    # fill the padded transfer buffers directly (one pass per component)
    stacked = []
    for c, proto in enumerate(payloads[0]):
        buf = np.zeros((n_pad,) + proto.shape, proto.dtype)
        for i, payload in enumerate(payloads):
            buf[i] = payload[c]
        stacked.append(jnp.asarray(buf))
    return _restore_padded(
        k_pool, v_pool, stacked, n, dst,
        k_scale=k_scale, v_scale=v_scale,
    )


def stack_host_payloads(
    payloads: Sequence[Tuple[np.ndarray, ...]],
) -> Tuple[np.ndarray, ...]:
    """Stack per-block payload tuples (each :func:`gather_blocks_host`
    output indexed ``[i]``, e.g. host-spill entries) into the ONE
    contiguous buffer per component that
    :func:`restore_blocks_host_stacked` scatters — the segmented-handoff
    wire format.  Lets an exporter mix batch-gathered device blocks and
    already-host spill payloads into one segment."""
    assert payloads
    return tuple(
        np.stack([np.asarray(p[c]) for p in payloads], axis=0)
        for c in range(len(payloads[0]))
    )


def restore_blocks_host_stacked(
    k_pool: jax.Array,
    v_pool: jax.Array,
    components: Sequence[np.ndarray],
    dst: Sequence[int],
    k_scale: Optional[jax.Array] = None,
    v_scale: Optional[jax.Array] = None,
):
    """Like :func:`restore_blocks_from_host`, but the payload arrives as
    ONE contiguous buffer per pool component — ``(k [n, L, Hkv, BS, hd],
    v, [k_scale [n, L, Hkv, BS], v_scale])`` indexed ``[i] -> dst[i]``,
    exactly :func:`gather_blocks_host`'s output shape.  This is the
    segmented KV-handoff wire format: a streamed segment ships its
    blocks coalesced and scatters them without a per-block
    split/re-stack round trip.  Pads to a power of two and dispatches
    ONE async :func:`restore_blocks`; returns the updated pools."""
    n = len(dst)
    assert n > 0
    n_pad = 1 << (n - 1).bit_length()
    stacked = []
    for c in components:
        c = np.asarray(c)
        assert c.shape[0] == n, (c.shape, n)
        if n_pad == n:
            buf = c
        else:
            buf = np.zeros((n_pad,) + c.shape[1:], c.dtype)
            buf[:n] = c
        stacked.append(jnp.asarray(buf))
    return _restore_padded(
        k_pool, v_pool, stacked, n, dst,
        k_scale=k_scale, v_scale=v_scale,
    )


def _restore_padded(
    k_pool, v_pool, stacked, n, dst, k_scale=None, v_scale=None
):
    """Shared dispatch tail of the two host-restore entry points:
    ``stacked`` components are already power-of-two padded device-ready
    buffers covering ``dst[:n]``."""
    n_pad = stacked[0].shape[0]
    # pad destinations point one past the pool: mode="drop" discards them
    dst_arr = np.full((n_pad,), k_pool.shape[1], np.int32)
    dst_arr[:n] = dst
    if k_scale is not None:
        kh, vh, ksh, vsh = stacked
        return restore_blocks(
            k_pool, v_pool, kh, vh, jnp.asarray(dst_arr),
            k_scale=k_scale, v_scale=v_scale,
            k_scale_host=ksh, v_scale_host=vsh,
        )
    kh, vh = stacked
    return restore_blocks(k_pool, v_pool, kh, vh, jnp.asarray(dst_arr))


@partial(
    jax.jit, donate_argnums=(0, 1), donate_argnames=("k_scale", "v_scale")
)
def copy_blocks(
    k_pool: jax.Array,
    v_pool: jax.Array,
    src: jax.Array,  # [n] pool block ids to copy from
    dst: jax.Array,  # [n] pool block ids to copy into (NB entries drop)
    k_scale: Optional[jax.Array] = None,
    v_scale: Optional[jax.Array] = None,
):
    """Copy whole blocks inside the pool (group-prompt tail blocks: the
    full blocks of a shared prompt are REFERENCED by every group member,
    but the partially-filled last block must be copied per member since
    their generated tokens diverge inside it).  Quantized pools copy the
    scale slices with the int8 bytes — a COW tail carries its donor's
    exact quantization."""
    src = jnp.clip(src, 0, k_pool.shape[1] - 1)  # pad entries gather blk 0
    k_pool = k_pool.at[:, dst].set(k_pool[:, src], mode="drop")
    v_pool = v_pool.at[:, dst].set(v_pool[:, src], mode="drop")
    if k_scale is None:
        return k_pool, v_pool
    k_scale = k_scale.at[:, dst].set(k_scale[:, src], mode="drop")
    v_scale = v_scale.at[:, dst].set(v_scale[:, src], mode="drop")
    return k_pool, v_pool, k_scale, v_scale

"""One SPMD controller process of a multi-host dry run.

Joins a jax.distributed cluster (``n_local`` virtual CPU devices per
process), builds the global mesh with real tp/fsdp/dp axes spanning all
processes, runs full TrainEngine train steps plus a logprob forward pass,
and prints a JSON line the parent cross-checks across processes — every
controller must compute identical global losses (the TPU-native equivalent
of the reference's multi-node NCCL bootstrap,
realhf/impl/model/comm/global_comm.py:48).

Usage: ``python -m areal_tpu.parallel.dryrun_worker COORD NPROCS PROC_ID
[N_LOCAL_DEVICES]``
"""

import json
import os
import sys


def main():
    coordinator, num_procs, proc_id = (
        sys.argv[1],
        int(sys.argv[2]),
        int(sys.argv[3]),
    )
    n_local = int(sys.argv[4]) if len(sys.argv) > 4 else 4
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={n_local}"
    ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from areal_tpu.parallel import distributed as dist

    dist.initialize(coordinator, num_procs, proc_id)
    assert jax.process_count() == num_procs
    assert len(jax.devices()) == n_local * num_procs, len(jax.devices())

    from areal_tpu.api.data import MicroBatchSpec, SequenceSample
    from areal_tpu.base.topology import MeshSpec
    from areal_tpu.engine.optimizer import OptimizerConfig
    from areal_tpu.engine.train_engine import TrainEngine
    from areal_tpu.interfaces.sft_interface import sft_loss_fn
    from areal_tpu.models import transformer
    from areal_tpu.models.config import tiny_config

    n_total = n_local * num_procs
    model = 2 if n_total % 2 == 0 else 1
    fsdp = 2 if (n_total // model) % 2 == 0 else 1
    data = n_total // model // fsdp
    spec = MeshSpec(data=data, fsdp=fsdp, model=model)
    mesh = spec.make_mesh(jax.devices())
    cfg = tiny_config(vocab_size=128)
    params = transformer.init_params(cfg, jax.random.PRNGKey(0))
    engine = TrainEngine(
        cfg,
        mesh,
        params,
        optimizer_cfg=OptimizerConfig(lr=1e-3),
        total_train_steps=4,
    )

    rng = np.random.default_rng(0)  # same data on every process (SPMD)
    seqlens = [12, 9, 17, 8, 11, 15, 10, 13]
    total = sum(seqlens)
    sample = SequenceSample.from_default(
        seqlens=seqlens,
        ids=list(range(len(seqlens))),
        data={
            "packed_input_ids": rng.integers(0, cfg.vocab_size, (total,)).astype(
                np.int64
            ),
            "prompt_mask": np.zeros((total,), bool),
        },
    )
    losses = []
    for _ in range(3):
        stats = engine.train_batch(
            sample, sft_loss_fn, MicroBatchSpec(n_mbs=2)
        )
        losses.append(stats["loss"])
    # step 0 runs at lr=0 (warmup); training bites from step 1 on
    assert losses[2] < losses[1], losses

    from areal_tpu.interfaces.ppo_interface import model_logprobs_fwd

    lps = engine.forward_batch(
        sample, model_logprobs_fwd(1.0), MicroBatchSpec(n_mbs=2), output_shift=1
    )
    assert np.isfinite(np.asarray(lps, np.float32)).all()

    # cross-host pipeline phase: ``pipe`` is the SLOWEST mesh axis, so with
    # pipe == process_count each stage lives entirely on one host and only
    # the thin [B, T, D] activation rotations cross the host boundary —
    # the cross-slice/DCN pattern docs/parallelism.md reserves PP for.
    # Fresh params from the same seed: the pre-update first-step loss must
    # reproduce the unpipelined engine's.
    if num_procs % 2 == 0 and cfg.n_layers % 2 == 0:
        pp_engine = TrainEngine(
            cfg,
            MeshSpec(pipe=2, data=n_total // 2).make_mesh(jax.devices()),
            transformer.init_params(cfg, jax.random.PRNGKey(0)),
            optimizer_cfg=OptimizerConfig(lr=1e-3),
            total_train_steps=4,
        )
        pp_stats = pp_engine.train_batch(
            sample, sft_loss_fn, MicroBatchSpec(n_mbs=2)
        )
        assert abs(pp_stats["loss"] - losses[0]) < 5e-3, (
            pp_stats["loss"], losses[0],
        )
        losses.append(pp_stats["loss"])  # cross-process identity check
        transformer.set_ambient_mesh(None)

    host = engine.get_host_params()
    n = sum(int(np.prod(x.shape)) for x in jax.tree.leaves(host))
    print(json.dumps({"proc": proc_id, "losses": losses, "n_params": n}))


if __name__ == "__main__":
    main()

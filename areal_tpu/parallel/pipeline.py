"""Pipeline parallelism over the ``pipe`` mesh axis.

TPU-native replacement for the reference's pipeline-instruction VM
(reference: realhf/impl/model/backend/pipe_runner.py — 1F1B/inference
schedules executed by a Python interpreter issuing NCCL p2p send/recvs;
reference: realhf/impl/model/backend/static_schedule.py:159-323).  On TPU
none of that machinery survives: the schedule is expressed *inside* one
jitted program as a ``lax.scan`` over pipeline steps within a
``jax.shard_map`` that is manual over only the ``pipe`` axis —

* each stage holds a contiguous slice of the stacked ``[L, ...]`` layer
  params (the mesh shards the leading layer axis over ``pipe``);
* micro-batch activations rotate stage-to-stage via ``lax.ppermute``
  (XLA lowers this to ICI neighbour transfers — the p2p send/recv pairs
  of the reference's VM, scheduled by the compiler instead of Python);
* every other mesh axis (``data``/``fsdp``/``model``/``expert``) stays
  *auto*: XLA keeps inserting the FSDP all-gathers and TP collectives
  inside each stage exactly as in the unpipelined path.

The backward schedule needs no hand-built 1F1B program: differentiating
through the scan-of-ppermute gives a GPipe schedule (all forwards, then
all backwards, with reverse-direction ppermutes), and per-layer
rematerialisation keeps the stored state to layer-boundary activations —
the same memory class as the unpipelined remat path.

Composition limits: ``pipe`` composes with data/fsdp/model/expert.
``pipe × seq`` (context parallelism inside a pipeline stage) would nest
two manual shard_maps and is rejected with an explicit error.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

Aux = Any
# stage_fn(local_stacked_params, {"x": [B,T,D], **side_inputs}) -> (y, aux)
StageFn = Callable[[Any, Dict[str, jax.Array]], Tuple[jax.Array, Aux]]


def pick_microbatches(n_rows: int, pipe: int, requested: int = 0) -> int:
    """Number of pipeline micro-batches.

    ``requested=0`` -> auto: ``2 * pipe`` (bubble fraction
    ``(p-1)/(m+p-1)`` ≈ 1/3) capped by the row count; always >= 1.
    """
    m = requested if requested > 0 else 2 * pipe
    return max(1, min(m, n_rows))


def pipeline_apply(
    mesh,
    stacked_params: Any,
    stage_fn: StageFn,
    x: jax.Array,
    side_inputs: Dict[str, jax.Array],
    n_mbs: int,
    aux_zero: Optional[Aux] = None,
):
    """Run ``stage_fn`` over ``pipe`` stages with micro-batch rotation.

    Args:
      mesh: the engine mesh; ``mesh.shape["pipe"] > 1``.
      stacked_params: pytree whose every leaf has leading dim ``L``
        (sharded over ``pipe`` by the caller's NamedSharding; inside the
        shard_map each stage sees its local ``[L/p, ...]`` slice).
      stage_fn: applies one stage's layers to one micro-batch.  Called
        under the shard_map with *auto* data/model axes — it may use
        sharded matmuls freely but must not touch the ``pipe`` axis.
      x: ``[B, T, D]`` hidden states entering the first stage.
      side_inputs: per-row arrays (``[B, ...]``) consumed by every stage
        alongside its current micro-batch (positions, seg_ids, ...).
      n_mbs: micro-batch count ``m``; must divide ``B``.
      aux_zero: zero-valued pytree matching stage_fn's aux output
        (None = no aux).

    Returns ``(y [B, T, D], aux_total)`` where aux_total sums stage_fn's
    aux over all layers and micro-batches (psum over ``pipe``).
    """
    p = mesh.shape["pipe"]
    assert p > 1, "pipeline_apply called without a pipe axis"
    if mesh.shape.get("seq", 1) > 1:
        raise NotImplementedError(
            "pipe x seq (context parallelism inside pipeline stages) nests "
            "two manual shard_maps; shard long sequences with seq OR pipe"
        )
    B = x.shape[0]
    m = n_mbs
    assert B % m == 0, f"rows {B} not divisible by pipeline micro-batches {m}"

    def split(a):
        return a.reshape((m, B // m) + a.shape[1:])

    xs = split(x)
    sides = {k: split(v) for k, v in side_inputs.items()}
    has_aux = aux_zero is not None

    @functools.partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(
            jax.sharding.PartitionSpec("pipe"),
            jax.sharding.PartitionSpec(),
            jax.sharding.PartitionSpec(),
        ),
        out_specs=(
            jax.sharding.PartitionSpec("pipe"),
            jax.sharding.PartitionSpec(),
        ),
        axis_names={"pipe"},
        check_vma=False,
    )
    def run(local_params, xs, sides):
        stage = jax.lax.axis_index("pipe")
        perm = [(i, (i + 1) % p) for i in range(p)]
        n_steps = m + p - 1

        def step(carry, t):
            recv, outs, aux_acc = carry
            # the micro-batch currently AT this stage entered the pipeline
            # ``stage`` steps ago (clamped for bubble steps)
            mb_idx = jnp.clip(t - stage, 0, m - 1)
            valid = (t - stage >= 0) & (t - stage < m)
            mb_x = jax.lax.dynamic_index_in_dim(
                xs, mb_idx, axis=0, keepdims=False
            )
            mb_sides = {
                k: jax.lax.dynamic_index_in_dim(
                    v, mb_idx, axis=0, keepdims=False
                )
                for k, v in sides.items()
            }
            inp = jnp.where(stage == 0, mb_x, recv)
            out, aux = stage_fn(local_params, {"x": inp, **mb_sides})
            if has_aux:
                aux_acc = jax.tree.map(
                    lambda acc, a: acc + jnp.where(valid, a, 0), aux_acc, aux
                )
            # the last stage banks its finished micro-batch
            bank = (stage == p - 1) & valid
            prev = jax.lax.dynamic_index_in_dim(
                outs, mb_idx, axis=0, keepdims=False
            )
            outs = jax.lax.dynamic_update_index_in_dim(
                outs, jnp.where(bank, out, prev), mb_idx, 0
            )
            recv = jax.lax.ppermute(out, "pipe", perm)
            return (recv, outs, aux_acc), None

        aux0 = (
            jax.tree.map(lambda a: jnp.asarray(a), aux_zero)
            if has_aux
            else jnp.zeros((), jnp.float32)
        )
        (recv, outs, aux_acc), _ = jax.lax.scan(
            step,
            (jnp.zeros_like(xs[0]), jnp.zeros_like(xs), aux0),
            jnp.arange(n_steps),
        )
        aux_total = jax.lax.psum(aux_acc, "pipe")
        return outs, aux_total

    outs, aux_total = run(stacked_params, xs, sides)
    # outs is the per-stage banks concatenated over ``pipe`` -> [p*m, ...];
    # only the last stage's block holds real outputs
    y = outs[(p - 1) * m :].reshape((B,) + x.shape[1:])
    return y, (aux_total if has_aux else None)

"""Pipeline parallelism over the ``pipe`` mesh axis.

TPU-native replacement for the reference's pipeline-instruction VM
(reference: realhf/impl/model/backend/pipe_runner.py — 1F1B/inference
schedules executed by a Python interpreter issuing NCCL p2p send/recvs;
reference: realhf/impl/model/backend/static_schedule.py:159-323).  On TPU
none of that machinery survives: the schedule is expressed *inside* one
jitted program as a ``lax.scan`` over pipeline steps within a
``jax.shard_map`` that is manual over only the ``pipe`` axis —

* each stage holds a contiguous slice of the stacked ``[L, ...]`` layer
  params (the mesh shards the leading layer axis over ``pipe``);
* micro-batch activations rotate stage-to-stage via ``lax.ppermute``
  (XLA lowers this to ICI neighbour transfers — the p2p send/recv pairs
  of the reference's VM, scheduled by the compiler instead of Python);
* every other mesh axis (``data``/``fsdp``/``model``/``expert``) stays
  *auto*: XLA keeps inserting the FSDP all-gathers and TP collectives
  inside each stage exactly as in the unpipelined path.

The backward schedule needs no hand-built 1F1B program: differentiating
through the scan-of-ppermute gives a GPipe schedule (all forwards, then
all backwards, with reverse-direction ppermutes), and per-layer
rematerialisation keeps the stored state to layer-boundary activations —
the same memory class as the unpipelined remat path.

Composition limits: ``pipe`` composes with data/fsdp/model/expert.
``pipe × seq`` (context parallelism inside a pipeline stage) would nest
two manual shard_maps and is rejected with an explicit error.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from areal_tpu.base.jax_compat import shard_map as _shard_map

Aux = Any
# stage_fn(local_stacked_params, {"x": [B,T,D], **side_inputs}) -> (y, aux)
StageFn = Callable[[Any, Dict[str, jax.Array]], Tuple[jax.Array, Aux]]


def pick_microbatches(n_rows: int, pipe: int, requested: int = 0) -> int:
    """Number of pipeline micro-batches.

    ``requested=0`` -> auto: ``2 * pipe`` (bubble fraction
    ``(p-1)/(m+p-1)`` ≈ 1/3) capped by the row count; always >= 1.
    """
    m = requested if requested > 0 else 2 * pipe
    return max(1, min(m, n_rows))


def _wavefront(stage, t, m):
    """Forward-wavefront indexing shared by every schedule: the
    micro-batch at ``stage`` on step ``t`` entered the pipeline ``stage``
    steps ago.  Returns (mb_idx clamped for bubble steps, valid)."""
    mb_idx = jnp.clip(t - stage, 0, m - 1)
    valid = (t - stage >= 0) & (t - stage < m)
    return mb_idx, valid


def _take_mb(xs, sides, mb_idx):
    """Slice micro-batch ``mb_idx`` out of stacked inputs + side inputs."""
    mb_x = jax.lax.dynamic_index_in_dim(xs, mb_idx, axis=0, keepdims=False)
    mb_sides = {
        k: jax.lax.dynamic_index_in_dim(v, mb_idx, axis=0, keepdims=False)
        for k, v in sides.items()
    }
    return mb_x, mb_sides


def _bank(outs, mb_idx, out, cond):
    """Store ``out`` at ``outs[mb_idx]`` when ``cond`` (else keep)."""
    prev = jax.lax.dynamic_index_in_dim(outs, mb_idx, axis=0, keepdims=False)
    return jax.lax.dynamic_update_index_in_dim(
        outs, jnp.where(cond, out, prev), mb_idx, 0
    )


def pipeline_apply(
    mesh,
    stacked_params: Any,
    stage_fn: StageFn,
    x: jax.Array,
    side_inputs: Dict[str, jax.Array],
    n_mbs: int,
    aux_zero: Optional[Aux] = None,
):
    """Run ``stage_fn`` over ``pipe`` stages with micro-batch rotation.

    Args:
      mesh: the engine mesh; ``mesh.shape["pipe"] > 1``.
      stacked_params: pytree whose every leaf has leading dim ``L``
        (sharded over ``pipe`` by the caller's NamedSharding; inside the
        shard_map each stage sees its local ``[L/p, ...]`` slice).
      stage_fn: applies one stage's layers to one micro-batch.  Called
        under the shard_map with *auto* data/model axes — it may use
        sharded matmuls freely but must not touch the ``pipe`` axis.
      x: ``[B, T, D]`` hidden states entering the first stage.
      side_inputs: per-row arrays (``[B, ...]``) consumed by every stage
        alongside its current micro-batch (positions, seg_ids, ...).
      n_mbs: micro-batch count ``m``; must divide ``B``.
      aux_zero: zero-valued pytree matching stage_fn's aux output
        (None = no aux).

    Returns ``(y [B, T, D], aux_total)`` where aux_total sums stage_fn's
    aux over all layers and micro-batches (psum over ``pipe``).
    """
    p = mesh.shape["pipe"]
    assert p > 1, "pipeline_apply called without a pipe axis"
    if mesh.shape.get("seq", 1) > 1:
        raise NotImplementedError(
            "pipe x seq (context parallelism inside pipeline stages) nests "
            "two manual shard_maps; shard long sequences with seq OR pipe"
        )
    B = x.shape[0]
    m = n_mbs
    assert B % m == 0, f"rows {B} not divisible by pipeline micro-batches {m}"

    def split(a):
        return a.reshape((m, B // m) + a.shape[1:])

    xs = split(x)
    sides = {k: split(v) for k, v in side_inputs.items()}
    has_aux = aux_zero is not None

    @functools.partial(
        _shard_map,
        mesh=mesh,
        in_specs=(
            jax.sharding.PartitionSpec("pipe"),
            jax.sharding.PartitionSpec(),
            jax.sharding.PartitionSpec(),
        ),
        out_specs=(
            jax.sharding.PartitionSpec("pipe"),
            jax.sharding.PartitionSpec(),
        ),
        axis_names={"pipe"},
        check_vma=False,
    )
    def run(local_params, xs, sides):
        stage = jax.lax.axis_index("pipe")
        perm = [(i, (i + 1) % p) for i in range(p)]
        n_steps = m + p - 1

        def step(carry, t):
            recv, outs, aux_acc = carry
            mb_idx, valid = _wavefront(stage, t, m)
            mb_x, mb_sides = _take_mb(xs, sides, mb_idx)
            inp = jnp.where(stage == 0, mb_x, recv)
            out, aux = stage_fn(local_params, {"x": inp, **mb_sides})
            if has_aux:
                aux_acc = jax.tree.map(
                    lambda acc, a: acc + jnp.where(valid, a, 0), aux_acc, aux
                )
            # the last stage banks its finished micro-batch
            outs = _bank(outs, mb_idx, out, (stage == p - 1) & valid)
            recv = jax.lax.ppermute(out, "pipe", perm)
            return (recv, outs, aux_acc), None

        aux0 = (
            jax.tree.map(lambda a: jnp.asarray(a), aux_zero)
            if has_aux
            else jnp.zeros((), jnp.float32)
        )
        (recv, outs, aux_acc), _ = jax.lax.scan(
            step,
            (jnp.zeros_like(xs[0]), jnp.zeros_like(xs), aux0),
            jnp.arange(n_steps),
        )
        aux_total = jax.lax.psum(aux_acc, "pipe")
        return outs, aux_total

    outs, aux_total = run(stacked_params, xs, sides)
    # outs is the per-stage banks concatenated over ``pipe`` -> [p*m, ...];
    # only the last stage's block holds real outputs
    y = outs[(p - 1) * m :].reshape((B,) + x.shape[1:])
    return y, (aux_total if has_aux else None)


def pipeline_apply_1f1b(
    mesh,
    stacked_params: Any,
    stage_fn: StageFn,
    x: jax.Array,
    side_inputs: Dict[str, jax.Array],
    n_mbs: int,
):
    """Memory-bounded pipeline schedule (the reference's 1F1B,
    realhf/impl/model/backend/static_schedule.py:323, re-expressed as a
    custom-VJP pair of shard_map scans instead of a p2p instruction VM).

    Differentiating :func:`pipeline_apply`'s scan gives GPipe: every
    step's stage input is saved for the backward, so per-device live
    activations are ~(m+p-1) micro-batches.  Here the FORWARD saves
    NOTHING (custom_vjp residuals = the pipeline's own inputs); the
    BACKWARD re-runs the forward pipeline and consumes each recomputed
    stage input as soon as its cotangent arrives — the 1F1B dependence
    pattern — holding only a ``2p-1``-slot ring of micro-batch inputs.
    Live activations therefore scale with ``p``, not ``m`` (verified by
    compiled memory analysis in tests/parallel/test_pipeline.py).

    Schedule (backward pass, step t, stage s, R = 2p-1):
      * recompute-forward of micro-batch ``t - s`` (same wavefront as the
        forward pass), stage input ring-buffered at slot ``mb mod R``;
      * backward of micro-batch ``t - 2(p-1) + s`` via ``jax.vjp`` on the
        ring-buffered input (one extra stage recompute — full-remat
        semantics, the policy the engine already runs);
      * activations rotate forward via ppermute, cotangents rotate
        backward; stage 0 banks input cotangents, every stage
        accumulates its local param grads.

    Cost: one extra forward sweep vs GPipe-with-remat.  ``stage_fn``'s
    aux output is NOT differentiated here (MoE router losses need grads
    — MoE models keep the GPipe schedule; transformer._run_layers_pipelined
    enforces this).

    Returns ``y [B, T, D]`` (no aux).
    """
    p = mesh.shape["pipe"]
    assert p > 1, "pipeline_apply_1f1b called without a pipe axis"
    if mesh.shape.get("seq", 1) > 1:
        raise NotImplementedError("pipe x seq is rejected (see module doc)")
    B = x.shape[0]
    m = n_mbs
    assert B % m == 0, f"rows {B} not divisible by micro-batches {m}"
    P = jax.sharding.PartitionSpec

    def split(a):
        return a.reshape((m, B // m) + a.shape[1:])

    xs = split(x)
    sides = {k: split(v) for k, v in side_inputs.items()}
    perm_fwd = [(i, (i + 1) % p) for i in range(p)]
    perm_bwd = [((i + 1) % p, i) for i in range(p)]

    def stage_call(local_params, mb_x, mb_sides):
        out, _aux = stage_fn(local_params, {"x": mb_x, **mb_sides})
        return out

    @functools.partial(
        _shard_map,
        mesh=mesh,
        in_specs=(P("pipe"), P(), P()),
        out_specs=P("pipe"),
        axis_names={"pipe"},
        check_vma=False,
    )
    def run_fwd(local_params, xs, sides):
        stage = jax.lax.axis_index("pipe")
        n_steps = m + p - 1

        def step(carry, t):
            recv, outs = carry
            mb_idx, valid = _wavefront(stage, t, m)
            mb_x, mb_sides = _take_mb(xs, sides, mb_idx)
            inp = jnp.where(stage == 0, mb_x, recv)
            out = stage_call(local_params, inp, mb_sides)
            outs = _bank(outs, mb_idx, out, (stage == p - 1) & valid)
            recv = jax.lax.ppermute(out, "pipe", perm_fwd)
            return (recv, outs), None

        (recv, outs), _ = jax.lax.scan(
            step,
            (jnp.zeros_like(xs[0]), jnp.zeros_like(xs)),
            jnp.arange(n_steps),
        )
        return outs

    @functools.partial(
        _shard_map,
        mesh=mesh,
        in_specs=(P("pipe"), P(), P(), P()),
        # dxs banks live ONLY on stage 0 — concatenate over pipe and let
        # the caller slice stage 0's block (a replicated out_spec on a
        # stage-varying value is undefined)
        out_specs=(P("pipe"), P("pipe")),
        axis_names={"pipe"},
        check_vma=False,
    )
    def run_bwd(local_params, xs, sides, dys):
        stage = jax.lax.axis_index("pipe")
        R = 2 * p - 1
        n_steps = 2 * (p - 1) + m
        g_params0 = jax.tree.map(jnp.zeros_like, local_params)
        ring0 = jnp.zeros((R,) + xs.shape[1:], xs.dtype)

        def sides_at(i):
            return {
                k: jax.lax.dynamic_index_in_dim(v, i, 0, False)
                for k, v in sides.items()
            }

        def step(carry, t):
            recv, cot_recv, ring, dxs, g_params = carry
            # ---- recompute-forward wavefront (same as the fwd pass) ----
            f_idx, f_valid = _wavefront(stage, t, m)
            mb_x = jax.lax.dynamic_index_in_dim(xs, f_idx, 0, False)
            inp = jnp.where(stage == 0, mb_x, recv)
            out = stage_call(local_params, inp, sides_at(f_idx))
            # ring-buffer this stage's input for its (later) backward;
            # invalid wavefront steps overwrite nothing that is still live
            slot_f = jnp.where(f_valid, f_idx % R, R - 1)
            keep = jax.lax.dynamic_index_in_dim(ring, slot_f, 0, False)
            ring = jax.lax.dynamic_update_index_in_dim(
                ring, jnp.where(f_valid, inp, keep), slot_f, 0
            )
            # ---- backward of the micro-batch whose cotangent arrived ----
            b_i = t - 2 * (p - 1) + stage
            b_idx = jnp.clip(b_i, 0, m - 1)
            b_valid = (b_i >= 0) & (b_i < m)
            dy_mb = jax.lax.dynamic_index_in_dim(dys, b_idx, 0, False)
            cot_in = jnp.where(stage == p - 1, dy_mb, cot_recv)
            saved = jax.lax.dynamic_index_in_dim(
                ring, b_idx % R, 0, False
            )
            _, vjp_fn = jax.vjp(
                lambda pp, xx: stage_call(pp, xx, sides_at(b_idx)),
                local_params,
                saved,
            )
            g_p, g_x = vjp_fn(cot_in)
            g_params = jax.tree.map(
                lambda acc, g: acc + jnp.where(b_valid, g, 0).astype(
                    acc.dtype
                ),
                g_params,
                g_p,
            )
            # stage 0 banks input cotangents (grads wrt xs)
            dxs = _bank(
                dxs, b_idx, g_x.astype(dxs.dtype), (stage == 0) & b_valid
            )
            recv = jax.lax.ppermute(out, "pipe", perm_fwd)
            cot_recv = jax.lax.ppermute(g_x, "pipe", perm_bwd)
            return (recv, cot_recv, ring, dxs, g_params), None

        carry0 = (
            jnp.zeros_like(xs[0]),
            jnp.zeros_like(xs[0]),
            ring0,
            jnp.zeros_like(xs),
            g_params0,
        )
        (recv, cot_recv, ring, dxs, g_params), _ = jax.lax.scan(
            step, carry0, jnp.arange(n_steps)
        )
        return g_params, dxs

    @jax.custom_vjp
    def _pipeline(stacked_params, xs, sides):
        outs = run_fwd(stacked_params, xs, sides)
        return outs[(p - 1) * m :]

    def _fwd(stacked_params, xs, sides):
        # residuals = the pipeline's own inputs; NOTHING per-step is saved
        return _pipeline(stacked_params, xs, sides), (
            stacked_params, xs, sides,
        )

    def _bwd(res, dy):
        stacked_params, xs, sides = res
        g_params, dxs_all = run_bwd(stacked_params, xs, sides, dy)
        dxs = dxs_all[:m]  # stage 0's bank
        g_sides = jax.tree.map(jnp.zeros_like, sides)
        return g_params, dxs, g_sides

    _pipeline.defvjp(_fwd, _bwd)
    ys = _pipeline(stacked_params, xs, sides)
    return ys.reshape((B,) + x.shape[1:])

"""Multi-host SPMD substrate: jax.distributed + global-array helpers.

The reference bootstraps NCCL process groups by hand (reference:
realhf/impl/model/comm/global_comm.py:48-150 — peers register in
name_resolve, a master is elected, ``init_process_group``).  The TPU-native
equivalent is ``jax.distributed.initialize`` + ONE global mesh whose axes
span all hosts' devices: XLA inserts every collective (over ICI within a
slice, DCN across slices) from sharding annotations; the per-(dp,tp,pp)
subgroup zoo disappears.

Every process must execute the same jitted computation (multi-controller
SPMD); host data enters via :func:`put_global`, which handles shardings
that span non-addressable devices.
"""

from __future__ import annotations

import os
from typing import Optional

import jax
import numpy as np

from areal_tpu.base import logging_

logger = logging_.getLogger("distributed")


def initialize(
    coordinator_address: str,
    num_processes: int,
    process_id: int,
) -> None:
    """Join the jax.distributed cluster (idempotent)."""
    from jax._src import distributed as _jd

    if getattr(_jd.global_state, "client", None) is not None:
        return
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    logger.info(
        "jax.distributed up: process %d/%d, %d global / %d local devices",
        jax.process_index(),
        jax.process_count(),
        len(jax.devices()),
        len(jax.local_devices()),
    )


def initialize_from_env() -> bool:
    """Initialize from AREAL_JAX_* env vars (set by the launcher); returns
    whether distributed mode is active."""
    coord = os.environ.get("AREAL_JAX_COORDINATOR")
    if not coord:
        return False
    initialize(
        coord,
        int(os.environ["AREAL_JAX_NUM_PROCESSES"]),
        int(os.environ["AREAL_JAX_PROCESS_ID"]),
    )
    return True


def put_global(value: np.ndarray, sharding) -> jax.Array:
    """Place a host array onto a (possibly multi-host) sharding.

    Every process passes the SAME full array (our MFC dispatch delivers the
    full batch to every SPMD peer); each process donates only its
    addressable shards."""
    if sharding.is_fully_addressable:
        return jax.device_put(value, sharding)
    return jax.make_array_from_callback(
        value.shape, sharding, lambda idx: value[idx]
    )


def host_gather(x: jax.Array) -> np.ndarray:
    """Fetch a (possibly multi-host-sharded) array fully to host."""
    if x.is_fully_addressable:
        return np.asarray(x)
    from jax.experimental import multihost_utils

    return np.asarray(multihost_utils.process_allgather(x, tiled=True))


def tree_put_global(tree, shardings):
    return jax.tree.map(put_global, tree, shardings)


def tree_host_gather(tree):
    return jax.tree.map(host_gather, tree)

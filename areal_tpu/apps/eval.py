"""Offline evaluation CLI: score a saved checkpoint on a prompt dataset or
a benchmark file (AIME24 / MATH-500 / AMC / GPQA-style jsonl).

The in-repo eval job the automatic evaluator submits per checkpoint
(reference: the ``evaluation/`` suite invoked by
realhf/scheduler/evaluator.py via ``install_deps_and_eval.sh``; ours loads
the HF-format checkpoint into the native continuous-batching engine,
generates n answers per prompt, scores with the hardened math parser /
local verifiers, and writes per-task pass@1/pass@k JSON).

Dataset schema is auto-detected per file: training-style
({query_id, prompt, solutions}) loads through the math_code dataset
validator; benchmark-style ({problem|question, answer}, reference:
evaluation/data/*/test.jsonl) normalizes through
areal_tpu/data/benchmarks.py, which appends the boxed-answer instruction
and handles multiple-choice options.

Usage::

    python -m areal_tpu.apps.eval --ckpt DIR --dataset D.jsonl \
        --output OUT.json [--max-prompts N] [--max-new-tokens M] \
        [--n-samples K] [--no-chat-template]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


def pass_at_k(n_correct, n_samples: int, k: int) -> float:
    """Unbiased pass@k over prompts: mean of 1 - C(n-c, k)/C(n, k)
    (the reference evaluation suite's estimator)."""
    from math import comb

    vals = []
    for c in n_correct:
        if n_samples - c < k:
            vals.append(1.0)
        else:
            vals.append(1.0 - comb(n_samples - c, k) / comb(n_samples, k))
    return sum(vals) / max(1, len(vals))


def load_eval_dataset(dataset_path: str):
    """(id2info, style) from either a training-style or benchmark-style
    jsonl (schema sniffed from the first record).  ``style`` is
    "training" or "benchmark" — benchmark prompts are bare problems that
    want the model's chat template; training prompts are already in the
    exact surface form the training pipeline tokenizes raw."""
    with open(dataset_path) as f:
        first = json.loads(next(line for line in f if line.strip()))
    if "query_id" in first and "prompt" in first:
        from areal_tpu.data.math_code_dataset import load_metadata

        id2info, _ = load_metadata(dataset_path)
        return id2info, "training"
    from areal_tpu.data.benchmarks import load_benchmark

    return load_benchmark(dataset_path), "benchmark"


def evaluate_checkpoint(
    ckpt_dir: str,
    dataset_path: str,
    max_prompts: int = 64,
    max_new_tokens: int = 512,
    kv_cache_len: int = 2048,
    max_batch: int = 16,
    n_samples: int = 1,
    temperature: float = 0.6,
    chat_template: bool = True,
) -> dict:
    """``n_samples == 1``: deterministic greedy accuracy.  ``n_samples > 1``:
    temperature sampling with the unbiased pass@k estimator
    (1 - C(n-c,k)/C(n,k); the reference's evaluation suite reports pass@k
    over sampled generations, evaluation/eval_and_aggregate.py)."""
    from transformers import AutoTokenizer

    from areal_tpu.api.model_api import (
        APIGenerateInput,
        GenerationHyperparameters,
    )
    from areal_tpu.engine.inference_server import ContinuousBatchingEngine
    from areal_tpu.models.hf.registry import load_hf_model
    from areal_tpu.verifiers.dispatch import verify_batch

    from areal_tpu.engine.sampling import SamplingParams

    if n_samples < 1:
        raise ValueError(f"n_samples must be >= 1, got {n_samples}")
    cfg, params = load_hf_model(ckpt_dir)
    tokenizer = AutoTokenizer.from_pretrained(ckpt_dir)
    greedy = n_samples == 1
    engine = ContinuousBatchingEngine(
        cfg,
        params,
        tokenizer=tokenizer,
        max_batch=max_batch,
        kv_cache_len=kv_cache_len,
        # sampling is engine-level (compile-time): pass@1 decodes greedily
        # so scores are deterministic and comparable across checkpoints
        sampling=SamplingParams(greedy=greedy, temperature=temperature),
    )

    id2info, style = load_eval_dataset(dataset_path)
    items = list(id2info.values())[:max_prompts]
    gcfg = GenerationHyperparameters(
        max_new_tokens=max_new_tokens, greedy=greedy, temperature=temperature
    )
    # chat template only for benchmark-style bare problems: training-style
    # prompts already carry their exact surface form (the training pipeline
    # tokenizes them raw), and double-wrapping would skew scores
    use_chat = (
        chat_template
        and style == "benchmark"
        and getattr(tokenizer, "chat_template", None)
    )
    t0 = time.time()
    qids = []  # submit order = aggregation order, single-source format
    for d in items:
        if use_chat:
            ids = tokenizer.apply_chat_template(
                [{"role": "user", "content": d["prompt"]}],
                add_generation_prompt=True,
            )
        else:
            ids = tokenizer(d["prompt"])["input_ids"]
        for s in range(n_samples):
            qid = f"{d['query_id']}#{s}"
            qids.append(qid)
            engine.submit(
                APIGenerateInput(
                    qid=qid, prompt_ids=ids, input_ids=ids, gconfig=gcfg
                )
            )
    outs = {}
    while len(outs) < len(qids):
        engine.step()
        for qid in qids:
            if qid not in outs:
                r = engine.try_get_result(qid)
                if r is not None:
                    outs[qid] = r
    gen_time = time.time() - t0

    texts, tasks, problems = [], [], []
    for i, d in enumerate(items):
        for s in range(n_samples):
            texts.append(
                tokenizer.decode(
                    outs[qids[i * n_samples + s]].output_ids,
                    skip_special_tokens=True,
                )
            )
            tasks.append(d.get("task", "math"))
            problems.append(d)
    rewards = verify_batch(tasks, texts, problems)

    # group per prompt: c = correct count among n samples
    per_task: dict = {}
    n_correct = []
    for i, d in enumerate(items):
        rs = rewards[i * n_samples : (i + 1) * n_samples]
        c = sum(1 for r in rs if r > 0)
        n_correct.append(c)
        per_task.setdefault(d.get("task", "math"), []).append(c)

    ks = sorted({1, n_samples} | {k for k in (4, 8, 16) if k < n_samples})
    result = {
        "dataset": os.path.basename(dataset_path),
        "n_prompts": len(items),
        "n_samples": n_samples,
        "accuracy": pass_at_k(n_correct, n_samples, 1),
        "pass_at_k": {
            str(k): round(pass_at_k(n_correct, n_samples, k), 4) for k in ks
        },
        "per_task": {
            t: {
                "accuracy": sum(cs) / (len(cs) * n_samples),
                "n": len(cs),
            }
            for t, cs in per_task.items()
        },
        "gen_time_s": round(gen_time, 2),
    }
    return result


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description="areal_tpu offline evaluation")
    p.add_argument("--ckpt", required=True)
    p.add_argument("--dataset", required=True)
    p.add_argument("--output", required=True)
    p.add_argument("--max-prompts", type=int, default=64)
    p.add_argument("--max-new-tokens", type=int, default=512)
    p.add_argument("--kv-cache-len", type=int, default=2048)
    p.add_argument("--n-samples", type=int, default=1)
    p.add_argument("--temperature", type=float, default=0.6)
    p.add_argument(
        "--no-chat-template",
        action="store_true",
        help="tokenize prompts raw even when the tokenizer has a chat template",
    )
    args = p.parse_args(argv)
    result = evaluate_checkpoint(
        args.ckpt,
        args.dataset,
        max_prompts=args.max_prompts,
        max_new_tokens=args.max_new_tokens,
        kv_cache_len=args.kv_cache_len,
        n_samples=args.n_samples,
        temperature=args.temperature,
        chat_template=not args.no_chat_template,
    )
    os.makedirs(os.path.dirname(os.path.abspath(args.output)), exist_ok=True)
    tmp = args.output + ".tmp"
    with open(tmp, "w") as f:
        json.dump(result, f, indent=2)
    os.replace(tmp, args.output)
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Offline evaluation CLI: score a saved checkpoint on a prompt dataset.

The in-repo eval job the automatic evaluator submits per checkpoint
(reference: the ``evaluation/`` suite invoked by
realhf/scheduler/evaluator.py via ``install_deps_and_eval.sh``; ours loads
the HF-format checkpoint into the native continuous-batching engine,
generates one answer per prompt, scores with the local verifiers, and
writes an aggregate JSON).

Usage::

    python -m areal_tpu.apps.eval --ckpt DIR --dataset D.jsonl \
        --output OUT.json [--max-prompts N] [--max-new-tokens M]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


def evaluate_checkpoint(
    ckpt_dir: str,
    dataset_path: str,
    max_prompts: int = 64,
    max_new_tokens: int = 512,
    kv_cache_len: int = 2048,
    max_batch: int = 16,
) -> dict:
    from transformers import AutoTokenizer

    from areal_tpu.api.model_api import (
        APIGenerateInput,
        GenerationHyperparameters,
    )
    from areal_tpu.data.math_code_dataset import load_metadata
    from areal_tpu.engine.inference_server import ContinuousBatchingEngine
    from areal_tpu.models.hf.registry import load_hf_model
    from areal_tpu.verifiers.dispatch import verify_batch

    from areal_tpu.engine.sampling import SamplingParams

    cfg, params = load_hf_model(ckpt_dir)
    tokenizer = AutoTokenizer.from_pretrained(ckpt_dir)
    engine = ContinuousBatchingEngine(
        cfg,
        params,
        tokenizer=tokenizer,
        max_batch=max_batch,
        kv_cache_len=kv_cache_len,
        # sampling is engine-level (compile-time): evals decode greedily so
        # scores are deterministic and comparable across checkpoints
        sampling=SamplingParams(greedy=True),
    )

    id2info, task_cnt = load_metadata(dataset_path)
    items = list(id2info.values())[:max_prompts]
    gcfg = GenerationHyperparameters(
        max_new_tokens=max_new_tokens, greedy=True
    )
    t0 = time.time()
    for d in items:
        ids = tokenizer(d["prompt"])["input_ids"]
        engine.submit(
            APIGenerateInput(
                qid=d["query_id"], prompt_ids=ids, input_ids=ids, gconfig=gcfg
            )
        )
    outs = {}
    while len(outs) < len(items):
        engine.step()
        for d in items:
            if d["query_id"] in outs:
                continue
            r = engine.try_get_result(d["query_id"])
            if r is not None:
                outs[d["query_id"]] = r
    gen_time = time.time() - t0

    texts, tasks, problems = [], [], []
    for d in items:
        answer = tokenizer.decode(
            outs[d["query_id"]].output_ids, skip_special_tokens=True
        )
        texts.append(answer)
        tasks.append(d.get("task", "math"))
        problems.append(d)
    rewards = verify_batch(tasks, texts, problems)

    per_task: dict = {}
    for t, r in zip(tasks, rewards):
        per_task.setdefault(t, []).append(r)
    result = {
        "dataset": os.path.basename(dataset_path),
        "n_prompts": len(items),
        "accuracy": sum(rewards) / max(1, len(rewards)),
        "per_task": {
            t: {"accuracy": sum(rs) / len(rs), "n": len(rs)}
            for t, rs in per_task.items()
        },
        "gen_time_s": round(gen_time, 2),
    }
    return result


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description="areal_tpu offline evaluation")
    p.add_argument("--ckpt", required=True)
    p.add_argument("--dataset", required=True)
    p.add_argument("--output", required=True)
    p.add_argument("--max-prompts", type=int, default=64)
    p.add_argument("--max-new-tokens", type=int, default=512)
    p.add_argument("--kv-cache-len", type=int, default=2048)
    args = p.parse_args(argv)
    result = evaluate_checkpoint(
        args.ckpt,
        args.dataset,
        max_prompts=args.max_prompts,
        max_new_tokens=args.max_new_tokens,
        kv_cache_len=args.kv_cache_len,
    )
    os.makedirs(os.path.dirname(os.path.abspath(args.output)), exist_ok=True)
    tmp = args.output + ".tmp"
    with open(tmp, "w") as f:
        json.dump(result, f, indent=2)
    os.replace(tmp, args.output)
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""In-process experiment runner: master + workers as threads.

Rebuild of the reference's local launch path (reference:
realhf/apps/main.py ``main_start`` + realhf/system/controller.py; the
threaded mode mirrors the CPU e2e test harness
tests/experiments/utils.py:52 ``run_test_exp``).  Suitable for single-host
experiments — which on TPU covers a whole slice, since one process drives
all local chips.
"""

from __future__ import annotations

import threading
import time
from typing import List, Optional

from areal_tpu.api import system_api
from areal_tpu.base import constants, logging_, name_resolve
from areal_tpu.system.master_worker import MasterWorker
from areal_tpu.system.model_worker import ModelWorker
from areal_tpu.system.worker_base import WorkerServerStatus

logger = logging_.getLogger("local_runner")


def register_impls():
    """Import all implementation modules so their registries populate
    (reference: realhf/apps/remote.py ``_patch_external_impl``)."""
    import areal_tpu.data.math_code_dataset  # noqa: F401
    import areal_tpu.data.prompt_answer_dataset  # noqa: F401
    import areal_tpu.data.prompt_dataset  # noqa: F401
    import areal_tpu.data.rw_paired_dataset  # noqa: F401
    import areal_tpu.agents.math_multi_turn_agent  # noqa: F401
    import areal_tpu.agents.math_single_step_agent  # noqa: F401
    import areal_tpu.engine.backend  # noqa: F401
    import areal_tpu.envs.math_code_single_step_env  # noqa: F401
    import areal_tpu.experiments.async_ppo_exp  # noqa: F401
    import areal_tpu.experiments.dpo_exp  # noqa: F401
    import areal_tpu.experiments.null_exp  # noqa: F401
    import areal_tpu.experiments.ppo_math_exp  # noqa: F401
    import areal_tpu.experiments.rm_exp  # noqa: F401
    import areal_tpu.experiments.sft_exp  # noqa: F401
    import areal_tpu.interfaces.dpo_interface  # noqa: F401
    import areal_tpu.interfaces.fused_interface  # noqa: F401
    import areal_tpu.interfaces.ppo_interface  # noqa: F401
    import areal_tpu.interfaces.rw_interface  # noqa: F401
    import areal_tpu.interfaces.sft_interface  # noqa: F401

    # pre-resolve transformers' lazy attributes in the main thread: its lazy
    # module loader is not thread-safe, and worker threads load tokenizers
    # concurrently at configure time
    from transformers import AutoConfig, AutoTokenizer  # noqa: F401


def run_experiment_local(
    cfg: system_api.ExperimentConfig,
    timeout: Optional[float] = None,
) -> MasterWorker:
    """Run to completion in this process; returns the master (stats inside)."""
    register_impls()
    constants.set_experiment_trial_names(cfg.experiment_name, cfg.trial_name)

    workers: List[ModelWorker] = []
    threads: List[threading.Thread] = []
    errors: List[BaseException] = []

    def _run_worker(w, wcfg):
        try:
            w.run(wcfg)
        except BaseException as e:  # noqa: BLE001
            errors.append(e)

    for wcfg in cfg.model_workers:
        w = ModelWorker()
        workers.append(w)
        t = threading.Thread(
            target=_run_worker, args=(w, wcfg), daemon=True,
            name=wcfg.worker_name,
        )
        t.start()
        threads.append(t)

    # rollout stack (async experiments)
    aux_threads, aux_workers = _start_rollout_stack(cfg, errors)

    # automatic evaluator (same component the process launcher drives;
    # reference: apps/main.py builds it alongside the monitor)
    evaluator = None
    eval_stop = threading.Event()
    if cfg.evaluator is not None:
        from areal_tpu.scheduler.evaluator import (
            make_evaluator,
            run_evaluator_loop,
        )

        evaluator = make_evaluator(cfg)
        et = threading.Thread(
            target=run_evaluator_loop,
            args=(evaluator, eval_stop, cfg.evaluator.interval),
            daemon=True,
            name="evaluator",
        )
        et.start()
        aux_threads.append(et)

    master = MasterWorker()
    master_err: List[BaseException] = []

    def _run_master():
        try:
            master.run_async(cfg.master)
        except BaseException as e:  # noqa: BLE001
            master_err.append(e)

    mt = threading.Thread(target=_run_master, daemon=True, name="master")
    mt.start()
    deadline = time.monotonic() + timeout if timeout else None
    try:
        while mt.is_alive():
            mt.join(timeout=0.5)
            if errors:
                for w in workers:
                    w.exit()
                raise RuntimeError("worker failed") from errors[0]
            if deadline and time.monotonic() > deadline:
                raise TimeoutError("experiment timed out")
        if master_err:
            raise RuntimeError("master failed") from master_err[0]
    finally:
        # stop the evaluator on every exit path (its subprocess is detached)
        eval_stop.set()
        if evaluator is not None:
            evaluator.shutdown()
    for w in workers + aux_workers:
        w.exit()
    for t in threads + aux_threads:
        t.join(timeout=10)
    return master


def _start_rollout_stack(cfg: system_api.ExperimentConfig, errors):
    threads = []
    aux = []
    if cfg.gen_servers:
        from areal_tpu.system.generation_server import GenerationServerWorker

        for gcfg in cfg.gen_servers:
            aux.append((GenerationServerWorker(), gcfg))
    if cfg.gserver_manager is not None:
        from areal_tpu.system.gserver_manager import GserverManager

        aux.append((GserverManager(), cfg.gserver_manager))
    if cfg.rollout_workers:
        from areal_tpu.system.rollout_worker import RolloutWorker

        for rcfg in cfg.rollout_workers:
            aux.append((RolloutWorker(), rcfg))
    if getattr(cfg, "gateway", None) is not None:
        from areal_tpu.gateway.worker import GatewayWorker

        aux.append((GatewayWorker(), cfg.gateway))

    from areal_tpu.system.worker_base import AsyncWorker

    def _run(w, wcfg):
        try:
            if isinstance(w, AsyncWorker):
                w.run_async(wcfg)
            else:
                w.run(wcfg)
        except BaseException as e:  # noqa: BLE001
            errors.append(e)

    worker_objs = []
    for w, wcfg in aux:
        worker_objs.append(w)
        t = threading.Thread(
            target=_run, args=(w, wcfg), daemon=True,
            name=getattr(wcfg, "worker_name", "aux"),
        )
        t.start()
        threads.append(t)
    return threads, worker_objs

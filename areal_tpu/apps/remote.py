"""Per-process worker entry.

Rebuild of the reference's remote worker entry (reference:
realhf/apps/remote.py ``main_worker``/``main_controller`` — the process the
scheduler actually launches; it re-registers the experiment from an on-disk
cache and runs one worker).  The launcher (areal_tpu/apps/main.py) dumps the
fully-resolved ``ExperimentConfig`` to the cluster cache dir; every worker
process loads it and picks its own slice, so no controller push-channel is
needed for configuration — name_resolve (NFS backend by default) is the only
cross-process dependency.

Usage::

    python -m areal_tpu.apps.remote --experiment_name E --trial_name T \
        --worker_type model_worker --worker_index 0
"""

from __future__ import annotations

import argparse
import os
import pickle
import sys


def config_cache_path(experiment_name: str, trial_name: str) -> str:
    from areal_tpu.base import constants

    return os.path.join(
        constants.get_cache_path(),
        f"{experiment_name}-{trial_name}-config.pkl",
    )


def dump_experiment_config(cfg) -> str:
    path = config_cache_path(cfg.experiment_name, cfg.trial_name)
    with open(path + ".tmp", "wb") as f:
        pickle.dump(cfg, f)
    os.replace(path + ".tmp", path)
    return path


def load_experiment_config(experiment_name: str, trial_name: str):
    with open(config_cache_path(experiment_name, trial_name), "rb") as f:
        return pickle.load(f)


def _maybe_init_jax_distributed():
    """Join the jax.distributed cluster when the launcher exported the
    coordination env (multi-host SPMD over DCN; reference analogue: the NCCL
    group bootstrap realhf/impl/model/comm/global_comm.py:48)."""
    coord = os.environ.get("AREAL_JAX_COORDINATOR")
    if not coord:
        return
    import jax

    jax.distributed.initialize(
        coordinator_address=coord,
        num_processes=int(os.environ["AREAL_JAX_NUM_PROCESSES"]),
        process_id=int(os.environ["AREAL_JAX_PROCESS_ID"]),
    )


def run_worker(
    experiment_name: str,
    trial_name: str,
    worker_type: str,
    worker_index: int,
) -> str:
    """Run one worker to completion in this process; returns final status."""
    from areal_tpu.apps.local_runner import register_impls
    from areal_tpu.base import constants, name_resolve
    from areal_tpu.system.worker_base import AsyncWorker, make_server

    # hermetic platform pinning for CPU-mesh tests and mixed fleets: the env
    # var alone can lose to an eagerly-registered platform plugin, so also
    # set jax.config (same pattern as tests/conftest.py)
    platform = os.environ.get("AREAL_JAX_PLATFORM")
    if platform:
        import jax

        jax.config.update("jax_platforms", platform)

    name_resolve.reconfigure(
        os.environ.get("AREAL_NAME_RESOLVE", "nfs"),
    )
    constants.set_experiment_trial_names(experiment_name, trial_name)
    register_impls()
    _maybe_init_jax_distributed()
    cfg = load_experiment_config(experiment_name, trial_name)

    if worker_type == "master":
        from areal_tpu.system.master_worker import MasterWorker

        cls, wcfg = MasterWorker, cfg.master
    elif worker_type == "model_worker":
        from areal_tpu.system.model_worker import ModelWorker

        cls, wcfg = ModelWorker, cfg.model_workers[worker_index]
    elif worker_type == "rollout_worker":
        from areal_tpu.system.rollout_worker import RolloutWorker

        cls, wcfg = RolloutWorker, cfg.rollout_workers[worker_index]
    elif worker_type == "gen_server":
        from areal_tpu.system.generation_server import GenerationServerWorker

        cls, wcfg = GenerationServerWorker, cfg.gen_servers[worker_index]
    elif worker_type == "gserver_manager":
        from areal_tpu.system.gserver_manager import GserverManager

        cls, wcfg = GserverManager, cfg.gserver_manager
    elif worker_type == "gateway":
        from areal_tpu.gateway.worker import GatewayWorker

        cls, wcfg = GatewayWorker, cfg.gateway
    else:
        raise ValueError(f"unknown worker type {worker_type!r}")

    server = make_server(wcfg.worker_name)
    worker = cls(server)
    if isinstance(worker, AsyncWorker):
        status = worker.run_async(wcfg)
    else:
        status = worker.run(wcfg)
    return str(status.value if hasattr(status, "value") else status)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description="areal_tpu remote worker entry")
    p.add_argument("--experiment_name", required=True)
    p.add_argument("--trial_name", required=True)
    p.add_argument("--worker_type", required=True)
    p.add_argument("--worker_index", type=int, default=0)
    args = p.parse_args(argv)
    status = run_worker(
        args.experiment_name,
        args.trial_name,
        args.worker_type,
        args.worker_index,
    )
    return 0 if status in ("COMPLETED", "PAUSED") else 1


if __name__ == "__main__":
    sys.exit(main())

"""Experiment launcher: one process per worker, monitored, restartable.

Rebuild of the reference's classic launch path (reference:
realhf/apps/main.py:78 ``main_start`` with the recover-restart loop
:108-288, plus the controller's configure/monitor/panic role,
realhf/system/controller.py:98).  Differences by design: workers read their
config slice from the dumped ``ExperimentConfig`` cache instead of a
controller push channel, and on TPU the launch unit is one process per HOST
(each process drives its local chips; jax.distributed joins them into one
SPMD world when ``AREAL_JAX_COORDINATOR`` is exported).
"""

from __future__ import annotations

import os
import sys
import time
from typing import Dict, List, Optional, Tuple

from areal_tpu.api import system_api
from areal_tpu.apps import remote
from areal_tpu.base import constants, logging_, name_resolve, names
from areal_tpu.scheduler.client import (
    JobException,
    JobState,
    make_scheduler,
)
from areal_tpu.system.worker_base import (
    WorkerControlPanel,
    WorkerServerStatus,
)

logger = logging_.getLogger("launcher")

TERMINAL_STATUSES = (
    WorkerServerStatus.COMPLETED,
    WorkerServerStatus.ERROR,
    WorkerServerStatus.LOST,
)


def _worker_specs(cfg: system_api.ExperimentConfig) -> List[Tuple[str, int, str]]:
    """[(worker_type, index, worker_name)] for every worker process."""
    specs = [("master", 0, cfg.master.worker_name)]
    for i, w in enumerate(cfg.model_workers):
        specs.append(("model_worker", i, w.worker_name))
    for i, w in enumerate(cfg.gen_servers):
        specs.append(("gen_server", i, w.worker_name))
    if cfg.gserver_manager is not None:
        specs.append(("gserver_manager", 0, cfg.gserver_manager.worker_name))
    for i, w in enumerate(cfg.rollout_workers):
        specs.append(("rollout_worker", i, w.worker_name))
    if getattr(cfg, "gateway", None) is not None:
        specs.append(("gateway", 0, cfg.gateway.worker_name))
    return specs


def launch_experiment(
    cfg: system_api.ExperimentConfig,
    mode: str = "local",
    recover_retries: int = 0,
    timeout: Optional[float] = None,
    env: Optional[Dict[str, str]] = None,
) -> None:
    """Launch every worker as its own process; monitor to completion.

    Restarts the whole experiment up to ``recover_retries`` times when a
    worker fails (the reference's experiment-level recovery policy,
    realhf/apps/main.py:108-288; recover ckpt loading happens inside the
    workers)."""
    trials = recover_retries + 1
    last_exc: Optional[BaseException] = None
    for attempt in range(trials):
        if attempt > 0:
            logger.warning(
                "restarting experiment (recover attempt %d/%d)",
                attempt,
                recover_retries,
            )
        try:
            _launch_once(cfg, mode=mode, timeout=timeout, env=env, recover=attempt > 0)
            return
        except (JobException, TimeoutError) as e:
            last_exc = e
            if attempt == trials - 1:
                raise
    if last_exc:
        raise last_exc


def _launch_once(
    cfg: system_api.ExperimentConfig,
    mode: str,
    timeout: Optional[float],
    env: Optional[Dict[str, str]],
    recover: bool = False,
) -> None:
    constants.set_experiment_trial_names(cfg.experiment_name, cfg.trial_name)
    backend = os.environ.get("AREAL_NAME_RESOLVE", "nfs")
    name_resolve.reconfigure(backend)
    name_resolve.clear_subtree(
        names.trial_root(cfg.experiment_name, cfg.trial_name)
    )
    remote.dump_experiment_config(cfg)

    sched = make_scheduler(mode, cfg.experiment_name, cfg.trial_name)
    wenv = {
        "AREAL_NAME_RESOLVE": backend,
        # the server backend resolves its endpoint from this var — workers
        # need it propagated just like the backend selector itself
        **(
            {"AREAL_NAME_RESOLVE_ADDR": os.environ["AREAL_NAME_RESOLVE_ADDR"]}
            if os.environ.get("AREAL_NAME_RESOLVE_ADDR")
            else {}
        ),
        **({"AREAL_RECOVER": "1"} if recover else {}),
        **(env or {}),
    }
    log_dir = constants.get_log_path()
    specs = _worker_specs(cfg)
    # observability plane: with AREAL_METRICS_PORT_BASE set, every worker's
    # /metrics endpoint gets a deterministic port (base + launch index) so
    # ops tooling/firewalls can pre-open them; unset, each worker binds a
    # random free port and publishes it via name_resolve either way.
    # Local mode only: the slurm client exports env at CONSTRUCTION, not
    # per-submit, and cross-host port pinning belongs in the sbatch prolog.
    metrics_base = None
    raw_base = os.environ.get("AREAL_METRICS_PORT_BASE")
    if raw_base and mode == "local":
        try:
            metrics_base = int(raw_base)
        except ValueError:
            logger.warning(
                "ignoring non-numeric AREAL_METRICS_PORT_BASE=%r", raw_base
            )
    for seq, (wtype, idx, wname) in enumerate(specs):
        worker_env = dict(wenv)
        if metrics_base is not None:
            worker_env["AREAL_METRICS_PORT"] = str(metrics_base + seq)
        sched.submit(
            wtype,
            [
                sys.executable,
                "-m",
                "areal_tpu.apps.remote",
                "--experiment_name",
                cfg.experiment_name,
                "--trial_name",
                cfg.trial_name,
                "--worker_type",
                wtype,
                "--worker_index",
                str(idx),
            ],
            env=worker_env,
            log_path=os.path.join(log_dir, f"{wname}.log"),
        )
    try:
        _monitor(sched, cfg, specs, timeout, mode=mode)
    except BaseException:
        sched.stop_all()
        raise


def _make_evaluator(cfg: system_api.ExperimentConfig, mode: str = "local"):
    """Checkpoint-watching evaluator driven by the controller loop
    (reference: realhf/apps/main.py:96-154 builds the AutomaticEvaluator and
    steps it while monitoring).  Eval jobs submit through the same
    scheduler layer as workers, so slurm experiments get slurm evals."""
    from areal_tpu.scheduler.evaluator import make_evaluator

    return make_evaluator(cfg, scheduler_mode=mode)


def _monitor(
    sched,
    cfg: system_api.ExperimentConfig,
    specs: List[Tuple[str, int, str]],
    timeout: Optional[float],
    mode: str = "local",
) -> None:
    """Controller role: watch job + worker statuses; panic on failure; when
    the master completes, gracefully exit the remaining workers."""
    deadline = time.monotonic() + timeout if timeout else None
    master_name = cfg.master.worker_name
    status_key = names.worker_status(
        cfg.experiment_name, cfg.trial_name, master_name
    )
    all_names = [w for _, _, w in specs]
    # beats come from a daemon thread, so this is a process-liveness bound
    # (not an MFC-duration bound); the scheduler catches clean process death
    # faster, heartbeats catch hosts that vanish without reaping
    hb_timeout = float(os.environ.get("AREAL_HEARTBEAT_TIMEOUT", "60"))
    panel = WorkerControlPanel(cfg.experiment_name, cfg.trial_name)
    evaluator = _make_evaluator(cfg, mode)
    last_eval_step = time.monotonic()
    completed = False
    try:
        _monitor_loop(
            sched,
            cfg,
            deadline,
            status_key,
            master_name,
            panel,
            all_names,
            hb_timeout,
            evaluator,
            last_eval_step,
        )
        completed = True
    finally:
        # every exit path (worker failure, timeout, Ctrl-C) must reap the
        # detached eval subprocess or a restart would race the orphan
        if evaluator is not None:
            evaluator._harvest()
            evaluator.shutdown()
        if not completed:
            panel.close()

    _shutdown_workers(sched, cfg, specs, panel, master_name)


def _monitor_loop(
    sched,
    cfg,
    deadline,
    status_key,
    master_name,
    panel,
    all_names,
    hb_timeout,
    evaluator,
    last_eval_step,
):
    last_hb_check = time.monotonic()
    while True:
        for job in sched.find_all():
            if job.state == JobState.FAILED:
                raise JobException(
                    sched.run_name, job.name, job.host, job.state
                )
        try:
            master_status = name_resolve.get(status_key)
        except name_resolve.NameEntryNotFoundError:
            master_status = None
        if master_status == WorkerServerStatus.COMPLETED.value:
            break
        if master_status == WorkerServerStatus.ERROR.value:
            raise JobException(
                sched.run_name, master_name, "?", JobState.FAILED
            )
        if time.monotonic() - last_hb_check > 10.0:
            last_hb_check = time.monotonic()
            stale = panel.find_stale_workers(all_names, timeout=hb_timeout)
            if stale:
                for w in stale:
                    logger.error(
                        "worker %s heartbeat stale > %.0fs; declaring LOST",
                        w,
                        hb_timeout,
                    )
                raise JobException(
                    sched.run_name, stale[0], "?", JobState.FAILED
                )
        if evaluator is not None and (
            time.monotonic() - last_eval_step > cfg.evaluator.interval
        ):
            last_eval_step = time.monotonic()
            evaluator.step()
        if deadline and time.monotonic() > deadline:
            raise TimeoutError("experiment timed out")
        time.sleep(0.5)


def _shutdown_workers(sched, cfg, specs, panel, master_name):
    # master done: ask everyone else to exit, then reap
    others = [w for t, i, w in specs if w != master_name]
    try:
        panel.connect(others, timeout=10)
        for w in others:
            try:
                panel.request(w, "exit", timeout=10)
            except Exception:  # noqa: BLE001 - best-effort shutdown
                logger.warning("worker %s did not ack exit", w)
    except Exception:  # noqa: BLE001
        logger.warning("could not connect control panel for shutdown")
    finally:
        panel.close()
    try:
        sched.wait(
            timeout=30,
            check_status=(JobState.FAILED,),
            remove_status=(JobState.COMPLETED, JobState.CANCELLED),
        )
    except TimeoutError:
        logger.warning("workers still running after master exit; killing")
    finally:
        sched.stop_all()


def main_stop(experiment_name: str, trial_name: str, mode: str = "local"):
    """Best-effort stop of a running trial (reference main.py ``main_stop``)."""
    constants.set_experiment_trial_names(experiment_name, trial_name)
    name_resolve.reconfigure(os.environ.get("AREAL_NAME_RESOLVE", "nfs"))
    panel = WorkerControlPanel(experiment_name, trial_name)
    root = names.worker_root(experiment_name, trial_name)
    try:
        workers = [k.rsplit("/", 1)[-1] for k in name_resolve.find_subtree(root)]
        panel.connect(workers, timeout=5)
        for w in workers:
            try:
                panel.request(w, "exit", timeout=5)
            except Exception:  # noqa: BLE001
                pass
    finally:
        panel.close()


def main(argv=None) -> int:
    import argparse

    p = argparse.ArgumentParser(description="areal_tpu experiment launcher")
    p.add_argument("command", choices=["stop"])
    p.add_argument("--experiment_name", required=True)
    p.add_argument("--trial_name", required=True)
    args = p.parse_args(argv)
    if args.command == "stop":
        main_stop(args.experiment_name, args.trial_name)
    return 0


if __name__ == "__main__":
    sys.exit(main())

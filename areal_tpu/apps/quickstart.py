"""Quickstart CLI: launch any registered experiment by name.

Rebuild of the reference's quickstart entrypoint (reference:
realhf/apps/quickstart.py + api/quickstart/entrypoint.py — hydra-backed
per-experiment subcommands over the experiment registry).  Ours resolves
the experiment class from the registry, parses ``--config``/dotted
overrides with the in-repo config system (api/cli_args.py), and launches
either in-process (threads, debug) or through the multi-process launcher
(apps/main.py).

Usage::

    python -m areal_tpu.apps.quickstart list
    python -m areal_tpu.apps.quickstart ppo_math --config cfg.yaml \
        trial_name=run0 actor.args.path=/ckpts/qwen2-1.5b
    python -m areal_tpu.apps.quickstart async_ppo_math --mode processes ...
"""

from __future__ import annotations

import os
import sys

from areal_tpu.api import system_api
from areal_tpu.api.cli_args import dump_config, parse_cli
from areal_tpu.base import constants, logging_

logger = logging_.getLogger("quickstart")


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    from areal_tpu.apps.local_runner import register_impls

    register_impls()

    if not argv or argv[0] in ("-h", "--help"):
        print(__doc__)
        print("registered experiments:")
        for name in system_api.list_experiments():
            print(f"  {name}")
        return 0
    cmd = argv.pop(0)
    if cmd == "list":
        for name in system_api.list_experiments():
            print(name)
        return 0

    mode = "threads"
    if "--mode" in argv:
        i = argv.index("--mode")
        mode = argv[i + 1]
        del argv[i : i + 2]

    cls = system_api.experiment_cls(cmd)
    exp = parse_cli(cls, argv=argv)
    exp.apply_device_overrides()
    cfg = exp.initial_setup()
    constants.set_experiment_trial_names(cfg.experiment_name, cfg.trial_name)
    dump_config(exp, os.path.join(constants.get_log_path(), "config.yaml"))
    logger.info(
        "quickstart %s (%s/%s): %d model worker(s), %d gen server(s), "
        "%d rollout worker(s)%s",
        cmd,
        cfg.experiment_name,
        cfg.trial_name,
        len(cfg.model_workers),
        len(cfg.gen_servers),
        len(cfg.rollout_workers),
        ", gateway" if getattr(cfg, "gateway", None) is not None else "",
    )
    if mode == "threads":
        from areal_tpu.apps.local_runner import run_experiment_local

        master = run_experiment_local(cfg)
        logger.info("finished: final stats %s", master.stats)
    else:
        from areal_tpu.apps.main import launch_experiment

        launch_experiment(cfg, mode="local" if mode == "processes" else mode)
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Loader for the in-repo C++ helpers (csrc/).

Compiles ``csrc/*.cpp`` into a shared library on first use (g++, cached
next to the sources with an mtime check) and binds it via ctypes — no
pybind11 dependency.  Every native entry point has a pure-Python fallback
in its caller, so a missing/failed toolchain degrades gracefully
(AREAL_NATIVE=0 forces the fallbacks).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as np

from areal_tpu.base import logging_

logger = logging_.getLogger("native")

_CSRC = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "csrc",
)
_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _build(src: str, out: str) -> bool:
    # build to a per-process temp path and os.replace into place: concurrent
    # workers on a fresh checkout must never dlopen a half-written library
    tmp = f"{out}.tmp-{os.getpid()}"
    try:
        subprocess.run(
            ["g++", "-O2", "-shared", "-fPIC", "-std=c++17", "-o", tmp, src],
            check=True,
            capture_output=True,
            timeout=120,
        )
        os.replace(tmp, out)
        return True
    except (subprocess.SubprocessError, FileNotFoundError, OSError) as e:
        logger.warning("native build failed (%s); using Python fallbacks", e)
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False


def get_lib() -> Optional[ctypes.CDLL]:
    """The datapack shared library, building it if needed; None if
    unavailable."""
    global _lib, _tried
    if os.environ.get("AREAL_NATIVE", "1") == "0":
        return None
    with _lock:
        if _tried:
            return _lib
        _tried = True
        src = os.path.join(_CSRC, "datapack.cpp")
        if not os.path.isfile(src):
            return None
        out = os.path.join(_CSRC, "libdatapack.so")
        if (
            not os.path.isfile(out)
            or os.path.getmtime(out) < os.path.getmtime(src)
        ):
            if not _build(src, out):
                return None
        try:
            lib = ctypes.CDLL(out)
        except OSError as e:
            logger.warning("native load failed (%s)", e)
            return None
        i64p = ctypes.POINTER(ctypes.c_int64)
        lib.ffd_pack.restype = ctypes.c_int64
        lib.ffd_pack.argtypes = [i64p, ctypes.c_int64, ctypes.c_int64, i64p]
        lib.partition_balanced_dp.restype = ctypes.c_int64
        lib.partition_balanced_dp.argtypes = [
            i64p,
            ctypes.c_int64,
            ctypes.c_int64,
            i64p,
        ]
        _lib = lib
        logger.debug("native datapack loaded from %s", out)
        return _lib


def _as_i64(a) -> np.ndarray:
    return np.ascontiguousarray(np.asarray(a, dtype=np.int64))


def ffd_pack(nums, capacity: int):
    """Native FFD; returns (bin_id per item [n], n_bins) or None."""
    lib = get_lib()
    if lib is None:
        return None
    arr = _as_i64(nums)
    out = np.empty(len(arr), np.int64)
    n_bins = lib.ffd_pack(
        arr.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        len(arr),
        int(capacity),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
    )
    return out, int(n_bins)


def partition_balanced(nums, k: int):
    """Native balanced partition; returns cut boundaries [k+1] or None."""
    lib = get_lib()
    if lib is None:
        return None
    arr = _as_i64(nums)
    cuts = np.empty(k + 1, np.int64)
    rc = lib.partition_balanced_dp(
        arr.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        len(arr),
        int(k),
        cuts.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
    )
    if rc != 0:
        return None
    return cuts

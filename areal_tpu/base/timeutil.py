"""Frequency control utilities (reference: realhf/base/timeutil.py, FrequencyControl
and EpochStepTimeFreqCtl :127).

Used by the master worker to decide when to save / eval / checkpoint, and the
state is serialized into RecoverInfo so resumed runs keep cadence.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Optional


@dataclasses.dataclass
class FrequencyControl:
    """Triggers every ``frequency_seconds`` seconds and/or ``frequency_steps``
    calls; either may be None.  ``initial_value`` makes the first check fire."""

    frequency_seconds: Optional[float] = None
    frequency_steps: Optional[int] = None
    initial_value: bool = False

    def __post_init__(self):
        self._last_time = time.monotonic()
        self._steps = 0
        self._initial = self.initial_value

    def check(self, steps: int = 1) -> bool:
        self._steps += steps
        if self._initial:
            self._initial = False
            self._last_time = time.monotonic()
            self._steps = 0
            return True
        hit = False
        if (
            self.frequency_steps is not None
            and self._steps >= self.frequency_steps
        ):
            hit = True
        if (
            self.frequency_seconds is not None
            and time.monotonic() - self._last_time >= self.frequency_seconds
        ):
            hit = True
        if hit:
            self._last_time = time.monotonic()
            self._steps = 0
        return hit

    def state_dict(self):
        return {
            "steps": self._steps,
            "elapsed": time.monotonic() - self._last_time,
            "initial": self._initial,
        }

    def load_state_dict(self, state):
        self._steps = state["steps"]
        self._last_time = time.monotonic() - state["elapsed"]
        self._initial = state["initial"]


@dataclasses.dataclass
class EpochStepTimeFreqCtl:
    """Triggers on epoch boundaries, global-step counts, or elapsed seconds —
    whichever fires (reference :127)."""

    freq_epoch: Optional[int] = None
    freq_step: Optional[int] = None
    freq_sec: Optional[float] = None
    initial_value: bool = False

    def __post_init__(self):
        self._epoch_cnt = 0
        self._step_cnt = 0
        self._last_time = time.monotonic()
        self._initial = self.initial_value

    def check(self, epochs: int = 0, steps: int = 1) -> bool:
        self._epoch_cnt += epochs
        self._step_cnt += steps
        if self._initial:
            self._initial = False
            return True
        hit = False
        if self.freq_epoch is not None and self._epoch_cnt >= self.freq_epoch:
            self._epoch_cnt = 0
            hit = True
        if self.freq_step is not None and self._step_cnt >= self.freq_step:
            self._step_cnt = 0
            hit = True
        if (
            self.freq_sec is not None
            and time.monotonic() - self._last_time >= self.freq_sec
        ):
            self._last_time = time.monotonic()
            hit = True
        if hit and self.freq_sec is not None:
            self._last_time = time.monotonic()
        return hit

    def state_dict(self):
        return {
            "epoch_cnt": self._epoch_cnt,
            "step_cnt": self._step_cnt,
            "elapsed": time.monotonic() - self._last_time,
            "initial": self._initial,
        }

    def load_state_dict(self, state):
        self._epoch_cnt = state["epoch_cnt"]
        self._step_cnt = state["step_cnt"]
        self._last_time = time.monotonic() - state["elapsed"]
        self._initial = state["initial"]

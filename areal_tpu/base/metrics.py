"""Experiment metrics sinks: JSONL + tensorboard + optional wandb/swanlab.

Rebuild of the reference's observability fan-out (reference:
realhf/system/master_worker.py:291-350 initializes wandb / swanlab /
tensorboard and realhf/base/logging.py ``log_swanlab_wandb_tensorboard``
writes every scalar to all three).  Differences by design: a JSONL sink is
always on (it is the machine-readable artifact tests and the offline
evaluator consume), tensorboard uses torch's bundled ``SummaryWriter``, and
wandb/swanlab are optional imports that degrade to no-ops when the package
or the opt-in env (``AREAL_WANDB=1`` / ``AREAL_SWANLAB=1``) is absent.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, Optional

from areal_tpu.base import logging_

logger = logging_.getLogger("metrics")


class MetricsLogger:
    """Fan-out scalar logger keyed by global step."""

    def __init__(
        self,
        log_dir: str,
        experiment_name: str = "",
        trial_name: str = "",
        enable_tensorboard: bool = True,
    ):
        os.makedirs(log_dir, exist_ok=True)
        self.log_dir = log_dir
        self._jsonl_path = os.path.join(log_dir, "stats.jsonl")
        self._jsonl = open(self._jsonl_path, "a", buffering=1)
        self._tb = None
        if enable_tensorboard:
            try:
                from torch.utils.tensorboard import SummaryWriter

                self._tb = SummaryWriter(
                    log_dir=os.path.join(log_dir, "tensorboard")
                )
            except Exception:  # noqa: BLE001 - tb is best-effort
                logger.warning("tensorboard unavailable; skipping")
        self._wandb = None
        if os.environ.get("AREAL_WANDB") == "1":
            try:
                import wandb

                self._wandb = wandb
                wandb.init(
                    project=experiment_name or "areal_tpu",
                    name=trial_name or None,
                    dir=log_dir,
                    mode=os.environ.get("WANDB_MODE", "online"),
                )
            except Exception:  # noqa: BLE001
                logger.warning("wandb requested but unavailable")
                self._wandb = None
        self._swanlab = None
        if os.environ.get("AREAL_SWANLAB") == "1":
            try:
                import swanlab

                self._swanlab = swanlab
                swanlab.init(
                    project=experiment_name or "areal_tpu",
                    experiment_name=trial_name or None,
                    logdir=log_dir,
                )
            except Exception:  # noqa: BLE001
                logger.warning("swanlab requested but unavailable")
                self._swanlab = None

    def log(self, stats: Dict[str, Any], step: int):
        """Write one step's scalars to every sink."""
        scalars = {
            k: float(v)
            for k, v in stats.items()
            if isinstance(v, (int, float)) and not isinstance(v, bool)
        }
        rec = {"step": step, "time": time.time(), **scalars}
        self._jsonl.write(json.dumps(rec) + "\n")
        if self._tb is not None:
            for k, v in scalars.items():
                self._tb.add_scalar(k, v, global_step=step)
        if self._wandb is not None:
            self._wandb.log(scalars, step=step)
        if self._swanlab is not None:
            self._swanlab.log(scalars, step=step)

    def close(self):
        self._jsonl.close()
        if self._tb is not None:
            self._tb.close()
        if self._wandb is not None:
            self._wandb.finish()
        if self._swanlab is not None:
            self._swanlab.finish()

"""Device-mesh topology.

TPU-native replacement for the reference's rank-math topology layer
(reference: realhf/base/topology.py:86 ``ProcessTopology``, :329/:350 the
pipe-data-tensor orderings, :369 ``ParallelGrid`` building NCCL subgroups).

On TPU there are no NCCL groups to build: parallelism is expressed as a
``jax.sharding.Mesh`` with named axes and XLA inserts collectives.  What
remains of the reference's topology layer is:

* ``MeshSpec`` — the named-axis shape of a model's device mesh (replaces
  ``PipeDataTensorParallelTopology``).  Axes:
    - ``data``:  pure data parallel (gradient all-reduce)
    - ``fsdp``:  parameter/optimizer sharding data axis (ZeRO-3 style)
    - ``model``: tensor parallelism (megatron-style sharded matmuls)
    - ``pipe``:  pipeline stages (optional; XLA SPMD usually suffices)
    - ``seq``:   context/sequence parallelism for ring attention
* ``ProcessTopology`` — generic named-axis cartesian rank math, still used by
  the *system* layer to reason about worker placement and data dispatch
  (which worker process owns which DP shard), and by tests.
"""

from __future__ import annotations

import dataclasses
import itertools
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

# Canonical mesh axis names, in layout-major order.  ``data`` and ``fsdp``
# vary slowest (DCN-friendly), ``model`` fastest (ICI-ring-friendly): tensor
# parallel collectives are the most latency sensitive so the model axis maps
# onto adjacent chips.
MESH_AXIS_ORDER = ("pipe", "data", "fsdp", "seq", "expert", "model")

DATA_AXES = ("data", "fsdp")  # batch is sharded over these
PARAM_AXES = ("fsdp", "model")  # params are sharded over these


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Named-axis mesh shape for one model role.

    The product of all axis sizes is the model's world size (number of chips).
    """

    data: int = 1
    fsdp: int = 1
    model: int = 1
    pipe: int = 1
    seq: int = 1
    expert: int = 1  # expert parallelism: shards the MoE expert dimension

    def __post_init__(self):
        for ax in MESH_AXIS_ORDER:
            if getattr(self, ax) < 1:
                raise ValueError(f"axis {ax} must be >= 1")

    @property
    def world_size(self) -> int:
        return (
            self.data
            * self.fsdp
            * self.model
            * self.pipe
            * self.seq
            * self.expert
        )

    @property
    def shape(self) -> Dict[str, int]:
        return {ax: getattr(self, ax) for ax in MESH_AXIS_ORDER}

    @property
    def axis_names(self) -> Tuple[str, ...]:
        return MESH_AXIS_ORDER

    @property
    def dp_size(self) -> int:
        """Number of independent data shards (gradient-averaged groups)."""
        return self.data * self.fsdp

    def make_mesh(self, devices: Optional[Sequence] = None):
        """Build a ``jax.sharding.Mesh`` over ``devices`` (default: all)."""
        import jax
        from jax.sharding import Mesh

        if devices is None:
            devices = jax.devices()
        if len(devices) < self.world_size:
            raise ValueError(
                f"need {self.world_size} devices for {self}, got {len(devices)}"
            )
        devices = np.asarray(devices[: self.world_size]).reshape(
            tuple(self.shape.values())
        )
        return Mesh(devices, axis_names=self.axis_names)

    @classmethod
    def from_str(cls, s: str) -> "MeshSpec":
        """Parse compact strings like ``d2f2m2`` / ``d4p1m1`` / ``d2f1m2s1p1``.

        Letters: d=data, f=fsdp, m=model, p=pipe, s=seq.  Mirrors the
        reference's ``AllocationMode.from_str`` parallel-strategy substrings
        (reference: realhf/experiments/common/utils.py:245-372).
        """
        import re

        mapping = {
            "d": "data",
            "f": "fsdp",
            "m": "model",
            "p": "pipe",
            "s": "seq",
            "e": "expert",
        }
        kwargs = {}
        for m in re.finditer(r"([dfmpse])(\d+)", s):
            kwargs[mapping[m.group(1)]] = int(m.group(2))
        if not kwargs:
            raise ValueError(f"cannot parse mesh spec {s!r}")
        return cls(**kwargs)

    def __str__(self):
        return (
            f"d{self.data}f{self.fsdp}m{self.model}"
            f"p{self.pipe}s{self.seq}e{self.expert}"
        )


class ProcessTopology:
    """Named-axis cartesian rank math (reference: realhf/base/topology.py:86).

    Maps between flat ranks and named coordinates; supports filtering by
    coordinate values.  Axes earlier in ``axes`` vary slowest.
    """

    def __init__(self, axes: Sequence[str], dims: Sequence[int]):
        if len(axes) != len(dims):
            raise ValueError("axes/dims length mismatch")
        self.axes = tuple(axes)
        self.dims = tuple(int(d) for d in dims)

    def world_size(self) -> int:
        return int(np.prod(self.dims)) if self.dims else 1

    def get_dim(self, axis: str) -> int:
        return self.dims[self.axes.index(axis)]

    def get_rank(self, **coords) -> int:
        if set(coords) != set(self.axes):
            raise ValueError(f"need all axes {self.axes}, got {set(coords)}")
        rank = 0
        for ax, dim in zip(self.axes, self.dims):
            c = coords[ax]
            if not 0 <= c < dim:
                raise ValueError(f"coord {ax}={c} out of range [0,{dim})")
            rank = rank * dim + c
        return rank

    def get_coord(self, rank: int) -> Dict[str, int]:
        if not 0 <= rank < self.world_size():
            raise ValueError(f"rank {rank} out of range")
        coords = {}
        for ax, dim in zip(reversed(self.axes), reversed(self.dims)):
            coords[ax] = rank % dim
            rank //= dim
        return {ax: coords[ax] for ax in self.axes}

    def filter_match(self, **filters) -> List[int]:
        """Ranks whose coordinates match all given axis=value filters."""
        out = []
        for rank in range(self.world_size()):
            coord = self.get_coord(rank)
            if all(coord[ax] == v for ax, v in filters.items()):
                out.append(rank)
        return out

    def all_coords(self):
        for combo in itertools.product(*(range(d) for d in self.dims)):
            yield dict(zip(self.axes, combo))

    def __repr__(self):
        return f"ProcessTopology({dict(zip(self.axes, self.dims))})"


def worker_topology(spec: MeshSpec) -> ProcessTopology:
    """Worker-grid topology for a mesh spec: one logical rank per chip, in the
    same pipe→data→fsdp→seq→model order the mesh uses."""
    return ProcessTopology(axes=list(MESH_AXIS_ORDER), dims=list(spec.shape.values()))

"""Recover / resume bookkeeping (reference: realhf/base/recover.py —
``StepInfo`` :19, ``RecoverInfo`` :26, dump/load :43-75, discover_ckpt :80).

A recover checkpoint = model checkpoints (saved elsewhere, via orbax /
safetensors) + this JSON-serializable RecoverInfo: where training stopped,
frequency-control states, and which dataset ids were already consumed.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any, Dict, List, Optional

from areal_tpu.base import constants, logging_

logger = logging_.getLogger("recover")

RECOVER_INFO_FILE = "recover_info.json"


@dataclasses.dataclass
class StepInfo:
    epoch: int = 0
    epoch_step: int = 0
    global_step: int = 0

    def next(self, steps_per_epoch: int) -> "StepInfo":
        ep, es = self.epoch, self.epoch_step + 1
        if es >= steps_per_epoch:
            ep, es = ep + 1, 0
        return StepInfo(ep, es, self.global_step + 1)


@dataclasses.dataclass
class RecoverInfo:
    recover_start: StepInfo = dataclasses.field(default_factory=StepInfo)
    last_step_info: StepInfo = dataclasses.field(default_factory=StepInfo)
    save_ctl_states: Dict[str, Any] = dataclasses.field(default_factory=dict)
    eval_ctl_states: Dict[str, Any] = dataclasses.field(default_factory=dict)
    ckpt_ctl_states: Dict[str, Any] = dataclasses.field(default_factory=dict)
    hash_vals_to_ignore: List[str] = dataclasses.field(default_factory=list)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "recover_start": dataclasses.asdict(self.recover_start),
            "last_step_info": dataclasses.asdict(self.last_step_info),
            "save_ctl_states": self.save_ctl_states,
            "eval_ctl_states": self.eval_ctl_states,
            "ckpt_ctl_states": self.ckpt_ctl_states,
            "hash_vals_to_ignore": list(self.hash_vals_to_ignore),
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "RecoverInfo":
        return cls(
            recover_start=StepInfo(**d["recover_start"]),
            last_step_info=StepInfo(**d["last_step_info"]),
            save_ctl_states=d.get("save_ctl_states", {}),
            eval_ctl_states=d.get("eval_ctl_states", {}),
            ckpt_ctl_states=d.get("ckpt_ctl_states", {}),
            hash_vals_to_ignore=d.get("hash_vals_to_ignore", []),
        )


def dump(info: RecoverInfo, path: Optional[str] = None):
    path = path or os.path.join(constants.get_recover_path(), RECOVER_INFO_FILE)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(info.to_dict(), f, indent=2)
    os.replace(tmp, path)
    logger.debug("dumped recover info to %s", path)


def load(path: Optional[str] = None) -> RecoverInfo:
    path = path or os.path.join(constants.get_recover_path(), RECOVER_INFO_FILE)
    with open(path) as f:
        return RecoverInfo.from_dict(json.load(f))


def discover(path: Optional[str] = None) -> Optional[RecoverInfo]:
    """Return RecoverInfo if a recover checkpoint exists, else None."""
    path = path or os.path.join(constants.get_recover_path(), RECOVER_INFO_FILE)
    if not os.path.isfile(path):
        return None
    try:
        return load(path)
    except (json.JSONDecodeError, KeyError):
        logger.warning("corrupt recover info at %s; ignoring", path)
        return None

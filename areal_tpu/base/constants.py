"""Process-global experiment context (reference: realhf/base/constants.py).

Holds experiment/trial names, the per-model mesh registry, and the
``model_scope`` context manager that the reference uses to switch "the
current model" (reference :215).  Path helpers mirror :82-118.
"""

from __future__ import annotations

import contextlib
import getpass
import os
from typing import Dict, Optional

_experiment_name: Optional[str] = None
_trial_name: Optional[str] = None

_model_scope_stack = []
_meshes: Dict[str, object] = {}  # model_name -> jax.sharding.Mesh
_mesh_specs: Dict[str, object] = {}  # model_name -> MeshSpec


def set_experiment_trial_names(experiment_name: str, trial_name: str):
    global _experiment_name, _trial_name
    if "_" in experiment_name or "_" in trial_name:
        raise ValueError("experiment/trial names may not contain underscores")
    _experiment_name = experiment_name
    _trial_name = trial_name


def experiment_name() -> str:
    if _experiment_name is None:
        raise RuntimeError("experiment name not set")
    return _experiment_name


def trial_name() -> str:
    if _trial_name is None:
        raise RuntimeError("trial name not set")
    return _trial_name


def set_mesh(model_name: str, mesh, spec=None):
    _meshes[model_name] = mesh
    if spec is not None:
        _mesh_specs[model_name] = spec


@contextlib.contextmanager
def model_scope(model_name: str):
    """Make ``model_name`` the current model within the block."""
    _model_scope_stack.append(model_name)
    try:
        yield
    finally:
        _model_scope_stack.pop()


def has_model_scope() -> bool:
    return bool(_model_scope_stack)


def current_model_name() -> str:
    if not _model_scope_stack:
        raise RuntimeError("not inside a model_scope")
    return _model_scope_stack[-1]


def current_mesh():
    return _meshes[current_model_name()]


def current_mesh_spec():
    return _mesh_specs[current_model_name()]


def get_mesh(model_name: str):
    return _meshes.get(model_name)


# ---------------------------------------------------------------------------
# Path helpers (reference :82-118).
# ---------------------------------------------------------------------------

def get_cache_path() -> str:
    root = os.environ.get("AREAL_CACHE_ROOT", "/tmp/areal_tpu/cache")
    os.makedirs(root, exist_ok=True)
    return root


def _trial_path(root_env: str, default_root: str, *sub) -> str:
    root = os.environ.get(root_env, default_root)
    p = os.path.join(root, getpass.getuser(), experiment_name(), trial_name(), *sub)
    os.makedirs(p, exist_ok=True)
    return p


def get_log_path() -> str:
    return _trial_path("AREAL_LOG_ROOT", "/tmp/areal_tpu/logs")


def get_save_path() -> str:
    return _trial_path("AREAL_SAVE_ROOT", "/tmp/areal_tpu/checkpoints")


def get_param_realloc_path() -> str:
    """Staging dir for train->generation weight sync (disk fallback path)."""
    return _trial_path("AREAL_SAVE_ROOT", "/tmp/areal_tpu/checkpoints", "param_realloc")


def get_recover_path() -> str:
    return _trial_path("AREAL_SAVE_ROOT", "/tmp/areal_tpu/checkpoints", "recover")


def reset():  # for tests
    global _experiment_name, _trial_name
    _experiment_name = None
    _trial_name = None
    _model_scope_stack.clear()
    _meshes.clear()
    _mesh_specs.clear()

"""Runtime monitoring: device-memory/host-utilization sampling + time marks.

TPU-native rebuild of the reference's monitor
(reference: realhf/base/monitor.py — ``gpu_utilization_monitor`` :266
NVML-sampling thread, ``time_mark``/``parse_time_mark_*`` :43-118 wall-clock
event marks dumped to logs, RolloutStat :37).  Differences by design: TPUs
expose ``device.memory_stats()`` instead of NVML, so the sampler records
HBM bytes-in-use/peak + host RSS/load; kernel-level time attribution comes
from ``jax.profiler.trace`` (wired per-MFC in model_worker) rather than a
trace-file parser, so the CUDAKernelTimeStat machinery has no counterpart.

Time marks are in-memory and exported as plain dicts — the stats tracker /
MetricsLogger fan them out — instead of being grepped back out of logfiles.
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time
from collections import defaultdict
from typing import Dict, List, Optional

from areal_tpu.base import logging_

logger = logging_.getLogger("monitor")


@dataclasses.dataclass
class RolloutStat:
    """Rollout accounting (reference: monitor.py:37)."""

    submitted: int = 0
    accepted: int = 0
    running: int = 0

    def as_dict(self) -> Dict[str, int]:
        return dataclasses.asdict(self)


# ---------------------------------------------------------------------------
# Time marks
# ---------------------------------------------------------------------------

_marks_lock = threading.Lock()
_marks: Dict[str, List[Dict]] = defaultdict(list)


class time_mark:
    """Context manager recording a named wall-clock interval.

    ``with time_mark("actor_train", rank, step): ...`` — the reference logs
    start/end lines and greps them back (monitor.py:48-116); we keep the
    events in memory and export on demand.
    """

    def __init__(self, name: str, identifier: str = "", step: int = 0):
        self.name = name
        self.identifier = str(identifier)
        self.step = step

    def __enter__(self):
        self._t0 = time.monotonic()
        return self

    def __exit__(self, *exc):
        t1 = time.monotonic()
        with _marks_lock:
            _marks[self.name].append(
                {
                    "identifier": self.identifier,
                    "step": self.step,
                    "start": self._t0,
                    "end": t1,
                    "duration": t1 - self._t0,
                }
            )
        # scrape-side mirror: one histogram series per mark name, so the
        # marks show up at /metrics instead of living log-only
        try:
            from areal_tpu.observability import get_registry

            get_registry().histogram("areal_time_mark_seconds").observe(
                t1 - self._t0, mark=self.name
            )
        except Exception:  # noqa: BLE001 - marks must never raise
            pass
        return False


def get_time_marks(name: Optional[str] = None) -> Dict[str, List[Dict]]:
    with _marks_lock:
        if name is not None:
            return {name: list(_marks.get(name, []))}
        return {k: list(v) for k, v in _marks.items()}


def summary_time_marks() -> Dict[str, float]:
    """Flat {mark/total_s, mark/count, mark/mean_s} gauges for metrics."""
    out: Dict[str, float] = {}
    with _marks_lock:
        for name, events in _marks.items():
            total = sum(e["duration"] for e in events)
            out[f"time_marks/{name}/total_s"] = total
            out[f"time_marks/{name}/count"] = float(len(events))
            out[f"time_marks/{name}/mean_s"] = total / max(1, len(events))
    return out


def clear_time_marks():
    with _marks_lock:
        _marks.clear()


# ---------------------------------------------------------------------------
# Device/host utilization sampling
# ---------------------------------------------------------------------------

#: dense bf16 peak TFLOP/s per chip, keyed by substrings of
#: ``device.device_kind`` (the MFU denominators bench.py also uses)
PEAK_TFLOPS_BF16 = {
    "v3": 123,
    "v4": 275,
    "v5e": 197,
    "v5 lite": 197,
    "v5p": 459,
    "v6e": 918,
    "v6 lite": 918,
    "trillium": 918,
}


def device_peak_flops(device) -> float:
    """Peak bf16 FLOP/s of one device, or 0.0 when unknown (CPU backends;
    MFU gauges are skipped then rather than reporting nonsense)."""
    kind = getattr(device, "device_kind", "").lower()
    for name, tf in PEAK_TFLOPS_BF16.items():
        if name in kind:
            return tf * 1e12
    return 0.0


def _host_stats() -> Dict[str, float]:
    out: Dict[str, float] = {}
    try:
        la1, la5, _ = os.getloadavg()
        out["host/load1"] = la1
        out["host/load5"] = la5
    except OSError:
        pass
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    out["host/rss_gb"] = float(line.split()[1]) / 1e6
                    break
    except OSError:
        pass
    return out


def device_memory_stats() -> Dict[str, float]:
    """Per-device HBM gauges from ``memory_stats()`` (absent on some
    backends — returns {} then)."""
    import jax

    out: Dict[str, float] = {}
    for d in jax.local_devices():
        stats = None
        try:
            stats = d.memory_stats()
        except Exception:  # noqa: BLE001 - backend-dependent
            pass
        if not stats:
            continue
        key = f"device{d.id}"
        if "bytes_in_use" in stats:
            out[f"{key}/hbm_in_use_gb"] = stats["bytes_in_use"] / 1e9
        if "peak_bytes_in_use" in stats:
            out[f"{key}/hbm_peak_gb"] = stats["peak_bytes_in_use"] / 1e9
        if "bytes_limit" in stats:
            out[f"{key}/hbm_limit_gb"] = stats["bytes_limit"] / 1e9
    return out


class UtilizationMonitor:
    """Background sampler (reference: gpu_utilization_monitor thread :266).

    Samples device + host gauges every ``interval`` seconds into a ring of
    the last ``keep`` snapshots; ``export()`` returns the latest gauges for
    the metrics fan-out."""

    def __init__(self, interval: float = 10.0, keep: int = 360, registry=None):
        self.interval = interval
        self.keep = keep
        self._registry = registry
        self._snapshots: List[Dict[str, float]] = []
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self):
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._run, name="util-monitor", daemon=True
        )
        self._thread.start()

    def _sample(self):
        snap = {"ts": time.time(), **_host_stats(), **device_memory_stats()}
        with self._lock:
            self._snapshots.append(snap)
            if len(self._snapshots) > self.keep:
                self._snapshots.pop(0)
        self._publish(snap)

    def _publish(self, snap: Dict[str, float]):
        """Mirror the latest sample into the scrape registry (instead of the
        log-only output the sampler used to be).  Metric names are literal
        at the call sites so scripts/check_metric_names.py can audit them."""
        try:
            from areal_tpu.observability import get_registry

            reg = self._registry or get_registry()
            if "host/load1" in snap:
                reg.gauge("areal_host_load1").set(snap["host/load1"])
            if "host/load5" in snap:
                reg.gauge("areal_host_load5").set(snap["host/load5"])
            if "host/rss_gb" in snap:
                reg.gauge("areal_host_rss_gb").set(snap["host/rss_gb"])
            for k, v in snap.items():
                if not k.startswith("device") or "/" not in k:
                    continue
                dev, field = k.split("/", 1)
                if field == "hbm_in_use_gb":
                    reg.gauge("areal_device_hbm_in_use_gb").set(v, device=dev)
                elif field == "hbm_peak_gb":
                    reg.gauge("areal_device_hbm_peak_gb").set(v, device=dev)
                elif field == "hbm_limit_gb":
                    reg.gauge("areal_device_hbm_limit_gb").set(v, device=dev)
            # HBM-ledger reconciliation: the subsystem attributions must
            # sum to <= the allocator's own in-use bytes; the excess
            # publishes as areal_hbm_ledger_drift_gb (0 when honest).
            # Backends without memory_stats (CPU) reconcile vacuously.
            from areal_tpu.observability.hbm_ledger import get_ledger

            in_use_gb = [
                v for k, v in snap.items()
                if k.startswith("device") and k.endswith("/hbm_in_use_gb")
            ]
            get_ledger().reconcile(
                reg,
                int(sum(in_use_gb) * 1e9) if in_use_gb else None,
            )
        except Exception:  # noqa: BLE001 - monitoring must not kill work
            logger.exception("metric registry publish failed")

    def _run(self):
        while not self._stop.wait(self.interval):
            try:
                self._sample()
            except Exception:  # noqa: BLE001 - monitoring must not kill work
                logger.exception("utilization sample failed")

    def export(self) -> Dict[str, float]:
        with self._lock:
            if not self._snapshots:
                return {}
            latest = dict(self._snapshots[-1])
        latest.pop("ts", None)
        return latest

    def history(self) -> List[Dict[str, float]]:
        with self._lock:
            return list(self._snapshots)

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None

"""Canonical name-resolve key paths.

Mirrors the key layout of the reference's realhf/base/names.py:1-110 so that
the discovery/synchronization vocabulary carries over: trial root, request
reply stream, distributed peers, model versions, generation servers, etc.
All functions return slash-separated keys rooted at ``/areal_tpu``.
"""

from __future__ import annotations

USER_NAMESPACE = "areal_tpu"


def trial_root(experiment_name: str, trial_name: str) -> str:
    return f"{USER_NAMESPACE}/{experiment_name}/{trial_name}"


def trial_registry(experiment_name: str, trial_name: str) -> str:
    return f"{trial_root(experiment_name, trial_name)}/registry"


def worker_status(experiment_name: str, trial_name: str, worker_name: str) -> str:
    return f"{trial_root(experiment_name, trial_name)}/status/{worker_name}"


def worker_heartbeat(
    experiment_name: str, trial_name: str, worker_name: str
) -> str:
    return f"{trial_root(experiment_name, trial_name)}/heartbeat/{worker_name}"


def worker_root(experiment_name: str, trial_name: str) -> str:
    return f"{trial_root(experiment_name, trial_name)}/worker/"


def worker(experiment_name: str, trial_name: str, worker_name: str) -> str:
    return f"{worker_root(experiment_name, trial_name)}{worker_name}"


def request_reply_stream(
    experiment_name: str, trial_name: str, stream_name: str
) -> str:
    return f"{trial_root(experiment_name, trial_name)}/request_reply_stream/{stream_name}"


def request_reply_stream_root(experiment_name: str, trial_name: str) -> str:
    return f"{trial_root(experiment_name, trial_name)}/request_reply_stream/"


def distributed_peer(
    experiment_name: str, trial_name: str, model_name: str
) -> str:
    return f"{trial_root(experiment_name, trial_name)}/distributed_peer/{model_name}"


def distributed_master(
    experiment_name: str, trial_name: str, model_name: str
) -> str:
    return f"{trial_root(experiment_name, trial_name)}/distributed_master/{model_name}"


def model_version(
    experiment_name: str, trial_name: str, model_name: str
) -> str:
    return f"{trial_root(experiment_name, trial_name)}/model_version/{model_name}"


def recover_load(
    experiment_name: str, trial_name: str, model_name: str
) -> str:
    """Which recover checkpoint a model was reloaded from on restart."""
    return f"{trial_root(experiment_name, trial_name)}/recover_load/{model_name}"


def gen_servers(experiment_name: str, trial_name: str) -> str:
    return f"{trial_root(experiment_name, trial_name)}/gen_servers/"

def gen_server(experiment_name: str, trial_name: str, server_idx) -> str:
    return f"{gen_servers(experiment_name, trial_name)}{server_idx}"


def gen_server_spmd(
    experiment_name: str, trial_name: str, server_idx, sub: str
) -> str:
    """Multi-host gen-server control keys (leader PUB address, follower
    readiness).  Deliberately OUTSIDE the ``gen_servers/`` subtree: the
    gserver manager discovers servers by subtree scan, and control keys
    there would be mistaken for server addresses."""
    root = trial_root(experiment_name, trial_name)
    return f"{root}/gen_server_spmd/{server_idx}/{sub}"


def gen_server_manager(experiment_name: str, trial_name: str) -> str:
    return f"{trial_root(experiment_name, trial_name)}/gen_server_manager"


def gateway(experiment_name: str, trial_name: str) -> str:
    """host:port of the OpenAI-style HTTP/SSE gateway front door."""
    return f"{trial_root(experiment_name, trial_name)}/gateway"


def training_samples(experiment_name: str, trial_name: str) -> str:
    return f"{trial_root(experiment_name, trial_name)}/training_samples"


def experiment_status(experiment_name: str, trial_name: str) -> str:
    return f"{trial_root(experiment_name, trial_name)}/experiment_status"


def used_ports(experiment_name: str, trial_name: str, host_name: str) -> str:
    return f"{trial_root(experiment_name, trial_name)}/used_ports/{host_name}/"


def verifier_server(experiment_name: str, trial_name: str) -> str:
    """Reward verifier service URL (reference: the functioncall cluster)."""
    return f"{trial_root(experiment_name, trial_name)}/verifier_server"


def metric_server(
    experiment_name: str, trial_name: str, group: str, name: str
) -> str:
    return f"{trial_root(experiment_name, trial_name)}/metric_server/{group}/{name}"


def metric_server_root(experiment_name: str, trial_name: str) -> str:
    return f"{trial_root(experiment_name, trial_name)}/metric_server/"


def profiler_capture(
    experiment_name: str, trial_name: str, worker_name: str
) -> str:
    """Latest on-demand profiler capture dir of one worker (written by
    the metric server's ``/profile`` route, harvested by ops tooling)."""
    return (
        f"{trial_root(experiment_name, trial_name)}"
        f"/profiler_capture/{worker_name}"
    )


def profiler_capture_root(experiment_name: str, trial_name: str) -> str:
    return f"{trial_root(experiment_name, trial_name)}/profiler_capture/"


def stream_pullers(experiment_name: str, trial_name: str) -> str:
    return f"{trial_root(experiment_name, trial_name)}/stream_pullers/"


def push_pull_stream(
    experiment_name: str, trial_name: str, stream_name: str
) -> str:
    return f"{trial_root(experiment_name, trial_name)}/push_pull_stream/{stream_name}"


def push_pull_stream_root(experiment_name: str, trial_name: str) -> str:
    return f"{trial_root(experiment_name, trial_name)}/push_pull_stream/"

"""Hierarchical scoped metric aggregation
(reference: realhf/base/stats_tracker.py:20).

Metrics are recorded under slash-joined scopes with a reduce type; masked
means use *denominators*: ``denominator("mask"); stat(denominator="mask",
loss=...)`` records a masked average whose export divides by the mask count.
Works on numpy / jax arrays / python scalars; everything is pulled to host
numpy at record time (stats are tiny).
"""

from __future__ import annotations

import contextlib
import enum
from typing import Dict, List, Optional, Union

import numpy as np


class ReduceType(enum.Enum):
    AVG = "avg"
    SUM = "sum"
    MIN = "min"
    MAX = "max"
    SCALAR = "scalar"


def _to_np(x) -> np.ndarray:
    if hasattr(x, "addressable_shards") or hasattr(x, "device_buffer"):
        x = np.asarray(x)
    return np.asarray(x)


class DistributedStatsTracker:
    def __init__(self, name: str = ""):
        self._scope: List[str] = [name] if name else []
        # key -> list of (sum, denom_sum) or raw values depending on type
        self._values: Dict[str, List[np.ndarray]] = {}
        self._denoms: Dict[str, List[np.ndarray]] = {}
        self._types: Dict[str, ReduceType] = {}
        self._denom_of: Dict[str, str] = {}

    @contextlib.contextmanager
    def scope(self, name: str):
        self._scope.append(name)
        try:
            yield
        finally:
            self._scope.pop()

    def _key(self, name: str) -> str:
        return "/".join(self._scope + [name])

    def denominator(self, **kwargs):
        """Record boolean masks that later stats divide by."""
        for name, mask in kwargs.items():
            key = self._key(name)
            mask = _to_np(mask).astype(np.float64)
            self._denoms.setdefault(key, []).append(mask)

    def stat(
        self,
        denominator: str,
        reduce_type: ReduceType = ReduceType.AVG,
        **kwargs,
    ):
        """Record masked statistics. ``denominator`` names a mask previously
        recorded in the same scope."""
        denom_key = self._key(denominator)
        if denom_key not in self._denoms:
            raise ValueError(f"unknown denominator {denom_key}")
        for name, value in kwargs.items():
            key = self._key(name)
            value = _to_np(value).astype(np.float64)
            mask = self._denoms[denom_key][-1]
            if value.shape != mask.shape:
                raise ValueError(
                    f"stat {key}: shape {value.shape} != mask {mask.shape}"
                )
            self._values.setdefault(key, []).append(value)
            self._types[key] = reduce_type
            self._denom_of[key] = denom_key

    def scalar(self, **kwargs):
        for name, value in kwargs.items():
            key = self._key(name)
            self._values.setdefault(key, []).append(
                np.asarray(float(value), dtype=np.float64)
            )
            self._types[key] = ReduceType.SCALAR

    def export(self, reset: bool = True) -> Dict[str, float]:
        """Aggregate everything recorded so far into plain floats."""
        out: Dict[str, float] = {}
        for key, vals in self._values.items():
            rt = self._types[key]
            if rt == ReduceType.SCALAR:
                out[key] = float(np.mean([v for v in vals]))
                continue
            denom_key = self._denom_of[key]
            masks = self._denoms[denom_key]
            # Each recorded value is aligned with the mask recorded at the
            # same position from the tail.
            n = len(vals)
            ms = masks[-n:]
            if rt == ReduceType.AVG:
                num = sum((v * m).sum() for v, m in zip(vals, ms))
                den = sum(m.sum() for m in ms)
                out[key] = float(num / max(den, 1e-8))
            elif rt == ReduceType.SUM:
                out[key] = float(sum((v * m).sum() for v, m in zip(vals, ms)))
            elif rt == ReduceType.MIN:
                cands = [
                    np.where(m > 0, v, np.inf).min()
                    for v, m in zip(vals, ms)
                    if m.sum() > 0
                ]
                out[key] = float(min(cands)) if cands else float("inf")
            elif rt == ReduceType.MAX:
                cands = [
                    np.where(m > 0, v, -np.inf).max()
                    for v, m in zip(vals, ms)
                    if m.sum() > 0
                ]
                out[key] = float(max(cands)) if cands else float("-inf")
        for key, ms in self._denoms.items():
            out.setdefault(
                key + "/count", float(sum(m.sum() for m in ms))
            )
        if reset:
            self._values.clear()
            self._denoms.clear()
            self._types.clear()
            self._denom_of.clear()
        return out


DEFAULT_TRACKER = DistributedStatsTracker()


def scope(name: str):
    return DEFAULT_TRACKER.scope(name)


def denominator(**kwargs):
    return DEFAULT_TRACKER.denominator(**kwargs)


def stat(denominator: str, reduce_type: ReduceType = ReduceType.AVG, **kwargs):
    return DEFAULT_TRACKER.stat(denominator, reduce_type, **kwargs)


def scalar(**kwargs):
    return DEFAULT_TRACKER.scalar(**kwargs)


def export(reset: bool = True) -> Dict[str, float]:
    return DEFAULT_TRACKER.export(reset=reset)

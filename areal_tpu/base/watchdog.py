"""Bounded execution of one named section/phase in a daemon thread.

Shared core of ``bench.py``'s ``_section`` and ``__graft_entry__``'s
dryrun ``_phase``: run ``fn`` in a daemon thread, join for ``timeout_s``,
and report ``{status: ok|error|timeout, seconds[, result|error]}`` — so
one hung or crashing section forfeits its own numbers instead of eating
the whole run's budget (BENCH_r05 lost two rounds to one axon-init hang;
MULTICHIP_r05 died at rc=124 with no way to tell which phase hung).

Best effort by design: a truly wedged thread may hold jax's dispatch
lock and time out the sections behind it too, but each of those is
bounded the same way and the run still emits its partial status table.
"""

from __future__ import annotations

import threading
import time
import traceback
from typing import Any, Dict


def run_bounded(
    fn, *args, name: str = "section", timeout_s: float = 300.0, **kw
) -> Dict[str, Any]:
    """Run ``fn(*args, **kw)`` in a daemon thread joined for
    ``timeout_s`` seconds.  Returns ``{"status": "ok", "seconds": s,
    "result": r}``, ``{"status": "error", "seconds": s, "error": msg}``
    (exception repr, truncated), or ``{"status": "timeout",
    "seconds": s}`` when the thread is still alive at the deadline."""
    t0 = time.perf_counter()
    box: Dict[str, Any] = {}

    def target():
        try:
            box["result"] = fn(*args, **kw)
        except Exception as e:  # noqa: BLE001 - the outcome IS the data
            traceback.print_exc()
            box["error"] = f"{type(e).__name__}: {e}"[:300]

    th = threading.Thread(target=target, daemon=True, name=name)
    th.start()
    th.join(timeout_s)
    out: Dict[str, Any] = {
        "seconds": round(time.perf_counter() - t0, 1)
    }
    if th.is_alive():
        out["status"] = "timeout"
    elif "error" in box:
        out["status"] = "error"
        out["error"] = box["error"]
    else:
        out["status"] = "ok"
        out["result"] = box.get("result")
    return out

"""Key-value discovery & synchronization service.

This is the rebuild of the reference's name-resolve layer
(reference: realhf/base/name_resolve.py:186,286 — Memory and NFS backends;
the Redis/ETCD/Ray backends are cluster-specific and gated behind the same
repository interface so they can be added without touching call sites).

Every worker discovery, barrier, version announcement, and address exchange in
the system goes through this module.  The default backend is in-memory (single
process); the file backend supports multi-process / multi-host via a shared
filesystem.
"""

from __future__ import annotations

import dataclasses
import os
import random
import shutil
import threading
import time
import uuid
from typing import Callable, Dict, List, Optional

from areal_tpu.base import logging_

logger = logging_.getLogger("name_resolve")


class NameEntryExistsError(Exception):
    pass


class NameEntryNotFoundError(Exception):
    pass


class NameRecordRepository:
    """Abstract KV repository with watch/keepalive semantics."""

    def add(
        self,
        name: str,
        value: str,
        delete_on_exit: bool = True,
        keepalive_ttl: Optional[float] = None,
        replace: bool = False,
    ):
        raise NotImplementedError()

    def add_subentry(self, name: str, value: str, **kwargs) -> str:
        """Add ``name/<uuid>`` = value; returns the sub-name."""
        sub_name = f"{name.rstrip('/')}/{uuid.uuid4().hex[:8]}"
        self.add(sub_name, value, **kwargs)
        return sub_name

    def delete(self, name: str):
        raise NotImplementedError()

    def clear_subtree(self, name_root: str):
        raise NotImplementedError()

    def get(self, name: str) -> str:
        raise NotImplementedError()

    def get_subtree(self, name_root: str) -> List[str]:
        """Values of all keys under the subtree, sorted by key."""
        raise NotImplementedError()

    def find_subtree(self, name_root: str) -> List[str]:
        """Keys (not values) under the subtree, sorted."""
        raise NotImplementedError()

    def wait(
        self,
        name: str,
        timeout: Optional[float] = None,
        poll_frequency: float = 0.1,
    ) -> str:
        """Block until ``name`` exists, returning its value."""
        start = time.monotonic()
        while True:
            try:
                return self.get(name)
            except NameEntryNotFoundError:
                if timeout is not None and time.monotonic() - start > timeout:
                    raise TimeoutError(
                        f"name_resolve.wait timeout after {timeout}s: {name}"
                    )
                time.sleep(poll_frequency + random.random() * 0.02)

    def watch_names(
        self,
        names: List[str],
        call_back: Callable[[], None],
        poll_frequency: float = 5.0,
        wait_timeout: float = 60.0,
    ):
        """Spawn a daemon thread that calls ``call_back`` once ANY of the names
        disappears (after first appearing).  Used for worker failure detection
        (reference: realhf/system/worker_base.py:701-708)."""
        if isinstance(names, str):
            names = [names]

        def _watch():
            try:
                for n in names:
                    self.wait(n, timeout=wait_timeout)
                while True:
                    for n in names:
                        try:
                            self.get(n)
                        except NameEntryNotFoundError:
                            logger.info("watched name %s disappeared", n)
                            call_back()
                            return
                    time.sleep(poll_frequency)
            except Exception:
                logger.exception("watch thread failed")
                call_back()

        t = threading.Thread(target=_watch, daemon=True)
        t.start()
        return t

    def reset(self):
        """Cleanup all entries added by this repository instance."""
        raise NotImplementedError()


class MemoryNameRecordRepository(NameRecordRepository):
    """Process-local dict-backed store (reference :186)."""

    def __init__(self):
        self.__store: Dict[str, str] = {}
        self.__lock = threading.Lock()

    def add(self, name, value, delete_on_exit=True, keepalive_ttl=None, replace=False):
        name = str(name).rstrip("/")
        if not name:
            raise ValueError("name cannot be empty")
        with self.__lock:
            if name in self.__store and not replace:
                raise NameEntryExistsError(name)
            self.__store[name] = str(value)

    def delete(self, name):
        with self.__lock:
            if name not in self.__store:
                raise NameEntryNotFoundError(name)
            del self.__store[name]

    def clear_subtree(self, name_root):
        with self.__lock:
            prefix = name_root.rstrip("/")
            keys = [
                k for k in self.__store if k == prefix or k.startswith(prefix + "/")
            ]
            for k in keys:
                del self.__store[k]

    def get(self, name):
        name = str(name).rstrip("/")
        with self.__lock:
            if name not in self.__store:
                raise NameEntryNotFoundError(name)
            return self.__store[name]

    def get_subtree(self, name_root):
        with self.__lock:
            prefix = name_root.rstrip("/")
            return [
                v
                for k, v in sorted(self.__store.items())
                if k == prefix or k.startswith(prefix + "/")
            ]

    def find_subtree(self, name_root):
        with self.__lock:
            prefix = name_root.rstrip("/")
            return sorted(
                k
                for k in self.__store
                if k == prefix or k.startswith(prefix + "/")
            )

    def reset(self):
        with self.__lock:
            self.__store.clear()


class NfsNameRecordRepository(NameRecordRepository):
    """Shared-filesystem store: one file per key (reference :286).

    Works across processes and across hosts that share the record root
    (NFS/GCS-fuse).  Values live in ``<root>/<key>/ENTRY``.
    """

    ENTRY = "ENTRY"

    def __init__(self, record_root: Optional[str] = None):
        self.record_root = record_root or os.environ.get(
            "AREAL_NAME_RESOLVE_ROOT", "/tmp/areal_tpu/name_resolve"
        )
        self.__to_delete = set()

    def __dir_path(self, name: str) -> str:
        return os.path.join(self.record_root, name.strip("/"))

    def __file_path(self, name: str) -> str:
        return os.path.join(self.__dir_path(name), self.ENTRY)

    def add(self, name, value, delete_on_exit=True, keepalive_ttl=None, replace=False):
        path = self.__file_path(name)
        if os.path.isfile(path) and not replace:
            raise NameEntryExistsError(name)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + f".tmp.{uuid.uuid4().hex[:8]}"
        with open(tmp, "w") as f:
            f.write(str(value))
        os.replace(tmp, path)
        if delete_on_exit:
            self.__to_delete.add(name)

    def delete(self, name):
        path = self.__file_path(name)
        if not os.path.isfile(path):
            raise NameEntryNotFoundError(name)
        os.remove(path)
        self.__to_delete.discard(name)
        # prune now-empty dirs
        d = os.path.dirname(path)
        while d != self.record_root:
            try:
                os.rmdir(d)
            except OSError:
                break
            d = os.path.dirname(d)

    def clear_subtree(self, name_root):
        path = self.__dir_path(name_root)
        if os.path.isdir(path):
            shutil.rmtree(path, ignore_errors=True)

    def get(self, name):
        path = self.__file_path(name)
        try:
            with open(path, "r") as f:
                return f.read()
        except FileNotFoundError:
            raise NameEntryNotFoundError(name) from None

    def _walk(self, name_root):
        root = self.__dir_path(name_root)
        out = []
        if not os.path.isdir(root):
            return out
        for dirpath, _, filenames in os.walk(root):
            if self.ENTRY in filenames:
                rel = os.path.relpath(dirpath, self.record_root)
                out.append(rel.replace(os.sep, "/"))
        return sorted(out)

    def get_subtree(self, name_root):
        return [self.get(k) for k in self._walk(name_root)]

    def find_subtree(self, name_root):
        return self._walk(name_root)

    def reset(self):
        for name in list(self.__to_delete):
            try:
                self.delete(name)
            except NameEntryNotFoundError:
                pass
        self.__to_delete.clear()


DEFAULT_REPOSITORY: NameRecordRepository = MemoryNameRecordRepository()


def reconfigure(backend: str = "memory", **kwargs) -> NameRecordRepository:
    """Swap the process-global repository (reference :1386)."""
    global DEFAULT_REPOSITORY
    try:
        DEFAULT_REPOSITORY.reset()
    except Exception:
        pass
    if backend == "memory":
        DEFAULT_REPOSITORY = MemoryNameRecordRepository()
    elif backend in ("nfs", "file"):
        DEFAULT_REPOSITORY = NfsNameRecordRepository(**kwargs)
    elif backend == "server":
        # in-repo ZMQ KV service (the redis/etcd3 role of the reference)
        import os

        from areal_tpu.base.name_resolve_server import (
            ServerNameRecordRepository,
        )

        address = kwargs.pop(
            "address", os.environ.get("AREAL_NAME_RESOLVE_ADDR", "")
        )
        if not address:
            raise ValueError(
                "server backend needs address=host:port or "
                "AREAL_NAME_RESOLVE_ADDR"
            )
        DEFAULT_REPOSITORY = ServerNameRecordRepository(address)
    else:
        raise NotImplementedError(f"name_resolve backend {backend}")
    return DEFAULT_REPOSITORY


def add(name, value, **kwargs):
    return DEFAULT_REPOSITORY.add(name, value, **kwargs)


def add_subentry(name, value, **kwargs):
    return DEFAULT_REPOSITORY.add_subentry(name, value, **kwargs)


def delete(name):
    return DEFAULT_REPOSITORY.delete(name)


def clear_subtree(name_root):
    return DEFAULT_REPOSITORY.clear_subtree(name_root)


def get(name):
    return DEFAULT_REPOSITORY.get(name)


def get_subtree(name_root):
    return DEFAULT_REPOSITORY.get_subtree(name_root)


def find_subtree(name_root):
    return DEFAULT_REPOSITORY.find_subtree(name_root)


def wait(name, **kwargs):
    return DEFAULT_REPOSITORY.wait(name, **kwargs)


def watch_names(names, call_back, **kwargs):
    return DEFAULT_REPOSITORY.watch_names(names, call_back, **kwargs)


def reset():
    return DEFAULT_REPOSITORY.reset()

"""Self-hosted name-resolve service: a ZMQ key-value server with TTLs.

The reference backs cross-host name resolution with external stores —
redis / etcd3 / ray KV (reference: realhf/base/name_resolve.py:382
``RedisNameRecordRepository``, :559 ``Etcd3NameRecordRepository`` with
leases + keepalive).  A TPU pod has no redis; NFS works but adds latency
and an FS dependency.  This module is the native equivalent: one tiny
in-repo server process (typically on the launcher host) speaking JSON over
ZMQ REQ/REP, with server-side TTL expiry and client keepalive threads —
the etcd lease/keepalive semantics without the external service.

Server:  ``python -m areal_tpu.base.name_resolve_server --port 7777``
Clients: ``name_resolve.reconfigure("server", address="host:7777")`` or
``AREAL_NAME_RESOLVE=server AREAL_NAME_RESOLVE_ADDR=host:7777``.
"""

from __future__ import annotations

import argparse
import json
import threading
import time
from typing import Dict, List, Optional, Tuple

import zmq

from areal_tpu.base import logging_
from areal_tpu.base.name_resolve import (
    NameEntryExistsError,
    NameEntryNotFoundError,
    NameRecordRepository,
)

logger = logging_.getLogger("name_resolve_server")


class NameResolveServer:
    """Threaded KV server. Store maps key -> (value, expiry|None)."""

    def __init__(self, port: int = 0, host: str = "0.0.0.0"):
        self._ctx = zmq.Context.instance()
        self._sock = self._ctx.socket(zmq.REP)
        if port == 0:
            self.port = self._sock.bind_to_random_port(f"tcp://{host}")
        else:
            self._sock.bind(f"tcp://{host}:{port}")
            self.port = port
        self._store: Dict[str, Tuple[str, Optional[float]]] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self):
        self._thread = threading.Thread(
            target=self._serve, name="name-resolve-server", daemon=True
        )
        self._thread.start()
        return self

    def _sweep(self):
        now = time.monotonic()
        dead = [
            k for k, (_, exp) in self._store.items()
            if exp is not None and exp < now
        ]
        for k in dead:
            del self._store[k]

    def _handle(self, req: Dict) -> Dict:
        op = req["op"]
        key = req.get("key", "")
        with self._lock:
            self._sweep()
            if op == "add":
                if key in self._store and not req.get("replace", False):
                    return {"ok": False, "err": "exists"}
                ttl = req.get("ttl")
                exp = time.monotonic() + ttl if ttl else None
                self._store[key] = (req["value"], exp)
                return {"ok": True}
            if op == "touch":
                if key not in self._store:
                    return {"ok": False, "err": "notfound"}
                value, exp = self._store[key]
                ttl = req.get("ttl")
                self._store[key] = (
                    value, time.monotonic() + ttl if ttl else None
                )
                return {"ok": True}
            if op == "get":
                if key not in self._store:
                    return {"ok": False, "err": "notfound"}
                return {"ok": True, "value": self._store[key][0]}
            if op == "delete":
                if key not in self._store:
                    return {"ok": False, "err": "notfound"}
                del self._store[key]
                return {"ok": True}
            if op == "clear_subtree":
                root = key.rstrip("/")
                dead = [
                    k for k in self._store
                    if k == root or k.startswith(root + "/")
                ]
                for k in dead:
                    del self._store[k]
                return {"ok": True, "n": len(dead)}
            if op == "get_subtree":
                root = key.rstrip("/")
                items = sorted(
                    (k, v[0]) for k, v in self._store.items()
                    if k == root or k.startswith(root + "/")
                )
                return {"ok": True, "keys": [k for k, _ in items],
                        "values": [v for _, v in items]}
            if op == "ping":
                return {"ok": True, "n_keys": len(self._store)}
        return {"ok": False, "err": f"bad op {op}"}

    def _serve(self):
        poller = zmq.Poller()
        poller.register(self._sock, zmq.POLLIN)
        while not self._stop.is_set():
            if not dict(poller.poll(timeout=100)):
                continue
            raw = self._sock.recv()
            try:
                resp = self._handle(json.loads(raw.decode()))
            except Exception as e:  # noqa: BLE001 - server must not die
                logger.exception("bad request")
                resp = {"ok": False, "err": repr(e)}
            self._sock.send(json.dumps(resp).encode())

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
        self._sock.close(linger=0)


class ServerNameRecordRepository(NameRecordRepository):
    """Client backend speaking to a :class:`NameResolveServer`.

    ``keepalive_ttl`` entries are refreshed by a daemon thread at ttl/3
    (etcd-lease semantics); ``delete_on_exit`` keys are removed on
    :meth:`reset`.
    """

    REQUEST_TIMEOUT = 5.0

    def __init__(self, address: str):
        self._address = address
        self._ctx = zmq.Context.instance()
        self._lock = threading.Lock()
        self._sock = self._new_socket()
        self._to_delete: set = set()
        self._keepalive: Dict[str, float] = {}
        self._stop = threading.Event()
        self._ka_thread: Optional[threading.Thread] = None

    def _new_socket(self):
        sock = self._ctx.socket(zmq.REQ)
        sock.setsockopt(zmq.LINGER, 0)
        sock.connect(f"tcp://{self._address}")
        return sock

    def _call(self, req: Dict) -> Dict:
        with self._lock:
            self._sock.send(json.dumps(req).encode())
            if not self._sock.poll(int(self.REQUEST_TIMEOUT * 1000)):
                # REQ sockets wedge after a lost reply: rebuild
                self._sock.close(linger=0)
                self._sock = self._new_socket()
                raise TimeoutError(
                    f"name_resolve server {self._address} timed out"
                )
            return json.loads(self._sock.recv().decode())

    def add(
        self,
        name: str,
        value: str,
        delete_on_exit: bool = True,
        keepalive_ttl: Optional[float] = None,
        replace: bool = False,
    ):
        resp = self._call(
            {
                "op": "add",
                "key": name,
                "value": str(value),
                "replace": replace,
                "ttl": keepalive_ttl,
            }
        )
        if not resp["ok"]:
            raise NameEntryExistsError(name)
        if delete_on_exit:
            self._to_delete.add(name)
        if keepalive_ttl:
            self._keepalive[name] = keepalive_ttl
            self._ensure_keepalive()

    def _ensure_keepalive(self):
        if self._ka_thread is not None:
            return

        def _loop():
            next_at: Dict[str, float] = {}
            while not self._stop.wait(0.2):
                now = time.monotonic()
                for key, ttl in list(self._keepalive.items()):
                    if now < next_at.get(key, 0.0):
                        continue
                    try:
                        self._call({"op": "touch", "key": key, "ttl": ttl})
                    except (TimeoutError, zmq.ZMQError):
                        pass
                    next_at[key] = now + max(0.1, ttl / 3)

        self._ka_thread = threading.Thread(
            target=_loop, name="name-resolve-keepalive", daemon=True
        )
        self._ka_thread.start()

    def delete(self, name: str):
        resp = self._call({"op": "delete", "key": name})
        self._to_delete.discard(name)
        self._keepalive.pop(name, None)
        if not resp["ok"]:
            raise NameEntryNotFoundError(name)

    def clear_subtree(self, name_root: str):
        self._call({"op": "clear_subtree", "key": name_root})

    def get(self, name: str) -> str:
        resp = self._call({"op": "get", "key": name})
        if not resp["ok"]:
            raise NameEntryNotFoundError(name)
        return resp["value"]

    def get_subtree(self, name_root: str) -> List[str]:
        return self._call({"op": "get_subtree", "key": name_root})["values"]

    def find_subtree(self, name_root: str) -> List[str]:
        return self._call({"op": "get_subtree", "key": name_root})["keys"]

    def reset(self):
        self._stop.set()
        if self._ka_thread is not None:
            self._ka_thread.join(timeout=2)
        for name in list(self._to_delete):
            try:
                self.delete(name)
            except (NameEntryNotFoundError, TimeoutError, zmq.ZMQError):
                pass
        self._to_delete.clear()
        self._keepalive.clear()
        # the repository stays usable after reset: a later add() with a TTL
        # must be able to restart keepalive
        self._stop = threading.Event()
        self._ka_thread = None


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description="areal_tpu name-resolve server")
    p.add_argument("--port", type=int, default=7777)
    p.add_argument("--host", default="0.0.0.0")
    args = p.parse_args(argv)
    server = NameResolveServer(port=args.port, host=args.host)
    logger.info("name-resolve server on %s:%d", args.host, server.port)
    server.start()
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        server.stop()
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())

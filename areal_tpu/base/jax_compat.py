"""Shims over the jax API drift this repo straddles (0.4.x images vs the
0.5+/0.6 spellings newer code was written against).

Rules of the module: resolve the modern name when it exists, translate to
the old one otherwise, NEVER fork behavior beyond the rename — so call
sites read like current jax and the shim disappears when the image moves.
"""

from __future__ import annotations

import jax

try:  # jax >= 0.5: public top-level shard_map with axis_names/check_vma
    from jax import shard_map as _shard_map_new

    _OLD_SHARD_MAP = None
except ImportError:  # jax 0.4.x: experimental, axis-set via `auto`
    from jax.experimental.shard_map import shard_map as _old_shard_map

    _shard_map_new = None
    _OLD_SHARD_MAP = _old_shard_map


def shard_map(
    f,
    *,
    mesh,
    in_specs,
    out_specs,
    axis_names=None,
    check_vma=None,
    **kwargs,
):
    """``jax.shard_map`` signature on every supported jax.

    On 0.4.x, ``axis_names`` (the MANUAL axes) becomes the complementary
    ``auto`` set and ``check_vma`` maps to ``check_rep``.
    """
    if _shard_map_new is not None:
        if axis_names is not None:
            kwargs["axis_names"] = axis_names
        if check_vma is not None:
            kwargs["check_vma"] = check_vma
        return _shard_map_new(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs
        )
    if check_vma is not None:
        kwargs["check_rep"] = check_vma
    if axis_names is not None:
        auto = frozenset(mesh.axis_names) - frozenset(axis_names)
        if auto:
            kwargs["auto"] = auto
    return _OLD_SHARD_MAP(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs
    )


def partial_auto_shard_map_supported() -> bool:
    """True when shard_map may be manual over a SUBSET of mesh axes with
    the rest auto (the pipeline's mode).  jax 0.4.x's experimental ``auto``
    cannot lower ``axis_index`` inside such a region (XLA rejects the
    PartitionId op under SPMD partitioning), so the pipeline path requires
    the jax >= 0.5 shard_map."""
    return _shard_map_new is not None


def pallas_tpu_compiler_params(**kwargs):
    """``pltpu.CompilerParams`` (jax >= 0.6 spelling) or the 0.4.x
    ``TPUCompilerParams`` — identical fields, renamed class."""
    import jax.experimental.pallas.tpu as pltpu

    cls = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams
    return cls(**kwargs)


def start_host_copies(arrs) -> bool:
    """Start async device->host copies for every ``jax.Array`` in
    ``arrs`` (``copy_to_host_async``), so a later blocking conversion
    finds the data already host-resident instead of paying one serial
    tunnel/PCIe round-trip per array.  Returns True iff copies were
    started; backends without the method (or arrays that reject it) are
    a silent no-op — the eventual ``device_get`` still fetches, just
    unhidden."""
    try:
        started = False
        for x in arrs:
            if isinstance(x, jax.Array):
                x.copy_to_host_async()
                started = True
        return started
    except Exception:  # noqa: BLE001 - best-effort prefetch only
        return False

"""Deterministic seeding (reference: realhf/base/seeding.py:22).

Derives per-key seeds as ``base_seed + stable_hash(key)`` and seeds python,
numpy, and (for the TPU build) provides the root ``jax.random.PRNGKey``.
"""

from __future__ import annotations

import hashlib
import random

import numpy as np

_BASE_SEED: int = 0
_SEEDED = False


def _stable_hash(key: str) -> int:
    return int.from_bytes(hashlib.sha256(key.encode()).digest()[:4], "little")


def set_random_seed(base_seed: int, key: str = "") -> None:
    """Seed python/numpy deterministically for this process.

    ``key`` should identify the worker (e.g. its name) so different workers get
    decorrelated but reproducible streams.
    """
    global _BASE_SEED, _SEEDED
    _BASE_SEED = base_seed
    seed = (base_seed + _stable_hash(key)) % (2**31)
    random.seed(seed)
    np.random.seed(seed)
    _SEEDED = True


def get_seed(key: str = "") -> int:
    return (_BASE_SEED + _stable_hash(key)) % (2**31)


def prng_key(key: str = ""):
    """Root jax PRNG key for the given stream name."""
    import jax

    return jax.random.PRNGKey(get_seed(key))

"""Logging helpers.

TPU-native analogue of the reference's colored/benchmark loggers
(reference: realhf/base/logging.py). We keep it minimal: a module-level
registry of named loggers with a compact colored formatter, plus a
``getLogger(name, type_)`` API matching the reference's call sites.
"""

from __future__ import annotations

import logging
import os
import sys

_FORMAT = "%(asctime)s.%(msecs)03d %(name)s %(levelname)s: %(message)s"
_DATE_FORMAT = "%Y%m%d-%H:%M:%S"

_COLORS = {
    "DEBUG": "\033[36m",  # cyan
    "INFO": "\033[32m",  # green
    "WARNING": "\033[33m",  # yellow
    "ERROR": "\033[31m",  # red
    "CRITICAL": "\033[41m",  # red background
}
_RESET = "\033[0m"


class _ColorFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        msg = super().format(record)
        if sys.stderr.isatty():
            color = _COLORS.get(record.levelname, "")
            if color:
                msg = f"{color}{msg}{_RESET}"
        return msg


_configured = False


def _configure_root():
    global _configured
    if _configured:
        return
    handler = logging.StreamHandler(sys.stderr)
    handler.setFormatter(_ColorFormatter(fmt=_FORMAT, datefmt=_DATE_FORMAT))
    root = logging.getLogger("areal")
    root.addHandler(handler)
    root.propagate = False
    level = os.environ.get("AREAL_LOG_LEVEL", "INFO").upper()
    root.setLevel(level)
    _configured = True


def getLogger(name: str = "areal", type_: str | None = None) -> logging.Logger:
    """Return a logger under the ``areal`` hierarchy.

    ``type_`` mirrors the reference's "benchmark"/"system" logger types; here it
    only namespaces the logger.
    """
    _configure_root()
    if name == "areal" or name is None:
        return logging.getLogger("areal")
    if type_:
        return logging.getLogger(f"areal.{type_}.{name}")
    return logging.getLogger(f"areal.{name}")

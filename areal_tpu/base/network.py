"""Host/port utilities (reference: realhf/base/network.py:25 — lockfile
coordinated free-port finder; ports registered in name_resolve ``used_ports``).
"""

from __future__ import annotations

import fcntl
import os
import socket
from typing import List, Optional

from areal_tpu.base import name_resolve, names


def gethostname() -> str:
    return socket.gethostname()


def gethostip() -> str:
    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        s.connect(("8.8.8.8", 80))
        ip = s.getsockname()[0]
        s.close()
        return ip
    except OSError:
        return "127.0.0.1"


_LOCKFILE = "/tmp/areal_tpu_ports.lock"


def find_free_ports(
    count: int = 1,
    low: int = 20000,
    high: int = 60000,
    experiment_name: Optional[str] = None,
    trial_name: Optional[str] = None,
) -> List[int]:
    """Find ``count`` distinct free TCP ports.

    A process-shared lockfile serializes the search so concurrent workers on
    one host don't race for the same port; if experiment/trial names are given,
    chosen ports are also registered in name_resolve (and skipped by later
    callers) mirroring the reference's ``used_ports`` registry.
    """
    used = set()
    if experiment_name and trial_name:
        root = names.used_ports(experiment_name, trial_name, gethostname())
        for v in name_resolve.get_subtree(root):
            try:
                used.add(int(v))
            except ValueError:
                pass

    ports: List[int] = []
    os.makedirs(os.path.dirname(_LOCKFILE) or "/", exist_ok=True)
    with open(_LOCKFILE, "w") as lockf:
        fcntl.flock(lockf, fcntl.LOCK_EX)
        try:
            for port in range(low, high):
                if port in used:
                    continue
                try:
                    with socket.socket(
                        socket.AF_INET, socket.SOCK_STREAM
                    ) as s:
                        s.setsockopt(
                            socket.SOL_SOCKET, socket.SO_REUSEADDR, 1
                        )
                        s.bind(("", port))
                except OSError:
                    continue
                ports.append(port)
                if experiment_name and trial_name:
                    root = names.used_ports(
                        experiment_name, trial_name, gethostname()
                    )
                    name_resolve.add_subentry(root, str(port))
                if len(ports) == count:
                    break
        finally:
            fcntl.flock(lockf, fcntl.LOCK_UN)
    if len(ports) < count:
        raise RuntimeError(f"could not find {count} free ports")
    return ports


def find_free_port(**kwargs) -> int:
    return find_free_ports(1, **kwargs)[0]

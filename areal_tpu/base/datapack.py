"""Sequence packing / balancing algorithms
(reference: realhf/base/datapack.py — flat2d and the balanced-partition
algorithms used by micro-batch splitting).

These drive ``MicroBatchSpec`` splitting: given per-sequence token counts,
partition sequences into k groups with near-equal total tokens (order
preserving for reproducibility) or bounded by a max token budget.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np


def flat2d(xs: Sequence[Sequence]) -> List:
    """Flatten one nesting level."""
    return [x for sub in xs for x in sub]


def partition_balanced(nums: Sequence[int], k: int) -> List[List[int]]:
    """Partition indices 0..n-1 (order preserving, contiguous) into exactly
    ``k`` non-empty groups minimizing the maximum group sum.

    Classic linear-partition DP; n and k are small (thousands / tens) so the
    O(n^2 k) DP is fine on host.
    """
    n = len(nums)
    if k > n:
        raise ValueError(f"cannot partition {n} items into {k} non-empty groups")
    if k == 1:
        return [list(range(n))]
    if n >= 64:  # amortize the ctypes boundary; parity tested either way
        from areal_tpu.base import _native

        cuts = _native.partition_balanced(nums, k)
        if cuts is not None:
            return [
                list(range(int(cuts[j]), int(cuts[j + 1])))
                for j in range(k)
            ]
    prefix = np.concatenate([[0], np.cumsum(nums)])
    INF = float("inf")
    # dp[j][i]: minimal max-sum partitioning first i items into j groups
    dp = np.full((k + 1, n + 1), INF)
    cut = np.zeros((k + 1, n + 1), dtype=int)
    dp[0][0] = 0.0
    for j in range(1, k + 1):
        for i in range(j, n + 1):
            # last group = items t..i-1
            for t in range(j - 1, i):
                cost = max(dp[j - 1][t], prefix[i] - prefix[t])
                if cost < dp[j][i]:
                    dp[j][i] = cost
                    cut[j][i] = t
    # reconstruct
    groups: List[List[int]] = []
    i = n
    for j in range(k, 0, -1):
        t = cut[j][i]
        groups.append(list(range(t, i)))
        i = t
    groups.reverse()
    return groups


def partition_by_budget(
    nums: Sequence[int], max_tokens: int, min_groups: int = 1
) -> List[List[int]]:
    """Greedy contiguous partition: each group's total <= max_tokens (single
    items above the budget get their own group).  Ensures >= min_groups by
    rebalancing with :func:`partition_balanced` when needed."""
    groups: List[List[int]] = []
    cur: List[int] = []
    cur_sum = 0
    for i, x in enumerate(nums):
        if cur and cur_sum + x > max_tokens:
            groups.append(cur)
            cur, cur_sum = [], 0
        cur.append(i)
        cur_sum += x
    if cur:
        groups.append(cur)
    if len(groups) < min_groups:
        groups = partition_balanced(nums, min_groups)
    return groups


def ffd_allocate(
    nums: Sequence[int], capacity: int, min_groups: int = 1
) -> List[List[int]]:
    """First-fit-decreasing allocation with a minimum group count
    (reference: realhf/base/datapack.py ``ffd_allocate`` used by
    ``SequenceSample.split_with_lengths``).

    Returns non-contiguous index groups, each with total <= capacity when
    possible; at least ``min_groups`` groups are returned (falling back to a
    longest-processing-time balance into exactly ``min_groups`` bins).
    """
    if min_groups > len(nums):
        raise ValueError(
            f"cannot allocate {len(nums)} items into {min_groups} groups"
        )
    bins = bin_pack_ffd(nums, capacity)
    if len(bins) >= min_groups:
        return bins
    # LPT into exactly min_groups bins.
    order = np.argsort(nums)[::-1]
    groups: List[List[int]] = [[] for _ in range(min_groups)]
    sums = np.zeros(min_groups)
    for i in order:
        b = int(np.argmin(sums))
        groups[b].append(int(i))
        sums[b] += nums[i]
    return [g for g in groups if g]


def bin_pack_ffd(
    nums: Sequence[int], capacity: int, use_native: Optional[bool] = None
) -> List[List[int]]:
    """First-fit-decreasing bin packing (non-contiguous), for packing variable
    length sequences into fixed token-capacity batches (this is the bin
    step of the train path's segment packing, ``batching.pack_batch``).

    ``use_native``: None = auto (native C path for n >= 64, parity-tested
    against the python loop); True forces native (returns via fallback if
    the toolchain is unavailable); False forces the pure-python path.
    Both paths are deterministic and produce IDENTICAL bins: the
    decreasing order is a reversed stable ascending sort (so ties break
    by DESCENDING original index), and first-fit scans bins in creation
    order."""
    native = use_native if use_native is not None else len(nums) >= 64
    if native:
        from areal_tpu.base import _native

        packed = _native.ffd_pack(nums, capacity)
        if packed is not None:
            bin_of, n_bins = packed
            native_bins: List[List[int]] = [[] for _ in range(n_bins)]
            for i in np.argsort(nums, kind="stable")[::-1]:
                native_bins[int(bin_of[i])].append(int(i))
            return native_bins
    # stable sort so tie order is deterministic and matches the native path
    order = np.argsort(nums, kind="stable")[::-1]
    bins: List[List[int]] = []
    sums: List[int] = []
    for i in order:
        x = nums[i]
        placed = False
        for b in range(len(bins)):
            if sums[b] + x <= capacity:
                bins[b].append(int(i))
                sums[b] += x
                placed = True
                break
        if not placed:
            bins.append([int(i)])
            sums.append(int(x))
    return bins

"""Per-tenant admission plane: priority classes, token-bucket rate
limits, and cumulative token budgets, with TYPED reject reasons.

The plane is pure host-side Python (no jax, no zmq) so it can live
inside the gserver manager's scheduling path, inside an in-process
gateway backend (bench/dryrun), and inside unit tests unchanged.  Every
time-dependent method takes an explicit ``now`` so the refill math is
deterministic under test; production callers pass ``time.monotonic()``.

Reject taxonomy (stable, wire-visible — the gateway maps them onto
HTTP statuses and the manager stamps them into the labeled
``areal_gateway_admission_rejects_total{reason}`` counter):

* ``rate_limited``   — the tenant's token bucket cannot cover the
  request right now; retryable, carries ``retry_after_s`` (HTTP 429 +
  ``Retry-After``).
* ``budget_exhausted`` — the tenant's cumulative token budget is spent;
  TERMINAL until an operator calls :meth:`AdmissionPlane.reset_budget`
  (HTTP 403, no Retry-After).
* ``request_too_large`` — a single request larger than the bucket can
  EVER hold; retrying cannot help (HTTP 403).

An unknown tenant falls back to ``default_policy`` (permissive
interactive by default) instead of rejecting — the plane throttles the
tenants an operator chose to constrain, it is not an auth layer.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Any, Dict, Iterable, Optional, Tuple

#: priority classes the engine's preemption understands: interactive
#: rows survive pool pressure at bulk rows' expense
PRIORITY_INTERACTIVE = "interactive"
PRIORITY_BULK = "bulk"

REJECT_RATE_LIMITED = "rate_limited"
REJECT_BUDGET_EXHAUSTED = "budget_exhausted"
REJECT_REQUEST_TOO_LARGE = "request_too_large"

#: reason -> HTTP status the gateway surfaces (structured body, never a
#: generic 500); 429s carry Retry-After
REJECT_HTTP_STATUS = {
    REJECT_RATE_LIMITED: 429,
    REJECT_BUDGET_EXHAUSTED: 403,
    REJECT_REQUEST_TOO_LARGE: 403,
}

#: the tenant rollout traffic is accounted under when it carries no
#: explicit tenant of its own (partial_rollout stamps it)
DEFAULT_BULK_TENANT = "rollout"


@dataclasses.dataclass(frozen=True)
class TenantPolicy:
    """One tenant's admission contract (config-layer object: plain
    fields only, carried in ``GserverManagerConfig.tenants``)."""

    name: str
    #: "interactive" rows outlive "bulk" rows under pool pressure
    priority: str = PRIORITY_BULK
    #: sustained token throughput; 0 = unlimited (no bucket)
    rate_tokens_per_s: float = 0.0
    #: bucket capacity (burst allowance); defaults to one second of
    #: sustained rate when left 0 with a rate set
    burst_tokens: float = 0.0
    #: cumulative token cap, terminal until reset; 0 = unlimited
    token_budget: float = 0.0


class TokenBucket:
    """Classic token bucket with explicit-clock refill.

    ``take(tokens, now)`` refills ``rate * dt``, capped at ``burst``,
    then either debits and admits or rejects with the exact wait until
    the deficit refills (the 429's Retry-After)."""

    def __init__(self, rate_tokens_per_s: float, burst_tokens: float = 0.0):
        assert rate_tokens_per_s > 0, "rate must be positive (0 = no bucket)"
        self.rate = float(rate_tokens_per_s)
        self.burst = float(burst_tokens) if burst_tokens > 0 else self.rate
        self.tokens = self.burst  # starts full: burst allowance up front
        self._last = None  # type: Optional[float]

    def _refill(self, now: float):
        if self._last is not None and now > self._last:
            self.tokens = min(
                self.burst, self.tokens + (now - self._last) * self.rate
            )
        self._last = now

    def peek(self, now: float) -> float:
        """Current token level at ``now`` (refilled, nothing taken)."""
        self._refill(now)
        return self.tokens

    def take(self, tokens: float, now: float) -> Tuple[bool, float]:
        """(admitted, retry_after_s).  ``retry_after_s`` is 0 on admit
        and the exact refill wait on reject; ``float('inf')`` marks a
        request larger than the bucket can ever hold."""
        self._refill(now)
        if tokens > self.burst:
            return False, float("inf")
        if tokens <= self.tokens:
            self.tokens -= tokens
            return True, 0.0
        return False, (tokens - self.tokens) / self.rate


@dataclasses.dataclass
class AdmissionDecision:
    ok: bool
    tenant: str
    priority: str
    reason: str = ""
    retry_after_s: float = 0.0
    http_status: int = 200

    def as_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


class _TenantState:
    def __init__(self, policy: TenantPolicy):
        self.policy = policy
        self.bucket = (
            TokenBucket(policy.rate_tokens_per_s, policy.burst_tokens)
            if policy.rate_tokens_per_s > 0
            else None
        )
        self.spent_tokens = 0.0  # budget accounting (admit-time estimate)
        self.admitted_total = 0
        self.rejects: Dict[str, int] = {}


class AdmissionPlane:
    """All tenants' admission state behind one lock (the manager serves
    from one thread, but in-process gateway backends admit from HTTP
    handler threads)."""

    def __init__(
        self,
        policies: Iterable[TenantPolicy] = (),
        default_policy: Optional[TenantPolicy] = None,
    ):
        self._lock = threading.Lock()
        self._tenants: Dict[str, _TenantState] = {
            p.name: _TenantState(p) for p in policies
        }
        #: unknown tenants run under this (permissive interactive unless
        #: the operator configures otherwise)
        self.default_policy = default_policy or TenantPolicy(
            name="default", priority=PRIORITY_INTERACTIVE
        )

    @classmethod
    def from_config(cls, tenants) -> "AdmissionPlane":
        """Build from ``GserverManagerConfig.tenants`` rows — each row a
        ``TenantPolicy`` or a plain dict of its fields."""
        policies = []
        for t in tenants or ():
            policies.append(
                t if isinstance(t, TenantPolicy) else TenantPolicy(**dict(t))
            )
        return cls(policies)

    def _state(self, tenant: str) -> _TenantState:
        st = self._tenants.get(tenant)
        if st is None:
            # unknown tenant -> default policy, materialized so repeat
            # requests share one bucket/budget line
            st = _TenantState(
                dataclasses.replace(self.default_policy, name=tenant)
            )
            self._tenants[tenant] = st
        return st

    def priority_of(self, tenant: str) -> str:
        with self._lock:
            return self._state(tenant).policy.priority

    def admit(self, tenant: str, tokens: float, now: float) -> AdmissionDecision:
        """One admission check, charging ``tokens`` (the request's
        estimated prompt + new-token footprint) against the tenant's
        bucket and budget on success."""
        with self._lock:
            st = self._state(tenant)
            pol = st.policy

            def reject(reason: str, retry_after: float = 0.0):
                st.rejects[reason] = st.rejects.get(reason, 0) + 1
                return AdmissionDecision(
                    ok=False,
                    tenant=tenant,
                    priority=pol.priority,
                    reason=reason,
                    retry_after_s=retry_after,
                    http_status=REJECT_HTTP_STATUS[reason],
                )

            if pol.token_budget > 0 and (
                st.spent_tokens + tokens > pol.token_budget
            ):
                return reject(REJECT_BUDGET_EXHAUSTED)
            if st.bucket is not None:
                ok, retry_after = st.bucket.take(tokens, now)
                if not ok:
                    if retry_after == float("inf"):
                        return reject(REJECT_REQUEST_TOO_LARGE)
                    return reject(REJECT_RATE_LIMITED, retry_after)
            st.spent_tokens += tokens
            st.admitted_total += 1
            return AdmissionDecision(
                ok=True, tenant=tenant, priority=pol.priority
            )

    def settle(self, tenant: str, reserved: float, used: float):
        """Refund the over-estimate once a request's ACTUAL token usage
        is known (budgets charge estimates at admit; finals true them
        up — never below zero, never above the reservation)."""
        with self._lock:
            st = self._state(tenant)
            refund = max(0.0, reserved - max(0.0, used))
            st.spent_tokens = max(0.0, st.spent_tokens - refund)

    def reset_budget(self, tenant: str):
        """Operator action: a budget-exhausted tenant becomes admissible
        again (budget exhaustion is terminal until THIS)."""
        with self._lock:
            self._state(tenant).spent_tokens = 0.0

    def stats(self) -> Dict[str, Dict[str, Any]]:
        with self._lock:
            out: Dict[str, Dict[str, Any]] = {}
            for name, st in self._tenants.items():
                out[name] = {
                    "priority": st.policy.priority,
                    "spent_tokens": st.spent_tokens,
                    "token_budget": st.policy.token_budget,
                    "admitted_total": st.admitted_total,
                    "rejects": dict(st.rejects),
                }
            return out

"""Gateway deployment shell: runs the HTTP/SSE front door as a fleet
worker.

``GatewayServer``/``FleetBackend`` are libraries; this module is the
launcher-facing wrapper that makes the gateway a first-class worker
(ROADMAP item 1a): it discovers the ``GserverManager`` through
name_resolve, builds the fleet backend (manager-scheduled, gen-server
streamed), optionally loads a real HF tokenizer for string prompts
(ROADMAP item 1b), serves until told to exit, and publishes its
``host:port`` under ``names.gateway`` so clients and ops tooling can
find the front door.

The HTTP server owns its own thread pool (``ThreadingHTTPServer``), so
``_poll`` only has to keep the worker lifecycle alive — all request
work happens on handler threads against the manager's control plane.
"""

from __future__ import annotations

import time

from areal_tpu.api import system_api
from areal_tpu.base import constants, logging_, name_resolve, names
from areal_tpu.system import worker_base

logger = logging_.getLogger("gateway_worker")


class GatewayWorker(worker_base.Worker):
    def _configure(self, config: system_api.GatewayConfig):
        from areal_tpu.gateway.server import FleetBackend, GatewayServer
        from areal_tpu.system.gserver_manager import GserverManagerClient

        self.config = config
        self.worker_name = config.worker_name
        self.logger = logging_.getLogger(self.worker_name)
        self._expr = constants.experiment_name()
        self._trial = constants.trial_name()

        tokenizer = None
        if config.tokenizer_path:
            from areal_tpu.api import dataset_api

            tokenizer = dataset_api.load_hf_tokenizer(config.tokenizer_path)

        self.manager_client = GserverManagerClient(
            self._expr, self._trial, timeout=config.manager_timeout_s
        )
        self.backend = FleetBackend(
            self.manager_client,
            request_timeout=config.request_timeout_s,
        )
        self.server = GatewayServer(
            self.backend,
            host=config.host,
            port=config.port,
            default_tenant=config.default_tenant,
            vocab_size=config.vocab_size,
            max_new_tokens_cap=config.max_new_tokens_cap,
            poll_interval_s=config.poll_interval_s,
            request_timeout_s=config.request_timeout_s,
            tokenizer=tokenizer,
        )
        self.server.start()
        name_resolve.add(
            names.gateway(self._expr, self._trial),
            self.server.address,
            replace=True,
        )
        from areal_tpu.observability import tracing

        self._tracer = tracing.configure(config.trace, worker=self.worker_name)
        self.logger.info(
            "gateway worker serving on %s (tokenizer=%s)",
            self.server.address,
            config.tokenizer_path or "byte-codec",
        )

    def _poll(self) -> worker_base.PollResult:
        # the HTTP server's handler threads do all the work; the poll
        # loop just keeps the worker responsive to lifecycle commands
        time.sleep(0.05)
        return worker_base.PollResult(sample_count=0)

    def _exit_hook(self):
        if hasattr(self, "server"):
            self.server.shutdown()
        if hasattr(self, "manager_client"):
            self.manager_client.close()

"""Streaming multi-tenant serving gateway.

An OpenAI-style HTTP/SSE front door over the generation-server fleet
(``server.py``) plus the per-tenant admission plane the gserver manager
enforces at allocate/schedule time (``admission.py``).  Submodules are
imported lazily by consumers — this package intentionally has no eager
imports so the admission plane (pure Python, no jax/zmq) stays cheap to
pull into the manager and unit tests.
"""

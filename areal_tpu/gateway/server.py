"""OpenAI-style HTTP/SSE front door over the engine fleet.

``GatewayServer`` is a stdlib ``ThreadingHTTPServer`` (the same shape
as ``observability/server.py``'s metrics server) exposing
``/v1/completions`` + ``/v1/chat/completions`` with token streaming:
each decode-ring harvest's chunk surfaces as one SSE frame, so a
client's time-to-first-byte is the engine's TTFT, not the full
generation wall.  Requests are admitted through the per-tenant
admission plane (``admission.py``) — typed rejects surface as
structured HTTP 429/403 bodies with ``Retry-After``, never generic
500s — and every request stamps its tenant into the SLO plane's
``workload`` label plus a ``priority_class`` the engine's preemption
honors (interactive rows outlive bulk rollout rows under pool
pressure).

Two backends speak the same five-call protocol (admit / submit / poll
/ cancel / finish):

* :class:`EngineBackend` — in-process engines, used by tests, bench's
  ``gateway_ab`` load generator, and the dryrun's gateway phase.  The
  caller (or :meth:`EngineBackend.start_pump`) steps the engines;
  cancels queue and apply on the stepping thread (the engine's cancel
  rewrites pool state and must never race a step).
* :class:`FleetBackend` — the deployment path: schedules through the
  ``GserverManager`` (session-sticky, cache-aware, P/D two-stage
  routing all for free), generates via the gen servers'
  ``generate_stream``/``stream_poll``/``stream_cancel`` commands, and
  settles tenant budgets back through the manager.

A client disconnect mid-stream cancels the engine row and releases its
blocks (leak-audited in tests/bench).
"""

from __future__ import annotations

import json
import math
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional

from areal_tpu.api import model_api
from areal_tpu.base import logging_
from areal_tpu.gateway import sse
from areal_tpu.gateway.admission import (
    PRIORITY_INTERACTIVE,
    AdmissionPlane,
)

logger = logging_.getLogger("gateway")


def estimate_tokens(prompt_len: int, max_new_tokens: int) -> float:
    """The admission plane's charge for one request: its worst-case
    token footprint (budgets true up via ``settle`` on finish)."""
    return float(prompt_len + max_new_tokens)


class ClientDisconnected(Exception):
    """The SSE consumer went away mid-stream (write failed)."""


# -- backends ---------------------------------------------------------------


class EngineBackend:
    """In-process fleet: round-robin over named engines + a local
    admission plane.  ``pump_once``/``start_pump`` own every
    state-mutating engine call (step + cancel); ``submit``/``poll`` are
    safe from HTTP handler threads (the engine's client API locks)."""

    def __init__(
        self,
        engines: Dict[str, Any],
        plane: Optional[AdmissionPlane] = None,
        pick: Optional[Callable[[str], str]] = None,
    ):
        self.engines = dict(engines)
        self.plane = plane
        self._names = list(self.engines)
        self._rr = 0
        self._pick = pick
        self._lock = threading.Lock()
        self._cancels: List[Dict[str, str]] = []
        self._pump_thread: Optional[threading.Thread] = None
        self._pump_stop = threading.Event()

    def admit(self, tenant: str, est_tokens: float) -> Dict[str, Any]:
        if self.plane is None:
            # admission plane off (the bench A/B's baseline arm): every
            # request admitted, no priority class stamped
            return {"ok": True, "tenant": tenant, "priority": ""}
        return self.plane.admit(tenant, est_tokens, time.monotonic()).as_dict()

    def admit_and_submit(
        self,
        inp: model_api.APIGenerateInput,
        tenant: str,
        est_tokens: float,
        stream: bool,
    ):
        """Admission + placement in one step (in-process both are local
        calls, so this is just the protocol's combined form).  Returns
        ``(decision, handle)``; handle is ``None`` on reject."""
        dec = self.admit(tenant, est_tokens)
        if not dec.get("ok"):
            return dec, None
        return dec, self.submit(inp, tenant, dec.get("priority", ""), stream)

    def submit(
        self,
        inp: model_api.APIGenerateInput,
        tenant: str,
        priority: str,
        stream: bool,
    ) -> Dict[str, str]:
        with self._lock:
            if self._pick is not None:
                name = self._pick(inp.qid)
            else:
                name = self._names[self._rr % len(self._names)]
                self._rr += 1
        md = dict(inp.metadata or {})
        md["workload"] = tenant
        if priority:
            md["priority_class"] = priority
        if stream:
            md["stream"] = True
        inp.metadata = md
        self.engines[name].submit(inp)
        return {"engine": name, "qid": inp.qid, "tenant": tenant}

    def poll(self, handle: Dict[str, str]) -> Dict[str, Any]:
        eng, qid = self.engines[handle["engine"]], handle["qid"]
        toks = eng.drain_stream(qid) or []
        out = eng.try_get_result(qid)
        if out is not None:
            toks += eng.drain_stream(qid) or []
            eng.stream_close(qid)
            return {
                "tokens": toks,
                "done": True,
                "result": {
                    "output_ids": list(out.output_ids),
                    "no_eos": bool(out.no_eos),
                    "version_start": out.version_start,
                    "version_end": out.version_end,
                },
            }
        return {"tokens": toks, "done": False, "result": None}

    def cancel(self, handle: Dict[str, str]):
        with self._lock:
            self._cancels.append(dict(handle))

    def finish(self, handle: Dict[str, str], used_tokens: float,
               reserved_tokens: float):
        if self.plane is not None:
            self.plane.settle(
                handle["tenant"], reserved_tokens, used_tokens
            )

    # -- pumping (the stepping thread owns all engine mutation) ---------

    def pump_once(self) -> int:
        """Apply queued cancels, then step every engine once.  Returns
        total tokens harvested this round."""
        with self._lock:
            cancels, self._cancels = self._cancels, []
        for h in cancels:
            self.engines[h["engine"]].cancel(h["qid"])
        n = 0
        for eng in self.engines.values():
            n += eng.step()
        return n

    def has_work(self) -> bool:
        return any(e.has_work for e in self.engines.values())

    def start_pump(self, interval_s: float = 0.0):
        assert self._pump_thread is None

        def loop():
            while not self._pump_stop.is_set():
                if self.pump_once() == 0 and not self.has_work():
                    time.sleep(max(interval_s, 0.002))

        self._pump_thread = threading.Thread(
            target=loop, name="gateway-pump", daemon=True
        )
        self._pump_thread.start()

    def stop_pump(self):
        if self._pump_thread is not None:
            self._pump_stop.set()
            self._pump_thread.join(timeout=10.0)
            self._pump_thread = None
            self._pump_stop.clear()


class FleetBackend:
    """ZMQ fleet: manager-scheduled, gen-server-streamed (deployment
    path; exercised end-to-end by the launcher, not tier-1)."""

    def __init__(self, manager_client, client_factory=None,
                 request_timeout: float = 600.0):
        from areal_tpu.system.generation_server import GenServerClient

        self.manager = manager_client
        self._timeout = request_timeout
        self._factory = client_factory or (
            lambda addr: GenServerClient(addr, timeout=request_timeout)
        )
        self._clients: Dict[str, Any] = {}
        # flipped off (permanently) the first time the manager rejects
        # the combined gateway_submit command — an older manager speaks
        # only the two-call admit + schedule protocol
        self._combined_ok = True

    def _client(self, addr: str):
        if addr not in self._clients:
            self._clients[addr] = self._factory(addr)
        return self._clients[addr]

    def admit(self, tenant: str, est_tokens: float) -> Dict[str, Any]:
        return self.manager.call(
            "gateway_admit", {"tenant": tenant, "tokens": est_tokens}
        )

    def _dispatch(
        self,
        inp: model_api.APIGenerateInput,
        tenant: str,
        priority: str,
        stream: bool,
        sched: Dict[str, Any],
        sched_wait_s: float,
    ) -> Dict[str, str]:
        """Stamp routing metadata from a schedule decision and hand the
        request to the scheduled gen server."""
        md = dict(inp.metadata or {})
        md["workload"] = tenant
        if priority:
            md["priority_class"] = priority
        if stream:
            md["stream"] = True
        md["slo_schedule_wait_s"] = sched_wait_s
        for key in ("handoff_to", "pd_shed", "kv_source"):
            if sched.get(key):
                md[key] = sched[key]
        inp.metadata = md
        self._client(sched["url"]).call(
            "generate_stream" if stream else "generate", inp,
            timeout=self._timeout,
        )
        return {"url": sched["url"], "qid": inp.qid, "tenant": tenant}

    def submit(
        self,
        inp: model_api.APIGenerateInput,
        tenant: str,
        priority: str,
        stream: bool,
    ) -> Dict[str, str]:
        t0 = time.monotonic()
        sched = self.manager.call(
            "schedule_request",
            {
                "qid": inp.qid,
                "prompt_len": len(inp.input_ids or inp.prompt_ids),
                "new_token_budget": inp.gconfig.max_new_tokens,
            },
        )
        return self._dispatch(
            inp, tenant, priority, stream, sched,
            sched_wait_s=time.monotonic() - t0,
        )

    def admit_and_submit(
        self,
        inp: model_api.APIGenerateInput,
        tenant: str,
        est_tokens: float,
        stream: bool,
    ):
        """One manager round trip instead of two: ``gateway_submit``
        returns the admission decision and — when admitted — the
        schedule for ``inp`` in the same reply.  Falls back (for good)
        to the legacy admit + schedule_request pair against managers
        that predate the combined command.  Returns ``(decision,
        handle)``; handle is ``None`` on reject."""
        if self._combined_ok:
            t0 = time.monotonic()
            try:
                dec = self.manager.call(
                    "gateway_submit",
                    {
                        "tenant": tenant,
                        "tokens": est_tokens,
                        "qid": inp.qid,
                        "prompt_len": len(inp.input_ids or inp.prompt_ids),
                        "new_token_budget": inp.gconfig.max_new_tokens,
                    },
                )
            except RuntimeError:
                # the manager replied {"error": "unknown command ..."}:
                # an older control plane — use the two-call protocol
                # from here on
                self._combined_ok = False
                logger.warning(
                    "manager does not speak gateway_submit; falling "
                    "back to admit + schedule round trips"
                )
            else:
                if not dec.get("ok"):
                    return dec, None
                sched = dec.get("schedule")
                if sched is not None:
                    handle = self._dispatch(
                        inp, tenant, dec.get("priority", ""), stream,
                        sched, sched_wait_s=time.monotonic() - t0,
                    )
                    return dec, handle
                # admitted but no schedule attached (defensive):
                # schedule separately below
                return dec, self.submit(
                    inp, tenant, dec.get("priority", ""), stream
                )
        dec = self.admit(tenant, est_tokens)
        if not dec.get("ok"):
            return dec, None
        return dec, self.submit(inp, tenant, dec.get("priority", ""), stream)

    def poll(self, handle: Dict[str, str]) -> Dict[str, Any]:
        return self._client(handle["url"]).call(
            "stream_poll", {"qid": handle["qid"]}, timeout=self._timeout
        )

    def cancel(self, handle: Dict[str, str]):
        self._client(handle["url"]).call(
            "stream_cancel", {"qid": handle["qid"]}, timeout=self._timeout
        )

    def finish(self, handle: Dict[str, str], used_tokens: float,
               reserved_tokens: float):
        self.manager.call(
            "gateway_finish",
            {
                "qid": handle["qid"],
                "tenant": handle["tenant"],
                "reserved_tokens": reserved_tokens,
                "used_tokens": used_tokens,
            },
        )


# -- request lifecycle (transport-agnostic: HTTP handler + bench) -----------


def run_request(
    backend,
    inp: model_api.APIGenerateInput,
    tenant: str,
    priority: str,
    *,
    stream: bool,
    on_chunk: Optional[Callable[[List[int]], None]] = None,
    poll_interval_s: float = 0.002,
    timeout_s: float = 600.0,
    pump: Optional[Callable[[], Any]] = None,
    handle: Optional[Dict[str, str]] = None,
) -> Dict[str, Any]:
    """Submit one admitted request and drive it to completion, invoking
    ``on_chunk`` with each incremental token batch (streaming mode).
    ``pump`` lets a single-threaded caller (bench, dryrun) step the
    in-process engines between polls.  A pre-made ``handle`` (from
    ``admit_and_submit``'s combined round trip) skips the submit.  A
    ``ClientDisconnected`` raised by ``on_chunk`` cancels the engine
    row and settles the tenant's budget for the tokens actually
    produced."""
    prompt_len = len(inp.input_ids or inp.prompt_ids)
    reserved = estimate_tokens(prompt_len, inp.gconfig.max_new_tokens)
    if handle is None:
        handle = backend.submit(inp, tenant, priority, stream)
    collected: List[int] = []
    deadline = time.monotonic() + timeout_s
    try:
        while True:
            if pump is not None:
                pump()
            r = backend.poll(handle)
            toks = r.get("tokens") or []
            if toks:
                collected.extend(toks)
                if on_chunk is not None:
                    on_chunk(toks)
            if r.get("done"):
                backend.finish(
                    handle, float(len(collected)) + prompt_len, reserved
                )
                return {
                    "token_ids": collected,
                    "result": r.get("result") or {},
                    "prompt_tokens": prompt_len,
                }
            if time.monotonic() > deadline:
                backend.cancel(handle)
                backend.finish(
                    handle, float(len(collected)) + prompt_len, reserved
                )
                raise TimeoutError(f"gateway request {inp.qid} timed out")
            if pump is None and poll_interval_s:
                time.sleep(poll_interval_s)
    except ClientDisconnected:
        backend.cancel(handle)
        backend.finish(
            handle, float(len(collected)) + prompt_len, reserved
        )
        raise


# -- HTTP server ------------------------------------------------------------


class GatewayServer:
    """The HTTP/SSE front door.  ``port=0`` binds an ephemeral port
    (tests); ``serve_forever`` runs on a daemon thread like the metrics
    server."""

    def __init__(
        self,
        backend,
        host: str = "127.0.0.1",
        port: int = 0,
        *,
        default_tenant: str = "anonymous",
        vocab_size: int = 256,
        max_new_tokens_cap: int = 1024,
        model_name: str = "areal-tpu",
        poll_interval_s: float = 0.002,
        request_timeout_s: float = 600.0,
        tokenizer: Optional[Any] = None,
    ):
        self.backend = backend
        self.default_tenant = default_tenant
        self.vocab_size = vocab_size
        # a real (HF-style) tokenizer makes string prompts first-class;
        # without one the byte-level codec in ``sse`` round-trips text
        self.tokenizer = tokenizer
        self.max_new_tokens_cap = max_new_tokens_cap
        self.model_name = model_name
        self.poll_interval_s = poll_interval_s
        self.request_timeout_s = request_timeout_s
        self._seq_lock = threading.Lock()
        self._seq = 0
        self._active_streams = 0
        self._init_metrics()
        gw = self

        class Handler(BaseHTTPRequestHandler):
            # HTTP/1.0: the SSE body ends at connection close (no
            # chunked framing), matching curl/openai-client behavior
            protocol_version = "HTTP/1.0"

            def log_message(self, fmt, *args):  # noqa: N802
                logger.debug("gateway http: " + fmt, *args)

            def do_GET(self):  # noqa: N802
                if self.path == "/healthz":
                    body = json.dumps({"ok": True}).encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                else:
                    self.send_error(404)

            def do_POST(self):  # noqa: N802
                if self.path == "/v1/completions":
                    gw._handle_completion(self, chat=False)
                elif self.path == "/v1/chat/completions":
                    gw._handle_completion(self, chat=True)
                else:
                    self.send_error(404)

        self.httpd = ThreadingHTTPServer((host, port), Handler)
        self.httpd.daemon_threads = True
        self.address = (
            f"{self.httpd.server_address[0]}:{self.httpd.server_address[1]}"
        )
        self._thread: Optional[threading.Thread] = None

    def _init_metrics(self):
        from areal_tpu.observability import get_registry

        reg = get_registry()
        self._m_requests = reg.counter("areal_gateway_requests_total")
        self._m_streams = reg.counter("areal_gateway_streams_total")
        self._m_rejects = reg.counter(
            "areal_gateway_admission_rejects_total"
        )
        self._m_active = reg.gauge("areal_gateway_active_streams")

    def start(self):
        assert self._thread is None
        self._thread = threading.Thread(
            target=self.httpd.serve_forever,
            name="gateway-http",
            daemon=True,
        )
        self._thread.start()
        logger.info("gateway listening on %s", self.address)

    def shutdown(self):
        self.httpd.shutdown()
        self.httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None

    # -- request handling ----------------------------------------------

    def _next_id(self) -> str:
        with self._seq_lock:
            self._seq += 1
            return f"{self._seq}"

    def _encode_text(self, text: str) -> List[int]:
        if self.tokenizer is not None:
            return [int(t) for t in self.tokenizer.encode(text)]
        return sse.encode_text(text, self.vocab_size)

    def _decode_tokens(self, toks: List[int]) -> str:
        if self.tokenizer is not None:
            return self.tokenizer.decode(toks)
        return sse.decode_tokens(toks)

    def _parse_prompt(self, body: Dict[str, Any], chat: bool) -> List[int]:
        if chat:
            ids: List[int] = []
            for msg in body.get("messages") or []:
                content = msg.get("content", "")
                if isinstance(content, list):
                    ids.extend(int(t) for t in content)
                else:
                    ids.extend(self._encode_text(str(content)))
            return ids
        prompt = body.get("prompt", [])
        if isinstance(prompt, str):
            return self._encode_text(prompt)
        return [int(t) for t in prompt]

    def _send_json(self, handler, status: int, obj: Dict[str, Any],
                   headers: Dict[str, str] = ()):
        body = json.dumps(obj).encode()
        handler.send_response(status)
        handler.send_header("Content-Type", "application/json")
        handler.send_header("Content-Length", str(len(body)))
        for k, v in dict(headers or {}).items():
            handler.send_header(k, v)
        handler.end_headers()
        handler.wfile.write(body)

    def _handle_completion(self, handler, chat: bool):
        try:
            n = int(handler.headers.get("Content-Length") or 0)
            body = json.loads(handler.rfile.read(n) or b"{}")
        except Exception:  # noqa: BLE001
            self._send_json(
                handler, 400,
                {"error": {"message": "invalid JSON body",
                           "type": "invalid_request_error"}},
            )
            return
        self._m_requests.inc()
        tenant = str(
            handler.headers.get("x-tenant")
            or body.get("user")
            or self.default_tenant
        )
        prompt = self._parse_prompt(body, chat)
        if not prompt:
            self._send_json(
                handler, 400,
                {"error": {"message": "empty prompt",
                           "type": "invalid_request_error"}},
            )
            return
        max_new = min(
            int(body.get("max_tokens") or 16), self.max_new_tokens_cap
        )
        stream = bool(body.get("stream"))
        temperature = body.get("temperature")
        greedy = temperature is None or float(temperature) <= 0.0
        # request object built BEFORE admission: a backend with the
        # combined admit_and_submit entry point collapses the admit and
        # schedule round trips into one manager call
        qid = str(body.get("qid") or f"gw-{self._next_id()}")
        gconfig = model_api.GenerationHyperparameters(
            max_new_tokens=max_new,
            greedy=greedy,
            temperature=float(temperature) if not greedy else 1.0,
            n=1,
        )
        inp = model_api.APIGenerateInput(
            qid=qid, prompt_ids=prompt, input_ids=prompt, gconfig=gconfig
        )
        handle = None
        try:
            if hasattr(self.backend, "admit_and_submit"):
                dec, handle = self.backend.admit_and_submit(
                    inp, tenant, estimate_tokens(len(prompt), max_new),
                    stream,
                )
            else:
                # stub/minimal backends speak the five-call protocol only
                dec = self.backend.admit(
                    tenant, estimate_tokens(len(prompt), max_new)
                )
        except Exception as e:  # noqa: BLE001 - manager/gen-server down
            logger.exception("admit/submit for %s failed", qid)
            self._send_json(
                handler, 502,
                {"error": {"message": repr(e), "type": "bad_gateway"}},
            )
            return
        if not dec.get("ok"):
            reason = dec.get("reason", "rejected")
            self._m_rejects.inc(reason=reason)
            headers = {}
            retry_after = dec.get("retry_after_s") or 0.0
            if dec.get("http_status") == 429:
                headers["Retry-After"] = str(
                    max(1, int(math.ceil(retry_after)))
                )
            self._send_json(
                handler,
                int(dec.get("http_status") or 429),
                {"error": {
                    "message": (
                        f"tenant {tenant!r} rejected: {reason}"
                    ),
                    "type": reason,
                    "retry_after_s": retry_after,
                }},
                headers,
            )
            return
        rid = f"cmpl-{qid}"
        obj = "chat.completion.chunk" if chat else "text_completion"
        if stream:
            self._m_streams.inc()
            self._stream_response(
                handler, inp, tenant, dec.get("priority", ""), rid, obj,
                chat, handle=handle,
            )
        else:
            self._sync_response(
                handler, inp, tenant, dec.get("priority", ""), rid, chat,
                handle=handle,
            )

    def _choice(self, toks: List[int], chat: bool,
                finish_reason: Optional[str]) -> Dict[str, Any]:
        text = self._decode_tokens(toks)
        if chat:
            delta = {"role": "assistant", "content": text}
            return {"index": 0, "delta": delta, "token_ids": toks,
                    "finish_reason": finish_reason}
        return {"index": 0, "text": text, "token_ids": toks,
                "finish_reason": finish_reason}

    def _stream_response(self, handler, inp, tenant, priority, rid, obj,
                         chat, handle=None):
        handler.send_response(200)
        handler.send_header("Content-Type", "text/event-stream")
        handler.send_header("Cache-Control", "no-cache")
        handler.end_headers()
        with self._seq_lock:
            self._active_streams += 1
            self._m_active.set(self._active_streams)

        def write_frame(payload):
            try:
                handler.wfile.write(sse.sse_frame(payload))
                handler.wfile.flush()
            except (BrokenPipeError, ConnectionResetError, OSError) as e:
                raise ClientDisconnected(str(e)) from e

        def on_chunk(toks: List[int]):
            write_frame({
                "id": rid, "object": obj, "model": self.model_name,
                "choices": [self._choice(toks, chat, None)],
            })

        try:
            out = run_request(
                self.backend, inp, tenant, priority,
                stream=True, on_chunk=on_chunk,
                poll_interval_s=self.poll_interval_s,
                timeout_s=self.request_timeout_s,
                handle=handle,
            )
            result = out["result"]
            finish = "length" if result.get("no_eos") else "stop"
            write_frame({
                "id": rid, "object": obj, "model": self.model_name,
                "choices": [self._choice([], chat, finish)],
                "usage": sse.usage_block(
                    out["prompt_tokens"], len(out["token_ids"])
                ),
            })
            write_frame(sse.DONE_SENTINEL)
        except ClientDisconnected:
            logger.info("client disconnected mid-stream (%s)", inp.qid)
        except Exception as e:  # noqa: BLE001
            logger.exception("stream %s failed", inp.qid)
            try:
                write_frame({"error": {"message": repr(e)}})
            except ClientDisconnected:
                pass
        finally:
            with self._seq_lock:
                self._active_streams -= 1
                self._m_active.set(self._active_streams)

    def _sync_response(self, handler, inp, tenant, priority, rid, chat,
                       handle=None):
        try:
            out = run_request(
                self.backend, inp, tenant, priority, stream=False,
                poll_interval_s=self.poll_interval_s,
                timeout_s=self.request_timeout_s,
                handle=handle,
            )
        except TimeoutError as e:
            self._send_json(
                handler, 504,
                {"error": {"message": str(e), "type": "timeout"}},
            )
            return
        result = out["result"]
        toks = result.get("output_ids") or out["token_ids"]
        finish = "length" if result.get("no_eos") else "stop"
        choice = self._choice(toks, chat, finish)
        if chat:
            choice = {
                "index": 0,
                "message": {
                    "role": "assistant",
                    "content": self._decode_tokens(toks),
                },
                "token_ids": toks,
                "finish_reason": finish,
            }
        self._send_json(handler, 200, {
            "id": rid,
            "object": "chat.completion" if chat else "text_completion",
            "model": self.model_name,
            "choices": [choice],
            "usage": sse.usage_block(out["prompt_tokens"], len(toks)),
        })

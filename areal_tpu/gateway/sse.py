"""Server-Sent-Events framing + the gateway's token<->text codec.

The wire protocol is the OpenAI streaming dialect: each chunk is one
``data: <json>\\n\\n`` frame, the final content frame carries the
``finish_reason`` and a ``usage`` block, and the stream terminates with
the literal ``data: [DONE]`` sentinel.  ``iter_sse_events`` is the
client-side parser the conformance tests (and any Python consumer)
drive against a readable byte stream.

Text codec: the reproduction has no HF tokenizer on the serving image,
so the gateway speaks TOKEN IDS natively (OpenAI's ``prompt`` field
legitimately accepts token arrays) and falls back to a reversible
byte-level codec for string prompts/messages — each UTF-8 byte maps to
one token id modulo the serving vocab.  Real deployments swap
``encode_text``/``decode_tokens`` for a tokenizer; everything else is
codec-agnostic.
"""

from __future__ import annotations

import json
from typing import Any, Dict, Iterator, List

DONE_SENTINEL = "[DONE]"


def sse_frame(payload: Any) -> bytes:
    """One SSE frame: ``data: <json>`` + blank-line terminator (the
    ``[DONE]`` sentinel is passed through as a bare string)."""
    if isinstance(payload, str):
        body = payload
    else:
        body = json.dumps(payload, separators=(",", ":"))
    return f"data: {body}\n\n".encode()


def sse_done() -> bytes:
    return sse_frame(DONE_SENTINEL)


def iter_sse_events(stream) -> Iterator[Any]:
    """Parse ``data:`` frames off a readable byte stream, yielding
    decoded JSON objects; the ``[DONE]`` sentinel yields the literal
    string ``"[DONE]"`` and ends iteration."""
    buf = b""
    while True:
        chunk = stream.read(1)
        if not chunk:
            return
        buf += chunk
        while b"\n\n" in buf:
            raw, buf = buf.split(b"\n\n", 1)
            for line in raw.splitlines():
                if not line.startswith(b"data:"):
                    continue
                body = line[len(b"data:"):].strip().decode()
                if body == DONE_SENTINEL:
                    yield DONE_SENTINEL
                    return
                yield json.loads(body)


def encode_text(text: str, vocab_size: int) -> List[int]:
    """Byte-level fallback encoding for string prompts (reversible when
    ``vocab_size >= 256``; degraded-but-deterministic below that)."""
    return [b % max(1, vocab_size) for b in text.encode("utf-8")]


def decode_tokens(tokens: List[int]) -> str:
    """Inverse of :func:`encode_text` for byte-range ids; out-of-range
    ids render as ``<id>`` placeholders so streams stay lossless to
    read even under a tiny test vocab."""
    parts = []
    run: List[int] = []

    def flush():
        if run:
            parts.append(bytes(run).decode("utf-8", errors="replace"))
            run.clear()

    for t in tokens:
        if 0 <= t < 256:
            run.append(t)
        else:
            flush()
            parts.append(f"<{t}>")
    flush()
    return "".join(parts)


def usage_block(prompt_tokens: int, completion_tokens: int) -> Dict[str, int]:
    return {
        "prompt_tokens": int(prompt_tokens),
        "completion_tokens": int(completion_tokens),
        "total_tokens": int(prompt_tokens) + int(completion_tokens),
    }

"""Multi-task reward dispatch: route each generated answer to its verifier.

Rebuild of the reference's task dispatch (reference:
realhf/impl/model/interface/math_rw_interface.py ``MultiTaskRewardInterface``
:181 groups answers by task tag and calls the math or code verifier;
realhf/impl/environment/math_code_single_step_env.py:42 does the same inside
the async env).  Verification runs locally by default; exporting
``AREAL_VERIFIER_URL`` routes every batch to the HTTP verifier service
(areal_tpu/verifiers/service.py) instead — the reference's "functioncall"
remote mode.
"""

from __future__ import annotations

import os
from typing import Dict, List, Sequence

from areal_tpu.base import logging_

logger = logging_.getLogger("verifier_dispatch")


def verify_batch(
    tasks: Sequence[str],
    texts: Sequence[str],
    problems: Sequence[Dict],
    timeout: float = 300.0,
) -> List[float]:
    """Score ``texts[i]`` (the generated answer for ``problems[i]``, a
    dataset info dict) under the verifier selected by ``tasks[i]``.

    Task tags: ``math`` / ``stem`` -> final-answer equivalence;
    ``code`` -> sandboxed testcase execution."""
    assert len(tasks) == len(texts) == len(problems)
    url = os.environ.get("AREAL_VERIFIER_URL")
    if url:
        return _client_for(url).verify(tasks, texts, problems, timeout)
    return verify_batch_local(tasks, texts, problems)


_clients: Dict[str, object] = {}


def _client_for(url: str):
    """One client per URL so its concurrency cap actually spans every
    concurrent verify_batch caller in the process."""
    if url not in _clients:
        from areal_tpu.verifiers.service import VerifierClient

        _clients[url] = VerifierClient(url)
    return _clients[url]


def verify_batch_local(
    tasks: Sequence[str],
    texts: Sequence[str],
    problems: Sequence[Dict],
) -> List[float]:
    rewards = [0.0] * len(texts)

    math_idx = [i for i, t in enumerate(tasks) if t in ("math", "stem")]
    if math_idx:
        from areal_tpu.verifiers.math_verify import math_verify

        math_rewards = math_verify(
            [texts[i] for i in math_idx],
            [problems[i].get("solutions", []) for i in math_idx],
        )
        for i, r in zip(math_idx, math_rewards):
            rewards[i] = r

    code_idx = [i for i, t in enumerate(tasks) if t == "code"]
    if code_idx:
        from areal_tpu.verifiers.code_verify import code_verify

        id2info = {}
        qids = []
        for i in code_idx:
            qid = str(problems[i].get("query_id", i))
            id2info[qid] = problems[i]
            qids.append(qid)
        code_rewards = code_verify(
            id2info, [extract_code(texts[i]) for i in code_idx], qids
        )
        for i, r in zip(code_idx, code_rewards):
            rewards[i] = r

    unknown = set(tasks) - {"math", "stem", "code"}
    if unknown:
        logger.warning("unknown task tags scored 0: %s", sorted(unknown))
    return rewards


def extract_code(text: str) -> str:
    """Last fenced code block, or the raw text when there is none (the
    reference extracts ```...``` blocks from generated answers)."""
    import re

    blocks = re.findall(
        r"```(?:python|py|cpp|c\+\+)?\s*\n(.*?)```", text, re.DOTALL
    )
    if blocks:
        return blocks[-1]
    return text

"""Parent-side code verification: sandbox processes + testcase batching.

Rebuild of the reference's code reward path (reference:
functioncall/code/local_verify.py ``code_verify`` — process-pool fan-out of
sandboxed per-solution runs with hard kill on timeout; and
functioncall/code/verify.py:111 ``code_verify`` — splitting each problem's
testcases into batches dispatched concurrently with fast-fail AND-reduction
over batch verdicts).  Ours merges both: every (solution, testcase-batch)
pair becomes one disposable sandbox subprocess
(areal_tpu/verifiers/sandbox_runner.py) run under a thread pool; a problem
scores 1 only if every batch passes every case.

Problem dicts use the dataset schema (areal_tpu/data/math_code_dataset.py):
``query_id`` and ``input_output`` — a JSON string with ``inputs``,
``outputs``, optional ``fn_name``/``timeout``.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import tempfile
import uuid
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional, Sequence

from areal_tpu.base import logging_

logger = logging_.getLogger("code_verify")

SINGLE_CASE_EXEC_TIMEOUT = 6
TEST_CASE_BATCH_SIZE = 4
JOB_WALL_TIMEOUT = 200


def _run_sandbox(job: Dict, wall_timeout: float) -> Dict:
    """One sandbox subprocess; hard process-group kill on timeout.

    The child gets a scrubbed environment (no worker env vars / credentials),
    a throwaway scratch directory as cwd+HOME+TMPDIR (relative-path writes
    land there and are deleted), its own session for group kill, and rlimits
    applied inside sandbox_runner before user code runs.  See the
    sandbox_runner module docstring for the honest trust model."""
    tmp = tempfile.gettempdir()
    tag = uuid.uuid4().hex
    in_path = os.path.join(tmp, f"areal-code-{tag}-in.json")
    out_path = os.path.join(tmp, f"areal-code-{tag}-out.json")
    scratch = tempfile.mkdtemp(prefix=f"areal-sbx-{tag}-")
    with open(in_path, "w") as f:
        json.dump(job, f)
    repo_root = os.path.dirname(os.path.dirname(os.path.dirname(__file__)))
    child_env = {
        "PATH": os.environ.get("PATH", "/usr/bin:/bin"),
        "PYTHONPATH": repo_root,
        "HOME": scratch,
        "TMPDIR": scratch,
        "LANG": os.environ.get("LANG", "C.UTF-8"),
    }
    proc = subprocess.Popen(
        [
            sys.executable,
            "-m",
            "areal_tpu.verifiers.sandbox_runner",
            in_path,
            out_path,
        ],
        stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL,
        start_new_session=True,
        cwd=scratch,
        env=child_env,
    )
    try:
        proc.wait(timeout=wall_timeout)
    except subprocess.TimeoutExpired:
        pass
    finally:
        try:
            os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass
        proc.wait()
    result = {"results": [False], "error": "no output (killed or crashed)"}
    try:
        with open(out_path) as f:
            result = json.load(f)
    except (FileNotFoundError, ValueError):
        pass
    finally:
        for p in (in_path, out_path):
            try:
                os.remove(p)
            except FileNotFoundError:
                pass
        import shutil

        shutil.rmtree(scratch, ignore_errors=True)
    return result


def _problem_jobs(
    problem: Dict,
    solution: str,
    query_index: int,
    timeout_per_case: int,
    batch_size: int,
) -> List[Dict]:
    io_spec = problem["input_output"]
    if isinstance(io_spec, str):
        io_spec = json.loads(io_spec)
    inputs = io_spec.get("inputs", [])
    outputs = io_spec.get("outputs", [])
    assert len(inputs) == len(outputs), problem.get("query_id")
    fn_name = io_spec.get("fn_name", "")
    # per-problem timeout: top-level field wins, then one embedded in the
    # input_output spec, then the caller default
    raw_timeout = problem.get("timeout", io_spec.get("timeout", timeout_per_case))
    timeout = int(min(100, max(1, float(raw_timeout))))
    if not inputs:
        # unit-test style: one load-and-run job
        return [
            {
                "code": solution,
                "fn_name": fn_name,
                "testcases": [],
                "timeout_per_case": timeout,
                "query_index": query_index,
            }
        ]
    batch_size = min(max(1, batch_size), len(inputs))
    jobs = []
    for start in range(0, len(inputs), batch_size):
        end = min(len(inputs), start + batch_size)
        jobs.append(
            {
                "code": solution,
                "fn_name": fn_name,
                "testcases": [
                    {"input": inputs[i], "expected_output": outputs[i]}
                    for i in range(start, end)
                ],
                "timeout_per_case": timeout,
                "fast_fail": True,
                "query_index": query_index,
            }
        )
    return jobs


def code_verify(
    id2info: Dict[str, Dict],
    generateds: Sequence[str],
    query_ids: Sequence[str],
    timeout_per_case: int = SINGLE_CASE_EXEC_TIMEOUT,
    test_case_batch_size: int = TEST_CASE_BATCH_SIZE,
    job_wall_timeout: float = JOB_WALL_TIMEOUT,
    max_workers: Optional[int] = None,
) -> List[float]:
    """Score each generated solution 1.0 iff every testcase passes."""
    assert len(generateds) == len(query_ids)
    jobs: List[Dict] = []
    malformed: List[int] = []
    for idx, (qid, sol) in enumerate(zip(query_ids, generateds)):
        try:
            jobs.extend(
                _problem_jobs(
                    id2info[qid],
                    sol,
                    idx,
                    timeout_per_case,
                    test_case_batch_size,
                )
            )
        except (KeyError, TypeError, AttributeError, ValueError, AssertionError) as e:
            # a malformed problem spec (e.g. missing input_output) scores 0
            # rather than killing the reward MFC / rollout task
            logger.warning("problem %s malformed (%r); reward 0", qid, e)
            malformed.append(idx)
    if max_workers is None:
        max_workers = max(2, (os.cpu_count() or 8) // 4)
    results = [1.0] * len(query_ids)
    for idx in malformed:
        results[idx] = 0.0
    with ThreadPoolExecutor(max_workers=max_workers) as pool:
        for job, out in zip(
            jobs,
            pool.map(lambda j: _run_sandbox(j, job_wall_timeout), jobs),
        ):
            per_case = out.get("results", [False])
            n_cases = len(job["testcases"])
            passed = (
                all(per_case)
                and (n_cases == 0 or len(per_case) == n_cases)
            )
            if not passed:
                results[job["query_index"]] = 0.0
    return results

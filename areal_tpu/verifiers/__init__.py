"""Reward verifiers: sandboxed code execution, hardened math checking, and
the multi-task dispatch + HTTP service that the reward interface and envs
consume (reference: the ``functioncall/`` reward service tree)."""

"""Sandboxed single-solution code runner (subprocess body).

Executes one generated solution against a list of testcases inside THIS
process — which the parent (areal_tpu/verifiers/code_verify.py) always
spawns as a disposable, process-group-isolated child with a scrubbed
environment and a throwaway scratch cwd.

**Trust model (read before deploying):** the isolation here is resource
limits (CPU/memory/procs/file-size via rlimits), a hard process-group kill,
env-var scrubbing, and best-effort API neutering — NOT an OS security
boundary.  There is no syscall filter, user namespace, or network
isolation in this image (no nsjail/bubblewrap), so deliberately malicious
code can still read world-readable files and open sockets.  This matches
training-time use on model-generated competition code; for adversarial or
multi-tenant inputs, route verification through the HTTP verifier service
(areal_tpu/verifiers/service.py) on an isolated host, which is how the
reference deploys it (reference: functioncall/ FaaS cluster,
functioncall/code/verify.py:111).

Semantics follow the reference's LiveCodeBench-derived checker
(reference: functioncall/code/function/testing_util.py ``run_test`` — two
problem styles) re-implemented from scratch:

- **stdin style** (no ``fn_name``): the solution is a whole program; each
  testcase feeds ``input`` on stdin and compares captured stdout
  line-by-line (trailing whitespace stripped, float-tolerant tokens).
- **call style** (``fn_name`` given): the solution defines a function (or a
  ``Solution`` class with the method); each testcase's ``input`` holds the
  argument list and ``expected_output`` the return value, compared with
  normalization (tuples->lists, float tolerance).

Per-case wall-clock timeout via SIGALRM; CPU/memory/process rlimits applied
before any user code runs.  Output: JSON ``{"results": [...], "error": ...}``
with one bool per case (fast-fail truncates).

Usage: ``python -m areal_tpu.verifiers.sandbox_runner IN.json OUT.json``
"""

from __future__ import annotations

import io
import json
import signal
import sys
import types
from typing import Any, Dict, List

#: import preamble exposed to solutions — competitive-programming staples
PREAMBLE = (
    "import sys, os, re, math, json, random, itertools, functools, "
    "operator, bisect, heapq, collections, string, copy, statistics, io\n"
    "from math import *\n"
    "from collections import *\n"
    "from itertools import *\n"
    "from functools import *\n"
    "from heapq import *\n"
    "from bisect import *\n"
    "from typing import *\n"
    "sys.setrecursionlimit(600000)\n"
)


class CaseTimeout(Exception):
    pass


def _alarm(signum, frame):
    raise CaseTimeout()


def apply_rlimits(cpu_seconds: int = 60, mem_bytes: int = 4 << 30):
    import resource

    resource.setrlimit(resource.RLIMIT_CPU, (cpu_seconds, cpu_seconds + 5))
    for limit, value in (
        ("RLIMIT_AS", (mem_bytes, mem_bytes)),
        ("RLIMIT_NPROC", (64, 64)),
        ("RLIMIT_FSIZE", (64 << 20, 64 << 20)),  # cap runaway file writes
        ("RLIMIT_CORE", (0, 0)),  # no core dumps from crashing solutions
    ):
        try:
            resource.setrlimit(getattr(resource, limit), value)
        except (ValueError, OSError, AttributeError):
            pass


def neuter_destructive_apis():
    """Best-effort guard against solutions nuking shared state (the real
    isolation is the disposable child process + rlimits)."""
    import builtins
    import os as _os
    import shutil as _shutil
    import subprocess as _subprocess

    for mod, name in (
        (_os, "system"),
        (_os, "popen"),
        (_os, "execv"),
        (_os, "execve"),
        (_os, "fork"),
        (_os, "forkpty"),
        (_os, "killpg"),
        (_os, "removedirs"),
        (_os, "rmdir"),
        (_shutil, "rmtree"),
        (_subprocess, "Popen"),
        (_subprocess, "run"),
        (_subprocess, "call"),
        (_subprocess, "check_output"),
    ):
        try:
            setattr(mod, name, None)
        except (AttributeError, TypeError):
            pass
    builtins.exit = None
    builtins.quit = None


def _float_tokens_equal(a: str, b: str, tol: float = 1e-6) -> bool:
    if a == b:
        return True
    try:
        return abs(float(a) - float(b)) <= tol * max(1.0, abs(float(b)))
    except (ValueError, OverflowError):
        return False


def stdout_matches(got: str, expected: str) -> bool:
    """Line-by-line comparison, trailing-whitespace insensitive, with
    float-tolerant token fallback."""
    glines = [l.rstrip() for l in got.rstrip().splitlines()]
    elines = [l.rstrip() for l in expected.rstrip().splitlines()]
    if glines == elines:
        return True
    if len(glines) != len(elines):
        return False
    for g, e in zip(glines, elines):
        if g == e:
            continue
        gt, et = g.split(), e.split()
        if len(gt) != len(et):
            return False
        if not all(_float_tokens_equal(x, y) for x, y in zip(gt, et)):
            return False
    return True


def values_equal(got: Any, expected: Any, tol: float = 1e-6) -> bool:
    """Normalized value comparison for call-style problems."""
    if isinstance(got, tuple):
        got = list(got)
    if isinstance(expected, tuple):
        expected = list(expected)
    if isinstance(got, list) and isinstance(expected, list):
        return len(got) == len(expected) and all(
            values_equal(g, e, tol) for g, e in zip(got, expected)
        )
    if isinstance(got, dict) and isinstance(expected, dict):
        return set(got) == set(expected) and all(
            values_equal(got[k], expected[k], tol) for k in got
        )
    if isinstance(got, float) or isinstance(expected, float):
        try:
            return abs(float(got) - float(expected)) <= tol * max(
                1.0, abs(float(expected))
            )
        except (TypeError, ValueError):
            return False
    return got == expected


def _load_solution_module(code: str):
    mod = types.ModuleType("solution")
    exec(compile(PREAMBLE + code, "<solution>", "exec"), mod.__dict__)
    return mod


def _resolve_fn(mod, fn_name: str):
    if hasattr(mod, fn_name):
        return getattr(mod, fn_name)
    if hasattr(mod, "Solution"):
        return getattr(mod.Solution(), fn_name)
    raise AttributeError(f"solution defines no {fn_name!r}")


def _parse_args(raw: Any) -> List[Any]:
    """Call-style testcase input -> argument list.  Accepts a JSON list, a
    newline-separated sequence of JSON values, or a single value."""
    if isinstance(raw, list):
        return raw
    if isinstance(raw, str):
        lines = [l for l in raw.splitlines() if l.strip()]
        if len(lines) > 1:
            return [json.loads(l) for l in lines]
        return [json.loads(raw)]
    return [raw]


def run_stdin_case(code: str, stdin_data: str, expected: str, timeout: int):
    old_stdin, old_stdout = sys.stdin, sys.stdout
    sys.stdin = io.StringIO(stdin_data if stdin_data.endswith("\n") else stdin_data + "\n")
    sys.stdout = captured = io.StringIO()
    signal.alarm(timeout)
    try:
        # fresh module per case: programs assume a clean global state
        mod = types.ModuleType("solution_main")
        mod.__dict__["__name__"] = "__main__"
        exec(compile(PREAMBLE + code, "<solution>", "exec"), mod.__dict__)
        ok = True
    except SystemExit:
        ok = True  # programs may sys.exit(0) after printing
    except BaseException:
        ok = False
    finally:
        signal.alarm(0)
        sys.stdin, sys.stdout = old_stdin, old_stdout
    return ok and stdout_matches(captured.getvalue(), expected)


def run_call_case(fn, raw_input: Any, expected: Any, timeout: int) -> bool:
    args = _parse_args(raw_input)
    if isinstance(expected, str):
        try:
            expected = json.loads(expected)
        except (ValueError, TypeError):
            pass
    old_stdout = sys.stdout
    sys.stdout = io.StringIO()  # solutions may print debug noise
    signal.alarm(timeout)
    try:
        got = fn(*args)
        ok = values_equal(got, expected)
    except BaseException:
        ok = False
    finally:
        signal.alarm(0)
        sys.stdout = old_stdout
    return ok


def run_job(job: Dict) -> Dict:
    code = job["code"]
    fn_name = job.get("fn_name") or ""
    cases = job["testcases"]
    timeout = int(job.get("timeout_per_case", 6))
    fast_fail = bool(job.get("fast_fail", True))

    results: List[bool] = []
    if not cases:
        # unit-test style: success = the solution merely loads and runs
        try:
            signal.alarm(timeout)
            _load_solution_module(code)
            results.append(True)
        except BaseException:
            results.append(False)
        finally:
            signal.alarm(0)
        return {"results": results}

    fn = None
    if fn_name:
        try:
            signal.alarm(timeout)
            fn = _resolve_fn(_load_solution_module(code), fn_name)
        except BaseException as e:  # noqa: BLE001
            return {
                "results": [False] * len(cases),
                "error": f"load: {type(e).__name__}: {e}",
            }
        finally:
            signal.alarm(0)

    for case in cases:
        if fn_name:
            ok = run_call_case(
                fn, case["input"], case["expected_output"], timeout
            )
        else:
            ok = run_stdin_case(
                code, str(case["input"]), str(case["expected_output"]), timeout
            )
        results.append(ok)
        if fast_fail and not ok:
            break
    return {"results": results}


def main():
    in_path, out_path = sys.argv[1], sys.argv[2]
    with open(in_path) as f:
        job = json.load(f)
    signal.signal(signal.SIGALRM, _alarm)
    apply_rlimits(
        cpu_seconds=int(job.get("cpu_limit", 60)),
        mem_bytes=int(job.get("mem_limit", 4 << 30)),
    )
    neuter_destructive_apis()
    try:
        out = run_job(job)
    except BaseException as e:  # noqa: BLE001 - report, don't crash silently
        out = {"results": [False], "error": f"{type(e).__name__}: {e}"}
    with open(out_path, "w") as f:
        json.dump(out, f)


if __name__ == "__main__":
    main()

"""HTTP verifier service + client (the reference's reward FaaS, stdlib-only).

Rebuild of the reference's functioncall service layer (reference:
functioncall/base/call.py:81-220 ``batch_function_call`` — async HTTP batch
dispatch with a concurrency semaphore, per-request timeout and retries with
backoff; the server side lives in a FaaS cluster).  Ours ships the server
too: a ``ThreadingHTTPServer`` exposing ``POST /verify`` over the same
multi-task dispatch used locally, so a verifier cluster is one process per
CPU host with ``AREAL_VERIFIER_URL`` pointed at it (it registers itself in
name_resolve for discovery).

Protocol: request ``{"tasks": [...], "texts": [...], "problems": [...]}``;
response ``{"rewards": [...]}``.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Sequence

from areal_tpu.base import logging_, network

logger = logging_.getLogger("verifier_service")

MAX_BATCH_PER_REQUEST = 64


class _Handler(BaseHTTPRequestHandler):
    def do_GET(self):  # noqa: N802 - http.server API
        if self.path == "/health":
            body = json.dumps({"status": "ok"}).encode()
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        else:
            self.send_error(404)

    def do_POST(self):  # noqa: N802
        if self.path != "/verify":
            self.send_error(404)
            return
        try:
            length = int(self.headers.get("Content-Length", "0"))
            req = json.loads(self.rfile.read(length))
            from areal_tpu.verifiers.dispatch import verify_batch_local

            rewards = verify_batch_local(
                req["tasks"], req["texts"], req["problems"]
            )
            body = json.dumps({"rewards": rewards}).encode()
            self.send_response(200)
        except Exception as e:  # noqa: BLE001 - report to client
            logger.exception("verify request failed")
            body = json.dumps({"error": repr(e)}).encode()
            self.send_response(500)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, fmt, *args):  # quiet
        logger.debug(fmt, *args)


class VerifierServer:
    """In-process verifier HTTP server (daemon thread)."""

    def __init__(self, port: int = 0, register: bool = False):
        if port == 0:
            port = network.find_free_port()
        self.port = port
        self._httpd = ThreadingHTTPServer(("0.0.0.0", port), _Handler)
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True
        )
        self.url = f"http://{network.gethostip()}:{port}"
        if register:
            from areal_tpu.base import constants, name_resolve, names

            name_resolve.add_subentry(
                names.verifier_server(
                    constants.experiment_name(), constants.trial_name()
                ),
                self.url,
            )

    def start(self):
        self._thread.start()
        return self

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()


class VerifierClient:
    """Chunked, concurrency-capped, retrying client
    (semantics of reference functioncall/base/call.py:81-220)."""

    def __init__(
        self,
        url: str,
        max_concurrency: int = 8,
        retries: int = 3,
        backoff: float = 0.5,
    ):
        self.url = url.rstrip("/")
        self._sem = threading.Semaphore(max_concurrency)
        self.retries = retries
        self.backoff = backoff

    def _post_chunk(
        self,
        tasks: Sequence[str],
        texts: Sequence[str],
        problems: Sequence[Dict],
        timeout: float,
    ) -> Optional[List[float]]:
        payload = json.dumps(
            {
                "tasks": list(tasks),
                "texts": list(texts),
                "problems": list(problems),
            }
        ).encode()
        last_err: Optional[Exception] = None
        for attempt in range(self.retries):
            with self._sem:
                try:
                    req = urllib.request.Request(
                        self.url + "/verify",
                        data=payload,
                        headers={"Content-Type": "application/json"},
                    )
                    with urllib.request.urlopen(req, timeout=timeout) as rsp:
                        out = json.loads(rsp.read())
                    return [float(r) for r in out["rewards"]]
                except (
                    urllib.error.URLError,
                    urllib.error.HTTPError,
                    TimeoutError,
                    KeyError,
                    ValueError,
                ) as e:
                    last_err = e
            # back off OUTSIDE the semaphore: a flaky server must not pin a
            # concurrency slot for the whole exponential wait, throttling
            # healthy requests
            time.sleep(self.backoff * (2**attempt))
        logger.warning(
            "verifier requests failed after %d retries: %r; scoring 0",
            self.retries,
            last_err,
        )
        return None

    def verify(
        self,
        tasks: Sequence[str],
        texts: Sequence[str],
        problems: Sequence[Dict],
        timeout: float = 300.0,
    ) -> List[float]:
        from concurrent.futures import ThreadPoolExecutor

        chunks = [
            (start, min(len(tasks), start + MAX_BATCH_PER_REQUEST))
            for start in range(0, len(tasks), MAX_BATCH_PER_REQUEST)
        ]
        rewards = [0.0] * len(tasks)
        with ThreadPoolExecutor(max_workers=8) as pool:
            outs = pool.map(
                lambda se: self._post_chunk(
                    tasks[se[0] : se[1]],
                    texts[se[0] : se[1]],
                    problems[se[0] : se[1]],
                    timeout,
                ),
                chunks,
            )
            for (start, end), out in zip(chunks, outs):
                if out is not None:
                    rewards[start:end] = out
        return rewards

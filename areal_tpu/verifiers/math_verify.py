"""Timeout-hardened math verification.

The raw parser (areal_tpu/data/math_parser.py) calls sympy ``simplify``,
which can pathologically hang on adversarial generated answers.  The
reference isolates this behind a process pool
(reference: realhf/impl/dataset/math_parser.py ``parse_lines_in_parallel``'s
ProcessPoolExecutor + per-chunk timeouts).  This wrapper does the same with
recovery: items are verified in a process pool with a collective deadline;
on timeout the poisoned pool is discarded (hung workers and all) and the
unfinished items score 0.
"""

from __future__ import annotations

import atexit
import concurrent.futures
from typing import List, Optional

from areal_tpu.base import logging_
from areal_tpu.data.math_parser import verify_math_solution

logger = logging_.getLogger("math_verify")

#: minimum collective deadline; the effective deadline scales with batch
#: size so large reward batches are not spuriously zeroed
DEFAULT_TIMEOUT = 60.0
PER_ITEM_BUDGET = 5.0

_pool: Optional[concurrent.futures.ProcessPoolExecutor] = None


def _get_pool() -> concurrent.futures.ProcessPoolExecutor:
    global _pool
    if _pool is None:
        import os

        _pool = concurrent.futures.ProcessPoolExecutor(
            max_workers=max(2, (os.cpu_count() or 8) // 4)
        )
        atexit.register(_shutdown_pool)
    return _pool


def _shutdown_pool():
    global _pool
    if _pool is not None:
        # shutdown() alone never terminates RUNNING workers — a hung sympy
        # call would leak a CPU-burning process — so kill them explicitly
        procs = list(getattr(_pool, "_processes", {}).values())
        _pool.shutdown(wait=False, cancel_futures=True)
        for p in procs:
            try:
                p.terminate()
            except (OSError, ValueError):
                pass
        _pool = None


def math_verify(
    generateds: List[str],
    solutions_list: List[List[str]],
    timeout: Optional[float] = None,
) -> List[float]:
    """Per-item 0/1 rewards; items unfinished by the deadline score 0.

    The default deadline scales with batch size over pool width (a 256-item
    PPO reward batch on 2 workers legitimately needs minutes; a fixed 60s
    would zero the healthy tail)."""
    assert len(generateds) == len(solutions_list)
    if not generateds:
        return []
    global _pool
    pool = _get_pool()
    if timeout is None:
        workers = pool._max_workers
        timeout = max(
            DEFAULT_TIMEOUT,
            PER_ITEM_BUDGET * len(generateds) / max(1, workers),
        )
    try:
        futures = [
            pool.submit(verify_math_solution, g, s)
            for g, s in zip(generateds, solutions_list)
        ]
    except (concurrent.futures.process.BrokenProcessPool, RuntimeError):
        _shutdown_pool()
        pool = _get_pool()
        futures = [
            pool.submit(verify_math_solution, g, s)
            for g, s in zip(generateds, solutions_list)
        ]
    done, not_done = concurrent.futures.wait(futures, timeout=timeout)
    rewards: List[float] = []
    for f in futures:
        if f in done and not f.exception():
            rewards.append(float(f.result()))
        else:
            rewards.append(0.0)
    if not_done:
        logger.warning(
            "math verify timed out on %d/%d items; recycling pool",
            len(not_done),
            len(futures),
        )
        _shutdown_pool()  # hung sympy workers poison the pool; start fresh
    return rewards

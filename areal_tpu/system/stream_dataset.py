"""Dataset facade over a trajectory puller (async mode)
(reference: realhf/system/stream_dataset.py ``PullerStreamDataset`` :23 — a
background thread pulls JSON trajectories from rollout workers and converts
them to SequenceSample; ``__len__`` mirrors the prompt dataset size for epoch
accounting)."""

from __future__ import annotations

import queue
import threading
from typing import List, Optional

import torch.utils.data

from areal_tpu.api.data import SequenceSample
from areal_tpu.base import logging_
from areal_tpu.system.push_pull_stream import (
    NameResolvingZmqPuller,
    queue_Empty,
)

logger = logging_.getLogger("stream_dataset")


class PullerStreamDataset(torch.utils.data.Dataset):
    def __init__(
        self,
        experiment_name: str,
        trial_name: str,
        puller_index: int = 0,
        dataset_size: int = 10**9,
        pull_timeout_ms: int = 100,
        max_queue_size: int = 10000,
    ):
        self.dataset_size = dataset_size
        self._queue: "queue.Queue" = queue.Queue(maxsize=max_queue_size)
        self._stop = threading.Event()
        self._puller_args = (experiment_name, trial_name, puller_index)
        self._pull_timeout_ms = pull_timeout_ms
        self._thread = threading.Thread(target=self._pull_loop, daemon=True)
        self._thread.start()

    def _pull_loop(self):
        puller = NameResolvingZmqPuller(*self._puller_args)
        try:
            while not self._stop.is_set():
                try:
                    payload = puller.pull(timeout_ms=self._pull_timeout_ms)
                except queue_Empty:
                    continue
                for traj in payload:
                    sample = SequenceSample.from_json_compatible(traj)
                    self._queue.put(sample)
        finally:
            puller.close()

    def drain(self, max_samples: int) -> List[SequenceSample]:
        """Non-blocking: up to max_samples pulled trajectories."""
        out = []
        while len(out) < max_samples:
            try:
                out.append(self._queue.get_nowait())
            except queue.Empty:
                break
        return out

    def get(self, timeout: float = 1.0) -> Optional[SequenceSample]:
        try:
            return self._queue.get(timeout=timeout)
        except queue.Empty:
            return None

    @property
    def qsize(self) -> int:
        return self._queue.qsize()

    def __len__(self):
        return self.dataset_size

    def __getitem__(self, idx):
        """Blocking fetch of the next pushed trajectory (idx is ignored —
        trajectories arrive in rollout-completion order)."""
        s = self.get(timeout=300.0)
        if s is None:
            raise TimeoutError("no trajectory arrived within 300s")
        return s

    def close(self):
        self._stop.set()
        self._thread.join(timeout=5)

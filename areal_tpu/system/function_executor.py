"""Per-step DFG execution on the master.

Rebuild of the reference's function-executor pair (reference:
realhf/system/function_executor.py — ``FunctionExecutor.execute_step`` :211,
``load_data`` :120; realhf/system/model_function_call.py —
``ModelFunctionCall.run`` :491 with buffer waits, dispatch, hook payloads,
reply gathering).

One asyncio task per MFC per step + one data-loading task; MFC tasks wait on
the buffer, derive a transfer plan, request every worker in the model's
group, await replies, and amend the buffer with output metadata.
"""

from __future__ import annotations

import asyncio
import itertools
from typing import Any, Dict, List, Optional, Sequence

from areal_tpu.api.data import SequenceSample
from areal_tpu.api.dfg import (
    MFCDef,
    ModelInterfaceType,
    OffloadHook,
    ParamReallocHook,
)
from areal_tpu.base import logging_, stats_tracker
from areal_tpu.system.buffer import AsyncIOSequenceBuffer
from areal_tpu.system.redistributor import (
    GlobalStorageTracker,
    RedistribPlanner,
)
from areal_tpu.system.request_reply_stream import (
    MasterRequestReplyStream,
    NoMessage,
    Payload,
)

logger = logging_.getLogger("function_executor")


class ReplyRouter:
    """Resolves stream replies to per-request futures."""

    def __init__(self, stream: MasterRequestReplyStream):
        self.stream = stream
        self._pending: Dict[str, asyncio.Future] = {}
        self._task: Optional[asyncio.Task] = None

    def expect(self, request_id: str) -> asyncio.Future:
        fut = asyncio.get_running_loop().create_future()
        self._pending[request_id] = fut
        return fut

    async def run(self):
        while True:
            try:
                reply = self.stream.poll_reply()
            except NoMessage:
                await asyncio.sleep(0.002)
                continue
            fut = self._pending.pop(reply.request_id, None)
            if fut is None:
                logger.warning("unexpected reply %s", reply.request_id)
                continue
            data = reply.data
            if isinstance(data, dict) and "__worker_error__" in data:
                fut.set_exception(
                    RuntimeError(
                        f"worker {reply.handled_by}: {data['__worker_error__']}"
                    )
                )
            else:
                fut.set_result(reply)

    def start(self):
        self._task = asyncio.get_running_loop().create_task(self.run())

    def stop(self):
        if self._task:
            self._task.cancel()


async def group_request(
    router: ReplyRouter,
    stream: MasterRequestReplyStream,
    workers: Sequence[str],
    handle_name: str,
    data: Any = None,
    pre_hooks_per_worker: Optional[Dict[str, List[Dict]]] = None,
    post_hooks: Optional[List[Dict]] = None,
) -> Dict[str, Payload]:
    futs = {}
    for w in workers:
        p = Payload(
            handler=w,
            handle_name=handle_name,
            data=data,
            pre_hooks=(pre_hooks_per_worker or {}).get(w, []),
            post_hooks=post_hooks or [],
        )
        futs[w] = router.expect(p.request_id)
        stream.post(p)
    results = await asyncio.gather(*futs.values())
    return dict(zip(futs.keys(), results))


class FunctionExecutor:
    def __init__(
        self,
        rpcs: List[MFCDef],
        stream: MasterRequestReplyStream,
        router: ReplyRouter,
        buffer: AsyncIOSequenceBuffer,
        model_groups: Dict[str, List[str]],
        data_owner_workers: List[str],
        src_rpc_name: str,
        fetch_batch_size: int = 32,
        shuffle_dataset: bool = True,
    ):
        self.rpcs = {r.name: r for r in rpcs}
        self.stream = stream
        self.router = router
        self.buffer = buffer
        self.model_groups = model_groups
        self.data_owner_workers = data_owner_workers
        self.src_rpc_name = src_rpc_name
        self.fetch_batch_size = fetch_batch_size
        self.tracker = GlobalStorageTracker()
        self.planner = RedistribPlanner(self.tracker)
        self._fetch_cycle = itertools.cycle(data_owner_workers)
        self.epoch = 0
        self.is_new_epoch = False
        # in-process absolute trained-sample counter (single writer); read
        # lazily so the master's recovery seed lands first
        self._training_samples: Optional[int] = None

    # -- data loading -------------------------------------------------------

    async def load_data(self, n_seqs_needed: int):
        """Fetch dataset batches round-robin across DP owner workers until
        the buffer holds enough fresh sequences for the source RPC
        (reference: function_executor.py:120)."""
        src = self.rpcs[self.src_rpc_name]
        loaded = 0
        while loaded < n_seqs_needed:
            w = next(self._fetch_cycle)
            reply = (
                await group_request(
                    self.router,
                    self.stream,
                    [w],
                    "fetch",
                    data={"batch_size": self.fetch_batch_size},
                )
            )[w]
            meta: SequenceSample = reply.data["meta"]
            if reply.data["is_new_epoch"]:
                self.is_new_epoch = True
                self.epoch = max(self.epoch, reply.data["epoch"])
            self.tracker.add_data(w, meta.ids, list(meta.keys))
            await self.buffer.put_batch([meta])
            loaded += meta.bs
            self._bump_training_samples(meta.bs)

    def _bump_training_samples(self, n: int):
        """Advance the globally-trained sample counter the gserver manager's
        staleness gate reads (reference: function_executor.py:185-200); the
        master seeds it on (re)start so it survives recovery.

        The counter is owned IN-PROCESS after the first bump and published
        as an absolute value: a name_resolve read-modify-write would lose
        increments if a second writer ever appeared (code-review r4
        finding).  Single-writer assumption: exactly one FunctionExecutor
        (the master's) bumps this key; the master's recovery seed happens
        before the first bump, so reading it once here is race-free."""
        from areal_tpu.base import constants, name_resolve, names

        key = names.training_samples(
            constants.experiment_name(), constants.trial_name()
        )
        if self._training_samples is None:
            try:
                self._training_samples = int(name_resolve.get(key))
            except name_resolve.NameEntryNotFoundError:
                self._training_samples = 0
        self._training_samples += n
        name_resolve.add(key, str(self._training_samples), replace=True)

    # -- one MFC ------------------------------------------------------------

    async def run_rpc(self, rpc: MFCDef) -> Dict[str, Any]:
        ids, gathered = await self.buffer.get_batch_for_rpc(
            rpc.name, rpc.input_keys, rpc.n_seqs
        )
        sample_ids = gathered.ids
        workers = self.model_groups[str(rpc.model_name)]
        plan = self.planner.derive_plan(
            workers, sample_ids, list(rpc.input_keys)
        )
        pre_hooks: Dict[str, List[Dict]] = {w: [] for w in workers}
        for w in workers:
            steps = [s for s in plan if s.dst == w]
            if steps:
                pre_hooks[w].append({"type": "data_transfer", "steps": steps})
            for hook in rpc.pre_hooks:
                pre_hooks[w].append(_hook_to_dict(hook, rpc))
        post_hooks = [_hook_to_dict(h, rpc) for h in rpc.post_hooks]

        replies = await group_request(
            self.router,
            self.stream,
            workers,
            rpc.interface_type.value,
            data={
                "rpc_name": rpc.name,
                "model_name": str(rpc.model_name),
                "handle_name": rpc.interface_type.value,
                "ids": sample_ids,
                "input_keys": list(rpc.input_keys),
                "mb_spec": rpc.mb_spec,
            },
            pre_hooks_per_worker=pre_hooks,
            post_hooks=post_hooks,
        )
        # all group workers produce identical outputs (SPMD); take the first
        lead = workers[0]
        reply = replies[lead].data
        stats: Dict[str, Any] = {}
        if "meta" in reply:
            meta: SequenceSample = reply["meta"]
            for w in workers:
                self.tracker.add_data(w, meta.ids, reply["output_keys"])
            await self.buffer.amend_batch(meta)
        if "stats" in reply and isinstance(reply["stats"], dict):
            stats = reply["stats"]
        if rpc.log_return_value:
            logger.info("MFC %s -> %s", rpc.name, stats)
        with stats_tracker.scope(rpc.name):
            elapsed = reply.get("elapsed", 0.0)
            stats_tracker.scalar(elapsed=elapsed)
            # per-MFC throughput from the worker's analytic accounting
            # (reference: realhf/system/flops_counter.py); tflops is
            # per-worker-group since every SPMD peer ran the same FLOPs
            if "flops" in reply and elapsed > 0:
                stats_tracker.scalar(
                    tflops=reply["flops"] / elapsed / 1e12,
                    tokens_per_sec=reply.get("n_tokens", 0) / elapsed,
                    n_tokens=float(reply.get("n_tokens", 0)),
                )
        return stats

    # -- one full step ------------------------------------------------------

    async def execute_step(self) -> Dict[str, Any]:
        self.is_new_epoch = False
        src = self.rpcs[self.src_rpc_name]
        tasks = [
            asyncio.ensure_future(self.load_data(src.n_seqs)),
        ]
        rpc_tasks = {
            name: asyncio.ensure_future(self.run_rpc(rpc))
            for name, rpc in self.rpcs.items()
        }
        await asyncio.gather(*tasks, *rpc_tasks.values())
        stats = {}
        for name, t in rpc_tasks.items():
            for k, v in (t.result() or {}).items():
                stats[f"{name}/{k}"] = v

        # gc: drop sequences that every terminal RPC consumed
        all_rpcs = list(self.rpcs)
        done_ids = await self.buffer.pop_consumed(all_rpcs)
        if done_ids:
            self.tracker.drop_ids(done_ids)
            await group_request(
                self.router,
                self.stream,
                list(
                    dict.fromkeys(
                        w for ws in self.model_groups.values() for w in ws
                    )
                ),
                "clear_data_cache",
                data={"ids": done_ids},
            )
        return stats


def _hook_to_dict(hook, rpc: MFCDef) -> Dict:
    if isinstance(hook, ParamReallocHook):
        src = str(hook.source or rpc.model_name)
        dst = str(hook.target or rpc.model_name)
        return {
            "type": "param_realloc",
            "source": src,
            "target": dst,
            "eta": hook.eta,
        }
    if isinstance(hook, OffloadHook):
        return {"type": "offload"}
    if isinstance(hook, dict):
        return hook
    raise ValueError(f"unknown hook {hook}")

"""Model worker: hosts model engines + dataset shard, executes MFCs.

Rebuild of the reference's model worker (reference:
realhf/system/model_worker.py — lazy setup :235-330, non-blocking requests
(fetch/spec/clear_data_cache) :554, blocking requests (initialize/inference/
generate/train_step + hooks) :694, MFC execution :911, data-transfer hook
:1026, param-realloc hook :1046, save/load hooks :1159-1245).

TPU mapping: one model worker process drives its host's chips for EVERY
model role assigned to it (roles share the mesh; JAX allows multiple Mesh
views over the same devices).  Parallelism happens *inside* engines via
sharding; the system layer only moves host data.
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time
from typing import Any, Dict, List, Optional

import jax
import numpy as np

from areal_tpu.api import dataset_api, model_api, system_api
from areal_tpu.api.config import ModelName
from areal_tpu.api.data import MicroBatchSpec, SequenceSample
from areal_tpu.base import constants, logging_, seeding
from areal_tpu.system import worker_base
from areal_tpu.system.data_manager import DataManager
from areal_tpu.system.redistributor import RedistribStep
from areal_tpu.system.request_reply_stream import (
    NoMessage,
    Payload,
    WorkerRequestReplyStream,
)

logger = logging_.getLogger("model_worker")

NON_BLOCKING_RPCS = ("fetch", "spec", "clear_data_cache", "model_config")


def _count_dataset_rows(d) -> int:
    """Row count of a jsonl/json dataset abstraction without building it."""
    path = (d.args or {}).get("dataset_path")
    if not path or not os.path.exists(path):
        return 0
    if path.endswith(".jsonl"):
        with open(path) as f:
            return sum(1 for line in f if line.strip())
    import json

    with open(path) as f:
        return len(json.load(f))


class ModelWorker(worker_base.Worker):
    def _configure(self, config: system_api.ModelWorkerConfig):
        self.config = config
        self.worker_name = config.worker_name
        self.logger = logging_.getLogger(self.worker_name)
        seeding.set_random_seed(config.seed, self.worker_name)

        from areal_tpu.observability import tracing

        self._tracer = tracing.configure(
            config.trace, worker=self.worker_name
        )
        self._stream = WorkerRequestReplyStream(
            constants.experiment_name(),
            constants.trial_name(),
            config.worker_name,
        )
        self._data_manager = DataManager(
            constants.experiment_name(),
            constants.trial_name(),
            config.worker_name,
        )
        self._models: Dict[str, model_api.Model] = {}
        self._publish_lock = threading.Lock()
        self._publish_threads: List[threading.Thread] = []
        self._last_published_version: Dict[str, int] = {}
        self._backends: Dict[str, model_api.ModelBackend] = {}
        self._interfaces: Dict[str, model_api.ModelInterface] = {}

        self._tokenizer = None
        if config.tokenizer_path:
            self._tokenizer = dataset_api.load_hf_tokenizer(
                config.tokenizer_path
            )

        self._dataset = None
        self._dataloader = None
        self._data_iter = None
        self._dataset_epoch = 0
        if config.datasets and not config.use_stream_dataset:
            dp_rank, dp_size = config.dataset_shard
            datasets = [
                dataset_api.make_dataset(
                    d,
                    seed=config.dataset_seed,
                    dp_rank=dp_rank,
                    world_size=dp_size,
                    tokenizer_or_path=self._tokenizer,
                )
                for d in config.datasets
            ]
            if len(datasets) > 1:
                import torch.utils.data

                self._dataset = torch.utils.data.ConcatDataset(datasets)
            else:
                self._dataset = datasets[0]
        elif config.use_stream_dataset:
            from areal_tpu.system.stream_dataset import PullerStreamDataset

            # epoch accounting mirrors the underlying prompt dataset size
            # (reference: stream_dataset.py:23 __len__ contract); count rows
            # cheaply instead of constructing (tokenizing) the full dataset
            size = 10**9
            if config.datasets:
                dp_rank, dp_size = config.dataset_shard
                n_rows = sum(_count_dataset_rows(d) for d in config.datasets)
                size = max(1, n_rows // max(1, dp_size))
                size *= config.stream_group_size
            self._dataset = PullerStreamDataset(
                experiment_name=constants.experiment_name(),
                trial_name=constants.trial_name(),
                puller_index=config.dataset_shard[0],
                dataset_size=size,
            )

    # -- dataset ------------------------------------------------------------

    def _ensure_loader(self, batch_size: int):
        if self._dataloader is None or self._dataloader.batch_size != batch_size:
            self._dataloader = dataset_api.SequenceSampleDataLoader(
                self._dataset,
                batch_size=batch_size,
                shuffle=not self.config.use_stream_dataset,
                seed=self.config.dataset_seed + self._dataset_epoch,
            )
            self._data_iter = iter(self._dataloader)

    def _handle_fetch(self, batch_size: int) -> Dict:
        """Next dataloader batch: store tensors locally, return metadata."""
        self._ensure_loader(batch_size)
        is_new_epoch = False
        try:
            batch = next(self._data_iter)
        except StopIteration:
            self._dataset_epoch += 1
            is_new_epoch = True
            self._dataloader = None  # reshuffle with a new epoch seed
            self._ensure_loader(batch_size)
            batch = next(self._data_iter)
        self._data_manager.store(batch)
        return {
            "meta": batch.meta(),
            "is_new_epoch": is_new_epoch,
            "epoch": self._dataset_epoch,
        }

    def _handle_spec(self) -> Dict:
        return {
            "dataset_size": len(self._dataset) if self._dataset is not None else 0,
        }

    # -- models -------------------------------------------------------------

    def _handle_initialize(self, shard: system_api.ModelShard, ft_spec) -> Dict:
        from areal_tpu.engine.backend import make_model

        name = str(shard.model_name)
        mesh = shard.mesh_spec.make_mesh()
        model = make_model(
            shard.model, shard.model_name, mesh, tokenizer=self._tokenizer
        )
        backend = model_api.make_backend(shard.backend)
        model = backend.initialize(model, ft_spec)
        self._models[name] = model
        self._backends[name] = backend
        self._maybe_recover_load(name, backend, model)
        if shard.eval_dataset is not None:
            model.eval_dataset = dataset_api.make_dataset(
                shard.eval_dataset,
                seed=self.config.dataset_seed,
                dp_rank=0,
                world_size=1,
                tokenizer_or_path=self._tokenizer,
            )
        self.logger.info("initialized model %s on mesh %s", name, shard.mesh_spec)
        return {"model_config": dataclasses.asdict(model.model_cfg)}

    def _maybe_recover_load(self, name: str, backend, model):
        """On a recover restart (AREAL_RECOVER=1, set by the launcher's
        restart policy), reload the model's latest recover checkpoint —
        weights, optimizer state, and version — instead of starting from the
        initial weights (reference: realhf/system/model_worker.py:723-733;
        master-side StepInfo restore alone would silently train a fresh
        model)."""
        if os.environ.get("AREAL_RECOVER") != "1":
            return
        from areal_tpu.base import recover
        from areal_tpu.engine.checkpoint import latest_train_state

        # cap at the master's recorded resume step: a crash between the
        # ckpt write and the recover-info write must not replay one extra
        # optimizer update
        info = recover.discover()
        max_step = info.recover_start.global_step if info else None
        base = os.path.join(constants.get_recover_path(), name)
        latest = latest_train_state(base, max_step=max_step)
        if latest is None:
            self.logger.info("recover: no checkpoint for %s; fresh start", name)
            return
        try:
            backend.load(model, latest)
            self.logger.info(
                "recover: %s reloaded from %s (version %d)",
                name,
                latest,
                getattr(model.engine, "version", -1),
            )
            from areal_tpu.base import name_resolve, names

            name_resolve.add(
                names.recover_load(
                    constants.experiment_name(), constants.trial_name(), name
                ),
                latest,
                replace=True,
            )
        except NotImplementedError:
            pass

    def _get_interface(self, rpc_name: str) -> model_api.ModelInterface:
        if rpc_name not in self._interfaces:
            self._interfaces[rpc_name] = model_api.make_interface(
                self.config.interfaces[rpc_name]
            )
        return self._interfaces[rpc_name]

    # -- hooks --------------------------------------------------------------

    def _run_hook(self, hook: Dict):
        htype = hook["type"]
        if htype == "data_transfer":
            for step in hook["steps"]:
                if isinstance(step, dict):
                    step = RedistribStep(**step)
                if step.dst == self.worker_name:
                    self._data_manager.execute_pull(step)
        elif htype == "param_realloc":
            self._param_realloc(
                hook["source"], hook["target"], hook.get("eta", 1.0)
            )
        elif htype == "save":
            self._save_model(hook["model_name"], hook["path"])
        elif htype == "publish_weights":
            self._publish_weights(hook["model_name"])
        elif htype == "offload":
            pass  # device arrays are dropped with the engine's arrays; no-op
        else:
            raise ValueError(f"unknown hook {htype}")

    def _param_realloc(self, source: str, target: str, eta: float):
        """target <- eta * source + (1 - eta) * target (EMA ref update /
        layout move).  Co-hosted roles move via device_put; a source hosted
        on OTHER workers is pulled from its latest published sharded
        checkpoint — the cross-host channel the reference implements with
        NCCL realloc plans (realhf/impl/model/comm/param_realloc.py:351;
        ours: realhf/system/model_worker.py:1046's role, orbax transport)."""
        dst = self._models[target].engine
        src_params = (
            self._models[source].engine.params
            if source in self._models
            else self._load_published_params(source, dst)
        )
        if eta == 1.0:
            new = jax.tree.map(
                lambda s, spec: jax.device_put(s, spec),
                src_params,
                dst.param_shardings,
            )
        else:
            eta_ = float(eta)

            @jax.jit
            def _ema(s, d):
                return jax.tree.map(
                    lambda a, b: (eta_ * a + (1 - eta_) * b).astype(b.dtype),
                    s,
                    d,
                )

            new = _ema(src_params, dst.params)
        dst.set_params(new)

    def _load_published_params(
        self, source: str, dst_engine, deadline_s: float = 10.0
    ):
        """Latest published sharded checkpoint of ``source``, restored
        directly onto the destination engine's shardings.

        The publisher GCs old snapshots (keep-last-2), so a restore can
        race the deletion of the very version it resolved: the ``v{n}``
        dir vanishes mid-restore.  Instead of crashing, every attempt
        RE-RESOLVES the version key and retries — the GC only ever runs
        after a newer version is advertised, so the re-resolved key
        names a strictly newer, intact snapshot.  A version that failed
        once is never retried (its deletion is permanent); if no newer
        version shows up before ``deadline_s``, the race is reported as
        such."""
        import pickle as _pickle

        from areal_tpu.base import name_resolve, names
        from areal_tpu.engine import checkpoint

        role = source.split("@", 1)[0]
        key = names.model_version(
            constants.experiment_name(), constants.trial_name(), role
        )
        last_exc = None
        failed_versions = set()
        deadline = time.monotonic() + deadline_s
        while True:
            try:
                payload = _pickle.loads(bytes.fromhex(name_resolve.get(key)))
            except name_resolve.NameEntryNotFoundError:
                raise RuntimeError(
                    f"param_realloc: source {source!r} is not hosted on "
                    f"{self.worker_name} and has never published weights; "
                    "add a publish_weights post-hook to its train MFC"
                ) from None
            version = payload.get("version")
            if version in failed_versions:
                # same doomed version still advertised: wait for the
                # publisher to advertise the next-newer one
                if time.monotonic() > deadline:
                    break
                time.sleep(0.2)
                continue
            try:
                return checkpoint.load_params_like(
                    dst_engine.params, payload["path"]
                )
            except (FileNotFoundError, ValueError, OSError) as e:
                last_exc = e
                failed_versions.add(version)
                getattr(self, "logger", logger).warning(
                    "published checkpoint v%s of %r vanished mid-restore "
                    "(keep-last-2 GC race); waiting for a newer version",
                    version, source,
                )
                if time.monotonic() > deadline:
                    break
                time.sleep(0.2)
        raise RuntimeError(
            f"param_realloc: published checkpoint for {source!r} kept "
            "disappearing mid-restore (GC race) and no newer version was "
            f"advertised within {deadline_s:.0f}s"
        ) from last_exc

    def _publish_weights(self, model_name: str):
        """Write current weights to the realloc dir as a SHARDED raw-param
        checkpoint (each host writes its own shards, inference dtype — no
        host gather, no HF conversion) and publish the version in
        name_resolve — the train->generation weight sync trigger (reference:
        realhf/system/model_worker.py:787-812 post-train realloc save +
        version publish; gserver manager picks it up and hot-swaps)."""
        import pickle as _pickle

        from areal_tpu.base import name_resolve, names
        from areal_tpu.engine import checkpoint

        model = self._models[model_name]
        version = model.version.global_step
        role = model.name.role
        path = os.path.join(
            constants.get_param_realloc_path(), role, f"v{version}"
        )
        tik = time.monotonic()
        # non-blocking: orbax snapshots the device buffers (~ms) and commits
        # in a background thread; the trainer proceeds immediately
        checkpoint.save_params(
            model.engine.params,
            path,
            cast_dtype=model.model_cfg.dtype,
            wait=False,
        )
        version_key = names.model_version(
            constants.experiment_name(), constants.trial_name(), role
        )
        payload = _pickle.dumps(
            {"version": version, "path": path, "format": "params"}
        ).hex()
        # layout/dtype manifest, captured EAGERLY (aval metadata only —
        # the params may be donated by the next train step before the
        # async commit runs).  Consumers (the gen servers' staged
        # restore) validate against it before opening tensorstore
        # arrays, and its presence is a cheap liveness probe for a
        # snapshot racing keep-last-2 GC.
        import jax.numpy as jnp

        _manifest_dtype = jnp.dtype(model.model_cfg.dtype)
        manifest_params = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(np.shape(x), _manifest_dtype),
            model.engine.params,
        )
        # int8 serving tree: ALSO publish the quantized format to the
        # sibling v{N}-int8 dir and advertise it in the manifest so
        # servers that opted in (serving_weight_dtype="int8") stage half
        # the bytes.  Quantization runs eagerly (the produced arrays are
        # independent of the maybe-donated params); a failure here only
        # withholds the advertisement — consumers fall back to the
        # full-precision tree, never crash.
        serving_quant = None
        if getattr(
            getattr(self, "config", None), "publish_quantized_int8", True
        ):
            qpath = checkpoint.quant_snapshot_path(path)
            try:
                qavals = checkpoint.save_quantized_params(
                    model.engine.params,
                    qpath,
                    cast_dtype=model.model_cfg.dtype,
                    wait=False,
                )
                if qavals is not None:
                    serving_quant = {
                        "int8": checkpoint.quant_manifest_entry(
                            qavals, qpath
                        )
                    }
            except Exception:  # noqa: BLE001 - full tree still publishes
                self.logger.warning(
                    "int8 serving-tree publish failed for %s; consumers "
                    "fall back to the full-precision tree",
                    qpath,
                    exc_info=True,
                )

        def _commit():
            # advertise the version only once the checkpoint is durable,
            # then gc older snapshots (keep last 2; ref gserver_manager
            # :287-305)
            try:
                checkpoint.wait_for_saves()
                # the OPTIONAL quant sibling settles on its own
                # checkpointer: a failed int8 commit only drops the
                # advertisement — the durable full-precision publish
                # below proceeds regardless
                quant_ok = serving_quant
                if quant_ok is not None:
                    try:
                        checkpoint.wait_for_quant_saves()
                    except Exception:  # noqa: BLE001 - degrade, don't die
                        self.logger.warning(
                            "int8 serving-tree commit failed for v%d; "
                            "advertising the full-precision tree only",
                            version,
                            exc_info=True,
                        )
                        quant_ok = None
                try:
                    checkpoint.write_manifest(
                        manifest_params,
                        path,
                        version=version,
                        serving_quant=quant_ok,
                    )
                except OSError:
                    # snapshot already GC'd by a newer publish: the
                    # version check below returns without advertising
                    self.logger.warning(
                        "manifest write failed for %s", path
                    )
                with self._publish_lock:
                    # concurrent commits may finish out of order (the
                    # shared checkpointer waits for ALL pending saves);
                    # never let an older version overwrite a newer key
                    if version <= self._last_published_version.get(role, -1):
                        return
                    self._last_published_version[role] = version
                    name_resolve.add(version_key, payload, replace=True)
                    base = os.path.dirname(path)
                    import re as _re
                    import shutil

                    snaps = sorted(
                        (
                            d
                            for d in os.listdir(base)
                            # skip orbax atomic-save tmp dirs of in-flight
                            # publishes (e.g. 'v7.orbax-checkpoint-tmp-...')
                            if _re.fullmatch(r"v\d+", d)
                        ),
                        key=lambda d: int(d[1:]),
                    )
                    keep = set(snaps[-2:])
                    # reap old versions AND their -int8 serving-tree
                    # siblings together (a kept version keeps its pair)
                    for d in os.listdir(base):
                        m = _re.fullmatch(r"(v\d+)(-int8)?", d)
                        if m is None or m.group(1) in keep:
                            continue
                        shutil.rmtree(
                            os.path.join(base, d), ignore_errors=True
                        )
                self.logger.debug(
                    "published %s v%d in %.2fs (async commit)",
                    model_name,
                    version,
                    time.monotonic() - tik,
                )
            except Exception:  # noqa: BLE001 - version stays unadvertised
                self.logger.exception("weight publish v%d failed", version)

        t = threading.Thread(
            target=_commit, daemon=True, name=f"publish-{role}-v{version}"
        )
        # prune finished commits so the list stays O(in-flight), not O(steps)
        self._publish_threads = [
            x for x in self._publish_threads if x.is_alive()
        ]
        self._publish_threads.append(t)
        t.start()

    def _save_model(self, model_name: str, path: str):
        model = self._models[model_name]
        # write-then-rename so watchers (the automatic evaluator's checkpoint
        # discovery) never see a half-written HF dir; the tmp name does not
        # match the epoch...globalstep... pattern the evaluator scans for
        tmp = path.rstrip("/") + f".tmp-{os.getpid()}"
        os.makedirs(tmp, exist_ok=True)
        model.engine.save_hf(tmp, model.backend_name, model.tokenizer)
        if os.path.isdir(path):
            import shutil

            shutil.rmtree(path)
        os.replace(tmp, path)

    def _ckpt_model(self, model_name: str, path: str):
        """Recover checkpoint: sharded train state (params+optimizer+version),
        every SPMD peer writing its own shards."""
        backend = self._backends[model_name]
        try:
            backend.save(self._models[model_name], path)
        except NotImplementedError:
            pass

    # -- MFC execution ------------------------------------------------------

    def _handle_model_rpc(self, req: Payload) -> Dict:
        spec = req.data
        rpc_name = spec["rpc_name"]
        model_name = spec["model_name"]
        handle = spec["handle_name"]
        ids = spec["ids"]
        input_keys = spec.get("input_keys")
        mb_spec = spec.get("mb_spec") or MicroBatchSpec()

        model = self._models[model_name]
        interface = self._get_interface(rpc_name)
        if handle == "evaluate":
            res = interface.evaluate(
                model, getattr(model, "eval_dataset", None)
            )
            return {"stats": res, "elapsed": 0.0}
        data = self._data_manager.get_batch(ids, input_keys)

        # optional per-MFC profiling (reference: the torch.profiler wrap in
        # realhf/system/model_worker.py:829 __maybe_profile_rpc); set
        # AREAL_PROFILE_DIR to collect an xplane trace per MFC kind
        profile_dir = os.environ.get("AREAL_PROFILE_DIR")
        prof_ctx = None
        if profile_dir:
            prof_ctx = jax.profiler.trace(
                os.path.join(profile_dir, rpc_name)
            )
            prof_ctx.__enter__()
        tik = time.monotonic()
        res: Any = None
        try:
            if handle == "train_step":
                res = interface.train_step(model, data, mb_spec)
                self._trace_train_consumption(model_name, model, ids)
            elif handle == "inference":
                res = interface.inference(model, data, mb_spec)
            elif handle == "generate":
                res = interface.generate(model, data, mb_spec)
            else:
                raise ValueError(f"unknown MFC handle {handle}")
        finally:
            if prof_ctx is not None:
                prof_ctx.__exit__(None, None, None)
        elapsed = time.monotonic() - tik

        reply: Dict = {"elapsed": elapsed}
        reply.update(self._mfc_flops_stats(model, handle, data, res))
        if isinstance(res, SequenceSample):
            self._data_manager.store(res)
            reply["meta"] = res.meta()
            reply["output_keys"] = sorted(res.keys)
        elif isinstance(res, dict):
            reply["stats"] = res
        return reply

    def _trace_train_consumption(self, model_name: str, model, ids):
        """Flight recorder: which train step consumed which qids, with
        per-sample weight-version staleness (current engine version minus
        the sample's ``version_end``) — the off-policyness the paper's
        staleness gate bounds, finally measurable per sample."""
        from areal_tpu.observability.tracing import record_train_consumption

        try:
            version = int(model.version.global_step)
            vends = None
            try:
                vsample = self._data_manager.get_batch(
                    list(ids), ["version_end"]
                )
                import numpy as _np

                vends = _np.asarray(
                    vsample.data["version_end"]
                ).reshape(-1).tolist()
            except Exception:  # noqa: BLE001 - SFT/DPO have no versions
                vends = None
            record_train_consumption(
                ids, version, vends, version,
                model=model_name, tracer=self._tracer,
            )
        except Exception:  # noqa: BLE001 - tracing never fails a train step
            self.logger.debug("train consumption trace failed", exc_info=True)

    def _mfc_flops_stats(self, model, handle: str, data, res) -> Dict:
        """Analytic FLOPs + token count for the master's throughput logs
        (reference: realhf/system/flops_counter.py feeding
        master_worker._log_training_stats)."""
        from areal_tpu.system import flops_counter

        cfg = getattr(model, "model_cfg", None)
        if cfg is None:
            return {}

        def _lens(sample, key):
            # flatten per ANSWER: grouped sampling stores n independent
            # sequences per id; summing them per id would square-inflate
            # the attention term
            return [
                int(l) for per_id in sample.seqlens[key] for l in per_id
            ]

        try:
            if handle == "generate" and isinstance(res, SequenceSample):
                key = (
                    "packed_input_ids"
                    if "packed_input_ids" in res.keys
                    else sorted(res.keys)[0]
                )
                # per-ANSWER lengths: each answer is an independent
                # prefill+decode over its own cache
                full = _lens(res, key)
                pkey = next(
                    (
                        k
                        for k in ("packed_prompts", "packed_input_ids")
                        if k in data.keys
                    ),
                    None,
                )
                prompts = []
                if pkey:
                    for per_id, out_per_id in zip(
                        data.seqlens[pkey], res.seqlens[key]
                    ):
                        prompts.extend([int(sum(per_id))] * len(out_per_id))
                else:
                    prompts = [0] * len(full)
                fl = flops_counter.mfc_flops(handle, cfg, full, prompts)
                n_tokens = sum(full)
            else:
                key = (
                    "packed_input_ids"
                    if "packed_input_ids" in data.keys
                    else sorted(data.keys)[0]
                )
                lens = _lens(data, key)
                fl = flops_counter.mfc_flops(handle, cfg, lens)
                n_tokens = sum(lens)
        except Exception:  # noqa: BLE001 - accounting must never kill an MFC
            return {}
        return {"flops": fl, "n_tokens": n_tokens}

    # -- poll ---------------------------------------------------------------

    def _handle_request(self, req: Payload):
        for hook in req.pre_hooks:
            self._run_hook(hook)
        h = req.handle_name
        if h == "fetch":
            resp = self._handle_fetch(**(req.data or {}))
        elif h == "spec":
            resp = self._handle_spec()
        elif h == "clear_data_cache":
            self._data_manager.drop(req.data["ids"])
            resp = "ok"
        elif h == "model_config":
            m = self._models[req.data["model_name"]]
            resp = dataclasses.asdict(m.model_cfg)
        elif h == "initialize":
            resp = self._handle_initialize(**req.data)
        elif h == "initialize_all":
            resp = {
                str(s.model_name): self._handle_initialize(
                    s, req.data["ft_spec"]
                )
                for s in self.config.shards
            }
        elif h == "save":
            self._save_model(req.data["model_name"], req.data["path"])
            resp = "ok"
        elif h == "ckpt":
            self._ckpt_model(req.data["model_name"], req.data["path"])
            resp = "ok"
        elif h in ("train_step", "inference", "generate", "evaluate"):
            resp = self._handle_model_rpc(req)
        elif h == "ping":
            resp = "pong"
        else:
            raise ValueError(f"unknown request {h}")
        for hook in req.post_hooks:
            self._run_hook(hook)
        self._stream.reply(req, resp)

    def _poll(self) -> worker_base.PollResult:
        count = 0
        for _ in range(8):
            try:
                req = self._stream.poll_request()
            except NoMessage:
                break
            try:
                self._handle_request(req)
            except Exception as e:  # noqa: BLE001 - propagate via reply
                self.logger.exception(
                    "request %s failed", req.handle_name
                )
                self._stream.reply(
                    req, {"__worker_error__": repr(e)}
                )
            count += 1
        return worker_base.PollResult(sample_count=count)

    def _exit_hook(self):
        # drain in-flight publish commits: the final trained version must be
        # advertised before the process goes away
        for t in getattr(self, "_publish_threads", []):
            t.join(timeout=60)
        if hasattr(self, "_data_manager"):
            self._data_manager.close()
        if hasattr(self, "_stream"):
            self._stream.close()
